#!/usr/bin/env python
"""Critical-path micro-benchmarks: the per-task fixed cost, measured on CPU.

The runtime's value proposition is micro-task scheduling overhead in the low
microseconds (PAPER.md; MPK and Design-in-Tiles both argue the per-task fixed
cost, not the kernels, is the lever for fine-grained tensor programs).  This
harness measures exactly that fixed cost — select→prepare→exec→complete→
release — with NOTHING accelerator-dependent, so the perf axis stays
measurable even when the TPU relay is dark:

- ``bench_dispatch_us``        — per-task latency on the EP CTL DAG through
  the compiled-DAG executor (the headline ``task_dispatch_us`` series) and
  through the dynamic Python scheduler (``dynamic_dispatch_us``);
- ``bench_release_throughput`` — dep-release tasks/s through the dynamic
  path (``release_deps`` → batched ``DependencyTracking.release_many``);
- ``bench_steal_us``           — lfq local-pop and steal latency against the
  sharded per-stream deques (sched/modules.py);
- ``bench_pins_disabled_ns``   — cost of one DISABLED instrumentation site
  (the per-event dispatch-slot fast path, prof/pins.py);
- ``bench_tracing``            — request-tracing costs (prof/spans.py +
  prof/histogram.py): span record ns, SLO histogram record ns, and the
  enabled-vs-disabled dynamic dispatch delta (the ≤1µs/task budget);
- ``bench_lowering_cache``     — first-vs-second compile seconds of an
  identical lowered taskpool (the persistent lowering cache,
  ptg/lowering.py);
- ``bench_lowering``           — XLA calls per DAG and trace/compile
  seconds across the lowering modes (ISSUE 8): dynamic task-per-dispatch
  vs megakernel regions vs whole-pool wavefront/scan vs chain-collapse,
  on cholesky's irregular 4-class DAG (docs/PERF.md, "Region lowering &
  compile budgets");
- ``bench_serve``              — sustained submissions/s and p50/p99
  ticket latency through a RuntimeServer: concurrent client threads,
  two tenants, one hot context (the serving layer, parsec_tpu/serve/);
- ``bench_comm``               — the comm wire data path (ISSUE 4): AM
  roundtrip µs over inproc + localhost sockets, coalesced compact
  activations/s, one-sided GET GB/s at 64KiB/4MiB/64MiB through the
  binary scatter-gather framing + windowed fragmented rendezvous, the
  legacy pickle-framing baseline and speedup ratio, and the overlap
  efficiency of compute retired during a saturating fragmented GET.

``python microbench.py`` prints one JSON object and finishes in seconds on a
CPU-only host.  ``run_all(smoke=True)`` shrinks every config for CI; the
``perf_smoke`` tier-1 marker (tests/test_perf_smoke.py) runs that with 10×
headroom thresholds so gross dispatch-path regressions fail fast without
timing flakes.  docs/PERF.md maps each number to the code it measures.
"""

from __future__ import annotations

import json
import statistics
import time


def _ep_pool(NT: int, DEPTH: int):
    """The reference's tests/runtime/scheduling/ep.jdf shape: NT independent
    lanes of DEPTH chained CTL-only tasks."""
    from parsec_tpu import ptg

    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l: None)
    return p


def _drain_ep_us(ntasks: int, reps: int, compiled: bool,
                 traced: bool = False) -> tuple:
    """Median enqueue-to-drain wall time per task in µs, plus whether the
    compiled-DAG executor actually engaged (it silently declines when the
    native extension is unavailable — the reading must say which path it
    measured, or the dispatch trend mixes incomparable series).
    ``traced=True`` attaches a trace context to every pool, so an
    INSTALLED span recorder actually records (the enabled-cost axis of
    ``bench_tracing``)."""
    import parsec_tpu.runtime.dagrun  # noqa: F401 — runtime_dag_compile
    from parsec_tpu.core.params import params
    from parsec_tpu.prof import spans
    from parsec_tpu.runtime import Context

    NT = 50
    DEPTH = max(ntasks // NT, 2)
    builder = _ep_pool(NT, DEPTH)
    saved = params.get("runtime_dag_compile")
    params.set("runtime_dag_compile", compiled)
    engaged = False
    try:
        times = []
        for _ in range(reps):
            tp = builder.build()
            if traced:
                tp._trace = spans.new_trace()
            ctx = Context(nb_cores=0)
            t0 = time.perf_counter()
            ctx.add_taskpool(tp)
            engaged = getattr(tp, "_compiled_dag", None) is not None
            ctx.wait(timeout=600)
            times.append(time.perf_counter() - t0)
            ctx.fini()
        return statistics.median(times) / (NT * DEPTH) * 1e6, engaged
    finally:
        params.set("runtime_dag_compile", saved)


def bench_dispatch_us(ntasks: int = 10000, reps: int = 5) -> dict:
    us, engaged = _drain_ep_us(ntasks, reps, True)
    return {"dispatch_us": round(us, 3), "ntasks": ntasks,
            "dispatch_path": "compiled" if engaged else "dynamic"}


def bench_release_throughput(ntasks: int = 10000, reps: int = 3) -> dict:
    """Dynamic-path drain: every non-startup task arrives through
    ``release_deps`` → ``release_many``, so tasks/s here IS dep-release +
    schedule throughput (body is empty)."""
    us, _ = _drain_ep_us(ntasks, reps, False)
    return {"dynamic_dispatch_us": round(us, 3),
            "release_tasks_per_s": round(1e6 / us, 1),
            "ntasks": ntasks}


class _BenchTask:
    __slots__ = ("priority",)

    def __init__(self) -> None:
        self.priority = 0


def bench_steal_us(n: int = 200, reps: int = 50) -> dict:
    """lfq local-pop vs steal latency on the sharded per-stream deques,
    driven through the real scheduler module (no Context needed)."""
    import parsec_tpu.sched  # noqa: F401 — registers components + params
    from parsec_tpu.sched.modules import LFQModule
    from parsec_tpu.runtime.scheduling import ExecutionStream, VirtualProcess

    class _Ctx:
        virtual_processes: list = []

    ctx = _Ctx()
    vp = VirtualProcess(0, ctx)
    ctx.virtual_processes = [vp]
    es0 = ExecutionStream(0, vp, ctx)
    es1 = ExecutionStream(1, vp, ctx)
    vp.execution_streams = [es0, es1]
    mod = LFQModule()
    mod.install(ctx)
    mod.flow_init(es0)
    mod.flow_init(es1)
    n = min(n, mod._cap)      # beyond capacity spills to the system queue
    tasks = [_BenchTask() for _ in range(n)]

    def run(selector_es) -> float:
        best = None
        for _ in range(reps):
            mod.schedule(es0, list(tasks), 0)
            t0 = time.perf_counter()
            for _i in range(n):
                t, _d = mod.select(selector_es)
                assert t is not None
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best / n * 1e6

    return {"local_pop_us": round(run(es0), 4),
            "steal_us": round(run(es1), 4), "n": n}


def bench_pins_disabled_ns(iters: int = 200000) -> dict:
    """One DISABLED instrumentation site (index load + falsy branch) vs
    the always-on recorder-enabled site, through the same dispatch-slot
    pattern the scheduling loop compiles in (prof/pins.py).  The recorder
    is detached for the disabled half and restored after."""
    from parsec_tpu.prof import pins

    hooks = pins.hooks
    ev = int(pins.PinsEvent.EXEC_BEGIN)
    payload = object()

    def run() -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            h = hooks[ev]
            if h is not None:
                h(None, payload)
        return (time.perf_counter() - t0) / iters * 1e9

    saved = pins.recorder
    pins.recorder = None
    try:
        disabled = run() if hooks[ev] is None else None
    finally:
        pins.recorder = saved
    out = {"pins_disabled_ns": round(disabled, 2)
           if disabled is not None else None}
    if hooks[ev] is not None:       # always-on recorder (or chains) present
        out["pins_enabled_ns"] = round(run(), 2)
    return out


def bench_tracing(ntasks: int = 2000, reps: int = 3,
                  smoke: bool = False) -> dict:
    """The request-tracing cost axes (prof/spans.py, prof/histogram.py):

    - ``span_record_ns``     — one finished-span record (tuple + append,
      the ring-write-shaped enabled cost);
    - ``hist_record_ns``     — one SLO histogram sample (one log, one
      bucket increment);
    - ``tracing_dispatch_off_us`` / ``_on_us`` / ``_delta_us`` — dynamic
      per-task dispatch with the recorder UNINSTALLED (the shipped
      default: the PINS table's one-branch cost, nothing more) vs
      INSTALLED with every pool traced.  The acceptance budget: disabled
      within 10% of the PR-2 overhead baseline, enabled ≤1µs/task
      (both gated with headroom in tests/test_perf_smoke.py)."""
    from parsec_tpu.prof import spans
    from parsec_tpu.prof.histogram import LogHistogram

    if smoke:
        ntasks, reps = 1000, 2
    out: dict = {}
    # -- span record cost (a throwaway recorder; never installed) ------
    rec = spans.SpanRecorder(1 << 20)
    tr = spans.new_trace()
    n = 20000
    t0 = time.perf_counter()
    for _i in range(n):
        rec.record("exec", tr.trace_id, 0, 100)
    out["span_record_ns"] = round(
        (time.perf_counter() - t0) / n * 1e9, 1)
    # -- histogram record cost -----------------------------------------
    h = LogHistogram()
    t0 = time.perf_counter()
    for _i in range(n):
        h.record(1.234)
    out["hist_record_ns"] = round(
        (time.perf_counter() - t0) / n * 1e9, 1)
    # -- enabled-vs-disabled dynamic dispatch --------------------------
    prev = spans.recorder      # a user-installed recorder (and its
    if prev is not None:       # accumulated spans) must survive this
        spans.uninstall()      # measurement — restored object-identical
    off, _ = _drain_ep_us(ntasks, reps, compiled=False)
    spans.install()
    try:
        on, _ = _drain_ep_us(ntasks, reps, compiled=False, traced=True)
        out["tracing_spans_recorded"] = len(spans.recorder.spans)
    finally:
        spans.uninstall()
        if prev is not None:
            spans.install(recorder_obj=prev)
    out["tracing_dispatch_off_us"] = round(off, 3)
    out["tracing_dispatch_on_us"] = round(on, 3)
    out["tracing_dispatch_delta_us"] = round(on - off, 3)
    return out


def bench_lowering_cache(n: int = 96, nb: int = 32) -> dict:
    """Two structurally identical lowerings of a tiled GEMM: the second
    must hit the process-wide lowering cache and skip trace+compile."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.ptg.lowering import lower_taskpool, lowering_cache

    def once() -> float:
        rng = np.random.default_rng(7)
        a = rng.standard_normal((n, n)).astype(np.float32)
        A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
        B = TiledMatrix.from_dense("B", a.copy(), nb, nb)
        C = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32), nb, nb)
        low = lower_taskpool(tiled_gemm_ptg(A, B, C))
        st = low.initial_stores()
        t0 = time.perf_counter()
        out = low.jitted()(st)
        float(np.asarray(out["C"]).reshape(-1)[0])
        return time.perf_counter() - t0

    h0, m0 = lowering_cache.hits, lowering_cache.misses
    cold = once()
    warm = once()
    return {"compile_cold_s": round(cold, 4),
            "compile_warm_s": round(warm, 4),
            "cache_hits": lowering_cache.hits - h0,
            "cache_misses": lowering_cache.misses - m0}


def bench_lowering(n: int = 256, nb: int = 32, smoke: bool = False) -> dict:
    """XLA calls per DAG + trace/compile seconds across the lowering modes
    (ISSUE 8, the MPK axis): on cholesky's irregular 4-class DAG, compare
    the dynamic task-per-dispatch path (vmapped batching OFF — every task
    is one XLA dispatch, the boundary cost megakernels delete) against the
    region lowering (one jitted program per convex subgraph), plus the
    whole-pool wavefront/scan emission and the GEMM chain-collapse for the
    per-mode compile-cost axis.  Every number is CPU-measurable; the
    dispatch counts come from the process-wide ledger feeding both paths
    (``device.note_xla_calls``)."""
    import jax
    import numpy as np

    from parsec_tpu.core.params import params
    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic, TiledMatrix
    from parsec_tpu.device import registry
    from parsec_tpu.device.device import xla_calls_total
    from parsec_tpu.device.tpu import TPUDevice
    from parsec_tpu.models.cholesky import make_spd, tiled_cholesky_ptg
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.ptg.lowering import lower_regions, lower_taskpool
    from parsec_tpu.runtime import Context

    if smoke:
        n, nb = 128, 32
    a = make_spd(n)

    def chol(devices="auto"):
        A = SymTwoDimBlockCyclic.from_dense("A", a.copy(), nb, nb)
        return tiled_cholesky_ptg(A, devices=devices)

    out: dict = {"lowering_n": n, "lowering_nb": nb}

    # --- task-per-dispatch baseline: the dynamic device path, vmapped
    # batching disabled, so EVERY task body is one XLA enqueue ---
    snapshot = list(registry.devices)
    saved_batch = params.get("device_tpu_batch")
    params.set("device_tpu_batch", False)
    dev = TPUDevice(jax.devices()[0])
    registry.add(dev)
    try:
        tp = chol(devices="tpu")
        ledger0, tasks0 = xla_calls_total(), dev.executed_tasks
        ctx = Context(nb_cores=0)
        try:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=120)
            dev.sync()
        finally:
            ctx.fini(timeout=30)
        out["lowering_tasks_per_dag"] = dev.executed_tasks - tasks0
        out["lowering_dispatch_xla_calls"] = xla_calls_total() - ledger0
    finally:
        params.set("device_tpu_batch", saved_batch)
        registry.devices = snapshot
        for i, d in enumerate(registry.devices):
            d.device_index = i

    # --- region mode: one program per verified subgraph, cold then warm
    # (the second structurally identical plan must hit the process cache
    # and report ~0 compile seconds — the AOT-warming contract) ---
    plan = lower_regions(chol())
    plan.compile()
    cold = plan.stats()
    ledger0 = xla_calls_total()
    plan.execute()
    st = plan.stats()
    out["lowering_region_count"] = st["regions"]
    # the same process-wide ledger as the dispatch baseline above, so
    # the two counts are one comparable axis; the plan's own counter
    # rides along as the cross-check (they diverge only if another
    # thread dispatched concurrently)
    out["lowering_region_xla_calls"] = xla_calls_total() - ledger0
    out["lowering_region_plan_xla_calls"] = st["xla_calls"]
    out["lowering_region_trace_s"] = cold["trace_s"]
    out["lowering_region_compile_cold_s"] = cold["compile_s"]
    warm = lower_regions(chol())
    warm.compile()
    out["lowering_region_compile_warm_s"] = warm.stats()["compile_s"]
    if out["lowering_region_xla_calls"]:
        out["lowering_region_xla_call_drop"] = round(
            out["lowering_dispatch_xla_calls"] / out["lowering_region_xla_calls"], 1)

    # --- whole-pool wavefront (scan-folded) emission: ONE program ---
    low = lower_taskpool(chol(), passes="wavefront")
    out["lowering_wavefront_xla_calls"] = 1
    wavefront = low.warm()
    out["lowering_wavefront_trace_s"] = wavefront["trace_s"]
    out["lowering_wavefront_compile_s"] = wavefront["compile_s"]

    # --- chain-collapse: the GEMM k-chain as one contraction ---
    gn, gnb = (64, 32) if smoke else (128, 32)
    rng = np.random.default_rng(3)
    g = rng.standard_normal((gn, gn)).astype(np.float32)
    A = TiledMatrix.from_dense("A", g.copy(), gnb, gnb)
    B = TiledMatrix.from_dense("B", g.copy(), gnb, gnb)
    C = TiledMatrix.from_dense("C", np.zeros((gn, gn), np.float32), gnb, gnb)
    low = lower_taskpool(tiled_gemm_ptg(A, B, C), passes="chain-collapse")
    out["lowering_chain_xla_calls"] = 1
    chain = low.warm()
    out["lowering_chain_trace_s"] = chain["trace_s"]
    out["lowering_chain_compile_s"] = chain["compile_s"]
    return out


def bench_serve(nsub: int = 64, nthreads: int = 4, depth: int = 8,
                nb_cores: int = 2) -> dict:
    """Serving-path fixed cost: ``nthreads`` client threads submit
    ``nsub`` small CTL-chain pools (4 lanes x ``depth``, the EP shape)
    into one hot :class:`RuntimeServer` under two tenants, each blocking
    on its ticket — sustained submissions/s plus p50/p99 end-to-end
    ticket latency.  Pure scheduler path (no accelerator, no lowering):
    the serving layer's admission + fair-queue + live-enqueue overhead
    is what this measures."""
    import threading

    from parsec_tpu.serve import RuntimeServer

    lat: list[float] = []
    lock = threading.Lock()
    errors: list[BaseException] = []
    server = RuntimeServer(nb_cores=nb_cores)
    per = max(nsub // nthreads, 1)

    def client(tenant: str) -> None:
        try:
            for _i in range(per):
                tp = _ep_pool(4, depth).build()
                t0 = time.perf_counter()
                tk = server.submit(tp, tenant=tenant)
                tk.result(timeout=120)
                dt = time.perf_counter() - t0
                with lock:
                    lat.append(dt)
        except BaseException as e:      # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(f"tenant{i % 2}",),
                                name=f"serve-client{i}")
               for i in range(nthreads)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    # the per-tenant SLO plane, read LIVE off the still-hot server
    # (RuntimeServer.metrics(), the histogram plane): queue wait +
    # end-to-end latency quantiles per tenant, before drain resets
    # anything — the mid-run acceptance read
    slo = server.metrics()["tenants"]
    server.drain(timeout=60)
    if errors:
        raise errors[0]
    lat.sort()
    n = len(lat)
    return {
        "serve_submits_per_s": round(n / wall, 1),
        "serve_p50_ms": round(lat[n // 2] * 1e3, 3),
        "serve_p99_ms": round(lat[min(int(n * 0.99), n - 1)] * 1e3, 3),
        "serve_nsub": n,
        "serve_threads": nthreads,
        "serve_tasks_per_sub": 4 * depth,
        "serve_slo": slo,
        "serve_drain_s": round(server.metrics()["drain_s"] or 0.0, 4),
    }


def bench_llm(streams_sweep: tuple = (1, 4, 8),
              steps_sweep: tuple = (1, 4, 8), new_tokens: int = 16,
              prompt_len: int = 8, nb_cores: int = 2,
              smoke: bool = False, note=None) -> dict:
    """The LLM serving axis: tokens/s and per-token p50/p99 latency of
    the continuous batcher on a hot RuntimeServer, swept over concurrent
    streams (the request-scale axis the ROADMAP names) AND over
    ``llm_steps_per_pool`` (the ISSUE-9 amortization axis: one k-step
    decode superpool per tenant per iteration, in-graph sampling, so
    submit/termdet overhead is paid 1/k per token).  Streams run under
    per-stream tenants — the ROADMAP's millions-of-users shape, where
    WFQ isolation is a hard boundary and cross-stream batching cannot
    hide the per-pool submit cost, so the k axis measures exactly what
    the superpool amortizes.  (PR 6 benched 2 shared tenants, whose
    intra-tenant batching already amortized submits 4x at 8 streams;
    that axis is still visible as the streams sweep.)  Each point also
    reports ``submits_per_token`` — the amortization claim (k steps ->
    1/k submits) made directly visible — and ``note(**kw)`` (the bench
    harness passes ``_note_partial``) fires per swept point, so a
    mid-sweep deadline keeps the completed points (the BENCH_r04/r05
    lesson).  No accelerator; ``docs/LLM.md``."""
    from parsec_tpu.core.params import params as _params
    from parsec_tpu.llm import ToyLM
    from parsec_tpu.serve import RuntimeServer

    if smoke:
        streams_sweep, steps_sweep, new_tokens = (1, 4), (1, 8), 8
    model = ToyLM()
    out: dict = {"llm_streams_sweep": {}, "llm_steps_sweep": {}}
    # 64-token generations: the first ~10-16 tokens are the transition
    # where the generation settles into its fixed point and the bigram
    # table learns it — the spec axis must measure the draftable steady
    # state, not the warmup (a 32-token stream is ~1/3 warmup and
    # understates the speedup ~2x)
    spec_streams, spec_tokens = 8, max(64, 4 * new_tokens)
    k_top = max(steps_sweep)
    saved_k = _params.get("llm_steps_per_pool")
    server = RuntimeServer(nb_cores=nb_cores)
    try:
        def run_point(ns: int, k: int) -> dict:
            _params.set("llm_steps_per_pool", k)
            before = server.stats().get("llm") or {}
            sub0 = before.get("decode_submits", 0)
            tok0 = before.get("tokens_generated", 0)
            prompts = [[(7 * i + 3 * j) % model.vocab
                        for j in range(prompt_len)] for i in range(ns)]
            t0 = time.perf_counter()
            tks = [server.submit_stream(p, max_new_tokens=new_tokens,
                                        tenant=f"tenant{i}")
                   for i, p in enumerate(prompts)]
            per_token: list[float] = []
            for tk in tks:
                per_token += tk.result(timeout=300)["per_token_s"]
            wall = time.perf_counter() - t0
            per_token.sort()
            n = len(per_token)
            after = server.stats()["llm"]
            d_sub = after["decode_submits"] - sub0
            d_tok = after["tokens_generated"] - tok0
            point = {
                "tokens_per_s": round(ns * new_tokens / wall, 1),
                "p50_ms": round(per_token[n // 2] * 1e3, 3),
                "p99_ms": round(
                    per_token[min(int(n * 0.99), n - 1)] * 1e3, 3),
                "submits_per_token": round(d_sub / max(1, d_tok), 4),
            }
            if note is not None:
                # one UNIQUE key per swept point: _note_partial merges
                # by dict update, so reusing flat keys would leave only
                # the last completed point in a deadline's degrade
                # record instead of all of them
                note(phase="llm", **{f"llm_point_s{ns}_k{k}": point})
            return point

        for ns in streams_sweep:
            out["llm_streams_sweep"][str(ns)] = run_point(ns, k_top)
        top_ns = streams_sweep[-1]
        # the amortization axis, measured IN THE SAME RUN at the top
        # stream count (k_top reuses the streams-sweep point)
        for k in steps_sweep:
            out["llm_steps_sweep"][str(k)] = (
                out["llm_streams_sweep"][str(top_ns)] if k == k_top
                else run_point(top_ns, k))
        base = out["llm_steps_sweep"][str(min(steps_sweep))]
        best = out["llm_steps_sweep"][str(k_top)]
        out["llm_superpool_speedup"] = round(
            best["tokens_per_s"] / max(base["tokens_per_s"], 1e-9), 2)
        out["llm_tokens_per_s"] = best["tokens_per_s"]
        out["llm_p50_ms"] = best["p50_ms"]
        out["llm_p99_ms"] = best["p99_ms"]
        out["llm_steps_per_pool"] = k_top
        out["serve_submits_per_token"] = best["submits_per_token"]
        out["llm_new_tokens"] = new_tokens
        out["llm_prompt_len"] = prompt_len
        out["llm_kv"] = server.stats()["llm"]["kv"]
        # per-tenant TTFT + inter-token latency quantiles off the SLO
        # histogram plane, read LIVE (RuntimeServer.metrics()) while the
        # server is still hot — the same numbers mid-run and in the emit
        out["llm_slo"] = {
            tenant: {k: v for k, v in d.items()
                     if k.startswith(("ttft_ms", "tok_latency_ms",
                                      "queue_wait_ms"))}
            for tenant, d in server.metrics()["tenants"].items()
            if "ttft_ms_p50" in d}
    finally:
        _params.set("llm_steps_per_pool", saved_k)
        server.drain(timeout=60)

    # the speculative-decode axis (ISSUE 12): off/2/4/adaptive on a
    # DRAFTABLE (repetitive) workload at 8 streams — the ROADMAP's
    # 10k+-tok/s leg.  Greedy ToyLM generations collapse to fixed
    # points / short cycles on arithmetic-ramp prompts, which is
    # exactly the templated-continuation shape the n-gram drafter
    # predicts; "off" shares the workload so llm_spec_speedup compares
    # the spec superpool against the PR-9 k-step path, nothing else.
    # Fresh server per point: per-tenant acceptance priors and drafter
    # state must not leak across points.
    saved_spec = {k: _params.get(k) for k in ("llm_spec_k",
                                              "llm_spec_adaptive")}
    # 8 distinct arithmetic-ramp (offset, stride) prompts whose greedy
    # generations collapse fast (~0.9 chain acceptance at draft 16 on
    # the bigram simulation) — the draftable workload the ISSUE-12
    # speedup criterion names; the "off" point runs the SAME prompts
    spec_shapes = ((48, 5), (44, 9), (36, 11), (20, 11),
                   (0, 3), (60, 1), (32, 3), (32, 1))
    spec_prompts = [[(a + b * j) % model.vocab
                     for j in range(prompt_len)]
                    for a, b in spec_shapes[:spec_streams]]

    def run_spec_point(spec_k: int, adaptive: bool) -> dict:
        _params.set("llm_spec_k", spec_k)
        _params.set("llm_spec_adaptive", adaptive)
        with RuntimeServer(nb_cores=nb_cores) as server:
            t0 = time.perf_counter()
            tks = [server.submit_stream(p, max_new_tokens=spec_tokens,
                                        tenant=f"tenant{i}")
                   for i, p in enumerate(spec_prompts)]
            for tk in tks:
                tk.result(timeout=300)
            wall = time.perf_counter() - t0
            llm = server.stats()["llm"]
        return {
            "tokens_per_s": round(spec_streams * spec_tokens / wall, 1),
            "accept_rate": llm.get("spec_accept_rate", 0.0),
            "tokens_per_submit": llm.get("spec_tokens_per_submit", 0.0),
            "rollbacks": llm["kv"]["tail_rollbacks"],
        }

    try:
        out["llm_spec_sweep"] = {}
        for label, k, ad in (("off", 0, False), ("2", 2, False),
                             ("4", 4, False), ("adaptive", 16, True)):
            point = run_spec_point(k, ad)
            out["llm_spec_sweep"][label] = point
            if note is not None:
                note(phase="llm", **{f"llm_spec_{label}": point})
        base = out["llm_spec_sweep"]["off"]["tokens_per_s"]
        out["llm_spec_speedup"] = round(
            out["llm_spec_sweep"]["adaptive"]["tokens_per_s"]
            / max(base, 1e-9), 2)
        out["llm_spec_accept_rate"] = \
            out["llm_spec_sweep"]["adaptive"]["accept_rate"]
        out["llm_spec_streams"] = spec_streams
        out["llm_spec_new_tokens"] = spec_tokens
        if note is not None:
            note(phase="llm", llm_spec_speedup=out["llm_spec_speedup"])
    finally:
        for k, v in saved_spec.items():
            _params.set(k, v)
    return out


def bench_llm_prefix(fracs: tuple = (0.0, 0.5, 0.9), nstreams: int = 8,
                     shared_pages: int = 12, tail_len: int = 8,
                     new_tokens: int = 2, nb_cores: int = 2,
                     page_size: int = 256, reps: int = 2,
                     smoke: bool = False, note=None) -> dict:
    """The automatic-prefix-cache axis (ISSUE 11): TTFT p50/p99 and the
    prefill work actually skipped, swept over the **shared-prefix
    fraction** of the traffic — the millions-of-users shape is most
    requests carrying one system prompt, and the radix trie
    (``llm/prefix_tree.py``) should convert exactly that fraction of
    prefill into copy-on-write page forks.

    Per swept point: a fresh server + batcher with ``llm_prefix_cache=1``
    is warmed by ONE donor stream (its retirement donates the shared
    prompt's pages to the trie), then ``nstreams`` streams arrive of
    which ``frac`` share the donor's prefix (plus per-stream tails — the
    hit-mid-page shape) and the rest carry disjoint prompts (misses).
    TTFT is client-observed: ``StreamTicket.first_token_at`` minus
    submit.  The headline ``llm_prefix_ttft_speedup`` re-runs the top
    fraction with the cache OFF and reports cold/hot TTFT p50 — the
    perf_smoke ``LLM_PREFIX_TTFT_SPEEDUP_MIN`` gate holds it ≥ 2x.
    ``note(**kw)`` fires per point (deadline-death keeps sweep points,
    the BENCH_r04/r05 lesson).  Pure CPU serving path.

    Geometry: 256-token pages — prefill work per cacheable token (chunk
    building + PF page copies) then dominates scheduler task overhead,
    so the measured speedup reflects the work the trie skips rather
    than the per-task cost the superpool axis already measures.  Each
    point runs ``reps`` waves on one hot server and keeps the best p50
    (arrival/iteration phase alignment is the flake source; the wave
    with the cleanest batch boundary is the representative one)."""
    import parsec_tpu.llm.batcher  # noqa: F401 — registers llm_* params
    from parsec_tpu.core.params import params as _params
    from parsec_tpu.llm import ToyLM
    from parsec_tpu.serve import RuntimeServer

    if smoke:
        fracs, nstreams = (0.0, 0.9), 6
    model = ToyLM()
    P = int(page_size)
    shared = [(5 * i + 11) % model.vocab for i in range(shared_pages * P)]
    saved = {k: _params.get(k) for k in ("llm_prefix_cache",
                                         "llm_steps_per_pool",
                                         "llm_page_size")}
    # 1-step superpools: TTFT then measures admission + prefill + one
    # decode step, so the prefill skip is visible instead of drowned
    # under a k-step first iteration
    _params.set("llm_steps_per_pool", 1)
    _params.set("llm_page_size", P)

    def run_point(frac: float, cache_on: bool) -> dict:
        _params.set("llm_prefix_cache", cache_on)
        with RuntimeServer(nb_cores=nb_cores) as server:
            donor = server.submit_stream(shared + [3], max_new_tokens=1,
                                         tenant="pfx")
            donor.result(timeout=300)      # retires -> donates the prefix
            llm0 = server.stats()["llm"]
            nshared = int(round(frac * nstreams))
            best = None
            for rep in range(max(1, reps)):
                # unique parts vary PER WAVE: a later wave's misses must
                # stay misses (the earlier wave's retirees donated their
                # prompts), or the 0.0 point would silently measure
                # repeat-traffic hits instead of the cold path
                prompts = []
                for i in range(nstreams):
                    # distinct mod vocab across (wave, stream) pairs, so
                    # no two "unique" prompts ever alias page runs
                    salt = (rep * nstreams + i) % model.vocab
                    if i < nshared:        # shared prefix + unique tail
                        prompts.append(shared
                                       + [(salt + j) % model.vocab
                                          for j in range(tail_len)])
                    else:                  # disjoint prompt, same length
                        prompts.append([(7 * salt + 3 * j + 1)
                                        % model.vocab
                                        for j in range(len(shared)
                                                       + tail_len)])
                t0 = time.perf_counter()
                tks = [server.submit_stream(p, max_new_tokens=new_tokens,
                                            tenant="pfx") for p in prompts]
                for tk in tks:
                    tk.result(timeout=300)
                wall = time.perf_counter() - t0
                ttfts = sorted((tk.first_token_at - tk.submitted_at) * 1e3
                               for tk in tks
                               if tk.first_token_at is not None)
                n = len(ttfts)
                wave = {
                    "ttft_p50_ms": round(ttfts[n // 2], 3) if n else 0.0,
                    "ttft_p99_ms": round(
                        ttfts[min(int(n * 0.99), n - 1)], 3) if n else 0.0,
                    "tokens_per_s": round(
                        nstreams * new_tokens / wall, 1),
                }
                if best is None or wave["ttft_p50_ms"] < best["ttft_p50_ms"]:
                    best = wave
            llm1 = server.stats()["llm"]
            d_tot = (llm1["prefill_tokens_total"]
                     - llm0["prefill_tokens_total"])
            d_skip = (llm1["prefill_tokens_skipped"]
                      - llm0["prefill_tokens_skipped"])
            best["prefill_skipped_frac"] = round(d_skip / max(1, d_tot), 4)
            best["prefix_hits"] = (llm1["kv"]["prefix_hits"]
                                   - llm0["kv"]["prefix_hits"])
            return best

    out: dict = {"llm_prefix_sweep": {}}
    try:
        for frac in fracs:
            point = run_point(frac, cache_on=True)
            out["llm_prefix_sweep"][str(frac)] = point
            if note is not None:
                note(phase="llm_prefix",
                     **{f"llm_prefix_f{frac}": point})
        top = max(fracs)
        cold = run_point(top, cache_on=False)
        out["llm_prefix_cold"] = cold
        hot = out["llm_prefix_sweep"][str(top)]
        out["llm_prefix_ttft_speedup"] = round(
            cold["ttft_p50_ms"] / max(hot["ttft_p50_ms"], 1e-9), 2)
        out["llm_prefill_skipped_frac"] = hot["prefill_skipped_frac"]
        out["llm_prefix_shared_tokens"] = len(shared)
        if note is not None:
            note(phase="llm_prefix",
                 llm_prefix_ttft_speedup=out["llm_prefix_ttft_speedup"],
                 llm_prefill_skipped_frac=out["llm_prefill_skipped_frac"])
    finally:
        for k, v in saved.items():
            _params.set(k, v)
    return out


def bench_llm_tier(nstreams: int = 4, prompt_pages: int = 3,
                   new_tokens: int = 24, nb_cores: int = 2,
                   smoke: bool = False, note=None) -> dict:
    """The KV-tiering axis (ISSUE 11): the SAME decode workload through
    the accelerator device tier twice — unconstrained, then with the
    device HBM budget squeezed BELOW the live-KV working set — reporting
    the tokens/s ratio (the "prefetch hides the spill" claim: the
    acceptance line is within 30%) plus the tier ledger
    (``host_tier_bytes``, spills, prefetched pages) of the constrained
    run.  Off-TPU the device is the host CPU wrapped as an accelerator
    (the same CPU-coverage trick the device suites use), so the number
    is CPU-provable; tokens are oracle-checked in both runs."""
    import jax

    import parsec_tpu.llm.batcher  # noqa: F401 — registers llm_* params
    from parsec_tpu.device import registry
    from parsec_tpu.device.tpu import TPUDevice
    from parsec_tpu.llm import ContinuousBatcher, ToyLM
    from parsec_tpu.serve import RuntimeServer

    if smoke:
        # >= 2 superpool iterations (k=8): iteration N's evictions are
        # what iteration N+1's prefetch stages back — a single-shot run
        # would race the deferred write-back drain and prefetch nothing
        nstreams, new_tokens = 2, 16
    model = ToyLM()

    def run_once(budget_pages: int | None) -> tuple[float, dict]:
        snapshot = list(registry.devices)
        dev = TPUDevice(jax.devices()[0])
        registry.add(dev)
        try:
            with RuntimeServer(nb_cores=nb_cores) as server:
                b = ContinuousBatcher(server, model=model, devices="tpu")
                # one warmup stream BEFORE the timed batch: both runs
                # then measure steady-state decode, not whichever run
                # happened to pay the process's first jit/vmap builds
                b.submit_stream([1, 2, 3], max_new_tokens=1) \
                    .result(timeout=300)
                if budget_pages is not None:
                    dev._mem_budget = budget_pages * b.kv.page_bytes
                P = b.kv.page_size
                prompts = [[(7 * i + 3 * j + 1) % model.vocab
                            for j in range(prompt_pages * P + 1)]
                           for i in range(nstreams)]
                t0 = time.perf_counter()
                tks = [b.submit_stream(p, max_new_tokens=new_tokens)
                       for p in prompts]
                for p, tk in zip(prompts, tks):
                    got = tk.result(timeout=300)["tokens"]
                    want = model.reference_generate(p, new_tokens)
                    assert got == want, ("tiered decode diverged from "
                                        "the dense oracle", got, want)
                wall = time.perf_counter() - t0
                stats = b.stats()
                b.stop()
            return nstreams * new_tokens / wall, stats
        finally:
            registry.devices = snapshot
            for i, d in enumerate(registry.devices):
                d.device_index = i

    tok_free, _ = run_once(None)
    # working set ~= nstreams * (prompt + decode tail) pages; squeeze to
    # roughly a third so eviction pressure is real every iteration
    squeeze = max(2, nstreams * (prompt_pages + 1) // 3)
    tok_tight, stats = run_once(squeeze)
    out = {
        "llm_tier_tokens_per_s_free": round(tok_free, 1),
        "llm_tier_tokens_per_s_tight": round(tok_tight, 1),
        "llm_tier_tokens_ratio": round(tok_tight / max(tok_free, 1e-9), 3),
        "llm_tier_budget_pages": squeeze,
        "llm_tier_spills": stats["tiers"]["spills"],
        "llm_tier_prefetched_pages": stats["tiers"]["prefetched_pages"],
        "llm_tier_host_bytes_peak": stats["kv"]["host_tier_bytes"],
    }
    if note is not None:
        note(phase="llm_tier", **out)
    return out


def _comm_socket_pair():
    """Two socket fabrics + engines in one process on a free localhost
    port range (the oversubscribed two-rank DCN shape)."""
    from parsec_tpu.comm.multiproc import _free_port_base
    from parsec_tpu.comm.socket_fabric import SocketCommEngine, SocketFabric

    base = _free_port_base(2)
    f0 = SocketFabric(2, 0, base_port=base)
    f1 = SocketFabric(2, 1, base_port=base)
    return SocketCommEngine(f0), SocketCommEngine(f1)


def _comm_wait(engines, pred, sleep_s: float = 0.0002,
               timeout: float = 60.0) -> None:
    """Progress all engines until ``pred()``; the tiny sleep yields the
    GIL to the fabric receive threads (a hard spin would throttle them to
    the interpreter's switch interval and measure the GIL, not the wire)."""
    deadline = time.perf_counter() + timeout
    while not pred():
        for e in engines:
            e.progress()
        if sleep_s:
            time.sleep(sleep_s)
        if time.perf_counter() > deadline:
            raise TimeoutError("comm bench wait timed out")


def _comm_get_gbps(e0, e1, nbytes: int, reps: int) -> float:
    """GET throughput rank1→rank0 for one payload size (warm wire)."""
    import numpy as np
    arr = np.random.default_rng(7).integers(
        0, 255, size=max(nbytes, 1), dtype=np.uint8)
    best = None
    for _ in range(reps):
        h = e1.mem_register(arr, refcount=1, owned=True)
        done: list = []
        t0 = time.perf_counter()
        e0.get(h.wire(), done.append)
        _comm_wait((e0, e1), lambda: done)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
        assert done[0].nbytes == arr.nbytes
    return arr.nbytes / best / 1e9


def bench_comm(smoke: bool = False) -> dict:
    """The comm data-path numbers (CPU-provable, no accelerator):

    - ``comm_am_roundtrip_us_*``      — ping-pong latency of one small AM
      over the in-process fabric and over localhost sockets;
    - ``comm_activations_per_s``      — coalesced compact-form activation
      batches through the binary socket framing, decoded end to end;
    - ``comm_get_*_gbps``             — one-sided GET throughput per tier
      at 64KiB / 4MiB / 64MiB (socket payloads move as scatter-gather
      binary frames, ≥4MiB as windowed fragments recv_into'd straight
      into the destination buffer);
    - ``comm_get_socket_pickle_gbps`` + ``comm_get_speedup_vs_pickle`` —
      the same 4MiB socket GET over the legacy length-prefixed-pickle
      framing (``comm_wire_binary=False``), the measured baseline the
      zero-copy path is judged against (ISSUE 4 acceptance: ≥3×);
    - ``comm_overlap_efficiency``     — fraction of a saturating 64MiB
      fragmented GET's wall time the consumer spent inside compute units
      (progress interleaved between compute units, the T3-style overlap);
      ``comm_overlap_compute_frac`` is the companion calibrated-compute
      fraction (units x solo unit cost / wall — lower under GIL/core
      contention, the gap is contention overhead).
    """
    import numpy as np

    from parsec_tpu.comm.engine import AM_TAG_USER_BASE, InprocFabric
    from parsec_tpu.core.params import params
    from parsec_tpu.prof import spans as _spans

    out: dict = {}
    reps = 3 if smoke else 5
    # observe the whole stage with a PRIVATE span recorder (the
    # bench_tracing save/restore idiom): every GET below records a
    # comm.get span, so critpath can attribute the stage afterwards —
    # the cross-check ISSUE 16's acceptance pins against the measured
    # comm_overlap_efficiency
    prev_rec = _spans.recorder
    if prev_rec is not None:
        _spans.uninstall()
    rec = _spans.install()
    # smoke keeps the 4MiB point: it is the acceptance size the pickle
    # baseline is compared at, and the ratio there is wide enough
    # (~4x idle) to stay unambiguous under CI load
    sizes = ((65536, "64kib"), (4 << 20, "4mib")) if smoke else \
        ((65536, "64kib"), (4 << 20, "4mib"), (64 << 20, "64mib"))
    saved = {k: params.get(k) for k in
             ("comm_wire_binary", "comm_get_frag_bytes", "comm_get_window")}
    params.set("comm_wire_binary", True)
    params.set("comm_get_frag_bytes", 1 << 20 if smoke else 4 << 20)
    params.set("comm_get_window", 4)
    try:
        # -- AM roundtrip: inproc ------------------------------------------
        fab = InprocFabric(2)
        i0, i1 = fab.attach(0), fab.attach(1)
        n_pp = 200 if smoke else 1000
        count = [0]
        i1.tag_register(AM_TAG_USER_BASE, lambda eng, src, p:
                        i1.send_am(AM_TAG_USER_BASE, src, p))   # echo
        i0.tag_register(AM_TAG_USER_BASE, lambda eng, src, p:
                        count.__setitem__(0, count[0] + 1))     # pong
        t0 = time.perf_counter()
        for _ in range(n_pp):
            want = count[0] + 1
            i0.send_am(AM_TAG_USER_BASE, 1, {"seq": 1})
            _comm_wait((i0, i1), lambda w=want: count[0] >= w, sleep_s=0)
        out["comm_am_roundtrip_us_inproc"] = round(
            (time.perf_counter() - t0) / n_pp * 1e6, 2)

        # -- AM roundtrip + activation batches: localhost sockets ----------
        e0, e1 = _comm_socket_pair()
        pong = [0]
        e0.tag_register(AM_TAG_USER_BASE, lambda eng, src, p:
                        pong.__setitem__(0, pong[0] + 1))
        e1.tag_register(AM_TAG_USER_BASE, lambda eng, src, p:
                        e1.send_am(AM_TAG_USER_BASE, src, p))
        n_pp = 50 if smoke else 200
        # warm the duplex connections first
        e0.send_am(AM_TAG_USER_BASE, 1, 0)
        _comm_wait((e0, e1), lambda: pong[0] == 1)
        t0 = time.perf_counter()
        for _ in range(n_pp):
            want = pong[0] + 1
            e0.send_am(AM_TAG_USER_BASE, 1, 0)
            _comm_wait((e0, e1), lambda w=want: pong[0] >= w, sleep_s=0)
        out["comm_am_roundtrip_us_socket"] = round(
            (time.perf_counter() - t0) / n_pp * 1e6, 2)

        # coalesced activations: compact positional batches with small
        # inline payloads, decoded by the receiver's AM dispatch
        from parsec_tpu.comm.remote_dep import pack_activation
        inline = np.arange(64, dtype=np.float32)       # short-limit rider
        batch = ("B", [pack_activation(
            {"tp": 1, "tc": 0, "locals": {"m": i, "k": 3}, "outputs": [
                {"flow_index": 0, "writeback": False, "version": 1,
                 "inline": inline}],
             "ranks": [0, 1], "tree": "binomial", "priority": i,
             "seq": i, "pos": 1}) for i in range(32)])
        got = [0]
        e1.tag_register(AM_TAG_USER_BASE + 1, lambda eng, src, p:
                        got.__setitem__(0, got[0] + len(p[1])))
        nb = 20 if smoke else 100
        t0 = time.perf_counter()
        for _ in range(nb):
            e0.send_am(AM_TAG_USER_BASE + 1, 1, batch)
        _comm_wait((e0, e1), lambda: got[0] >= nb * 32)
        out["comm_activations_per_s"] = round(
            nb * 32 / (time.perf_counter() - t0), 1)

        # -- GET throughput ladder: socket tier ----------------------------
        for nbytes, label in sizes:
            out[f"comm_get_socket_{label}_gbps"] = round(
                _comm_get_gbps(e0, e1, nbytes, reps), 3)

        # -- overlap: compute retired during a saturating fragmented GET --
        big = np.random.default_rng(3).integers(
            0, 255, size=(8 << 20) if smoke else (64 << 20), dtype=np.uint8)
        a = np.random.default_rng(4).standard_normal((192, 192)) \
            .astype(np.float32)
        unit = lambda: float(np.dot(a, a).sum())        # noqa: E731
        t0 = time.perf_counter()
        n_cal = 20
        for _ in range(n_cal):
            unit()
        unit_s = (time.perf_counter() - t0) / n_cal
        h = e1.mem_register(big, refcount=1, owned=True)
        done: list = []
        units = [0]
        # the overlap GET runs TRACED: its comm.get span plus an exec
        # span per retired unit let critpath recompute the overlap
        # efficiency from the span plane alone (agreement gate below)
        tr = _spans.new_trace()
        _now_ns = time.perf_counter_ns
        busy_ns = 0
        t0 = time.perf_counter()
        e0.get(h.wire(), done.append, trace=tr.trace_id)
        while not done:
            u0 = _now_ns()
            unit()                      # compute retired mid-transfer
            u1 = _now_ns()
            rec.record("exec", tr.trace_id, u0, u1, None, "overlap_unit")
            busy_ns += u1 - u0
            units[0] += 1
            e0.progress()
            e1.progress()
            if time.perf_counter() - t0 > 60.0:
                raise TimeoutError("comm overlap GET did not complete")
        wall = time.perf_counter() - t0
        # wall fraction spent inside compute units — the same quantity
        # critpath recomputes from the span plane (|exec| within the GET
        # window / |GET|), measured independently by inline accumulation
        out["comm_overlap_efficiency"] = round(
            min(busy_ns / 1e9 / wall, 1.0), 3)
        # calibrated-compute fraction: units retired x solo unit cost;
        # trails the wall fraction by the GIL/core contention overhead
        out["comm_overlap_compute_frac"] = round(
            min(units[0] * unit_s / wall, 1.0), 3)
        out["comm_overlap_units"] = units[0]
        e0.fini()
        e1.fini()

        # -- the pickle baseline (legacy framing, monolithic replies) ------
        params.set("comm_wire_binary", False)
        params.set("comm_get_frag_bytes", 0)
        p0, p1 = _comm_socket_pair()
        out["comm_get_socket_pickle_gbps"] = round(
            _comm_get_gbps(p0, p1, 4 << 20, reps), 3)
        p0.fini()
        p1.fini()
        out["comm_get_speedup_vs_pickle"] = round(
            out["comm_get_socket_4mib_gbps"]
            / max(out["comm_get_socket_pickle_gbps"], 1e-9), 2)

        # -- GET throughput ladder: inproc tier (fragment pipeline only,
        # no sockets — the engine-protocol fixed cost) ---------------------
        params.set("comm_wire_binary", True)
        params.set("comm_get_frag_bytes", 1 << 20 if smoke else 4 << 20)
        fab2 = InprocFabric(2)
        j0, j1 = fab2.attach(0), fab2.attach(1)
        for nbytes, label in sizes:
            arr = np.random.default_rng(9).integers(
                0, 255, size=nbytes, dtype=np.uint8)
            best = None
            for _ in range(reps):
                h = j1.mem_register(arr, refcount=1, owned=True)
                done = []
                t0 = time.perf_counter()
                j0.get(h.wire(), done.append)
                _comm_wait((j0, j1), lambda: done, sleep_s=0)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            out[f"comm_get_inproc_{label}_gbps"] = round(
                nbytes / best / 1e9, 3)

        # -- critpath attribution over the stage's own spans ---------------
        # the traced overlap request's span-derived efficiency must agree
        # with the measured one (ISSUE 16 acceptance: within 15% rel);
        # the untraced ladder GETs contribute the nonzero overlap_lost
        # edge classes (no exec overlapped them by construction)
        try:
            from parsec_tpu.prof.critpath import attribute, normalize
            t0 = time.perf_counter()
            rep = attribute(normalize(list(rec.spans)))
            out["comm_critpath_replay_s"] = round(
                time.perf_counter() - t0, 4)
            req = rep["requests"].get(format(tr.trace_id, "x"))
            if req and req.get("overlap_efficiency") is not None:
                out["comm_critpath_overlap_efficiency"] = round(
                    req["overlap_efficiency"], 3)
            out["comm_critpath_top_lost"] = rep["top_overlap_lost"]
            out["comm_critpath_overlap_lost_ms"] = rep["overlap_lost_ms"]
        except Exception as e:        # noqa: BLE001 — evidence over abort
            out["comm_critpath_error"] = f"{type(e).__name__}: {e}"
    finally:
        for k, v in saved.items():
            params.set(k, v)
        _spans.uninstall()
        if prev_rec is not None:
            _spans.install(recorder_obj=prev_rec)
    return out


def bench_commcheck(smoke: bool = False) -> dict:
    """Static comm-pattern derivation cost (ISSUE 20): the analyzer's own
    wall time and tasks/s over a distributed broadcast pool, plus the
    rank-sweep prediction latency bench.py's ``comm_ranks`` cross-check
    pays per point — commcheck runs in the CI gate and before real
    submissions, so its replay must stay cheap relative to the graphs it
    clears."""
    from parsec_tpu.analysis.commcheck import (check_comm,
                                               predict_collective_traffic)
    from parsec_tpu.comm.collectives import bcast_taskpool
    from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic

    out: dict = {}
    n = 16 if smoke else 64
    reps = 2 if smoke else 3
    best = None
    for _ in range(reps):
        V = VectorTwoDimCyclic("V", lm=1024 * n, mb=1024, P=min(n, 8))
        tp = bcast_taskpool(V, n=n)
        t0 = time.perf_counter()
        cr = check_comm(tp, nb_ranks=min(n, 8))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    assert cr.pattern == "broadcast", cr
    out["commcheck_derive_s"] = round(best, 4)
    out["commcheck_tasks_per_s"] = round(cr.ntasks / max(best, 1e-9), 1)
    t0 = time.perf_counter()
    predict_collective_traffic(4, payload_bytes=1 << 16)
    out["commcheck_predict_s"] = round(time.perf_counter() - t0, 4)
    return out


def bench_tune(smoke: bool = False) -> dict:
    """Autotuner plumbing costs (ISSUE 18): the search-harness overhead
    per trial (no-op objective, so everything BUT the workload is on
    the clock), and the tuning-DB consult latency over a populated
    store through the cached generation-checked path — the
    Context-start / per-tenant-submit probe the perf_smoke gate pins
    at <= 50us."""
    import os
    import tempfile

    from parsec_tpu.core.params import KnobSpec, params
    from parsec_tpu.tune.db import TuneDB, cached_db
    from parsec_tpu.tune.search import search

    out: dict = {}
    trials = 16 if smoke else 48
    saved = params.get("perfdb")
    with tempfile.TemporaryDirectory(prefix="tune_mb_") as d:
        db = TuneDB(os.path.join(d, "tunedb.jsonl"))
        space = {"a": KnobSpec(name="a", lo=1, hi=1 << 20, scale="log2"),
                 "b": KnobSpec(name="b", values=("x", "y", "z"))}
        params.set("perfdb", False)     # pure harness cost, no ledger I/O
        # backend_signature's first call imports jax — a one-time
        # process cost, not a per-trial one: warm it off the clock
        from parsec_tpu.prof.perfdb import backend_signature
        backend_signature()
        try:
            t0 = time.perf_counter()
            res = search(lambda _k: 1.0, signature="microbench:noop",
                         space=space, budget=trials, restarts=4,
                         objective="cost_s", seed=3, db=db, persist=False)
            dt = time.perf_counter() - t0
        finally:
            params.set("perfdb", saved)
        out["tune_search_trials"] = res["evals"]
        out["tune_search_overhead_us_per_trial"] = round(
            dt / max(res["evals"], 1) * 1e6, 2)
        # the consult path: 200 signatures' bests out of one parsed
        # generation — the dict probe is what repeats per Context/tenant
        nsig = 200
        for i in range(nsig):
            db.note(f"wl:mb:{i}", {"a": i + 1}, float(i + 1),
                    objective="wall_s")
        reps = 500 if smoke else 2000
        cached_db(db.path).best("wl:mb:0", objective="wall_s")  # warm parse
        t0 = time.perf_counter()
        for i in range(reps):
            cached_db(db.path).best(f"wl:mb:{i % nsig}",
                                    objective="wall_s")
        dt = time.perf_counter() - t0
        out["tune_db_records"] = nsig
        out["tune_db_lookup_us"] = round(dt / reps * 1e6, 3)
    return out


def run_all(smoke: bool = False, include_lowering: bool = True,
            include_serve: bool = True, include_comm: bool = True,
            include_llm: bool = True) -> dict:
    """Every micro number in one dict (the bench `overhead` stage payload).
    ``include_lowering=False`` skips the only jax-touching section — the
    scheduling-path numbers then need no accelerator stack at all.
    ``include_serve=False``/``include_comm=False``/``include_llm=False``
    skip the serving/comm/LLM numbers (bench.py runs those in dedicated
    stages instead of twice)."""
    ntasks = 2000 if smoke else 10000
    reps = 3 if smoke else 5
    out: dict = {}
    out.update(bench_dispatch_us(ntasks, reps))
    out.update(bench_release_throughput(ntasks, max(reps - 2, 1)))
    out.update(bench_steal_us())
    out.update(bench_pins_disabled_ns(50000 if smoke else 200000))
    out.update(bench_tracing(smoke=smoke))
    if include_serve:
        out.update(bench_serve(nsub=16 if smoke else 64,
                               depth=4 if smoke else 8))
    if include_llm:
        out.update(bench_llm(smoke=smoke))
        try:
            out.update(bench_llm_prefix(smoke=smoke))
        except Exception as e:        # noqa: BLE001 — evidence over abort
            out["llm_prefix_error"] = f"{type(e).__name__}: {e}"
        try:
            out.update(bench_llm_tier(smoke=smoke))
        except Exception as e:        # noqa: BLE001 — evidence over abort
            out["llm_tier_error"] = f"{type(e).__name__}: {e}"
    if include_comm:
        out.update(bench_comm(smoke=smoke))
    if include_lowering:
        try:
            out.update(bench_lowering_cache())
        except Exception as e:            # noqa: BLE001 — evidence over abort
            out["lowering_cache_error"] = f"{type(e).__name__}: {e}"
        try:
            out.update(bench_lowering(smoke=smoke))
        except Exception as e:            # noqa: BLE001 — evidence over abort
            out["lowering_bench_error"] = f"{type(e).__name__}: {e}"
    try:
        out.update(bench_tune(smoke=smoke))
    except Exception as e:            # noqa: BLE001 — evidence over abort
        out["tune_bench_error"] = f"{type(e).__name__}: {e}"
    try:
        out.update(bench_commcheck(smoke=smoke))
    except Exception as e:            # noqa: BLE001 — evidence over abort
        out["commcheck_bench_error"] = f"{type(e).__name__}: {e}"
    # persistent perf ledger (prof/perfdb.py): every scalar lands under
    # the microbench.run_all workload so consecutive runs accrue EWMA
    # history; MCA perfdb=0 disables, and a ledger failure never costs
    # the run its numbers
    try:
        from parsec_tpu.core.params import params as _params
        from parsec_tpu.prof.perfdb import PerfDB
        if _params.get("perfdb"):
            PerfDB().note_result("microbench.run_all", out)
    except Exception:       # noqa: BLE001 — evidence over abort
        pass
    return out


if __name__ == "__main__":
    import os
    import sys
    smoke = os.environ.get("BENCH_SMOKE") == "1" or "--smoke" in sys.argv
    print(json.dumps(run_all(smoke=smoke)))
