#!/usr/bin/env python
"""Critical-path micro-benchmarks: the per-task fixed cost, measured on CPU.

The runtime's value proposition is micro-task scheduling overhead in the low
microseconds (PAPER.md; MPK and Design-in-Tiles both argue the per-task fixed
cost, not the kernels, is the lever for fine-grained tensor programs).  This
harness measures exactly that fixed cost — select→prepare→exec→complete→
release — with NOTHING accelerator-dependent, so the perf axis stays
measurable even when the TPU relay is dark:

- ``bench_dispatch_us``        — per-task latency on the EP CTL DAG through
  the compiled-DAG executor (the headline ``task_dispatch_us`` series) and
  through the dynamic Python scheduler (``dynamic_dispatch_us``);
- ``bench_release_throughput`` — dep-release tasks/s through the dynamic
  path (``release_deps`` → batched ``DependencyTracking.release_many``);
- ``bench_steal_us``           — lfq local-pop and steal latency against the
  sharded per-stream deques (sched/modules.py);
- ``bench_pins_disabled_ns``   — cost of one DISABLED instrumentation site
  (the per-event dispatch-slot fast path, prof/pins.py);
- ``bench_lowering_cache``     — first-vs-second compile seconds of an
  identical lowered taskpool (the persistent lowering cache,
  ptg/lowering.py);
- ``bench_serve``              — sustained submissions/s and p50/p99
  ticket latency through a RuntimeServer: concurrent client threads,
  two tenants, one hot context (the serving layer, parsec_tpu/serve/).

``python microbench.py`` prints one JSON object and finishes in seconds on a
CPU-only host.  ``run_all(smoke=True)`` shrinks every config for CI; the
``perf_smoke`` tier-1 marker (tests/test_perf_smoke.py) runs that with 10×
headroom thresholds so gross dispatch-path regressions fail fast without
timing flakes.  docs/PERF.md maps each number to the code it measures.
"""

from __future__ import annotations

import json
import statistics
import time


def _ep_pool(NT: int, DEPTH: int):
    """The reference's tests/runtime/scheduling/ep.jdf shape: NT independent
    lanes of DEPTH chained CTL-only tasks."""
    from parsec_tpu import ptg

    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l: None)
    return p


def _drain_ep_us(ntasks: int, reps: int, compiled: bool) -> tuple:
    """Median enqueue-to-drain wall time per task in µs, plus whether the
    compiled-DAG executor actually engaged (it silently declines when the
    native extension is unavailable — the reading must say which path it
    measured, or the dispatch trend mixes incomparable series)."""
    import parsec_tpu.runtime.dagrun  # noqa: F401 — runtime_dag_compile
    from parsec_tpu.core.params import params
    from parsec_tpu.runtime import Context

    NT = 50
    DEPTH = max(ntasks // NT, 2)
    builder = _ep_pool(NT, DEPTH)
    saved = params.get("runtime_dag_compile")
    params.set("runtime_dag_compile", compiled)
    engaged = False
    try:
        times = []
        for _ in range(reps):
            tp = builder.build()
            ctx = Context(nb_cores=0)
            t0 = time.perf_counter()
            ctx.add_taskpool(tp)
            engaged = getattr(tp, "_compiled_dag", None) is not None
            ctx.wait(timeout=600)
            times.append(time.perf_counter() - t0)
            ctx.fini()
        return statistics.median(times) / (NT * DEPTH) * 1e6, engaged
    finally:
        params.set("runtime_dag_compile", saved)


def bench_dispatch_us(ntasks: int = 10000, reps: int = 5) -> dict:
    us, engaged = _drain_ep_us(ntasks, reps, True)
    return {"dispatch_us": round(us, 3), "ntasks": ntasks,
            "dispatch_path": "compiled" if engaged else "dynamic"}


def bench_release_throughput(ntasks: int = 10000, reps: int = 3) -> dict:
    """Dynamic-path drain: every non-startup task arrives through
    ``release_deps`` → ``release_many``, so tasks/s here IS dep-release +
    schedule throughput (body is empty)."""
    us, _ = _drain_ep_us(ntasks, reps, False)
    return {"dynamic_dispatch_us": round(us, 3),
            "release_tasks_per_s": round(1e6 / us, 1),
            "ntasks": ntasks}


class _BenchTask:
    __slots__ = ("priority",)

    def __init__(self) -> None:
        self.priority = 0


def bench_steal_us(n: int = 200, reps: int = 50) -> dict:
    """lfq local-pop vs steal latency on the sharded per-stream deques,
    driven through the real scheduler module (no Context needed)."""
    import parsec_tpu.sched  # noqa: F401 — registers components + params
    from parsec_tpu.sched.modules import LFQModule
    from parsec_tpu.runtime.scheduling import ExecutionStream, VirtualProcess

    class _Ctx:
        virtual_processes: list = []

    ctx = _Ctx()
    vp = VirtualProcess(0, ctx)
    ctx.virtual_processes = [vp]
    es0 = ExecutionStream(0, vp, ctx)
    es1 = ExecutionStream(1, vp, ctx)
    vp.execution_streams = [es0, es1]
    mod = LFQModule()
    mod.install(ctx)
    mod.flow_init(es0)
    mod.flow_init(es1)
    n = min(n, mod._cap)      # beyond capacity spills to the system queue
    tasks = [_BenchTask() for _ in range(n)]

    def run(selector_es) -> float:
        best = None
        for _ in range(reps):
            mod.schedule(es0, list(tasks), 0)
            t0 = time.perf_counter()
            for _i in range(n):
                t, _d = mod.select(selector_es)
                assert t is not None
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best / n * 1e6

    return {"local_pop_us": round(run(es0), 4),
            "steal_us": round(run(es1), 4), "n": n}


def bench_pins_disabled_ns(iters: int = 200000) -> dict:
    """One DISABLED instrumentation site (index load + falsy branch) vs
    the always-on recorder-enabled site, through the same dispatch-slot
    pattern the scheduling loop compiles in (prof/pins.py).  The recorder
    is detached for the disabled half and restored after."""
    from parsec_tpu.prof import pins

    hooks = pins.hooks
    ev = int(pins.PinsEvent.EXEC_BEGIN)
    payload = object()

    def run() -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            h = hooks[ev]
            if h is not None:
                h(None, payload)
        return (time.perf_counter() - t0) / iters * 1e9

    saved = pins.recorder
    pins.recorder = None
    try:
        disabled = run() if hooks[ev] is None else None
    finally:
        pins.recorder = saved
    out = {"pins_disabled_ns": round(disabled, 2)
           if disabled is not None else None}
    if hooks[ev] is not None:       # always-on recorder (or chains) present
        out["pins_enabled_ns"] = round(run(), 2)
    return out


def bench_lowering_cache(n: int = 96, nb: int = 32) -> dict:
    """Two structurally identical lowerings of a tiled GEMM: the second
    must hit the process-wide lowering cache and skip trace+compile."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.ptg.lowering import lower_taskpool, lowering_cache

    def once() -> float:
        rng = np.random.default_rng(7)
        a = rng.standard_normal((n, n)).astype(np.float32)
        A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
        B = TiledMatrix.from_dense("B", a.copy(), nb, nb)
        C = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32), nb, nb)
        low = lower_taskpool(tiled_gemm_ptg(A, B, C))
        st = low.initial_stores()
        t0 = time.perf_counter()
        out = low.jitted()(st)
        float(np.asarray(out["C"]).reshape(-1)[0])
        return time.perf_counter() - t0

    h0, m0 = lowering_cache.hits, lowering_cache.misses
    cold = once()
    warm = once()
    return {"compile_cold_s": round(cold, 4),
            "compile_warm_s": round(warm, 4),
            "cache_hits": lowering_cache.hits - h0,
            "cache_misses": lowering_cache.misses - m0}


def bench_serve(nsub: int = 64, nthreads: int = 4, depth: int = 8,
                nb_cores: int = 2) -> dict:
    """Serving-path fixed cost: ``nthreads`` client threads submit
    ``nsub`` small CTL-chain pools (4 lanes x ``depth``, the EP shape)
    into one hot :class:`RuntimeServer` under two tenants, each blocking
    on its ticket — sustained submissions/s plus p50/p99 end-to-end
    ticket latency.  Pure scheduler path (no accelerator, no lowering):
    the serving layer's admission + fair-queue + live-enqueue overhead
    is what this measures."""
    import threading

    from parsec_tpu.serve import RuntimeServer

    lat: list[float] = []
    lock = threading.Lock()
    errors: list[BaseException] = []
    server = RuntimeServer(nb_cores=nb_cores)
    per = max(nsub // nthreads, 1)

    def client(tenant: str) -> None:
        try:
            for _i in range(per):
                tp = _ep_pool(4, depth).build()
                t0 = time.perf_counter()
                tk = server.submit(tp, tenant=tenant)
                tk.result(timeout=120)
                dt = time.perf_counter() - t0
                with lock:
                    lat.append(dt)
        except BaseException as e:      # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(f"tenant{i % 2}",),
                                name=f"serve-client{i}")
               for i in range(nthreads)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    server.drain(timeout=60)
    if errors:
        raise errors[0]
    lat.sort()
    n = len(lat)
    return {
        "serve_submits_per_s": round(n / wall, 1),
        "serve_p50_ms": round(lat[n // 2] * 1e3, 3),
        "serve_p99_ms": round(lat[min(int(n * 0.99), n - 1)] * 1e3, 3),
        "serve_nsub": n,
        "serve_threads": nthreads,
        "serve_tasks_per_sub": 4 * depth,
    }


def run_all(smoke: bool = False, include_lowering: bool = True,
            include_serve: bool = True) -> dict:
    """Every micro number in one dict (the bench `overhead` stage payload).
    ``include_lowering=False`` skips the only jax-touching section — the
    scheduling-path numbers then need no accelerator stack at all.
    ``include_serve=False`` skips the serving numbers (bench.py runs them
    in its dedicated ``serve`` stage instead of twice)."""
    ntasks = 2000 if smoke else 10000
    reps = 3 if smoke else 5
    out: dict = {}
    out.update(bench_dispatch_us(ntasks, reps))
    out.update(bench_release_throughput(ntasks, max(reps - 2, 1)))
    out.update(bench_steal_us())
    out.update(bench_pins_disabled_ns(50000 if smoke else 200000))
    if include_serve:
        out.update(bench_serve(nsub=16 if smoke else 64,
                               depth=4 if smoke else 8))
    if include_lowering:
        try:
            out.update(bench_lowering_cache())
        except Exception as e:            # noqa: BLE001 — evidence over abort
            out["lowering_cache_error"] = f"{type(e).__name__}: {e}"
    return out


if __name__ == "__main__":
    import os
    import sys
    smoke = os.environ.get("BENCH_SMOKE") == "1" or "--smoke" in sys.argv
    print(json.dumps(run_all(smoke=smoke)))
