#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): PTG tiled-GEMM GFLOPS/chip at N=16384, nb=512.
The taskpool executes through the framework's compiled path — the PTG GEMM
dataflow lowered to a single XLA program on the chip (the dynamic-runtime
path covers irregular/distributed graphs; on one chip the lowered program is
the framework's GEMM incarnation).  ``vs_baseline`` is measured GFLOPS over
the north-star target (70% of the chip's peak bf16 GFLOPS, BASELINE.md), so
>= 1.0 beats the target.

``extra`` carries the secondary metric: task-dispatch per-task latency of the
dynamic runtime on the EP CTL-only DAG (the reference's
tests/runtime/scheduling/ep.jdf shape).
"""

from __future__ import annotations

import json
import statistics
import time


def bench_gemm_gflops(n: int = 16384, reps: int = 16) -> dict:
    """Steady-state GEMM throughput: a dependent chain of ``reps`` C += A·B
    updates inside one program (repeated taskpool execution), synced by a
    host scalar read (block_until_ready is unreliable through the TPU
    tunnel; a read cannot complete before the compute does)."""
    import functools

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    from parsec_tpu.device.tpu import _flop_rating
    peak_bf16, _ = _flop_rating(kind.lower())

    a = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype=jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), dtype=jnp.bfloat16)
    c0 = jnp.zeros((n, n), dtype=jnp.float32)

    @functools.partial(jax.jit, static_argnames=("reps",))
    def chain(a, b, c, reps):
        # the (zero) feedback of c into a makes each dot loop-carried, so
        # XLA cannot hoist the matmul out of the scan as loop-invariant
        def step(c, _):
            a2 = a + (c[0:1, 0:1] * 0).astype(a.dtype)
            return c + jnp.dot(a2, b, preferred_element_type=jnp.float32), None
        c, _ = jax.lax.scan(step, c, None, length=reps)
        return c

    _ = float(chain(a, b, c0, reps)[0, 0])  # compile + warm
    times = []
    for _i in range(3):
        t0 = time.perf_counter()
        out = chain(a, b, c0, reps)
        _sink = float(out[0, 0])
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    gflops = 2.0 * n * n * n * reps / t / 1e9
    return {
        "gflops": gflops,
        "peak_gflops": peak_bf16,
        "pct_peak": 100.0 * gflops / peak_bf16,
        "device_kind": kind,
        "n": n,
        "reps": reps,
        "seconds": t,
    }


def bench_dispatch_us(ntasks: int = 2000) -> float:
    """Per-task dispatch latency of the dynamic runtime (EP DAG shape)."""
    from parsec_tpu import ptg
    from parsec_tpu.runtime import Context

    NT, DEPTH = 50, ntasks // 50
    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l: None)
    tp = p.build()
    ctx = Context(nb_cores=0)
    t0 = time.perf_counter()
    ctx.add_taskpool(tp)
    ctx.wait(timeout=600)
    dt = time.perf_counter() - t0
    ctx.fini()
    return dt / (NT * DEPTH) * 1e6


def main() -> None:
    import os
    n = int(os.environ.get("BENCH_N", "16384"))
    gemm = bench_gemm_gflops(n=n)
    dispatch_us = bench_dispatch_us()
    target = 0.70 * gemm["peak_gflops"]
    print(json.dumps({
        "metric": "ptg_tiled_gemm_gflops_per_chip",
        "value": round(gemm["gflops"], 1),
        "unit": "GFLOPS",
        "vs_baseline": round(gemm["gflops"] / target, 4),
        "extra": {
            "pct_peak": round(gemm["pct_peak"], 2),
            "device_kind": gemm["device_kind"],
            "n": gemm["n"],
            "nb": 512,
            "gemm_seconds": round(gemm["seconds"], 4),
            "task_dispatch_us": round(dispatch_us, 2),
        },
    }))


if __name__ == "__main__":
    main()
