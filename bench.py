#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): PTG tiled-GEMM GFLOPS/chip at N=16384, nb=512.
The taskpool executes through the framework's compiled path — the PTG GEMM
dataflow lowered to a single XLA program on the chip (the dynamic-runtime
path covers irregular/distributed graphs; on one chip the lowered program is
the framework's GEMM incarnation).  ``vs_baseline`` is measured GFLOPS over
the north-star target (70% of the chip's peak bf16 GFLOPS, BASELINE.md), so
>= 1.0 beats the target.

``extra`` carries the secondary metric: task-dispatch per-task latency of the
dynamic runtime on the EP CTL-only DAG (the reference's
tests/runtime/scheduling/ep.jdf shape).
"""

from __future__ import annotations

import json
import statistics
import time


def bench_gemm_gflops(n: int = 16384, nb: int = 512, reps: int = 48) -> dict:
    """Steady-state throughput of the PTG tiled-GEMM taskpool, executed
    through the framework's compiled incarnation: ``tiled_gemm_ptg`` builds
    the GEMM(m,n,k) task graph, ``lower_taskpool`` collapses its k-chain to
    one XLA contraction over the tile stores, and a dependent chain of
    ``reps`` taskpool executions runs inside one program.  Synced by a host
    scalar read (block_until_ready is unreliable through the TPU tunnel; a
    read cannot complete before the compute does)."""
    import functools

    import jax
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.device.tpu import _flop_rating
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.ptg.lowering import lower_taskpool

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    peak_bf16, _ = _flop_rating(kind.lower())

    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)

    def mk(name, dtype):
        def init(m, n_, shape):
            rng = np.random.default_rng((hash((name, m, n_)) & 0x7FFFFFFF))
            return rng.standard_normal(shape, dtype=np.float32).astype(dtype)
        return TiledMatrix(name, n, n, nb, nb, dtype=dtype, init_fn=init)

    A, B = mk("A", bf16), mk("B", bf16)
    C = TiledMatrix("C", n, n, nb, nb, dtype=np.float32,
                    init_fn=lambda m, n_, s: np.zeros(s, np.float32))

    low = lower_taskpool(tiled_gemm_ptg(A, B, C))
    assert low.mode == "chain-collapse", low.mode
    stores = {k: jax.device_put(v, dev) for k, v in
              low.initial_stores().items()}
    step = low.step_fn

    @functools.partial(jax.jit, static_argnames=("reps",))
    def chain(st, reps):
        # the (zero) feedback of C into A makes each taskpool execution
        # loop-carried, so XLA cannot hoist the contraction as invariant
        def body(st, _):
            # tiny in-place (DUS) perturbation instead of a full A+eps copy
            eps = (st["C"].reshape(-1)[0] * 0).astype(st["A"].dtype)
            st = dict(st)
            st["A"] = st["A"].at[0, 0].add(eps)
            return step(st), None
        st, _ = jax.lax.scan(body, st, None, length=reps)
        return st

    _note_partial(phase="compile", lowering_mode=low.mode)
    tc = time.perf_counter()
    _ = float(chain(stores, reps)["C"].reshape(-1)[0])  # compile + warm
    compile_s = time.perf_counter() - tc
    _note_partial(phase="measure", compile_s=round(compile_s, 1))
    times = []
    for _i in range(3):
        t0 = time.perf_counter()
        out = chain(stores, reps)
        _sink = float(out["C"].reshape(-1)[0])
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    gflops = 2.0 * n * n * n * reps / t / 1e9
    return {
        "gflops": gflops,
        "peak_gflops": peak_bf16,
        "pct_peak": 100.0 * gflops / peak_bf16,
        "device_kind": kind,
        "n": n,
        "nb": nb,
        "reps": reps,
        "seconds": t,
        "compile_s": round(compile_s, 1),
        "lowering": low.mode,
    }


def bench_raw_dot_gflops(n: int = 16384, reps: int = 48) -> dict:
    """Honesty cross-check for the headline (VERDICT r3 weak #6): the same
    flops as ONE bare ``jnp.dot`` chain, no framework anywhere — pct_peak
    rests on the hand-entered flop table, so record what the raw compiler
    achieves on this chip under the identical loop-carry discipline."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n), dtype=np.float32),
                    dtype=jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((n, n), dtype=np.float32),
                    dtype=jnp.bfloat16)

    @functools.partial(jax.jit, static_argnames=("reps",))
    def chain(a, b, reps):
        def body(c, _):
            # feed (zero of) c back into a so the dot is loop-carried
            eps = (c.reshape(-1)[0] * 0).astype(a.dtype)
            return jnp.dot(a.at[0, 0].add(eps), b,
                           preferred_element_type=jnp.float32), None
        c0 = jnp.zeros((n, n), jnp.float32)
        c, _ = jax.lax.scan(body, c0, None, length=reps)
        return c

    _ = float(chain(a, b, reps).reshape(-1)[0])   # compile + warm
    times = []
    for _i in range(3):
        t0 = time.perf_counter()
        out = chain(a, b, reps)
        _sink = float(out.reshape(-1)[0])
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    return {"gflops": 2.0 * n * n * n * reps / t / 1e9, "n": n,
            "reps": reps, "seconds": t}


def _scalar_sync(copy) -> float:
    """Force completion by reading ONE element of a (possibly device)
    copy — ``jax.block_until_ready`` is a NO-OP through the axon relay,
    so a timed region closed by ``dev.sync()`` alone would measure
    enqueue, not completion.  One element = one RTT, not a tile D2H."""
    import numpy as np
    v = copy.value
    ndim = getattr(v, "ndim", 0)
    return float(np.asarray(v[(0,) * ndim] if ndim else v))


def bench_dynamic_gemm_gflops(n: int = 8192, nb: int = 1024) -> dict:
    """The dynamic-runtime path on the real chip: PTG GEMM(m,n,k) executed
    task by task through the TPU device module (stage-in, LRU cache, vmapped
    same-class batching) — no lowering.  The number the reference's
    ``dtd_test_simple_gemm`` prints (VERDICT r2 weak #1: the dynamic path
    had never produced a TPU figure)."""
    import jax
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.device.tpu import init_tpu_devices
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.runtime import Context

    devs = init_tpu_devices()
    if not devs:
        return {"gflops": 0.0, "note": "no accelerator visible"}
    dev = devs[0]

    def init(name):
        def fn(m, n_, shape):
            rng = np.random.default_rng(hash((name, m, n_)) & 0x7FFFFFFF)
            return rng.standard_normal(shape, dtype=np.float32)
        return fn

    A = TiledMatrix("A", n, n, nb, nb, init_fn=init("A"))
    B = TiledMatrix("B", n, n, nb, nb, init_fn=init("B"))
    C = TiledMatrix("C", n, n, nb, nb,
                    init_fn=lambda m, n_, s: np.zeros(s, np.float32))
    # materialize every tile BEFORE the clock starts: host RNG generation
    # is harness setup, not framework work (the reference's harnesses also
    # exclude matrix generation from the timed region)
    for M in (A, B, C):
        for i in range(M.mt):
            for j in range(M.nt):
                M.data_of(i, j)
    tp = tiled_gemm_ptg(A, B, C, devices="tpu")

    # relay RTT: one tiny dispatch, synced by a host value read — the
    # per-call latency floor every enqueue through the tunnel pays
    import jax.numpy as jnp
    tiny = jax.jit(lambda x: x + 1)
    _ = float(tiny(jnp.float32(0)))          # compile
    rtts = []
    for _i in range(5):
        r0 = time.perf_counter()
        _ = float(tiny(jnp.float32(_i)))
        rtts.append(time.perf_counter() - r0)
    rtt = statistics.median(rtts)

    calls0, ts0 = dev.xla_calls, dev.t_stage_in
    td0, tc0, tdr0 = dev.t_dispatch, dev.t_complete, dev.t_drain
    bin0 = dev.bytes_in
    tm0 = dev.t_manager
    ctx = Context(nb_cores=0)
    t0 = time.perf_counter()
    deadline = t0 + 120
    try:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
        t_drained = time.perf_counter() - t0
        dev.sync()
        # completion fence the relay can't fake: one-element D2H read
        _scalar_sync(C.data_of(C.mt - 1, C.nt - 1).newest_copy())
        t = time.perf_counter() - t0
    finally:
        # bounded drain reusing this stage's (possibly expired) deadline:
        # a timed-out wait must not leak the Context + tile set into every
        # later stage, and fini on a wedged relay must not hang the
        # cleanup forever either (it stall-dumps and aborts instead)
        ctx.fini(timeout=max(0.0, deadline - time.perf_counter()))
    calls = dev.xla_calls - calls0
    h2d = dev.bytes_in - bin0
    stage_s = dev.t_stage_in - ts0
    breakdown = {
        # H2D volume + achieved rate: through the PJRT relay the transfer
        # bandwidth, not the framework, bounds the stage-in phase
        "h2d_mb": round(h2d / 1e6, 1),
        "h2d_MBps": round(h2d / 1e6 / stage_s, 1) if stage_s > 0 else 0.0,
        # phase walls: what the manager thread actually spent
        "stage_in_s": round(dev.t_stage_in - ts0, 3),
        "dispatch_s": round(dev.t_dispatch - td0, 3),
        "complete_s": round(dev.t_complete - tc0, 3),
        "drain_s": round(dev.t_drain - tdr0, 3),
        "manager_s": round(dev.t_manager - tm0, 3),
        "final_sync_s": round(t - t_drained, 3),
        "xla_calls": calls,
        "relay_rtt_ms": round(rtt * 1e3, 2),
        # the relay-latency floor: a dependent-call chain cannot finish
        # faster than calls * rtt; compare with the measured wall to
        # attribute relay vs framework cost
        "relay_floor_s": round(calls * rtt, 3),
        # MXU floor: the same flops at the chip's fp32 rating (the
        # dynamic path computes in f32, not the bf16 headline peak)
        "onchip_floor_s": round(
            2.0 * n * n * n / (dev.gflops_fp32 * 1e9), 3),
    }
    return {
        "gflops": 2.0 * n * n * n / t / 1e9,
        "n": n, "nb": nb, "seconds": t,
        "tasks": dev.executed_tasks,
        "batched_dispatches": dev.batched_dispatches,
        "breakdown": breakdown,
    }




def bench_dynamic_cholesky_gflops(n: int = 8192, nb: int = 1024) -> dict:
    """Dynamic-path tiled Cholesky on the chip (BASELINE staged config #5):
    four task classes, triangular space, range arrows."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
    from parsec_tpu.device.tpu import init_tpu_devices
    from parsec_tpu.models.cholesky import (cholesky_flops, make_spd,
                                            tiled_cholesky_ptg)
    from parsec_tpu.runtime import Context

    devs = init_tpu_devices()
    if not devs:
        return {"gflops": 0.0, "note": "no accelerator visible"}
    dev = devs[0]
    a = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb)
    tp = tiled_cholesky_ptg(A, devices="tpu")
    ctx = Context(nb_cores=0)
    t0 = time.perf_counter()
    deadline = t0 + 120
    try:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
        dev.sync()
        _scalar_sync(A.data_of(A.mt - 1, A.mt - 1).newest_copy())
        t = time.perf_counter() - t0
    finally:
        ctx.fini(timeout=max(0.0, deadline - time.perf_counter()))
    # correctness spot check: || L[0,0] - chol(A)[0,0] tile || small
    got = np.asarray(A.data_of(0, 0).newest_copy().value)
    expect = np.linalg.cholesky(a[:nb, :nb].astype(np.float64))
    err = float(np.max(np.abs(np.tril(got) - expect)))
    return {
        "gflops": cholesky_flops(n) / t / 1e9,
        "n": n, "nb": nb, "seconds": t, "tile00_abs_err": err,
    }


def bench_tuned_cholesky(n: int = 512, nb_bad: int = 32,
                         budget: int = 8) -> dict:
    """The closed-loop autotuner stage (ISSUE 18): a deliberately
    mis-knobbed small dynamic Cholesky — tile ``nb`` far too small, so
    per-task dispatch overhead dominates — is handed to ``tune.search``
    with the tile size as a workload-level knob.  The search must
    recover a sane configuration within its trial budget; the winner
    persists to ``tunedb.jsonl`` under the workload's structural
    signature.  Headline: ``tune_speedup`` = seeded-bad wall / tuned
    wall (perf_smoke gates >= 1.2).  Every trial partial-flushes via
    ``_note_partial`` so a deadline death keeps the search trajectory."""
    import numpy as np

    from parsec_tpu.core.params import KnobSpec
    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
    from parsec_tpu.device.tpu import init_tpu_devices
    from parsec_tpu.models.cholesky import make_spd, tiled_cholesky_ptg
    from parsec_tpu.runtime import Context
    from parsec_tpu.tune import workload_signature
    from parsec_tpu.tune.search import search

    if not init_tpu_devices():
        return {"tune_speedup": 0.0, "note": "no accelerator visible"}
    a = make_spd(n)

    def one(nb: int) -> float:
        A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb)
        tp = tiled_cholesky_ptg(A, devices="tpu")
        ctx = Context(nb_cores=0)
        t0 = time.perf_counter()
        try:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=60)
            t = time.perf_counter() - t0
        finally:
            ctx.fini(timeout=30)
        return t

    warmed: set = set()

    def run_once(knobs: dict) -> float:
        # each tile shape compiles its kernels on first touch; the
        # tuner scores STEADY STATE (the config a server would run at),
        # so a trial's first visit to a shape warms it off the clock
        nb = int(knobs.get("nb", nb_bad))
        if nb not in warmed:
            warmed.add(nb)
            one(nb)
        return one(nb)

    sig = workload_signature(
        tiled_cholesky_ptg(
            SymTwoDimBlockCyclic.from_dense("A", a, nb_bad, nb_bad),
            devices="tpu"),
        size_hint=n)
    # the seeded-bad configuration IS the baseline the loop must beat
    baseline_s = run_once({"nb": nb_bad})
    _note_partial(tuned_baseline_s=round(baseline_s, 4))
    space = {"nb": KnobSpec(name="nb", lo=32, hi=max(64, n // 2),
                            scale="log2")}

    def flush(trial: int, score: float, knobs: dict) -> None:
        _note_partial(tune_trials=trial,
                      **{f"tune_trial{trial}_s": round(score, 4),
                         f"tune_trial{trial}_nb": int(knobs.get(
                             "nb", 0))})

    out = search(run_once, signature=sig, space=space, budget=budget,
                 restarts=1, objective="wall_s", seed=0,
                 start={"nb": nb_bad}, note=flush)
    best = out["best"] or {"nb": nb_bad}
    tuned_s = float(out["best_score"] or baseline_s)
    _note_partial(tune_speedup=round(baseline_s / max(tuned_s, 1e-9), 3))
    # correctness is not negotiable for a tuner: the winner's factor is
    # still a Cholesky factor
    A = SymTwoDimBlockCyclic.from_dense("A", a, int(best["nb"]),
                                        int(best["nb"]))
    tp = tiled_cholesky_ptg(A, devices="tpu")
    ctx = Context(nb_cores=0)
    try:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    finally:
        ctx.fini(timeout=30)
    got = np.asarray(A.data_of(0, 0).newest_copy().value)
    k = int(best["nb"])
    expect = np.linalg.cholesky(a[:k, :k].astype(np.float64))
    err = float(np.max(np.abs(np.tril(got) - expect)))
    return {
        "tune_speedup": round(baseline_s / max(tuned_s, 1e-9), 3),
        "baseline_s": round(baseline_s, 4), "tuned_s": round(tuned_s, 4),
        "nb_bad": nb_bad, "best_nb": int(best["nb"]), "n": n,
        "evals": out["evals"], "pruned": out["pruned"],
        "signature": sig, "db_path": out.get("db_path", ""),
        "tile00_abs_err": err,
    }


def _stage_budgets() -> dict[str, float]:
    """Per-stage wall-clock budgets from the ``bench_stage_budget_s``
    MCA param (env: ``PARSEC_MCA_bench_stage_budget_s``).  Spec grammar:
    a bare float rebudgets EVERY stage; a comma list of ``name=seconds``
    pairs rebudgets named stages (``*=seconds`` sets the default).  The
    hard-coded defaults in :func:`main` are the fallback — this is the
    knob that lets a TPU run give ``lowered_cholesky`` the compile room
    BENCH_r04/r05 lacked without recutting the harness."""
    import os
    spec = ""
    try:
        from parsec_tpu.core.params import params as _p
        _p.register(
            "bench_stage_budget_s", "",
            "per-stage bench budget override: '<seconds>' for all stages "
            "or 'name=sec,name2=sec' ('*' = default); empty keeps the "
            "harness defaults")
        spec = str(_p.get("bench_stage_budget_s") or "")
    except Exception:                      # noqa: BLE001 — env fallback
        spec = os.environ.get("PARSEC_MCA_bench_stage_budget_s", "")
    out: dict[str, float] = {}
    spec = spec.strip()
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, val = part.partition("=")
            try:
                out[name.strip()] = float(val)
            except ValueError:
                pass
        else:
            try:
                out["*"] = float(part)
            except ValueError:
                pass
    return out


_stage_partials: dict[str, dict] = {}


def _note_partial(**kw) -> None:
    """Flush partial metrics from INSIDE a running stage (keyed by the
    ``bench-<stage>`` worker-thread name).  When the stage later dies on
    its deadline — historically in XLA compile (BENCH_r04/r05, rc 124) —
    the degrade record carries whatever landed here instead of losing
    the stage entirely, and ``phase == "compile"`` at timeout turns the
    record into a ``{"status": "compile_timeout"}`` entry.

    Every flush also snapshots the live SLO histogram planes
    (serialized bucket arrays, ``prof/histogram.serialized_planes``): a
    deadline death mid-serve/llm stage keeps the latency DISTRIBUTION
    collected so far — reconstructable with ``LogHistogram.from_dict``
    — not just the counters."""
    import threading
    name = threading.current_thread().name
    if name.startswith("bench-"):
        d = _stage_partials.setdefault(name[len("bench-"):], {})
        d.update(kw)
        try:
            from parsec_tpu.prof.histogram import serialized_planes
            s = serialized_planes()
            if s:
                d["slo_hist"] = s
        except Exception:       # noqa: BLE001 — partials must never raise
            pass
        try:
            # the XLA-dispatch ledger rides every flush too: an rc-124
            # death keeps the calls-per-DAG axis (ISSUE 16 satellite —
            # the r06 campaign reads it off the partial)
            from parsec_tpu.device.device import xla_calls_total
            d["xla_calls_total"] = xla_calls_total()
        except Exception:       # noqa: BLE001 — partials must never raise
            pass


_perfdb_state: dict = {"regressions": []}


def _perfdb_note(name: str, result) -> None:
    """Append this stage's scalars to the persistent perf ledger and
    verdict each against its EWMA history (prof/perfdb.py): the
    regression sentinel's bench hook.  Prints one per-stage verdict
    line to stderr; regressions accumulate into ``_perfdb_state`` and
    ride the emit as ``perfdb_regressions``.  Never raises, and MCA
    ``perfdb=0`` disables it entirely."""
    import sys
    try:
        from parsec_tpu.core.params import params
        from parsec_tpu.prof.perfdb import PerfDB
        if not params.get("perfdb"):
            return
        if isinstance(result, (int, float)) and not isinstance(result, bool):
            result = {"value": float(result)}
        if not isinstance(result, dict):
            return
        notes = PerfDB().note_result(f"bench.{name}", result)
        if not notes:
            return
        reg = [n for n in notes if n["verdict"] == "regressed"]
        imp = [n for n in notes if n["verdict"] == "improved"]
        for n2 in reg:
            _perfdb_state["regressions"].append(
                {"stage": name, "metric": n2["metric"],
                 "value": n2["value"], "z": n2.get("z"),
                 "ewma": n2.get("ewma")})
        if reg:
            verdict = "REGRESSED " + ",".join(
                f"{n['metric']} (z={n['z']})" for n in reg)
        elif imp:
            verdict = "improved " + ",".join(n["metric"] for n in imp)
        elif all(n["verdict"] == "warming" for n in notes):
            verdict = "warming"
        else:
            verdict = "ok"
        print(f"[perfdb] {name}: {len(notes)} metric(s) -> {verdict}",
              file=sys.stderr, flush=True)
    except Exception:       # noqa: BLE001 — the ledger must never cost a run
        pass


def _time_lowered(low, sync_store: str, reps: int = 3):
    """Shared lowered-bench harness: device stores, jit, warm, then the
    median of ``reps`` runs each synced by a device-side SCALAR read —
    ``np.asarray(out)`` would drag the whole store through the TPU tunnel
    and time the transfer (the round-3 bench bug this guards against).
    Returns ``(median_seconds, compile_seconds, last_out)`` — compile is
    attributed separately (VERDICT r4 weak #2: at O(wavefronts x classes)
    ops the XLA compile may itself be the wall; without the split the run
    number is uninterpretable).  ``low.jitted()`` consults the process-wide
    lowering cache, so a re-invoked identical stage reports a near-zero
    ``*_compile_s`` instead of re-paying the trace+compile."""
    import jax
    st = {k: jax.device_put(v) for k, v in low.initial_stores().items()}
    jf = low.jitted()
    # pre-flight BEFORE the first (compiling) call: a deadline death
    # mid-XLA-compile then names the program and its budget context
    # (whole-pool lowerings are one region; the region stage reports
    # its own per-region notes through plan.compile(note=...))
    from parsec_tpu.core.params import params as _mca
    _note_partial(phase="compile", lowering_mode=low.mode, region_count=1,
                  budget_s=float(_mca.get("lowering_compile_budget_s",
                                          0.0) or 0.0))
    tc = time.perf_counter()
    out = jf(st)
    _ = float(out[sync_store].reshape(-1)[0])    # compile + warm
    compile_s = time.perf_counter() - tc
    _note_partial(phase="measure", compile_s=round(compile_s, 1))
    times = []
    for _i in range(reps):
        t0 = time.perf_counter()
        out = jf(st)
        _ = float(out[sync_store].reshape(-1)[0])
        times.append(time.perf_counter() - t0)
    return statistics.median(times), compile_s, out


def bench_lowered_cholesky_gflops(n: int = 16384, nb: int = 512) -> dict:
    """The compiled incarnation of the Cholesky PTG: four task classes,
    triangular space, batched per topological wavefront by the lowering —
    every panel's trailing update lands on the MXU as ONE batched tile
    matmul.  For scale: XLA's own jnp.linalg.cholesky runs n=8192 at ~12
    GFLOPS on a v5e; the wavefront program measures in the TFLOPS.  Synced
    by a device-side scalar read (np.asarray(out) would drag the whole
    factored matrix through the TPU tunnel and time the transfer, which is
    exactly the round-3 bench bug this replaces)."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
    from parsec_tpu.models.cholesky import (cholesky_flops, make_spd_fast,
                                            tiled_cholesky_ptg)
    from parsec_tpu.ptg.lowering import lower_taskpool

    a = make_spd_fast(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb)
    low = lower_taskpool(tiled_cholesky_ptg(A))
    t, compile_s, out = _time_lowered(low, "A")
    # spot-check the first tile against the dense factorization
    got = np.asarray(out["A"][0])
    expect = np.linalg.cholesky(a[:nb, :nb].astype(np.float64))
    err = float(np.max(np.abs(np.tril(got) - expect)))
    return {"gflops": cholesky_flops(n) / t / 1e9, "n": n, "nb": nb,
            "seconds": t, "compile_s": round(compile_s, 1),
            "mode": low.mode, "tile00_abs_err": err}


def bench_region_cholesky_gflops(n: int = 8192, nb: int = 512,
                                 budget_s: float | None = None) -> dict:
    """The megakernel-region incarnation of the Cholesky PTG (ISSUE 8):
    graphcheck-verified regions, one jitted program each, the runtime
    scheduling regions at boundaries — compiled under an explicit budget
    so this stage can never die rc-124 mid-XLA-compile (the BENCH_r04/r05
    shape): regions the budget cannot afford run the eager op-by-op path
    instead, and the stats say which.  Every region's compile progress
    pre-flights through ``_note_partial``, so a deadline death names the
    region that was compiling."""
    import numpy as np

    from parsec_tpu.core.params import params
    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
    from parsec_tpu.models.cholesky import (cholesky_flops, make_spd_fast,
                                            tiled_cholesky_ptg)
    from parsec_tpu.ptg.lowering import lower_regions

    a = make_spd_fast(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb)
    plan = lower_regions(tiled_cholesky_ptg(A))
    if budget_s is None:
        b = float(params.get("lowering_compile_budget_s") or 0.0)
        # unbudgeted MCA default -> still bound the stage's compile: the
        # harness gives this stage ~150s, leave the rest for execution
        budget_s = b if b > 0 else 90.0
    _note_partial(phase="compile", region_count=len(plan.regions),
                  budget_s=round(budget_s, 1))
    plan.compile(budget_s=budget_s,
                 note=lambda **kw: _note_partial(phase="compile", **kw))
    st = plan.stats()
    _note_partial(phase="measure", compile_s=st["compile_s"],
                  regions_eager=st["regions_eager"])
    # timed region: region-grained scheduling + execution only — table
    # materialization is harness setup (the lowered stages' discipline),
    # writeback rides the pool's completion listener inside the run
    from parsec_tpu.runtime import Context
    table = plan.materialize_table()
    ctx = Context(nb_cores=0)
    t0 = time.perf_counter()
    try:
        ctx.add_taskpool(plan.taskpool(table))
        ctx.wait(timeout=120)
        t = time.perf_counter() - t0
    finally:
        ctx.fini(timeout=30)
    plan.finalize(table)        # no-op when the listener already ran
    st = plan.stats()
    got = np.asarray(A.data_of(0, 0).newest_copy().value)
    expect = np.linalg.cholesky(a[:nb, :nb].astype(np.float64))
    err = float(np.max(np.abs(np.tril(got) - expect)))
    return {"gflops": cholesky_flops(n) / t / 1e9, "n": n, "nb": nb,
            "seconds": t, "mode": "region", "regions": st["regions"],
            "regions_compiled": st["regions_compiled"],
            "regions_eager": st["regions_eager"],
            "xla_calls": st["xla_calls"],
            "trace_s": st["trace_s"], "compile_s": st["compile_s"],
            "budget_s": round(budget_s, 1), "tile00_abs_err": err}


def bench_lowered_lu_gflops(n: int = 8192, nb: int = 512) -> dict:
    """The compiled incarnation of the LU-nopiv PTG — the third dense
    factorization through the wavefront pass (GETRF/TRSM_L/TRSM_U/GEMM,
    square space): every panel's trailing update is one batched tile
    matmul.  Scalar-read synced like the Cholesky stage."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.lu import lu_flops, make_dd, tiled_lu_ptg
    from parsec_tpu.ptg.lowering import lower_taskpool

    a = make_dd(n, seed=1).astype(np.float32)
    A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
    low = lower_taskpool(tiled_lu_ptg(A))
    t, compile_s, out = _time_lowered(low, "A")
    # spot-check tile (0,0): L\U packed must match the dense recursion
    from parsec_tpu.models.lu import _getrf_nopiv_np
    got = np.asarray(out["A"][0])
    expect = _getrf_nopiv_np(a[:nb, :nb].astype(np.float64))
    err = float(np.max(np.abs(got - expect)))
    return {"gflops": lu_flops(n) / t / 1e9, "n": n, "nb": nb,
            "seconds": t, "compile_s": round(compile_s, 1),
            "mode": low.mode, "tile00_abs_err": err}


def bench_lowered_stencil_gflops(n: int = 1 << 24, mb: int = 1 << 18,
                                 radius: int = 4, iterations: int = 64) -> dict:
    """The compiled incarnation of the 1-D stencil app (halo-exchange tier):
    T wavefronts, each ONE batched (2R+1)-tap update over all tiles, ghost
    reads as store gathers.  Memory-bound by design — the number measures
    how close the emitted program gets to HBM bandwidth."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
    from parsec_tpu.models.stencil import (stencil_1d_ptg, stencil_flops,
                                           stencil_reference)
    from parsec_tpu.ptg.lowering import lower_taskpool

    rng = np.random.default_rng(0)
    base = rng.standard_normal(n).astype(np.float32)
    V = VectorTwoDimCyclic("V", lm=n, mb=mb, P=1,
                           init_fn=lambda m, size:
                           base[m * mb:m * mb + size])
    weights = np.full(2 * radius + 1, 1.0 / (2 * radius + 1))
    low = lower_taskpool(stencil_1d_ptg(V, weights, iterations))
    t, compile_s, out = _time_lowered(low, "V")
    # spot-check the first tile against the dense oracle
    got = np.asarray(out["V"][0])
    want = stencil_reference(base, weights, iterations)[:mb]
    err = float(np.max(np.abs(got - want)))
    return {"gflops": stencil_flops(n, radius, iterations) / t / 1e9,
            "seconds": t, "compile_s": round(compile_s, 1), "n": n,
            "mb": mb, "radius": radius,
            "iterations": iterations, "mode": low.mode, "max_abs_err": err}


def bench_dtd_gemm_tpu(n: int = 8192, nb: int = 1024) -> dict:
    """DTD (dynamic task discovery) GEMM on the chip — the reference's
    flagship DTD perf harness (``tests/dsl/dtd/dtd_test_simple_gemm.c:
    649-667``): GEMM(m,n,k) tasks inserted at runtime, hazards discovered
    from tile access chains, bodies dispatched through the TPU device
    module (``tpu_kernel="gemm"`` chores, vmapped same-class batching)."""
    import numpy as np

    import parsec_tpu.ops.gemm  # noqa: F401  registers the "gemm" kernels
    from parsec_tpu.device.tpu import init_tpu_devices
    from parsec_tpu.dtd import INOUT, INPUT, DTDTaskpool
    from parsec_tpu.runtime import Context

    devs = init_tpu_devices()
    if not devs:
        return {"gflops": 0.0, "note": "no accelerator visible"}
    dev = devs[0]
    NT = n // nb
    rng = np.random.default_rng(5)

    def tile():
        return rng.standard_normal((nb, nb), dtype=np.float32)

    A = [[tile() for _ in range(NT)] for _ in range(NT)]
    B = [[tile() for _ in range(NT)] for _ in range(NT)]
    C = [[np.zeros((nb, nb), np.float32) for _ in range(NT)]
         for _ in range(NT)]

    def gemm(a, b, c):          # CPU incarnation (fallback chore)
        c += a.astype(np.float32) @ b.astype(np.float32)

    ctx = Context(nb_cores=0)
    tp = DTDTaskpool()
    deadline = time.perf_counter() + 150
    try:
        ctx.add_taskpool(tp)
        t0 = time.perf_counter()
        for m in range(NT):
            for n_ in range(NT):
                for k in range(NT):
                    tp.insert_task(gemm, (A[m][k], INPUT),
                                   (B[k][n_], INPUT),
                                   (C[m][n_], INOUT), tpu_kernel="gemm")
        tp.wait()
        dev.sync()
        _scalar_sync(tp.tile_of_array(C[0][0]).data.newest_copy())
        t = time.perf_counter() - t0
        # spot-check OUTSIDE the timed section: read the final (device)
        # version of one C tile — a FULL-tile D2H pull, which through the
        # axon relay times the tunnel (~70ms RTT/tile), not the framework
        got = np.asarray(tp.tile_of_array(C[0][0]).data.newest_copy().value)
    finally:
        ctx.fini(timeout=max(0.0, deadline - time.perf_counter()))
    want = np.zeros((nb, nb), np.float32)
    for k in range(NT):
        want += A[0][k] @ B[k][0]
    err = float(np.max(np.abs(got - want)) / max(1.0, np.abs(want).max()))
    return {"gflops": 2.0 * n * n * n / t / 1e9, "n": n, "nb": nb,
            "seconds": t, "tile00_rel_err": err,
            "tasks": dev.executed_tasks,
            "batched_dispatches": dev.batched_dispatches}


def bench_overhead() -> dict:
    """The critical-path micro stage (microbench.py): dispatch latency,
    dep-release throughput, lfq local-pop/steal latency, PINS site cost,
    and lowering-cache compile times — ALL measurable with no accelerator,
    so this stage runs FIRST and the perf axis can never go fully dark
    again (ISSUE 2; round 5 shipped no dispatch evidence at all).  The
    lowering-cache half touches jax, so it only runs when the platform is
    explicitly CPU (a dark relay must not hang the always-first stage)."""
    import os

    from microbench import run_all
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    platform = (os.environ.get("BENCH_PLATFORM")
                or os.environ.get("JAX_PLATFORMS") or "")
    out = run_all(smoke=smoke, include_lowering=platform == "cpu",
                  include_serve=False,   # the dedicated serve stage owns it
                  include_comm=False,    # ...and the comm stage likewise
                  include_llm=False)     # ...and the llm stage
    out["gflops"] = 0.0   # not a throughput stage; keep the stage shape
    return out


def bench_comm_stage() -> dict:
    """The comm data-path stage (microbench.bench_comm): AM roundtrip
    latency, coalesced activation throughput, GET GB/s per tier and
    payload size, the pickled-framing baseline + speedup ratio, and
    overlap efficiency during a saturating fragmented GET.  Pure
    CPU+sockets — rides the always-first CPU-safe group with the
    overhead stage, so the comm perf axis has numbers even when the
    accelerator relay is dark (ISSUE 4)."""
    import os

    from microbench import bench_comm
    out = bench_comm(smoke=os.environ.get("BENCH_SMOKE") == "1")
    out["gflops"] = 0.0   # not a compute stage; keep the stage shape
    return out


def bench_comm_ranks_stage() -> dict:
    """The collective-tree rank sweep (ISSUE 14): one staged broadcast
    + one tree reduction per rank count, across real subprocess ranks
    (``run_multiproc``).  Emits the worst-rank broadcast/reduce latency
    and the ROOT's egress bytes — the number the tree exists to bound:
    ~⌈log₂ n⌉ payload transfers instead of n-1.  Each completed rank
    count flushes through ``_note_partial`` so a deadline death keeps
    the finished points.

    Each point also carries the static-vs-dynamic agreement cross-check
    (ISSUE 20): ``analysis/commcheck.predict_collective_traffic`` derives
    the expected cross-rank payload bytes per edge class WITHOUT running
    anything, and ``comm_agree_{n}r_err`` is the relative disagreement
    against the measured ``peer_stats`` wire ledger — perfdb verdicts it
    lower-is-better, so drift between the static model and the wire
    shows up in the regression sentinel."""
    import os

    from parsec_tpu.comm.multiproc import run_multiproc
    from parsec_tpu.core.params import params as _p

    smoke = os.environ.get("BENCH_SMOKE") == "1"
    sweep = [2, 4] if smoke else [2, 4, 8]
    payload = int(_p.get("comm_coll_bench_bytes"))
    out: dict = {"gflops": 0.0, "payload_bytes": payload,
                 "tree": _p.get("comm_bcast_tree")}
    for nranks in sweep:
        res = run_multiproc(
            nranks, "parsec_tpu.comm.collectives:_mp_collective_body",
            timeout=240, nb_cores=1)
        digests = {r["digest"] for r in res}
        root_tx = res[0]["peer_stats"].get("tx", {})
        egress = sum(d["bytes"] for d in root_tx.values())
        point = {
            f"bcast_{nranks}r_s": round(max(r["bcast_s"] for r in res), 4),
            f"reduce_{nranks}r_s": round(max(r["reduce_s"] for r in res),
                                         4),
            f"root_egress_{nranks}r_bytes": egress,
            f"root_egress_{nranks}r_payloads": round(
                egress / payload, 2) if payload else 0.0,
            f"bcast_{nranks}r_identical": len(digests) == 1,
        }
        try:
            # partials must never raise: the cross-check is advisory here
            # (tests/test_perf_smoke.py gates it)
            from parsec_tpu.analysis.commcheck import (
                agreement_rel_err, predict_collective_traffic)
            pred = predict_collective_traffic(nranks)
            observed = sum(
                d["bytes"]
                for r in res
                for d in r["peer_stats"].get("tx", {}).values())
            point[f"comm_pred_{nranks}r_bytes"] = pred["total_bytes"]
            point[f"comm_agree_{nranks}r_err"] = round(
                agreement_rel_err(pred["total_bytes"], observed), 4)
            _note_partial(phase="measure", ranks_done=nranks,
                          **{f"pred_{nranks}r_{ec}": b for ec, b
                             in sorted(pred["edge_bytes"].items())})
        except Exception:
            pass
        out.update(point)
        _note_partial(phase="measure", ranks_done=nranks, **point)
    return out


def bench_serve_stage() -> dict:
    """The serving-path stage: sustained concurrent submissions/s and
    p50/p99 ticket latency through a hot RuntimeServer (microbench.py's
    serve entry — pure scheduler path, no accelerator), plus the warm-vs-
    cold lowering-cache split across repeat-class *lowered* submissions —
    the number that justifies keeping the runtime resident (PR 2's warm
    compile only pays when the process outlives one DAG).  The lowered
    half touches jax, so like the overhead stage it only runs when the
    platform is explicitly CPU."""
    import os

    from microbench import bench_serve
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    out = bench_serve(nsub=16 if smoke else 64, depth=4 if smoke else 8)
    platform = (os.environ.get("BENCH_PLATFORM")
                or os.environ.get("JAX_PLATFORMS") or "")
    if platform == "cpu":
        import numpy as np

        from parsec_tpu.data_dist.matrix import TiledMatrix
        from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
        from parsec_tpu.ptg.lowering import lowering_cache
        from parsec_tpu.serve import RuntimeServer

        n, nb = (64, 32) if smoke else (128, 32)

        def gemm_pool():
            rng = np.random.default_rng(11)
            a = rng.standard_normal((n, n)).astype(np.float32)
            A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
            B = TiledMatrix.from_dense("B", a.copy(), nb, nb)
            C = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32),
                                       nb, nb)
            return tiled_gemm_ptg(A, B, C)

        with RuntimeServer(nb_cores=1) as server:
            h0, m0 = lowering_cache.hits, lowering_cache.misses
            t0 = time.perf_counter()
            server.submit_lowered(gemm_pool()).result(timeout=120)
            cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            server.submit_lowered(gemm_pool()).result(timeout=120)
            warm = time.perf_counter() - t0
            out["serve_lowered_cold_s"] = round(cold, 4)
            out["serve_lowered_warm_s"] = round(warm, 4)
            out["serve_lowered_cache_hits"] = lowering_cache.hits - h0
            out["serve_lowered_cache_misses"] = lowering_cache.misses - m0
    out["gflops"] = 0.0   # not a throughput stage; keep the stage shape
    return out


def bench_llm_stage() -> dict:
    """The LLM inference-serving stage (microbench.bench_llm): tokens/s
    and per-token p50/p99 of the continuous batcher over paged-KV decode
    superpools on a hot RuntimeServer, swept over concurrent streams AND
    over llm_steps_per_pool (the ISSUE-9 amortization axis, with
    serve_submits_per_token making the k-steps -> 1/k-submits claim
    directly visible).  Every swept point pre-flights through
    _note_partial, so a mid-sweep deadline keeps the completed points
    (the BENCH_r04/r05 lesson).  Pure scheduler+serve path on CPU:
    rides the relay-safe group, so the axis has numbers whatever the
    accelerator weather."""
    import os

    from microbench import bench_llm, bench_llm_prefix, bench_llm_tier
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    out = bench_llm(smoke=smoke, note=_note_partial)
    # the serving-memory axes (ISSUE 11), each flushing per point so a
    # deadline death keeps whatever swept: the shared-prefix-fraction
    # sweep (TTFT p50/p99 + prefill_skipped_frac per point, headline
    # llm_prefix_ttft_speedup vs trie-off) and the HBM-squeeze tier run
    # (tokens/s ratio with the device budget below the working set)
    try:
        out.update(bench_llm_prefix(smoke=smoke, note=_note_partial))
    except Exception as e:            # noqa: BLE001 — evidence over abort
        out["llm_prefix_error"] = f"{type(e).__name__}: {e}"
    try:
        out.update(bench_llm_tier(smoke=smoke, note=_note_partial))
    except Exception as e:            # noqa: BLE001 — evidence over abort
        out["llm_tier_error"] = f"{type(e).__name__}: {e}"
    out["gflops"] = 0.0   # not a compute stage; keep the stage shape
    return out


def bench_dispatch_us(ntasks: int = 2000) -> float:
    """Per-task dispatch latency on the EP DAG (the reference's
    tests/runtime/scheduling/ep.jdf shape): enqueue-to-drain wall time over
    the task count.  Exercises the enqueue-time DAG compilation
    (runtime/dagrun.py) and the native select→release executor — the
    rebuild's answer to scheduling.c:562-575's C hot loop.  Pools the
    compiler refuses take the dynamic Python scheduler instead.  ONE
    measurement implementation process-wide: this delegates to
    microbench.py, so the dedicated stage and the overhead stage can never
    drift into incomparable readings."""
    from microbench import _drain_ep_us
    us, _engaged = _drain_ep_us(ntasks, reps=5, compiled=True)
    return us


_abandoned: list = []    # stages whose worker thread outlived its timeout


def _runtime_report() -> dict:
    """The flight-recorder self-measurement embedded in EVERY stage
    result — degraded ones included, so even a relay outage ships
    per-stage runtime evidence (the round-5 lesson: a zero with no
    self-report is indistinguishable from a framework bug).  Must never
    raise: a broken report is itself reported."""
    try:
        from parsec_tpu.prof import runtime_report
        return runtime_report()
    except Exception as e:                     # noqa: BLE001 — evidence
        return {"unavailable": f"{type(e).__name__}: {e}"}


def _staged(name, fn, *a, timeout=120.0, retries=1, **kw):
    """Run one bench stage in a worker thread with a HARD join timeout.

    Two failure modes this guards (VERDICT r4 item 1 — round 4 shipped NO
    numbers because neither was handled):
    - the PJRT relay drops connections (remote_compile body truncation,
      transfer resets): catch, retry, then degrade to an error record;
    - the relay HANGS (a blocked device read never returns — ``import
      jax`` alone has been observed to stall 9+ minutes): a ``join``
      timeout abandons the stage thread (daemon) and moves on, so one
      stuck ``ctx.wait`` can never eat the rest of the run.  The
      reference's harnesses embody the same rule — they always print
      (``tests/dsl/dtd/dtd_test_simple_gemm.c:649-667``).

    ``timeout`` bounds the stage as a whole — retries share it, so a
    primary stage with retries can never exceed its allotment and push
    the whole run past the driver's patience.  An abandoned thread may
    still be driving the shared device when later stages run; that taint
    is recorded in ``_abandoned`` and surfaced per result (a wrong-but-
    flagged number is reportable; a wrong-and-silent one is not)."""
    import sys
    import threading
    t_stage = time.perf_counter()
    # the degraded-stage taint convention: snapshot the abandoned list
    # BEFORE this stage can add itself, so no degrade path ever lists the
    # stage as its own taint (ADVICE round 5: the budget path diverged)
    prior = list(_abandoned)
    for attempt in range(retries + 1):
        _stage_partials.pop(name, None)   # fresh flush per attempt
        box = {}

        def work():
            try:
                box["out"] = fn(*a, **kw)
            except BaseException as e:        # noqa: BLE001 — degrade, report
                box["err"] = e

        left = timeout - (time.perf_counter() - t_stage)
        if attempt and left <= 1.0:
            print(f"[bench] {name}: stage budget {timeout:.0f}s exhausted "
                  f"after {attempt} attempt(s)", file=sys.stderr, flush=True)
            return {"gflops": 0.0,
                    "error": f"stage budget {timeout:.0f}s exhausted "
                             f"after {attempt} attempt(s)",
                    "runtime_report": _runtime_report(),
                    **({"tainted_by": prior} if prior else {})}
        th = threading.Thread(target=work, daemon=True, name=f"bench-{name}")
        t0 = time.perf_counter()
        th.start()
        th.join(left)
        wall = time.perf_counter() - t0
        if th.is_alive():
            # a stage dying on its deadline mid-XLA-compile is the
            # BENCH_r04/r05 failure shape (rc 124): record it as a typed
            # compile_timeout WITH the partial metrics the stage flushed
            # (_note_partial) instead of losing the stage entirely
            part = dict(_stage_partials.get(name, {}))
            status = "compile_timeout" if part.get("phase") == "compile" \
                else "timeout"
            print(f"[bench] {name}: {status.upper()} after {wall:.1f}s — "
                  f"stage thread abandoned", file=sys.stderr, flush=True)
            _abandoned.append(name)
            return {"gflops": 0.0, "status": status,
                    "error": f"stage timeout after {timeout:.0f}s",
                    **({"partial": part} if part else {}),
                    "runtime_report": _runtime_report(),
                    **({"tainted_by": prior} if prior else {})}
        if "err" in box:
            e = box["err"]
            print(f"[bench] {name}: attempt {attempt + 1} failed "
                  f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)
            if attempt >= retries:
                part = dict(_stage_partials.get(name, {}))
                return {"gflops": 0.0, "error": f"{type(e).__name__}: {e}",
                        **({"partial": part} if part else {}),
                        "runtime_report": _runtime_report()}
            continue
        print(f"[bench] {name}: {wall:.1f}s", file=sys.stderr, flush=True)
        out = box["out"]
        if isinstance(out, dict):
            out.setdefault("runtime_report", _runtime_report())
            if _abandoned:
                # a zombie stage may still be dispatching on the shared
                # device: this stage's counters/deltas are suspect
                out["tainted_by"] = list(_abandoned)
        return out


def main() -> None:
    """Stage order and reporting are built so that a number ALWAYS lands,
    whatever the relay weather or the driver's patience:

    - dispatch + the headline GEMM run FIRST (round 4 ordered the headline
      dead last for HBM hygiene and the driver's kill erased the round's
      entire perf story — evidence beats hygiene);
    - after EVERY stage the full cumulative result JSON is re-printed to
      stdout (and mirrored to BENCH_partial.json), so a kill at any moment
      leaves the latest complete line in the tail for the driver to parse;
    - every stage runs under a hard thread-join timeout, and secondaries
      are skipped once the global deadline (BENCH_DEADLINE_S, default 420s
      — below the driver's observed ~600s patience) is near."""
    import os
    import sys
    # sitecustomize pins JAX_PLATFORMS=axon (the TPU relay) and imports
    # jax at interpreter start, so a shell-level env var is captured
    # before main() runs — override the live config too (conftest.py
    # does the same for the test suite)
    if os.environ.get("BENCH_PLATFORM"):
        os.environ["JAX_PLATFORMS"] = os.environ["BENCH_PLATFORM"]
        import jax
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    # observability defaults for the whole run (read when the prof params
    # register, i.e. on the first parsec_tpu import inside a stage): keep
    # the metrics snapshotter sampling so every stage's runtime_report
    # carries a series, and stall dumps land next to the BENCH artifacts
    os.environ.setdefault("PARSEC_MCA_prof_snapshot_interval", "0.25")
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    if smoke:
        # exercise the dynamic device path on the host CPU device too —
        # otherwise the smoke run skips every dynamic stage
        os.environ.setdefault("PARSEC_MCA_device_tpu_allow_cpu", "1")
    n = int(os.environ.get("BENCH_N", "512" if smoke else "16384"))
    deadline = float(os.environ.get("BENCH_DEADLINE_S",
                                    "120" if smoke else "420"))
    t_start = time.perf_counter()
    res: dict = {}

    def _dispatch_us():
        """The dispatch series value: the dedicated stage's reading, else
        the overhead micro stage's, else absent (never a sentinel)."""
        v = res.get("dispatch_us")
        if isinstance(v, (int, float)) and v >= 0:
            return v
        ov = res.get("overhead", {})
        w = ov.get("dispatch_us") if isinstance(ov, dict) else None
        return w if isinstance(w, (int, float)) and w >= 0 else None

    def emit():
        gemm = res.get("gemm") or {}
        peak = gemm.get("peak_gflops") or 1.0
        target = 0.70 * peak
        dyn = res.get("dynamic_gemm", {})
        degraded = {nm: d.get("error") or d.get("skipped")
                    for nm, d in res.items()
                    if isinstance(d, dict) and (d.get("error")
                                                or d.get("skipped"))}
        # the per-stage runtime self-reports (flight-recorder counters,
        # per-worker last activity): EVERY stage ships one, degraded
        # stages included — a relay outage still reads as runtime
        # evidence, not silence
        reports = {nm: d["runtime_report"] for nm, d in res.items()
                   if isinstance(d, dict) and "runtime_report" in d}
        line = json.dumps({
            "metric": "ptg_tiled_gemm_gflops_per_chip",
            "value": round(gemm.get("gflops", 0.0), 1),
            "unit": "GFLOPS",
            "vs_baseline": round(gemm.get("gflops", 0.0) / target, 4),
            "extra": {
                "pct_peak": round(gemm.get("pct_peak", 0.0), 2),
                "device_kind": gemm.get("device_kind", "pending"),
                "n": gemm.get("n", n),
                "nb": gemm.get("nb", 0),
                "gemm_seconds": round(gemm.get("seconds", 0.0), 4),
                "gemm_compile_s": gemm.get("compile_s", 0.0),
                "lowering": gemm.get("lowering",
                                     gemm.get("error", "pending")),
                # raw-compiler cross-check: bare jnp.dot, same config;
                # framework/raw ~ 1.0 = the taskpool lowering costs nothing
                "raw_dot_gflops": round(
                    res.get("raw_dot", {}).get("gflops", 0.0), 1),
                # a MISSING dispatch measurement is omitted (formerly a
                # -1.0 sentinel that poisoned trend averages over
                # BENCH_r*.json); the overhead micro stage's reading
                # backstops a skipped/failed dispatch stage
                **({"task_dispatch_us": _dispatch_us()}
                   if _dispatch_us() is not None else {}),
                "overhead": {k: v for k, v in
                             res.get("overhead", {}).items()
                             if k not in ("runtime_report", "gflops")},
                # the comm wire-path stage: AM roundtrips, GET GB/s per
                # tier/size, pickle-baseline speedup, overlap (ISSUE 4)
                "comm": {k: v for k, v in
                         res.get("comm", {}).items()
                         if k not in ("runtime_report", "gflops")},
                # the collective-tree rank sweep: bcast/reduce latency +
                # measured root egress per rank count (ISSUE 14)
                "comm_ranks": {k: v for k, v in
                               res.get("comm_ranks", {}).items()
                               if k not in ("runtime_report", "gflops")},
                # the serving stage: submissions/s, ticket latency, and
                # the warm-vs-cold lowered split (ISSUE 3)
                "serve": {k: v for k, v in
                          res.get("serve", {}).items()
                          if k not in ("runtime_report", "gflops")},
                # the LLM serving stage: tokens/s + per-token p50/p99
                # with concurrent streams as the sweep axis (ISSUE 6)
                "llm": {k: v for k, v in
                        res.get("llm", {}).items()
                        if k not in ("runtime_report", "gflops")},
                "dynamic_gemm_gflops": round(dyn.get("gflops", 0.0), 1),
                "dynamic_gemm_batched": dyn.get("batched_dispatches", 0),
                "dynamic_gemm_breakdown": dyn.get("breakdown", {}),
                "dtd_gemm_tpu_gflops": round(
                    res.get("dtd_gemm", {}).get("gflops", 0.0), 1),
                "dynamic_cholesky_gflops": round(
                    res.get("dynamic_cholesky", {}).get("gflops", 0.0), 1),
                # the closed-loop autotuner stage (ISSUE 18): seeded-bad
                # knobs recovered by tune.search, winner -> tunedb.jsonl
                "tune_speedup": round(
                    res.get("tuned_cholesky", {}).get("tune_speedup",
                                                      0.0), 3),
                "tuned_cholesky": {k: v for k, v in
                                   res.get("tuned_cholesky", {}).items()
                                   if k not in ("runtime_report",
                                                "gflops")},
                # n=8192 is the round-3-comparable config (VERDICT r4 weak
                # #8: keep configs frozen; new sizes are NEW keys)
                "lowered_cholesky_gflops": round(
                    res.get("lowered_cholesky", {}).get("gflops", 0.0), 1),
                "lowered_cholesky_n": res.get("lowered_cholesky",
                                              {}).get("n", 0),
                "lowered_cholesky_compile_s": res.get(
                    "lowered_cholesky", {}).get("compile_s", 0.0),
                "lowered_cholesky_16k_gflops": round(
                    res.get("lowered_cholesky_16k", {}).get("gflops",
                                                            0.0), 1),
                # the megakernel-region stage (ISSUE 8): same DAG, one
                # program per verified region, budgeted staged compile
                "region_cholesky_gflops": round(
                    res.get("region_cholesky", {}).get("gflops", 0.0), 1),
                "region_cholesky_regions": res.get(
                    "region_cholesky", {}).get("regions", 0),
                "region_cholesky_eager": res.get(
                    "region_cholesky", {}).get("regions_eager", 0),
                "region_cholesky_compile_s": res.get(
                    "region_cholesky", {}).get("compile_s", 0.0),
                "lowered_lu_gflops": round(
                    res.get("lowered_lu", {}).get("gflops", 0.0), 1),
                "lowered_lu_compile_s": res.get("lowered_lu",
                                                {}).get("compile_s", 0.0),
                "stencil_gflops": round(
                    res.get("stencil", {}).get("gflops", 0.0), 2),
                "lowered_stencil_gflops": round(
                    res.get("lowered_stencil", {}).get("gflops", 0.0), 1),
                "lowered_stencil_compile_s": res.get(
                    "lowered_stencil", {}).get("compile_s", 0.0),
                "elapsed_s": round(time.perf_counter() - t_start, 1),
                # the regression sentinel's verdicts (prof/perfdb.py):
                # always present so the driver can key on it — empty
                # list = no EWMA-flagged regressions this run
                "perfdb_regressions": list(_perfdb_state["regressions"]),
                "runtime_reports": reports,
                **({"degraded_stages": degraded} if degraded else {}),
                **({"abandoned_stages": list(_abandoned)}
                   if _abandoned else {}),
            },
        })
        print(line, flush=True)
        try:
            with open("BENCH_partial.json", "w") as f:
                f.write(line + "\n")
        except OSError:
            pass

    budgets = _stage_budgets()

    def stage(name, fn, *a, timeout=120.0, retries=0, primary=False, **kw):
        # per-stage MCA/env budget override (bench_stage_budget_s):
        # named entry wins, then the '*' default, then the harness value
        timeout = budgets.get(name, budgets.get("*", timeout))
        left = deadline - (time.perf_counter() - t_start)
        if not primary and left < 15.0:
            print(f"[bench] {name}: SKIPPED ({deadline:.0f}s deadline)",
                  file=sys.stderr, flush=True)
            res[name] = {"gflops": 0.0, "skipped": "deadline exhausted",
                         "runtime_report": _runtime_report()}
        else:
            # a primary stage may overshoot the deadline (the headline
            # matters more than the tail) but never unboundedly — its
            # retries share one stage budget, clamped so the driver's
            # ~600s patience is never at risk
            timeout = (min(timeout, max(left, 60.0)) if primary
                       else min(timeout, max(left, 15.0)))
            res[name] = _staged(name, fn, *a, timeout=timeout,
                                retries=retries, **kw)
        _perfdb_note(name, res[name])
        emit()
        return res[name]

    # smoke configs keep every stage under a few seconds on CPU so the
    # whole harness (ordering, emit, degrade paths) is CI-testable —
    # round 4's lesson: an untested bench harness ships nothing
    cfg = {
        "gemm": dict(n=n, nb=128 if smoke else 512,
                     reps=4 if smoke else 48),
        "raw": dict(n=n, reps=4 if smoke else 48),
        "stencil": dict(n=1 << 16, mb=1 << 12, iterations=4)
        if smoke else {},
        "lchol": dict(n=1024, nb=256) if smoke else dict(n=8192, nb=512),
        "rchol": dict(n=1024, nb=256) if smoke else dict(n=8192, nb=512),
        "lsten": dict(n=1 << 16, mb=1 << 12, iterations=8)
        if smoke else {},
        "llu": dict(n=1024, nb=256) if smoke else {},
        "dyn": dict(n=512, nb=128) if smoke else {},
        "dtd": dict(n=512, nb=128) if smoke else {},
        "lchol16": dict(n=2048, nb=256) if smoke else dict(n=16384,
                                                           nb=512),
        "dchol": dict(n=512, nb=128) if smoke else {},
        "tchol": dict(n=512, nb_bad=32, budget=6)
        if smoke else dict(n=1024, nb_bad=64, budget=8),
    }

    # --- the overhead micro stage runs FIRST, before anything that can
    # touch the relay: dispatch/release/steal numbers land even when
    # every accelerator stage is dark (ISSUE 2 satellite) ---
    stage("overhead", bench_overhead, timeout=120.0, primary=True)
    # --- the comm wire-path stage rides the same CPU-safe always-first
    # group: AM latency, GET GB/s vs the pickle baseline, and overlap
    # efficiency need only sockets (ISSUE 4) ---
    stage("comm", bench_comm_stage, timeout=90.0, primary=True)

    # --- primary metrics next: a headline must land within minutes ---
    d = _staged("dispatch", bench_dispatch_us, timeout=90.0)
    res["dispatch_us"] = round(d, 2) if isinstance(d, float) else None
    # the dispatch stage's self-report rides like every other stage's
    # (its headline value stays the flat task_dispatch_us key)
    res["dispatch"] = d if isinstance(d, dict) else \
        {"dispatch_us": res["dispatch_us"]}
    res["dispatch"].setdefault("runtime_report", _runtime_report())
    _perfdb_note("dispatch", res["dispatch"])
    emit()
    stage("gemm", bench_gemm_gflops, timeout=300.0, retries=2,
          primary=True, **cfg["gemm"])
    stage("raw_dot", bench_raw_dot_gflops, timeout=120.0, **cfg["raw"])

    # --- secondaries, most valuable first, each deadline-bounded.  The
    # serving stage leads them: submissions/s and ticket latency need no
    # accelerator (the lowered warm/cold split self-gates on an
    # explicit-CPU platform), so it lands even in relay-dark weather —
    # but never ahead of the headline (the round-4 ordering lesson) ---
    stage("serve", bench_serve_stage, timeout=150.0)
    stage("llm", bench_llm_stage, timeout=150.0)
    # the collective-tree rank sweep spawns subprocess ranks — CPU-safe
    # but slow, so it rides the secondary group, never ahead of the
    # headline
    stage("comm_ranks", bench_comm_ranks_stage, timeout=600.0)
    from parsec_tpu.models.stencil import run_stencil_bench
    stage("stencil", run_stencil_bench, timeout=60.0, **cfg["stencil"])
    stage("lowered_cholesky", bench_lowered_cholesky_gflops,
          timeout=150.0, **cfg["lchol"])
    stage("region_cholesky", bench_region_cholesky_gflops, timeout=150.0,
          **cfg["rchol"])
    stage("lowered_stencil", bench_lowered_stencil_gflops, timeout=150.0,
          **cfg["lsten"])
    stage("lowered_lu", bench_lowered_lu_gflops, timeout=150.0,
          **cfg["llu"])
    stage("dynamic_gemm", bench_dynamic_gemm_gflops, timeout=150.0,
          **cfg["dyn"])
    stage("dtd_gemm", bench_dtd_gemm_tpu, timeout=150.0, **cfg["dtd"])
    stage("lowered_cholesky_16k", bench_lowered_cholesky_gflops,
          timeout=180.0, **cfg["lchol16"])
    stage("dynamic_cholesky", bench_dynamic_cholesky_gflops,
          timeout=150.0, **cfg["dchol"])
    stage("tuned_cholesky", bench_tuned_cholesky, timeout=150.0,
          **cfg["tchol"])


if __name__ == "__main__":
    main()
