#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): PTG tiled-GEMM GFLOPS/chip at N=16384, nb=512.
The taskpool executes through the framework's compiled path — the PTG GEMM
dataflow lowered to a single XLA program on the chip (the dynamic-runtime
path covers irregular/distributed graphs; on one chip the lowered program is
the framework's GEMM incarnation).  ``vs_baseline`` is measured GFLOPS over
the north-star target (70% of the chip's peak bf16 GFLOPS, BASELINE.md), so
>= 1.0 beats the target.

``extra`` carries the secondary metric: task-dispatch per-task latency of the
dynamic runtime on the EP CTL-only DAG (the reference's
tests/runtime/scheduling/ep.jdf shape).
"""

from __future__ import annotations

import json
import statistics
import time


def bench_gemm_gflops(n: int = 16384, nb: int = 512, reps: int = 48) -> dict:
    """Steady-state throughput of the PTG tiled-GEMM taskpool, executed
    through the framework's compiled incarnation: ``tiled_gemm_ptg`` builds
    the GEMM(m,n,k) task graph, ``lower_taskpool`` collapses its k-chain to
    one XLA contraction over the tile stores, and a dependent chain of
    ``reps`` taskpool executions runs inside one program.  Synced by a host
    scalar read (block_until_ready is unreliable through the TPU tunnel; a
    read cannot complete before the compute does)."""
    import functools

    import jax
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.device.tpu import _flop_rating
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.ptg.lowering import lower_taskpool

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    peak_bf16, _ = _flop_rating(kind.lower())

    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)

    def mk(name, dtype):
        def init(m, n_, shape):
            rng = np.random.default_rng((hash((name, m, n_)) & 0x7FFFFFFF))
            return rng.standard_normal(shape, dtype=np.float32).astype(dtype)
        return TiledMatrix(name, n, n, nb, nb, dtype=dtype, init_fn=init)

    A, B = mk("A", bf16), mk("B", bf16)
    C = TiledMatrix("C", n, n, nb, nb, dtype=np.float32,
                    init_fn=lambda m, n_, s: np.zeros(s, np.float32))

    low = lower_taskpool(tiled_gemm_ptg(A, B, C))
    assert low.mode == "chain-collapse", low.mode
    stores = {k: jax.device_put(v, dev) for k, v in
              low.initial_stores().items()}
    step = low.step_fn

    @functools.partial(jax.jit, static_argnames=("reps",))
    def chain(st, reps):
        # the (zero) feedback of C into A makes each taskpool execution
        # loop-carried, so XLA cannot hoist the contraction as invariant
        def body(st, _):
            # tiny in-place (DUS) perturbation instead of a full A+eps copy
            eps = (st["C"].reshape(-1)[0] * 0).astype(st["A"].dtype)
            st = dict(st)
            st["A"] = st["A"].at[0, 0].add(eps)
            return step(st), None
        st, _ = jax.lax.scan(body, st, None, length=reps)
        return st

    _ = float(chain(stores, reps)["C"].reshape(-1)[0])  # compile + warm
    times = []
    for _i in range(3):
        t0 = time.perf_counter()
        out = chain(stores, reps)
        _sink = float(out["C"].reshape(-1)[0])
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    gflops = 2.0 * n * n * n * reps / t / 1e9
    return {
        "gflops": gflops,
        "peak_gflops": peak_bf16,
        "pct_peak": 100.0 * gflops / peak_bf16,
        "device_kind": kind,
        "n": n,
        "nb": nb,
        "reps": reps,
        "seconds": t,
        "lowering": low.mode,
    }


def bench_raw_dot_gflops(n: int = 16384, reps: int = 48) -> dict:
    """Honesty cross-check for the headline (VERDICT r3 weak #6): the same
    flops as ONE bare ``jnp.dot`` chain, no framework anywhere — pct_peak
    rests on the hand-entered flop table, so record what the raw compiler
    achieves on this chip under the identical loop-carry discipline."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n), dtype=np.float32),
                    dtype=jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((n, n), dtype=np.float32),
                    dtype=jnp.bfloat16)

    @functools.partial(jax.jit, static_argnames=("reps",))
    def chain(a, b, reps):
        def body(c, _):
            # feed (zero of) c back into a so the dot is loop-carried
            eps = (c.reshape(-1)[0] * 0).astype(a.dtype)
            return jnp.dot(a.at[0, 0].add(eps), b,
                           preferred_element_type=jnp.float32), None
        c0 = jnp.zeros((n, n), jnp.float32)
        c, _ = jax.lax.scan(body, c0, None, length=reps)
        return c

    _ = float(chain(a, b, reps).reshape(-1)[0])   # compile + warm
    times = []
    for _i in range(3):
        t0 = time.perf_counter()
        out = chain(a, b, reps)
        _sink = float(out.reshape(-1)[0])
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    return {"gflops": 2.0 * n * n * n * reps / t / 1e9, "n": n,
            "reps": reps, "seconds": t}


def bench_dynamic_gemm_gflops(n: int = 8192, nb: int = 1024) -> dict:
    """The dynamic-runtime path on the real chip: PTG GEMM(m,n,k) executed
    task by task through the TPU device module (stage-in, LRU cache, vmapped
    same-class batching) — no lowering.  The number the reference's
    ``dtd_test_simple_gemm`` prints (VERDICT r2 weak #1: the dynamic path
    had never produced a TPU figure)."""
    import jax
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.device.tpu import init_tpu_devices
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.runtime import Context

    devs = init_tpu_devices()
    if not devs:
        return {"gflops": 0.0, "note": "no accelerator visible"}
    dev = devs[0]

    def init(name):
        def fn(m, n_, shape):
            rng = np.random.default_rng(hash((name, m, n_)) & 0x7FFFFFFF)
            return rng.standard_normal(shape, dtype=np.float32)
        return fn

    A = TiledMatrix("A", n, n, nb, nb, init_fn=init("A"))
    B = TiledMatrix("B", n, n, nb, nb, init_fn=init("B"))
    C = TiledMatrix("C", n, n, nb, nb,
                    init_fn=lambda m, n_, s: np.zeros(s, np.float32))
    # materialize every tile BEFORE the clock starts: host RNG generation
    # is harness setup, not framework work (the reference's harnesses also
    # exclude matrix generation from the timed region)
    for M in (A, B, C):
        for i in range(M.mt):
            for j in range(M.nt):
                M.data_of(i, j)
    tp = tiled_gemm_ptg(A, B, C, devices="tpu")

    # relay RTT: one tiny dispatch, synced by a host value read — the
    # per-call latency floor every enqueue through the tunnel pays
    import jax.numpy as jnp
    tiny = jax.jit(lambda x: x + 1)
    _ = float(tiny(jnp.float32(0)))          # compile
    rtts = []
    for _i in range(5):
        r0 = time.perf_counter()
        _ = float(tiny(jnp.float32(_i)))
        rtts.append(time.perf_counter() - r0)
    rtt = statistics.median(rtts)

    calls0, ts0 = dev.xla_calls, dev.t_stage_in
    td0, tc0, tdr0 = dev.t_dispatch, dev.t_complete, dev.t_drain
    bin0 = dev.bytes_in
    tm0 = dev.t_manager
    ctx = Context(nb_cores=0)
    t0 = time.perf_counter()
    ctx.add_taskpool(tp)
    ctx.wait(timeout=600)
    t_drained = time.perf_counter() - t0
    dev.sync()
    t = time.perf_counter() - t0
    ctx.fini()
    calls = dev.xla_calls - calls0
    h2d = dev.bytes_in - bin0
    stage_s = dev.t_stage_in - ts0
    breakdown = {
        # H2D volume + achieved rate: through the PJRT relay the transfer
        # bandwidth, not the framework, bounds the stage-in phase
        "h2d_mb": round(h2d / 1e6, 1),
        "h2d_MBps": round(h2d / 1e6 / stage_s, 1) if stage_s > 0 else 0.0,
        # phase walls: what the manager thread actually spent
        "stage_in_s": round(dev.t_stage_in - ts0, 3),
        "dispatch_s": round(dev.t_dispatch - td0, 3),
        "complete_s": round(dev.t_complete - tc0, 3),
        "drain_s": round(dev.t_drain - tdr0, 3),
        "manager_s": round(dev.t_manager - tm0, 3),
        "final_sync_s": round(t - t_drained, 3),
        "xla_calls": calls,
        "relay_rtt_ms": round(rtt * 1e3, 2),
        # the relay-latency floor: a dependent-call chain cannot finish
        # faster than calls * rtt; compare with the measured wall to
        # attribute relay vs framework cost
        "relay_floor_s": round(calls * rtt, 3),
        # MXU floor: the same flops at the chip's fp32 rating (the
        # dynamic path computes in f32, not the bf16 headline peak)
        "onchip_floor_s": round(
            2.0 * n * n * n / (dev.gflops_fp32 * 1e9), 3),
    }
    return {
        "gflops": 2.0 * n * n * n / t / 1e9,
        "n": n, "nb": nb, "seconds": t,
        "tasks": dev.executed_tasks,
        "batched_dispatches": dev.batched_dispatches,
        "breakdown": breakdown,
    }




def bench_dynamic_cholesky_gflops(n: int = 8192, nb: int = 1024) -> dict:
    """Dynamic-path tiled Cholesky on the chip (BASELINE staged config #5):
    four task classes, triangular space, range arrows."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
    from parsec_tpu.device.tpu import init_tpu_devices
    from parsec_tpu.models.cholesky import (cholesky_flops, make_spd,
                                            tiled_cholesky_ptg)
    from parsec_tpu.runtime import Context

    devs = init_tpu_devices()
    if not devs:
        return {"gflops": 0.0, "note": "no accelerator visible"}
    dev = devs[0]
    a = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb)
    tp = tiled_cholesky_ptg(A, devices="tpu")
    ctx = Context(nb_cores=0)
    t0 = time.perf_counter()
    ctx.add_taskpool(tp)
    ctx.wait(timeout=600)
    dev.sync()
    t = time.perf_counter() - t0
    ctx.fini()
    # correctness spot check: || L[0,0] - chol(A)[0,0] tile || small
    got = np.asarray(A.data_of(0, 0).newest_copy().value)
    expect = np.linalg.cholesky(a[:nb, :nb].astype(np.float64))
    err = float(np.max(np.abs(np.tril(got) - expect)))
    return {
        "gflops": cholesky_flops(n) / t / 1e9,
        "n": n, "nb": nb, "seconds": t, "tile00_abs_err": err,
    }


def _time_lowered(low, sync_store: str, reps: int = 3):
    """Shared lowered-bench harness: device stores, jit, warm, then the
    median of ``reps`` runs each synced by a device-side SCALAR read —
    ``np.asarray(out)`` would drag the whole store through the TPU tunnel
    and time the transfer (the round-3 bench bug this guards against).
    Returns ``(median_seconds, last_out)``."""
    import jax
    st = {k: jax.device_put(v) for k, v in low.initial_stores().items()}
    jf = jax.jit(low.step_fn)
    out = jf(st)
    _ = float(out[sync_store].reshape(-1)[0])    # compile + warm
    times = []
    for _i in range(reps):
        t0 = time.perf_counter()
        out = jf(st)
        _ = float(out[sync_store].reshape(-1)[0])
        times.append(time.perf_counter() - t0)
    return statistics.median(times), out


def bench_lowered_cholesky_gflops(n: int = 16384, nb: int = 512) -> dict:
    """The compiled incarnation of the Cholesky PTG: four task classes,
    triangular space, batched per topological wavefront by the lowering —
    every panel's trailing update lands on the MXU as ONE batched tile
    matmul.  For scale: XLA's own jnp.linalg.cholesky runs n=8192 at ~12
    GFLOPS on a v5e; the wavefront program measures in the TFLOPS.  Synced
    by a device-side scalar read (np.asarray(out) would drag the whole
    factored matrix through the TPU tunnel and time the transfer, which is
    exactly the round-3 bench bug this replaces)."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
    from parsec_tpu.models.cholesky import (cholesky_flops, make_spd_fast,
                                            tiled_cholesky_ptg)
    from parsec_tpu.ptg.lowering import lower_taskpool

    a = make_spd_fast(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb)
    low = lower_taskpool(tiled_cholesky_ptg(A))
    t, out = _time_lowered(low, "A")
    # spot-check the first tile against the dense factorization
    got = np.asarray(out["A"][0])
    expect = np.linalg.cholesky(a[:nb, :nb].astype(np.float64))
    err = float(np.max(np.abs(np.tril(got) - expect)))
    return {"gflops": cholesky_flops(n) / t / 1e9, "n": n, "nb": nb,
            "seconds": t, "mode": low.mode, "tile00_abs_err": err}


def bench_lowered_lu_gflops(n: int = 8192, nb: int = 512) -> dict:
    """The compiled incarnation of the LU-nopiv PTG — the third dense
    factorization through the wavefront pass (GETRF/TRSM_L/TRSM_U/GEMM,
    square space): every panel's trailing update is one batched tile
    matmul.  Scalar-read synced like the Cholesky stage."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.lu import lu_flops, make_dd, tiled_lu_ptg
    from parsec_tpu.ptg.lowering import lower_taskpool

    a = make_dd(n, seed=1).astype(np.float32)
    A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
    low = lower_taskpool(tiled_lu_ptg(A))
    t, out = _time_lowered(low, "A")
    # spot-check tile (0,0): L\U packed must match the dense recursion
    from parsec_tpu.models.lu import _getrf_nopiv_np
    got = np.asarray(out["A"][0])
    expect = _getrf_nopiv_np(a[:nb, :nb].astype(np.float64))
    err = float(np.max(np.abs(got - expect)))
    return {"gflops": lu_flops(n) / t / 1e9, "n": n, "nb": nb,
            "seconds": t, "mode": low.mode, "tile00_abs_err": err}


def bench_lowered_stencil_gflops(n: int = 1 << 24, mb: int = 1 << 18,
                                 radius: int = 4, iterations: int = 64) -> dict:
    """The compiled incarnation of the 1-D stencil app (halo-exchange tier):
    T wavefronts, each ONE batched (2R+1)-tap update over all tiles, ghost
    reads as store gathers.  Memory-bound by design — the number measures
    how close the emitted program gets to HBM bandwidth."""
    import numpy as np

    from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
    from parsec_tpu.models.stencil import (stencil_1d_ptg, stencil_flops,
                                           stencil_reference)
    from parsec_tpu.ptg.lowering import lower_taskpool

    rng = np.random.default_rng(0)
    base = rng.standard_normal(n).astype(np.float32)
    V = VectorTwoDimCyclic("V", lm=n, mb=mb, P=1,
                           init_fn=lambda m, size:
                           base[m * mb:m * mb + size])
    weights = np.full(2 * radius + 1, 1.0 / (2 * radius + 1))
    low = lower_taskpool(stencil_1d_ptg(V, weights, iterations))
    t, out = _time_lowered(low, "V")
    # spot-check the first tile against the dense oracle
    got = np.asarray(out["V"][0])
    want = stencil_reference(base, weights, iterations)[:mb]
    err = float(np.max(np.abs(got - want)))
    return {"gflops": stencil_flops(n, radius, iterations) / t / 1e9,
            "seconds": t, "n": n, "mb": mb, "radius": radius,
            "iterations": iterations, "mode": low.mode, "max_abs_err": err}


def bench_dtd_gemm_tpu(n: int = 8192, nb: int = 1024) -> dict:
    """DTD (dynamic task discovery) GEMM on the chip — the reference's
    flagship DTD perf harness (``tests/dsl/dtd/dtd_test_simple_gemm.c:
    649-667``): GEMM(m,n,k) tasks inserted at runtime, hazards discovered
    from tile access chains, bodies dispatched through the TPU device
    module (``tpu_kernel="gemm"`` chores, vmapped same-class batching)."""
    import numpy as np

    import parsec_tpu.ops.gemm  # noqa: F401  registers the "gemm" kernels
    from parsec_tpu.device.tpu import init_tpu_devices
    from parsec_tpu.dtd import INOUT, INPUT, DTDTaskpool
    from parsec_tpu.runtime import Context

    devs = init_tpu_devices()
    if not devs:
        return {"gflops": 0.0, "note": "no accelerator visible"}
    dev = devs[0]
    NT = n // nb
    rng = np.random.default_rng(5)

    def tile():
        return rng.standard_normal((nb, nb), dtype=np.float32)

    A = [[tile() for _ in range(NT)] for _ in range(NT)]
    B = [[tile() for _ in range(NT)] for _ in range(NT)]
    C = [[np.zeros((nb, nb), np.float32) for _ in range(NT)]
         for _ in range(NT)]

    def gemm(a, b, c):          # CPU incarnation (fallback chore)
        c += a.astype(np.float32) @ b.astype(np.float32)

    ctx = Context(nb_cores=0)
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    t0 = time.perf_counter()
    for m in range(NT):
        for n_ in range(NT):
            for k in range(NT):
                tp.insert_task(gemm, (A[m][k], INPUT), (B[k][n_], INPUT),
                               (C[m][n_], INOUT), tpu_kernel="gemm")
    tp.wait()
    dev.sync()
    t = time.perf_counter() - t0
    # spot-check OUTSIDE the timed section: read the final (device) version
    # of one C tile — a D2H pull, which through the axon relay times the
    # tunnel (~70ms RTT/tile), not the framework (BASELINE.md env note)
    got = np.asarray(tp.tile_of_array(C[0][0]).data.newest_copy().value)
    ctx.fini()
    want = np.zeros((nb, nb), np.float32)
    for k in range(NT):
        want += A[0][k] @ B[k][0]
    err = float(np.max(np.abs(got - want)) / max(1.0, np.abs(want).max()))
    return {"gflops": 2.0 * n * n * n / t / 1e9, "n": n, "nb": nb,
            "seconds": t, "tile00_rel_err": err,
            "tasks": dev.executed_tasks,
            "batched_dispatches": dev.batched_dispatches}


def bench_dispatch_us(ntasks: int = 2000) -> float:
    """Per-task dispatch latency on the EP DAG (the reference's
    tests/runtime/scheduling/ep.jdf shape): enqueue-to-drain wall time over
    the task count.  Exercises the enqueue-time DAG compilation
    (runtime/dagrun.py) and the native select→release executor — the
    rebuild's answer to scheduling.c:562-575's C hot loop.  Pools the
    compiler refuses take the dynamic Python scheduler instead."""
    from parsec_tpu import ptg
    from parsec_tpu.runtime import Context

    NT, DEPTH = 50, ntasks // 50
    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l: None)
    times = []
    for _rep in range(5):   # median of 5: the metric is steady-state
        tp = p.build()      # per-task latency, not one-time dlopen/import
        ctx = Context(nb_cores=0)
        t0 = time.perf_counter()
        ctx.add_taskpool(tp)
        ctx.wait(timeout=600)
        times.append(time.perf_counter() - t0)
        ctx.fini()
    return statistics.median(times) / (NT * DEPTH) * 1e6


def _staged(name, fn, *a, retries=1, **kw):
    """Run one bench stage, logging its wall to stderr (progress trace for
    long driver runs; stdout stays the single JSON line).

    The PJRT relay drops connections now and then (remote_compile body
    truncation, transfer resets); one flaky stage must not kill the whole
    bench — retry, then degrade to an error record so every other metric
    still reports."""
    import sys
    for attempt in range(retries + 1):
        t0 = time.perf_counter()
        try:
            out = fn(*a, **kw)
        except Exception as e:
            print(f"[bench] {name}: attempt {attempt + 1} failed "
                  f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)
            if attempt >= retries:
                return {"gflops": 0.0, "error": f"{type(e).__name__}: {e}"}
            continue
        print(f"[bench] {name}: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr, flush=True)
        return out


def main() -> None:
    import os
    import sys
    n = int(os.environ.get("BENCH_N", "16384"))
    # secondary-stage wall budget: relay weather varies 10x between runs
    # (compiles and transfers ride a shared tunnel); once the budget is
    # spent the remaining SECONDARY stages are skipped so the headline
    # always reports within the driver's patience
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    t_start = time.perf_counter()

    def secondary(name, fn, *a, **kw):
        if time.perf_counter() - t_start > budget:
            print(f"[bench] {name}: SKIPPED (over {budget:.0f}s budget)",
                  file=sys.stderr, flush=True)
            return {"gflops": 0.0, "skipped": "bench budget exhausted"}
        return _staged(name, fn, *a, **kw)

    # order matters for measurement quality: host-only metrics first, then
    # the small device programs, and the headline GEMM dead last — its
    # ~1.5GB store set fragments HBM and perturbs whatever follows it
    dispatch_us = _staged("dispatch", bench_dispatch_us)
    from parsec_tpu.models.stencil import run_stencil_bench
    stencil = secondary("stencil", run_stencil_bench)
    lsten = secondary("lowered_stencil", bench_lowered_stencil_gflops)
    lchol = secondary("lowered_cholesky", bench_lowered_cholesky_gflops)
    llu = secondary("lowered_lu", bench_lowered_lu_gflops)
    dyn = secondary("dynamic_gemm", bench_dynamic_gemm_gflops)
    dtd = secondary("dtd_gemm", bench_dtd_gemm_tpu)
    chol = secondary("dynamic_cholesky", bench_dynamic_cholesky_gflops)
    raw = secondary("raw_dot", bench_raw_dot_gflops, n=n)
    gemm = _staged("gemm", bench_gemm_gflops, n=n, retries=2)
    if not isinstance(dispatch_us, float):
        dispatch_us = -1.0              # stage degraded
    if "error" in gemm:                 # headline unobtainable: report the
        gemm.update(peak_gflops=1.0, pct_peak=0.0,   # failure, not nothing
                    device_kind="error", n=n, nb=0, seconds=0.0,
                    lowering=gemm["error"])
    # a degraded stage must be DISTINGUISHABLE from a measured zero in
    # the one-line JSON: name -> why, for every stage that errored/skipped
    degraded = {nm: d.get("error") or d.get("skipped")
                for nm, d in (("stencil", stencil),
                              ("lowered_stencil", lsten),
                              ("lowered_cholesky", lchol),
                              ("lowered_lu", llu),
                              ("dynamic_gemm", dyn), ("dtd_gemm", dtd),
                              ("dynamic_cholesky", chol), ("raw_dot", raw),
                              ("gemm", gemm))
                if isinstance(d, dict) and (d.get("error")
                                            or d.get("skipped"))}
    target = 0.70 * gemm["peak_gflops"]
    print(json.dumps({
        "metric": "ptg_tiled_gemm_gflops_per_chip",
        "value": round(gemm["gflops"], 1),
        "unit": "GFLOPS",
        "vs_baseline": round(gemm["gflops"] / target, 4),
        "extra": {
            "pct_peak": round(gemm["pct_peak"], 2),
            "device_kind": gemm["device_kind"],
            "n": gemm["n"],
            "nb": gemm["nb"],
            "gemm_seconds": round(gemm["seconds"], 4),
            "lowering": gemm["lowering"],
            # raw-compiler cross-check: bare jnp.dot at the same config;
            # framework/raw ~ 1.0 means the taskpool lowering costs nothing
            "raw_dot_gflops": round(raw.get("gflops", 0.0), 1),
            "task_dispatch_us": round(dispatch_us, 2),
            "dynamic_gemm_gflops": round(dyn.get("gflops", 0.0), 1),
            "dynamic_gemm_batched": dyn.get("batched_dispatches", 0),
            "dynamic_gemm_breakdown": dyn.get("breakdown", {}),
            "dtd_gemm_tpu_gflops": round(dtd.get("gflops", 0.0), 1),
            "dynamic_cholesky_gflops": round(chol.get("gflops", 0.0), 1),
            "lowered_cholesky_gflops": round(lchol.get("gflops", 0.0), 1),
            "lowered_cholesky_n": lchol.get("n", 0),
            "lowered_lu_gflops": round(llu.get("gflops", 0.0), 1),
            "stencil_gflops": round(stencil.get("gflops", 0.0), 2),
            "lowered_stencil_gflops": round(lsten.get("gflops", 0.0), 1),
            **({"degraded_stages": degraded} if degraded else {}),
        },
    }))


if __name__ == "__main__":
    main()
