#!/usr/bin/env bash
# One-command static gate: style (ruff, when installed) + concurrency lint
# + graph verification over every shipped model (docs/ANALYSIS.md).
#
#   scripts/check.sh            # the full gate
#   scripts/check.sh --fast     # lint only, skip the model-graph sweep
#
# Exit nonzero on the first failing stage.  The same checks run inside the
# default pytest invocation via tests/test_analysis.py (marker: analysis),
# so CI needs nothing beyond tier-1; this script is the local loop.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== ruff (style) =="
if python -m ruff --version >/dev/null 2>&1; then
    python -m ruff check parsec_tpu tests examples
elif command -v ruff >/dev/null 2>&1; then
    ruff check parsec_tpu tests examples
else
    echo "ruff not installed — skipping style stage (config lives in" \
         "pyproject.toml [tool.ruff])"
fi

if [[ "${1:-}" == "--fast" ]]; then
    echo "== runtimelint (concurrency + hygiene) =="
    python -m parsec_tpu.analysis --self-lint
else
    echo "== runtimelint + graphcheck (every shipped model graph) =="
    python -m parsec_tpu.analysis

    echo "== commcheck (static comm-pattern derivation: model sweep" \
         "classified at 4 ranks + built-in invariants) =="
    python -m parsec_tpu.analysis --comm
    python -m parsec_tpu.analysis.commcheck --self-test

    echo "== tracemerge (cross-rank trace stitching self-test) =="
    python -m parsec_tpu.prof.tracemerge --self-test

    echo "== critpath (critical-path attribution self-test: additive" \
         "sweep, overlap_lost, chrome round-trip, DAG, cycle-safety) =="
    python -m parsec_tpu.prof.critpath --self-test

    echo "== perfdb (perf ledger + regression sentinel: EWMA verdicts," \
         "note_result walk, backfill ingest) =="
    python -m parsec_tpu.prof.perfdb --self-test
    python -m pytest tests/test_critpath.py tests/test_perf_smoke.py -q \
        -k "perfdb or critpath" -p no:cacheprovider

    echo "== tune (closed-loop autotuner self-test: quadratic-basin" \
         "search, scoped override restore, tunedb round-trip + ambient" \
         "consult) =="
    python -m parsec_tpu.tune --self-test
    python -m pytest tests/test_tune.py -q -p no:cacheprovider
    python -m pytest tests/test_perf_smoke.py -q -k tune \
        -p no:cacheprovider

    echo "== tracing overhead gate (disabled span path within 10% of" \
         "the overhead baseline; allocation-free; enabled <=1us budget" \
         "at headroom) =="
    python -m pytest tests/test_perf_smoke.py -q -k tracing \
        -p no:cacheprovider
    python -m pytest tests/test_tracing.py -q \
        -k "allocation_free" -p no:cacheprovider

    echo "== prefix-cache trie unit tests (radix tree vs the brute-force" \
         "LCP oracle + LRU/byte-budget eviction + CoW pin semantics) =="
    python -m pytest tests/test_llm_prefix.py -q -k "trie or privatize" \
        -p no:cacheprovider

    echo "== speculative decode unit tests (VERIFY incarnation trios," \
         "spec pools vs the greedy oracle at acceptance 0/partial/1.0," \
         "tail rollback across page boundaries + device-copy" \
         "invalidation) =="
    python -m pytest tests/test_llm_spec.py -q \
        -k "incarnations or rollback or acceptance_sweep or rejected" \
        -p no:cacheprovider

    echo "== sharded serving plane (2-rank acceptance: token-for-token" \
         "oracle-equal decode on both ranks + bucket-exact cross-rank" \
         "SLO metrics merge) =="
    python -m pytest tests/test_serve_sharded.py -q \
        -k "oracle_equal_and_metrics_merge" -p no:cacheprovider

    echo "== llm microbench (smoke: tokens/s through the serving stack," \
         "swept over llm_steps_per_pool — superpool amortization) =="
    python -c 'import json, microbench; \
print(json.dumps(microbench.bench_llm(smoke=True)))'

    echo "== lowering microbench (XLA calls per DAG: dispatch/region/" \
         "wavefront/chain + compile seconds) =="
    python -c 'import json, microbench; \
print(json.dumps(microbench.bench_lowering(smoke=True)))'
fi

echo "check.sh: all stages green"
