#!/usr/bin/env bash
# AOT lowering/compile cache warmer (ISSUE 8): populate the persistent
# lowering + XLA compilation caches BEFORE a bench run, so r06+ TPU
# stages pay deserialization instead of the ~141s compiles that killed
# BENCH_r04/r05 (rc 124).  See docs/PERF.md, "Region lowering & compile
# budgets".
#
#   scripts/warm_cache.sh                        # default workload set
#   scripts/warm_cache.sh cholesky gemm          # named workloads
#   WARM_N=8192 WARM_NB=512 scripts/warm_cache.sh cholesky
#   WARM_MODES=region WARM_BUDGET=120 scripts/warm_cache.sh cholesky
#
# The cache directory is PARSEC_TPU_COMPILE_CACHE_DIR (default
# <tmp>/parsec-tpu-xla-cache) with a per-(jax version, backend) leaf, so
# one dir can be shared by CPU and TPU processes safely.
set -euo pipefail
cd "$(dirname "$0")/.."

# llm_decode_k is the k-step decode superpool's region program (ISSUE 9):
# warming it is what keeps a region-lowered serving path
# (--mca llm_lower_regions 1) from paying XLA at first-token time.
# llm_prefill_tail is the prefix-cache admission shape (ISSUE 11): a
# trie-hit stream prefills only its unmatched tail, and warming that
# pool geometry keeps cache hits from paying cold compile at admission.
# llm_spec_k is the batched speculative superpool (ISSUE 12): warming it
# keeps the spec serving path (--mca llm_spec_k N) from hitting cold XLA
# at first-draft time in bench/tier-1.
WORKLOADS=("$@")
if [[ ${#WORKLOADS[@]} -eq 0 ]]; then
    WORKLOADS=(gemm cholesky lu stencil llm_decode_k llm_spec_k
               llm_prefill_tail)
fi

ARGS=()
[[ -n "${WARM_N:-}" ]] && ARGS+=(--n "$WARM_N")
[[ -n "${WARM_NB:-}" ]] && ARGS+=(--nb "$WARM_NB")
[[ -n "${WARM_NT:-}" ]] && ARGS+=(--nt "$WARM_NT")
[[ -n "${WARM_MODES:-}" ]] && ARGS+=(--modes "$WARM_MODES")
[[ -n "${WARM_BUDGET:-}" ]] && ARGS+=(--budget "$WARM_BUDGET")

for w in "${WORKLOADS[@]}"; do
    echo "== warm: $w ==" >&2
    python -m parsec_tpu.ptg.lowering --warm "$w" "${ARGS[@]}"
done
