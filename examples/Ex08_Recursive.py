"""Ex08: recursive task bodies — a task re-enters the runtime with a
nested taskpool over a finer tiling of its own tile.

Reference ``parsec/recursive.h`` + ``PARSEC_DEV_RECURSIVE``
(``device.h:64``): the body views its RW tile as a
:class:`SubtileCollection`, spawns an inner GEMM taskpool over the
sub-tiles, and detaches (``HOOK_RETURN_ASYNC``); the runtime completes
it — and releases its successors — when the nested pool drains.
"""

import numpy as np

from parsec_tpu.data_dist.matrix import TiledMatrix
from parsec_tpu.models.tiled_gemm import tiled_gemm_recursive_ptg
from parsec_tpu.runtime import Context

N, NB, SUB = 64, 32, 8   # outer 2x2 tiles of 32, inner 4x4 sub-tiles of 8


def main() -> float:
    rng = np.random.default_rng(0)
    a = rng.standard_normal((N, N)).astype(np.float32)
    b = rng.standard_normal((N, N)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a.copy(), NB, NB)
    B = TiledMatrix.from_dense("B", b.copy(), NB, NB)
    C = TiledMatrix.from_dense("C", np.zeros((N, N), np.float32), NB, NB)

    # each outer GEMM(m,n,k) recurses into an 8x8-tile inner GEMM; tiles
    # smaller than min_tile would run the plain CPU chore instead
    tp = tiled_gemm_recursive_ptg(A, B, C, sub_mb=SUB, sub_nb=SUB)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)

    err = float(np.abs(C.to_dense() - a @ b).max())
    print(f"recursive tiled GEMM: max|C - A@B| = {err:.2e}")
    return err


if __name__ == "__main__":
    assert main() < 1e-3
