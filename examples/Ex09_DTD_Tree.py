"""Ex09: runtime task discovery — a tree whose shape the data decides.

Reference ``tests/apps/haar_tree/project_dyn.jdf``: adaptive projection
of a Gaussian onto a Haar basis.  Each PROJECT(n, l) task measures its
local approximation error and, FROM ITS BODY, inserts its two children
when the error is still too large — the task graph is discovered as it
executes (DTD), not enumerated by any front-end.
"""

from parsec_tpu.dtd import DTDTaskpool
from parsec_tpu.models.irregular import (haar_project_dtd,
                                         haar_project_reference)
from parsec_tpu.runtime import Context

ALPHA, THRESH = 1.0, 1e-5


def main() -> int:
    with Context(nb_cores=4) as ctx:
        tp = DTDTaskpool("haar")
        ctx.add_taskpool(tp)
        tree = haar_project_dtd(tp, ALPHA, THRESH, min_depth=4, max_depth=22)
        tp.wait(timeout=120)

    want = haar_project_reference(ALPHA, THRESH, min_depth=4, max_depth=22)
    assert set(tree) == set(want)
    depth = max(n for n, _ in tree)
    print(f"discovered {len(tree)} interior nodes, depth {depth} "
          f"(matches the sequential oracle)")
    return len(tree)


if __name__ == "__main__":
    assert main() > 100
