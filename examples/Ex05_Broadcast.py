"""Ex05: broadcast — one writer task fans out to a reader on every rank.

Reference ``examples/Ex05_Broadcast.jdf``: rank 0's Writer produces a
value; Reader(r) on each rank receives it through one activation that the
comm engine propagates down a binomial tree.
"""

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic

NRANKS = 4


def body_fn(ctx, rank, nranks):
    V = VectorTwoDimCyclic("V", lm=nranks, mb=1, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size, np.float32))
    p = ptg.PTGBuilder("bcast", V=V, NR=nranks)
    w = p.task("W", z=ptg.span(0, 0))
    w.affinity("V", lambda g, l: (0,))
    fw = w.flow("A", ptg.WRITE)
    for r in range(nranks):
        fw.output(succ=("R", "X", lambda g, l, r=r: {"r": r}))

    @w.body
    def wbody(es, task, g, l):
        from parsec_tpu.data.data import data_create
        task.set_flow_data("A", data_create(
            np.full(1, 42.0, np.float32), key=("w", 0)).get_copy(0))

    t = p.task("R", r=ptg.span(0, lambda g, l: g.NR - 1))
    t.affinity("V", lambda g, l: (l.r,))
    t.flow("X", ptg.READ).input(pred=("W", "A", lambda g, l: {"z": 0}))
    fy = t.flow("Y", ptg.RW)
    fy.input(data=("V", lambda g, l: (l.r,)))
    fy.output(data=("V", lambda g, l: (l.r,)))

    @t.body
    def rbody(es, task, g, l):
        y = task.flow_data("Y")
        y.value = np.asarray(task.flow_data("X").value).copy()

    ctx.add_taskpool(p.build())
    ctx.wait(timeout=60)
    ctx.comm_barrier()
    return float(np.asarray(V.data_of(rank).newest_copy().value)[0])


def main() -> list:
    res = run_multirank(NRANKS, body_fn)
    assert res == [42.0] * NRANKS, res
    return res


if __name__ == "__main__":
    print(f"broadcast landed on all {NRANKS} ranks: {main()}")
