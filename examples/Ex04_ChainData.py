"""Ex04: the chain reading/writing the data collection — textual JDF.

Reference ``examples/Ex04_ChainData.jdf``: each task reads its own tile
``A(i)`` from the collection, adds the running value, and writes it back —
direct memory access colocated with task placement.  This is the exit test
of SURVEY §7 step 3: a reference-shaped ``.jdf`` ingested by the textual
front-end.
"""

import numpy as np

from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.ptg.jdf import parse_jdf
from parsec_tpu.runtime import Context

NB = 6

JDF = """
A     [type = data]
NB    [type = int]

Task(i)
  i = 0 .. NB - 1
  : A(i)
  RW  V <- (i == 0) ? A(0) : V Task(i - 1)
        -> (i < NB - 1) ? V Task(i + 1) : A(0)
BODY
  V[...] = V + i
END
"""


def main() -> float:
    coll = DictCollection("A", dtt=TileType((1,), np.float32),
                          init_fn=lambda *k: np.zeros(1, np.float32))
    tp = parse_jdf(JDF, "chaindata").build(A=coll, NB=NB)
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    out = float(coll.data_of(0).newest_copy().value[0])
    assert out == sum(range(NB)), out
    return out


if __name__ == "__main__":
    print(f"chain-data summed 0..{NB - 1} = {main():.0f}")
