"""Ex02: a task chain — one RW flow threaded through ``T(i-1) -> T(i)``.

Reference ``examples/Ex02_Chain.jdf``: NB tasks in a chain, each
incrementing the value it received from its predecessor (the first task
creates it).  Built with the programmatic DSL.
"""

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.runtime import Context

NB = 10


def main() -> float:
    coll = DictCollection("A", dtt=TileType((1,), np.float32),
                          init_fn=lambda *k: np.zeros(1, np.float32))
    p = ptg.PTGBuilder("chain", A=coll, NB=NB)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    f = t.flow("V", ptg.RW)
    f.input(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "V", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "V", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NB - 1)
    f.output(data=("A", lambda g, l: (0,)),
             guard=lambda g, l: l.i == g.NB - 1)

    @t.body
    def body(es, task, g, l):
        v = task.flow_data("V")
        v.value = v.value + 1

    ctx = Context(nb_cores=0)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=30)
    ctx.fini()
    out = float(coll.data_of(0).newest_copy().value[0])
    assert out == NB, out
    return out


if __name__ == "__main__":
    print(f"chain of {NB} tasks counted to {main():.0f}")
