"""Ex03: the chain distributed — task affinity walks the ranks.

Reference ``examples/Ex03_ChainMPI.jdf``: the Ex02 chain where task ``T(i)``
lives on rank ``i % nranks`` (the data collection's ``rank_of``), so the
tile hops rank to rank through the remote-dep protocol.  Runs 4 inproc
ranks over the comm engine — the oversubscribed-MPI analog; pass
``transport="device"`` for the device-backed fabric.
"""

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic

NB = 8
NRANKS = 4


def body_fn(ctx, rank, nranks):
    V = VectorTwoDimCyclic("V", lm=NB, mb=1, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size, np.float32))
    p = ptg.PTGBuilder("chainmpi", V=V, NB=NB)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    t.affinity("V", lambda g, l: (l.i,))      # T(i) runs on rank_of(V(i))
    f = t.flow("A", ptg.RW)
    f.input(data=("V", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "A", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "A", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NB - 1)
    f.output(data=("V", lambda g, l: (0,)),
             guard=lambda g, l: l.i == g.NB - 1)

    @t.body
    def body(es, task, g, l):
        v = task.flow_data("A")
        v.value = np.asarray(v.value) + 1

    ctx.add_taskpool(p.build())
    ctx.wait(timeout=60)
    ctx.comm_barrier()
    if rank == 0:     # V(0) is homed on rank 0
        return float(np.asarray(V.data_of(0).newest_copy().value)[0])
    return None


def main() -> float:
    res = run_multirank(NRANKS, body_fn)
    assert res[0] == NB, res
    return res[0]


if __name__ == "__main__":
    print(f"chain hopped {NRANKS} ranks, counted to {main():.0f}")
