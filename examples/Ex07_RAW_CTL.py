"""Ex07: ordering anti-dependencies explicitly with a CTL arrow.

Reference ``examples/Ex07_RAW_CTL.jdf``: the Ex06 shape, but the updater
must wait until EVERY reader is done — a pure-control arrow from each
``Recv(r)`` to ``Update`` encodes the anti-dependency (write-after-read)
that the data edges alone cannot express.
"""

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.runtime import Context

NREADERS = 4


def main() -> list:
    coll = DictCollection("M", dtt=TileType((1,), np.float32),
                          init_fn=lambda *k: np.zeros(1, np.float32))
    order: list = []
    p = ptg.PTGBuilder("rawctl", M=coll, NR=NREADERS)

    w = p.task("Bcast", k=ptg.span(0, 0))
    fw = w.flow("A", ptg.RW)
    fw.input(data=("M", lambda g, l: (0,)))
    fw.output(succ=("Update", "A", lambda g, l: {"k": 0}))
    for r in range(NREADERS):
        fw.output(succ=("Recv", "A", lambda g, l, r=r: {"r": r}))

    @w.body
    def wbody(es, task, g, l):
        task.flow_data("A").value = np.full(1, 7.0, np.float32)

    t = p.task("Recv", r=ptg.span(0, lambda g, l: g.NR - 1))
    t.flow("A", ptg.READ).input(pred=("Bcast", "A", lambda g, l: {"k": 0}))
    # the WAR edge: tell Update this reader is done
    t.flow("ctl", ptg.CTL).output(
        succ=("Update", "ctl", lambda g, l: {"k": 0}))

    @t.body
    def rbody(es, task, g, l):
        order.append(("read", l.r))

    u = p.task("Update", k=ptg.span(0, 0))
    fu = u.flow("A", ptg.RW)
    fu.input(pred=("Bcast", "A", lambda g, l: {"k": 0}))
    fu.output(data=("M", lambda g, l: (0,)))
    fc = u.flow("ctl", ptg.CTL)
    for r in range(NREADERS):
        fc.input(pred=("Recv", "ctl", lambda g, l, r=r: {"r": r}))

    @u.body
    def ubody(es, task, g, l):
        order.append(("update",))
        a = task.flow_data("A")
        a.value = np.asarray(a.value) * 100

    ctx = Context(nb_cores=0)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=30)
    ctx.fini()
    assert order[-1] == ("update",), order    # CTL held the update back
    assert len(order) == NREADERS + 1
    assert float(coll.data_of(0).newest_copy().value[0]) == 700.0
    return order


if __name__ == "__main__":
    print(f"update ran strictly after {NREADERS} reads: {main()[-1]}")
