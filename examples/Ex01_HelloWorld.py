"""Ex01: one task class, one body — through the textual JDF front-end.

Reference ``examples/Ex01_HelloWorld.jdf``: a single HelloWorld task whose
body runs once.  ``SINK`` shows how build-time globals flow into bodies.
"""

from parsec_tpu.ptg.jdf import parse_jdf
from parsec_tpu.runtime import Context

JDF = """
SINK  [type = int]

HelloWorld(k)
  k = 0 .. 0
BODY
  SINK.append("Hello World from task %d" % k)
END
"""


def main() -> list:
    sink: list = []
    tp = parse_jdf(JDF, "hello").build(SINK=sink)
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    ctx.fini()
    assert sink == ["Hello World from task 0"], sink
    return sink


if __name__ == "__main__":
    print(main()[0])
