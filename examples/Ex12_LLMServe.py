"""Ex12: LLM inference serving — continuous batching on the runtime.

The serving layer's flagship tenant (``parsec_tpu/llm/``,
``docs/LLM.md``): generation *streams* ride a hot
:class:`~parsec_tpu.serve.RuntimeServer` through ``submit_stream``.
Each decode iteration is a fresh PTG taskpool — a ragged per-page
attention chain per live sequence over the paged KV cache
(`PagedKVCollection`) — submitted under the stream's tenant, so WFQ
arbitrates interactive decode against everything else the server runs.
The examples ladder executes this under ``analysis_check=1``: graphcheck
statically verifies every decode step's dataflow (edge symmetry on the
ragged chains, WAR ordering on the tail page, page bounds via the
``has_key`` oracle) on its way into the context.

Self-check: every stream's tokens must equal the dense numpy oracle
(:meth:`ToyLM.reference_generate`) token for token — paging, batching,
and fairness may reorder *work*, never a sequence's own chain.
"""

from parsec_tpu.llm import ToyLM
from parsec_tpu.serve import RuntimeServer

MODEL = ToyLM()
PROMPTS = {
    "pro": [[3, 7, 11, 5], [40, 2, 9, 9, 30]],
    "free": [[1, 22], [8, 30, 22, 8]],
}
NEW_TOKENS = 8


def main() -> dict:
    with RuntimeServer(nb_cores=2,
                       tenant_weights={"pro": 4.0, "free": 1.0}) as server:
        tickets = [(tenant, prompt,
                    server.submit_stream(prompt,
                                         max_new_tokens=NEW_TOKENS,
                                         tenant=tenant))
                   for tenant, prompts in PROMPTS.items()
                   for prompt in prompts]
        for tenant, prompt, tk in tickets:
            r = tk.result(timeout=120)
            want = MODEL.reference_generate(prompt, NEW_TOKENS)
            assert r["tokens"] == want, (tenant, prompt, r["tokens"], want)
        stats = server.stats()
        llm = stats["llm"]
        assert llm["streams_completed"] == 4, llm
        assert llm["tokens_generated"] == 4 * NEW_TOKENS, llm
        # every retired stream's pages went back to the free list
        assert llm["kv"]["physical_pages"] == 0, llm["kv"]
    return stats


if __name__ == "__main__":
    s = main()
    llm = s["llm"]
    print(f"served {llm['streams_completed']} streams / "
          f"{llm['tokens_generated']} tokens in {llm['steps']} batched "
          f"decode iterations; KV pages recycled: "
          f"{llm['kv']['pages_allocated']} allocated -> "
          f"{llm['kv']['free_pages']} free")
