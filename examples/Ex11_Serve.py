"""Ex11: the persistent serving layer — one hot runtime, many clients.

Two tenants share a :class:`RuntimeServer` (the long-lived ``Context``
wrapper, ``parsec_tpu/serve/``): the ``pro`` tenant carries a 4x fair-
share weight and one of its requests a priority bump; a deadline-bounded
request queued behind a full admission window is shed with the typed
:class:`DeadlineExceeded`.  See ``docs/SERVING.md``.
"""

import itertools
import time

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.serve import (AdmissionController, DeadlineExceeded,
                              RuntimeServer)

NB = 6
_uniq = itertools.count()


def chain_request(body_sleep: float = 0.0):
    """One client request: the Ex02 counting chain as a private pool."""
    tag = next(_uniq)
    coll = DictCollection(f"A{tag}", dtt=TileType((1,), np.float32),
                          init_fn=lambda *k: np.zeros(1, np.float32))
    p = ptg.PTGBuilder(f"req{tag}", A=coll, NB=NB)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    f = t.flow("V", ptg.RW)
    f.input(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "V", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "V", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NB - 1)
    f.output(data=("A", lambda g, l: (0,)),
             guard=lambda g, l: l.i == g.NB - 1)

    def body(es, task, g, l):
        if body_sleep:
            time.sleep(body_sleep)
        v = task.flow_data("V")
        v.value = v.value + 1

    t.body(body)
    return p.build(), coll


def main() -> dict:
    stats = {}
    with RuntimeServer(nb_cores=2,
                       tenant_weights={"free": 1.0, "pro": 4.0}) as server:
        # a burst of requests from both tenants, one with a priority bump
        tickets = []
        for i in range(6):
            tp, coll = chain_request()
            tickets.append((server.submit(
                tp, tenant="pro" if i % 2 else "free",
                priority=10 if i == 5 else 0), coll))
        for tk, coll in tickets:
            tk.result(timeout=30)       # THIS submission, not a full drain
            got = float(coll.data_of(0).newest_copy().value[0])
            assert got == NB, got
        stats = server.stats()
        assert stats["completed"] == 6, stats

    # deadline-expired shedding: a 1-slot admission window held by a slow
    # request sheds the deadline-bounded one behind it
    with RuntimeServer(nb_cores=1,
                       admission=AdmissionController(max_inflight=1)
                       ) as server:
        slow, _ = chain_request(body_sleep=0.1)
        holder = server.submit(slow, tenant="free")
        quick, _ = chain_request()
        try:
            server.submit(quick, tenant="free", deadline=0.05)
            raise AssertionError("expected DeadlineExceeded")
        except DeadlineExceeded:
            pass
        holder.result(timeout=30)
        assert server.stats()["admission"]["shed_deadline"] == 1
    return stats


if __name__ == "__main__":
    s = main()
    print(f"served {s['completed']} requests across tenants "
          f"{sorted(s['per_tenant_completed'])}; "
          f"1 deadline-bounded request shed")
