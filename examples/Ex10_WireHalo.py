"""Ex10: partial-tile wire datatypes — halo edges ship ghost regions,
not whole tiles.

Reference ``[type_remote = LR, displ_remote = ...]`` dep properties
(``tests/apps/stencil/stencil_1D.jdf:83-92``; MPI derived datatypes +
``parsec_reshape.c`` underneath): a remote edge tagged with a wire view
moves only the declared sub-block.  Here a ring of ranks exchanges the
edge column of an (MB, NB) tile each step; with ``wire=`` the payload is
MB elements instead of MB*NB, and the byte counters prove it.  The
consumer branches on shape exactly like the reference's
``CORE_copydata_stencil_1D`` displacement logic branches on
local-vs-remote buffers.
"""

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.comm.multirank import run_multirank
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic

MB, NB, STEPS = 32, 64, 4


def _rank_body(ctx, rank, nranks):
    # one tile per rank, in a row: tile j lives on rank j
    M = TwoDimBlockCyclic("M", lm=MB, ln=nranks * NB, mb=MB, nb=NB,
                          P=1, Q=nranks, myrank=rank,
                          init_fn=lambda i, j, s:
                          np.full(s, float(j), np.float32))

    p = ptg.PTGBuilder("ring", M=M, NT=nranks, T=STEPS)
    t = p.task("ST",
               t=ptg.span(0, lambda g, l: g.T - 1),
               j=ptg.span(0, lambda g, l: g.NT - 1))
    t.affinity("M", lambda g, l: (0, l.j))

    fc = t.flow("C", ptg.RW)
    fc.input(data=("M", lambda g, l: (0, l.j)),
             guard=lambda g, l: l.t == 0)
    fc.input(pred=("ST", "C", lambda g, l: {"t": l.t - 1, "j": l.j}),
             guard=lambda g, l: l.t > 0)
    fc.output(succ=("ST", "C", lambda g, l: {"t": l.t + 1, "j": l.j}),
              guard=lambda g, l: l.t < g.T - 1)
    # the halo edge to the right neighbor: ONLY the last column crosses
    # the wire (drop wire= and the full MB x NB tile ships instead)
    fc.output(succ=("ST", "L",
                    lambda g, l: {"t": l.t + 1,
                                  "j": (l.j + 1) % g.NT}),
              guard=lambda g, l: l.t < g.T - 1,
              wire=(slice(None), slice(-1, None)))
    fc.output(data=("M", lambda g, l: (0, l.j)),
              guard=lambda g, l: l.t == g.T - 1)

    fl = t.flow("L", ptg.READ)
    fl.input(pred=("ST", "C",
                   lambda g, l: {"t": l.t - 1,
                                 "j": (l.j - 1) % g.NT}),
             guard=lambda g, l: l.t > 0)

    def body(es, task, g, l):
        c = task.flow_data("C").value
        left = task.flow_data("L")
        if left is not None:
            ghost = np.asarray(left.value)
            # local neighbor hands the full tile; a remote one's payload
            # IS the ghost column (the reference's displacement branch)
            col = ghost if ghost.shape[1] == 1 else ghost[:, -1:]
            c[:, :1] = col

    t.body(body)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=60)
    ctx.comm_barrier()
    tile = np.asarray(M.data_of(0, rank).newest_copy().value)
    # after STEPS-1 exchanges, my first column carries my left
    # neighbor's fill value
    left_val = float((rank - 1) % nranks)
    assert tile[0, 0] == left_val, (rank, tile[0, 0], left_val)
    return ctx.comm_engine.payload_bytes_staged


def main() -> int:
    nranks = 4
    staged = sum(run_multirank(nranks, _rank_body))
    full = MB * NB * 4
    region = MB * 1 * 4
    print(f"ring halo over {nranks} ranks: {staged} payload bytes "
          f"staged ({region}B/edge vs {full}B full tiles — "
          f"{full // region}x cut)")
    assert staged % region == 0 and staged < full
    return staged


if __name__ == "__main__":
    main()
