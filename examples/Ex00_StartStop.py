"""Ex00: runtime lifecycle — init a context, start it, wait, shut down.

The smallest possible program (reference ``examples/Ex00_StartStop.c``):
no taskpool at all, just the `parsec_init` / `parsec_context_start` /
`parsec_context_wait` / `parsec_fini` sequence.
"""

from parsec_tpu.runtime import Context


def main() -> str:
    ctx = Context(nb_cores=0)
    ctx.start()
    ctx.wait()      # nothing enqueued: returns immediately
    ctx.fini()
    return "context lifecycle ok"


if __name__ == "__main__":
    print(main())
