"""Ex06: the read-after-write PROBLEM — an anti-dependency left implicit.

Reference ``examples/Ex06_RAW.jdf``, which "illustrates the Read After
Write problem that might happen when anti-dependencies are present": a
Bcast task hands one datum to several readers AND to an updater that
overwrites it in place.  Nothing orders the readers against the update, so
whether each reader observes 7 or 700 depends on scheduling — the hazard
is real in the reference and real here.  Ex07 fixes it with CTL arrows.
"""

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.runtime import Context

NREADERS = 4


def main() -> tuple:
    coll = DictCollection("M", dtt=TileType((1,), np.float32),
                          init_fn=lambda *k: np.zeros(1, np.float32))
    seen: list = []
    p = ptg.PTGBuilder("raw", M=coll, NR=NREADERS)

    w = p.task("Bcast", k=ptg.span(0, 0))
    fw = w.flow("A", ptg.RW)
    fw.input(data=("M", lambda g, l: (0,)))
    fw.output(succ=("Update", "A", lambda g, l: {"k": 0}))
    for r in range(NREADERS):
        fw.output(succ=("Recv", "A", lambda g, l, r=r: {"r": r}))

    @w.body
    def wbody(es, task, g, l):
        task.flow_data("A").value = np.full(1, 7.0, np.float32)

    u = p.task("Update", k=ptg.span(0, 0))
    fu = u.flow("A", ptg.RW)
    fu.input(pred=("Bcast", "A", lambda g, l: {"k": 0}))
    fu.output(data=("M", lambda g, l: (0,)))

    @u.body
    def ubody(es, task, g, l):
        a = task.flow_data("A")
        a.value = np.asarray(a.value) * 100    # the unordered update

    t = p.task("Recv", r=ptg.span(0, lambda g, l: g.NR - 1))
    t.flow("A", ptg.READ).input(pred=("Bcast", "A", lambda g, l: {"k": 0}))

    @t.body
    def rbody(es, task, g, l):
        seen.append(float(np.asarray(task.flow_data("A").value)[0]))

    ctx = Context(nb_cores=0)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=30)
    ctx.fini()
    assert len(seen) == NREADERS
    assert all(v in (7.0, 700.0) for v in seen), seen
    return seen, float(coll.data_of(0).newest_copy().value[0])


if __name__ == "__main__":
    seen, final = main()
    racy = [v for v in seen if v != 7.0]
    print(f"readers saw {seen} (final={final:.0f})"
          + (f" — {len(racy)} hit the RAW hazard; Ex07 shows the fix"
             if racy else " — no hazard this run, but nothing forbids it"))
