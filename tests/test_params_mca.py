"""Tests for the MCA param system and component registry (SURVEY §5.6, §2.4)."""

import pytest

from parsec_tpu.core.mca import Component, ComponentRepository
from parsec_tpu.core.params import ParamRegistry


class TestParams:
    def test_register_default(self):
        reg = ParamRegistry()
        p = reg.register("runtime_num_cores", 4, "worker thread count")
        assert p.value == 4 and p.source == "default"
        assert reg.get("runtime_num_cores") == 4

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("PARSEC_MCA_sched", "spq")
        reg = ParamRegistry()
        reg.register("sched", "lfq", "scheduler component")
        assert reg.get("sched") == "spq"

    def test_cli_override_beats_env(self, monkeypatch):
        monkeypatch.setenv("PARSEC_MCA_sched", "spq")
        reg = ParamRegistry()
        reg.register("sched", "lfq")
        rest = reg.parse_cmdline(["prog", "--mca", "sched", "gd", "-x"])
        assert rest == ["prog", "-x"]
        assert reg.get("sched") == "gd"

    def test_paramfile(self, tmp_path):
        f = tmp_path / "mca.conf"
        f.write_text("# comment\ncomm_yield_ns = 500\n")
        reg = ParamRegistry()
        reg.parse_paramfile(str(f))
        reg.register("comm_yield_ns", 100)
        assert reg.get("comm_yield_ns") == 500

    def test_typed_conversion(self, monkeypatch):
        monkeypatch.setenv("PARSEC_MCA_device_tpu_enabled", "true")
        reg = ParamRegistry()
        reg.register("device_tpu_enabled", False)
        assert reg.get("device_tpu_enabled") is True

    def test_set_and_readonly(self):
        reg = ParamRegistry()
        reg.register("window", 2048)
        reg.set("window", 16)
        assert reg.get("window") == 16
        reg.register("fixed", 1, read_only=True)
        with pytest.raises(PermissionError):
            reg.set("fixed", 2)

    def test_dump_lists_all(self):
        reg = ParamRegistry()
        reg.register("a", 1, "first")
        reg.register("b", "x", "second")
        d = reg.dump()
        assert "a = 1" in d and "second" in d


class TestMCA:
    def _mk(self, type_name, name, priority, accepts=True):
        class C(Component):
            pass

        c = C()
        c.type_name, c.name, c.priority = type_name, name, priority
        c.query = lambda ctx=None: accepts
        return c

    def test_priority_selection(self):
        repo = ComponentRepository()
        repo.register(self._mk("sched", "low", 5))
        best = self._mk("sched", "high", 20)
        repo.register(best)
        assert repo.query("sched", requested="") is best

    def test_query_skips_rejecting(self):
        repo = ComponentRepository()
        repo.register(self._mk("sched", "broken", 99, accepts=False))
        ok = self._mk("sched", "ok", 1)
        repo.register(ok)
        assert repo.query("sched", requested="") is ok

    def test_explicit_request(self):
        repo = ComponentRepository()
        lo = self._mk("sched", "lo", 1)
        repo.register(lo)
        repo.register(self._mk("sched", "hi", 50))
        assert repo.query("sched", requested="lo") is lo
        with pytest.raises(LookupError):
            repo.query("sched", requested="nope")
