"""The persistent serving layer (parsec_tpu/serve/): concurrent
submission, admission control, fair scheduling, deadlines, drain, and the
live-enqueue context plumbing underneath it (ISSUE 3).

The flagship test drives the acceptance shape: >= 2 tenants submitting
>= 50 mixed cholesky/pingpong/reduction taskpools from >= 4 client
threads into ONE running server, every ticket resolving with a verified
result.
"""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic, VectorTwoDimCyclic
from parsec_tpu.runtime import Context
from parsec_tpu.runtime.context import ContextWaitTimeout
from parsec_tpu.runtime.taskpool import Taskpool
from parsec_tpu.sched.api import SchedulerModule
from parsec_tpu.serve import (AdmissionController, AdmissionRejected,
                              DeadlineExceeded, RuntimeServer,
                              TicketCancelled)
from parsec_tpu.serve.fair import FairScheduler

_uniq = itertools.count()


# ---------------------------------------------------------------------------
# request builders — each returns (taskpool, check_fn)
# ---------------------------------------------------------------------------

def _chain_pool(nb: int = 5, body_sleep: float = 0.0):
    tag = next(_uniq)
    coll = DictCollection(f"chainA{tag}", dtt=TileType((1,), np.float32),
                          init_fn=lambda *k: np.zeros(1, np.float32))
    p = ptg.PTGBuilder(f"chain{tag}", A=coll, NB=nb)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    f = t.flow("V", ptg.RW)
    f.input(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "V", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "V", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NB - 1)
    f.output(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.i == g.NB - 1)

    def body(es, task, g, l):
        if body_sleep:
            time.sleep(body_sleep)
        v = task.flow_data("V")
        v.value = v.value + 1

    t.body(body)

    def check():
        got = float(coll.data_of(0).newest_copy().value[0])
        assert got == nb, (got, nb)

    return p.build(), check


def _cholesky_pool(n: int = 64, nb: int = 32):
    from parsec_tpu.models.cholesky import make_spd, tiled_cholesky_ptg
    a = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense(f"chol{next(_uniq)}", a, nb, nb)
    tp = tiled_cholesky_ptg(A)

    def check():
        got = np.asarray(A.data_of(0, 0).newest_copy().value)
        expect = np.linalg.cholesky(a[:nb, :nb].astype(np.float64))
        err = float(np.max(np.abs(np.tril(got) - expect)))
        assert err < 1e-3, err

    return tp, check


def _pingpong_pool(nt: int = 6):
    from parsec_tpu.models.pingpong import pingpong_ptg
    V = VectorTwoDimCyclic(f"pp{next(_uniq)}", lm=4, mb=4, P=1,
                           init_fn=lambda m, size:
                           np.zeros(size, np.float32))
    tp = pingpong_ptg(V, nt)

    def check():
        got = float(np.asarray(V.data_of(0).newest_copy().value)[0])
        assert got == nt, (got, nt)

    return tp, check


def _reduction_pool(nt: int = 5):
    from parsec_tpu.models.reduction import bt_reduction_ptg
    rng = np.random.default_rng(nt)
    base = rng.standard_normal((nt, 4)).astype(np.float32)
    V = VectorTwoDimCyclic(f"red{next(_uniq)}", lm=nt * 4, mb=4, P=1,
                           init_fn=lambda m, size: base[m, :size].copy())
    tp = bt_reduction_ptg(V)

    def check():
        got = np.asarray(V.data_of(0).newest_copy().value)
        np.testing.assert_allclose(got, base.sum(axis=0), rtol=1e-4,
                                   atol=1e-5)

    return tp, check


_MAKERS = [_chain_pool, _cholesky_pool, _pingpong_pool, _reduction_pool]


# ---------------------------------------------------------------------------
# the acceptance shape: concurrent mixed submission
# ---------------------------------------------------------------------------

def test_concurrent_mixed_submissions_all_tickets_resolve():
    """2 tenants, 4 client threads, 56 mixed pools into one hot server —
    every ticket resolves and every result verifies."""
    server = RuntimeServer(nb_cores=2)
    errors: list[BaseException] = []
    done = []
    lock = threading.Lock()

    def client(cid: int):
        tenant = f"tenant{cid % 2}"
        try:
            for i in range(14):
                tp, check = _MAKERS[(cid + i) % len(_MAKERS)]()
                tk = server.submit(tp, tenant=tenant)
                tk.result(timeout=120)
                check()
                assert tk.state == "done"
                assert tk.latency_s is not None and tk.latency_s >= 0
                with lock:
                    done.append(tenant)
        except BaseException as e:      # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(done) == 56
    s = server.stats()
    assert s["completed"] == 56 and s["failed"] == 0
    assert set(s["per_tenant_completed"]) == {"tenant0", "tenant1"}
    # the fair shim really carried the load (dynamic path, not bypassed)
    assert sum(s["fair_dispatched"].values()) > 0
    server.drain(timeout=60)
    assert not any(t.is_alive() for t in server.context._threads)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_shed_nonblocking_under_budget():
    server = RuntimeServer(
        nb_cores=1, admission=AdmissionController(max_inflight=1))
    slow, _check = _chain_pool(nb=2, body_sleep=0.15)
    tk = server.submit(slow)
    fast, _ = _chain_pool(nb=2)
    with pytest.raises(AdmissionRejected):
        server.submit(fast, block=False)
    tk.result(timeout=30)
    s = server.stats()
    assert s["rejected"] == 1
    assert s["admission"]["rejected"] >= 1
    server.drain(timeout=30)


def test_admission_backpressure_blocks_until_capacity():
    server = RuntimeServer(
        nb_cores=1, admission=AdmissionController(max_inflight=1))
    slow, _ = _chain_pool(nb=2, body_sleep=0.1)
    t_slow = server.submit(slow)
    fast, check = _chain_pool(nb=2)
    t0 = time.monotonic()
    tk = server.submit(fast, block=True)     # waits for the slow one
    blocked = time.monotonic() - t0
    assert blocked >= 0.05, blocked
    tk.result(timeout=30)
    t_slow.result(timeout=30)
    check()
    assert server.stats()["admission"]["blocked_waits"] >= 1
    server.drain(timeout=30)


def test_deadline_expired_submission_is_shed():
    server = RuntimeServer(
        nb_cores=1, admission=AdmissionController(max_inflight=1))
    slow, _ = _chain_pool(nb=2, body_sleep=0.3)
    t_slow = server.submit(slow)
    fast, _ = _chain_pool(nb=2)
    with pytest.raises(DeadlineExceeded):
        server.submit(fast, deadline=0.05)
    assert server.stats()["admission"]["shed_deadline"] == 1
    t_slow.result(timeout=30)
    server.drain(timeout=30)


def test_already_expired_deadline_sheds_even_with_free_budget():
    server = RuntimeServer(nb_cores=1)
    tp, _ = _chain_pool(nb=2)
    with pytest.raises(DeadlineExceeded):
        server.submit(tp, deadline=0.0)   # already late: never starts
    assert server.stats()["admission"]["shed_deadline"] == 1
    server.drain(timeout=30)


def test_admission_cancel_probe_and_ticket_cancel_semantics():
    adm = AdmissionController(max_inflight=1)
    adm.admit("a")
    flag = {"c": False}

    def canceller():
        time.sleep(0.05)
        flag["c"] = True
        adm.kick()

    threading.Thread(target=canceller).start()
    with pytest.raises(TicketCancelled):
        adm.admit("a", cancelled=lambda: flag["c"], timeout=5.0)
    adm.release("a")
    # a ticket that already ran cannot be cancelled
    server = RuntimeServer(nb_cores=1)
    tp, _ = _chain_pool(nb=2)
    tk = server.submit(tp)
    tk.result(timeout=30)
    assert tk.cancel() is False
    server.drain(timeout=30)


def test_submit_after_drain_rejected():
    server = RuntimeServer(nb_cores=1)
    tp, _ = _chain_pool(nb=2)
    server.submit(tp).result(timeout=30)
    server.drain(timeout=30)
    tp2, _ = _chain_pool(nb=2)
    with pytest.raises(AdmissionRejected):
        server.submit(tp2)


# ---------------------------------------------------------------------------
# fair scheduling
# ---------------------------------------------------------------------------

class _StubInner(SchedulerModule):
    name = "stub"

    def __init__(self):
        self.items = []

    def schedule(self, es, tasks, distance=0):
        self.items.extend(tasks)

    def select(self, es):
        return (self.items.pop(0), 0) if self.items else (None, 0)

    def pending_tasks(self, context):
        return len(self.items)


class _FakeSub:
    def __init__(self, tenant, priority=0, deadline_at=None):
        self.tenant = tenant
        self.priority = priority
        self.deadline_at = deadline_at


class _FakeTask:
    __slots__ = ("taskpool", "priority", "tag")

    def __init__(self, sub, tag, priority=0):
        class _TP:          # minimal taskpool stand-in
            pass
        self.taskpool = _TP()
        self.taskpool._serve_sub = sub
        self.priority = priority
        self.tag = tag


def test_fair_scheduler_weighted_share_is_proportional():
    fair = FairScheduler(_StubInner())
    fair.set_weight("heavy", 3.0)
    fair.set_weight("light", 1.0)
    heavy, light = _FakeSub("heavy"), _FakeSub("light")
    fair.schedule(None, [_FakeTask(heavy, f"h{i}") for i in range(40)])
    fair.schedule(None, [_FakeTask(light, f"l{i}") for i in range(40)])
    picks = [fair.select(None)[0].taskpool._serve_sub.tenant
             for _ in range(40)]
    h = picks.count("heavy")
    assert 28 <= h <= 32, picks     # WFQ: 3:1 share within rounding
    # drains completely and falls back to the inner when empty
    rest = [fair.select(None)[0] for _ in range(40)]
    assert all(t is not None for t in rest)
    assert fair.select(None) == (None, 0)


def test_fair_scheduler_inner_nested_work_dispatches_first():
    """Non-serve tasks (nested local_only pools spawned by serve bodies)
    must not be starved behind the tenant queues — they block a parent
    submission that already holds an admission slot."""
    fair = FairScheduler(_StubInner())
    fair.schedule(None, [_FakeTask(_FakeSub("a"), "fair0")])

    class _Plain:
        priority = 0
    plain = _Plain()
    plain.taskpool = type("_TP", (), {})()      # no _serve_sub
    fair.schedule(None, [plain])
    assert fair.select(None)[0] is plain        # nested work first
    assert fair.select(None)[0].tag == "fair0"
    assert fair.select(None) == (None, 0)


def test_fair_scheduler_priority_then_deadline_within_tenant():
    fair = FairScheduler(_StubInner())
    lo = _FakeSub("a", priority=0)
    hi = _FakeSub("a", priority=5)
    soon = _FakeSub("a", priority=0, deadline_at=100.0)
    fair.schedule(None, [_FakeTask(lo, "lo")])
    fair.schedule(None, [_FakeTask(soon, "soon")])
    fair.schedule(None, [_FakeTask(hi, "hi")])
    order = [fair.select(None)[0].tag for _ in range(3)]
    assert order == ["hi", "soon", "lo"]


def test_serve_fair_is_mca_selectable_and_never_double_wrapped():
    """``Context(scheduler="serve_fair")`` yields the shim over the
    best-priority inner module; a RuntimeServer given that context
    reuses it instead of stacking a second shim."""
    ctx = Context(nb_cores=1, scheduler="serve_fair")
    assert isinstance(ctx.scheduler, FairScheduler)
    assert not isinstance(ctx.scheduler.inner, FairScheduler)
    server = RuntimeServer(context=ctx)
    assert server._fair is ctx.scheduler
    tp, check = _chain_pool(nb=3)
    server.submit(tp).result(timeout=30)
    check()
    server.drain(timeout=30)


def test_tenant_fairness_under_saturation():
    """Backlog both tenants on one worker: the 3x-weighted tenant's
    submissions finish markedly earlier than the 1x tenant's."""
    server = RuntimeServer(
        nb_cores=1, tenant_weights={"heavy": 3.0, "light": 1.0},
        admission=AdmissionController(max_inflight=0,
                                      max_tenant_inflight=0))
    completions: list[str] = []
    lock = threading.Lock()

    def noting(tenant):
        def fn(tp):
            with lock:
                completions.append(tenant)
            return tp
        return fn

    tickets = []
    for _i in range(12):
        for tenant in ("heavy", "light"):
            tp, _ = _chain_pool(nb=4, body_sleep=0.001)
            tickets.append(server.submit(tp, tenant=tenant,
                                         result_fn=noting(tenant)))
    for tk in tickets:
        tk.result(timeout=120)
    first = completions[:12]
    assert first.count("heavy") >= first.count("light") + 2, completions
    server.drain(timeout=60)


# ---------------------------------------------------------------------------
# drain / failure / observability
# ---------------------------------------------------------------------------

def test_drain_is_clean_and_flight_recorder_consistent():
    from parsec_tpu.prof import flight_recorder
    from parsec_tpu.prof.pins import PinsEvent
    rec = flight_recorder.ensure_installed()
    assert rec is not None
    c0, _ = rec.aggregate()
    server = RuntimeServer(nb_cores=2)
    for _i in range(5):
        tp, check = _chain_pool(nb=3)
        server.submit(tp).result(timeout=30)
        check()
    workers = list(server.context._threads)
    server.drain(timeout=30)
    assert not any(t.is_alive() for t in workers)
    c1, _ = rec.aggregate()
    d = [c1[i] - c0[i] for i in range(len(c0))]
    assert d[PinsEvent.SERVE_SUBMIT] == 5
    assert d[PinsEvent.SERVE_ADMIT] == 5
    assert d[PinsEvent.SERVE_START] == 5
    assert d[PinsEvent.SERVE_COMPLETE] == 5
    assert d[PinsEvent.SERVE_REJECT] == 0
    assert d[PinsEvent.SERVE_DRAIN] == 1
    # the run report exposes the same tallies (docs/SERVING.md)
    rep = flight_recorder.runtime_report()
    assert rep["serve"]["submitted"] >= 5


def test_drain_timeout_fails_leftover_tickets_and_clears_books(param):
    param("prof_stall_dump", False)
    server = RuntimeServer(nb_cores=1)
    slow, _ = _chain_pool(nb=2, body_sleep=0.6)
    tk = server.submit(slow)
    time.sleep(0.05)                    # let the worker enter the body
    with pytest.raises(ContextWaitTimeout):
        server.drain(timeout=0.1)
    with pytest.raises(ContextWaitTimeout):
        tk.result(timeout=5)            # failed promptly, not hung
    assert server.stats()["inflight"] == 0
    t0 = time.monotonic()
    server.drain(timeout=5)             # re-entry returns, never wedges
    assert time.monotonic() - t0 < 2


def test_exit_on_exception_fails_blocked_clients_promptly():
    got: list[BaseException] = []

    def waiter(tk):
        try:
            tk.result(timeout=30)
        except BaseException as e:      # noqa: BLE001
            got.append(e)

    with pytest.raises(ValueError):
        with RuntimeServer(nb_cores=1) as server:
            slow, _ = _chain_pool(nb=2, body_sleep=0.5)
            th = threading.Thread(target=waiter,
                                  args=(server.submit(slow),))
            th.start()
            raise ValueError("client bug")
    th.join(timeout=5)
    assert not th.is_alive()            # freed long before its 30s timeout
    assert got and isinstance(got[0], RuntimeError)


def test_worker_failure_fails_inflight_tickets_and_poisons_server():
    server = RuntimeServer(nb_cores=1)
    tag = next(_uniq)
    p = ptg.PTGBuilder(f"boom{tag}")
    t = p.task("BOOM", i=ptg.span(0, lambda g, l: 0))
    t.flow("ctl", ptg.CTL)

    def body(es, task, g, l):
        raise ValueError("serving body exploded")

    t.body(body)
    tk = server.submit(p.build())
    with pytest.raises(RuntimeError):
        tk.result(timeout=30)
    assert tk.state == "failed"
    tp2, _ = _chain_pool(nb=2)
    with pytest.raises(AdmissionRejected):
        server.submit(tp2)
    with pytest.raises(RuntimeError):
        server.drain(timeout=10)


# ---------------------------------------------------------------------------
# warm lowering-cache reuse across submissions
# ---------------------------------------------------------------------------

def _gemm_ptg_pool(n=64, nb=32):
    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    rng = np.random.default_rng(3)
    a = rng.standard_normal((n, n)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
    B = TiledMatrix.from_dense("B", a.copy(), nb, nb)
    C = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32), nb, nb)
    return tiled_gemm_ptg(A, B, C)


def test_repeat_lowered_submissions_hit_warm_cache():
    from parsec_tpu.ptg.lowering import lowering_cache
    server = RuntimeServer(nb_cores=1)
    r1 = server.submit_lowered(_gemm_ptg_pool()).result(timeout=120)
    h0 = lowering_cache.hits
    r2 = server.submit_lowered(_gemm_ptg_pool()).result(timeout=120)
    assert lowering_cache.hits - h0 >= 1    # repeat class: no re-compile
    assert set(r1) == set(r2)
    np.testing.assert_allclose(np.asarray(r1["C"]), np.asarray(r2["C"]),
                               rtol=1e-4, atol=1e-4)
    server.drain(timeout=60)


# ---------------------------------------------------------------------------
# the context plumbing: live enqueue + per-taskpool wait
# ---------------------------------------------------------------------------

def test_live_concurrent_add_taskpool_thread_safety():
    """N client threads add_taskpool directly into a RUNNING context —
    the satellite's rank-agreed-id/live-enqueue race.  Every pool
    completes with the right value and the terminated pools are retired
    from the comm-id registry (no long-lived-context leak)."""
    ctx = Context(nb_cores=2)
    ctx.start()
    made = []
    lock = threading.Lock()
    errors = []

    def feeder(k):
        try:
            for _i in range(8):
                tp, check = _chain_pool(nb=4)
                ctx.add_taskpool(tp)
                with lock:
                    made.append((tp, check))
        except BaseException as e:      # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=feeder, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    ctx.wait(timeout=60)
    for tp, check in made:
        assert tp.test()
        check()
    # comm ids were unique (the lock) and retired at termination
    assert len({tp.comm_id for tp, _ in made}) == 32
    assert ctx.taskpool_list == [] and ctx._tp_by_comm_id == {}
    ctx.fini()


def test_wait_taskpool_and_timeout_names_live_pools(param):
    param("prof_stall_dump", False)
    ctx = Context(nb_cores=1)
    never = Taskpool(name="neverending")
    never.termdet_name = "user_trigger"
    ctx.add_taskpool(never)
    fast, check = _chain_pool(nb=3)
    ctx.add_taskpool(fast)
    # one submission awaited without draining the context
    ctx.wait_taskpool(fast, timeout=30)
    assert fast.test() and ctx.test(fast)
    assert not ctx.test()               # the user-trigger pool still lives
    with pytest.raises(ContextWaitTimeout) as ei:
        ctx.wait_taskpool(never, timeout=0.2)
    assert "neverending" in str(ei.value)
    check()
    never.tdm.trigger()
    ctx.wait(timeout=30)
    ctx.fini()
