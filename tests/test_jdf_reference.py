"""Reference-shaped JDF ingestion: translated .jdf files + UD overrides.

Two suites the VERDICT r3 called for:

1. In-tree mechanical translations of reference JDFs
   (``tests/apps/stencil/stencil_1D.jdf``, ``examples/Ex05-07``) parsed by
   the textual front-end and run single- and multi-rank — exercising the
   grammar features those files need: derived locals, range arrows
   (fan-out AND counted CTL fan-in), NULL else-branches.
2. The user-defined override family (``jdf.h:185-210``):
   ``nb_local_tasks_fn``, ``make_key_fn``, ``find_deps_fn``,
   ``hash_struct``, ``startup_fn``, per-pool ``termdet``, body
   ``evaluate``, and ``SIMCOST`` (``parsec.y:635-641``) — mirroring
   ``tests/dsl/ptg/user-defined-functions/udf.jdf``.
"""

import pathlib

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic, VectorTwoDimCyclic
from parsec_tpu.models.stencil import stencil_reference
from parsec_tpu.runtime import Context, UserTriggerTermDet

JDF_DIR = pathlib.Path(__file__).resolve().parent.parent / "examples" / "jdf"


# ---------------------------------------------------------------------------
# translated stencil
# ---------------------------------------------------------------------------

def _stencil_desc(nranks, rank, MB, NB, LMT, LNT, R, seed=0):
    """LMT x LNT buffer tiles of (MB, NB); interior random, ghosts zero."""
    rng = np.random.default_rng(seed)
    interior = rng.standard_normal((MB, LNT * (NB - 2 * R))).astype(np.float32)

    def init(m, n, shape):
        tile = np.zeros(shape, np.float32)
        if m == 0:  # generation-0 state lives in buffer row 0
            w = NB - 2 * R
            tile[:, R:NB - R] = interior[:, n * w:(n + 1) * w]
        return tile

    desc = TwoDimBlockCyclic(
        "descA", lm=LMT * MB, ln=LNT * NB, mb=MB, nb=NB,
        P=1, Q=nranks, myrank=rank, init_fn=init)
    return desc, interior


def _stencil_oracle(interior, W, iters):
    return np.stack([stencil_reference(row, np.asarray(W, np.float64), iters)
                     for row in interior])


def _gather_interior(desc, MB, NB, LNT, R, t, LMT):
    m = t % LMT
    cols = []
    for n in range(LNT):
        tile = np.asarray(desc.data_of(m, n).newest_copy().value)
        cols.append(tile[:, R:NB - R])
    return np.concatenate(cols, axis=1)


def test_translated_stencil_single_rank():
    MB, NB, LMT, LNT, R, iters = 3, 8, 2, 4, 2, 5
    desc, interior = _stencil_desc(1, 0, MB, NB, LMT, LNT, R)
    W = np.array([0.05, 0.2, 0.5, 0.2, 0.05])
    jdf = ptg.load_jdf(JDF_DIR / "stencil_1D.jdf")
    tp = jdf.build(descA=desc, iter=iters, R=R, W=W, LMT=LMT, LNT=LNT)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    got = _gather_interior(desc, MB, NB, LNT, R, iters, LMT)
    want = _stencil_oracle(interior, W, iters)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_translated_stencil_matches_programmatic():
    """The translated reference JDF and the repo's programmatic stencil
    produce the same trajectory (1-row tiles -> identical 1-D problem)."""
    from parsec_tpu.models.stencil import stencil_1d_ptg
    MB, R, iters = 1, 1, 4
    NB, LMT, LNT = 6, 2, 3
    desc, interior = _stencil_desc(1, 0, MB, NB, LMT, LNT, R, seed=3)
    W = np.array([0.25, 0.5, 0.25])
    jdf = ptg.load_jdf(JDF_DIR / "stencil_1D.jdf")
    tp = jdf.build(descA=desc, iter=iters, R=R, W=W, LMT=LMT, LNT=LNT)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    got = _gather_interior(desc, MB, NB, LNT, R, iters, LMT)[0]

    n = interior.shape[1]
    V = VectorTwoDimCyclic("V", lm=n, mb=NB - 2 * R, P=1,
                           init_fn=lambda m, size:
                           interior[0, m * (NB - 2 * R):
                                    m * (NB - 2 * R) + size])
    tp2 = stencil_1d_ptg(V, W, iters)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp2)
        ctx.wait(timeout=120)
    prog = np.concatenate([
        np.asarray(V.data_of(i).newest_copy().value) for i in range(V.mt)])
    np.testing.assert_allclose(got, prog, rtol=1e-4, atol=1e-5)


def _stencil_rank_body(ctx, rank, nranks):
    MB, NB, LMT, LNT, R, iters = 2, 8, 2, 8, 2, 4
    desc, interior = _stencil_desc(nranks, rank, MB, NB, LMT, LNT, R, seed=1)
    W = np.array([0.1, 0.2, 0.4, 0.2, 0.1])
    jdf = ptg.load_jdf(JDF_DIR / "stencil_1D.jdf")
    tp = jdf.build(descA=desc, iter=iters, R=R, W=W, LMT=LMT, LNT=LNT)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=180)
    ctx.comm_barrier()
    want = _stencil_oracle(interior, W, iters)
    m = iters % LMT
    w = NB - 2 * R
    for n in range(LNT):
        if desc.rank_of(m, n) != rank:
            continue
        tile = np.asarray(desc.data_of(m, n).newest_copy().value)
        np.testing.assert_allclose(tile[:, R:NB - R],
                                   want[:, n * w:(n + 1) * w],
                                   rtol=1e-4, atol=1e-5)
    return True


@pytest.mark.parametrize("nranks", [4, 8])
def test_translated_stencil_multirank(nranks):
    assert all(run_multirank(nranks, _stencil_rank_body))


# ---------------------------------------------------------------------------
# translated Ex05-07
# ---------------------------------------------------------------------------

def _mydata(nranks, rank, nodes, NB=6):
    return VectorTwoDimCyclic("mydata", lm=nodes + NB + 1, mb=1,
                              P=nranks, myrank=rank, dtype=np.int32,
                              init_fn=lambda m, size: np.zeros(size,
                                                               np.int32))


def _ex_rank_body_factory(fname, check):
    def body(ctx, rank, nranks):
        nodes = nranks
        md = _mydata(nranks, rank, nodes)
        jdf = ptg.load_jdf(JDF_DIR / fname)
        tp = jdf.build(mydata=md, nodes=nodes)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
        ctx.comm_barrier()
        return check(md, rank, nranks)
    return body


def _check_ex05(md, rank, nranks):
    return True   # the Recv assertions inside the bodies are the test


def _check_ex0607(md, rank, nranks):
    for k in range(nranks):
        if md.rank_of(k) == rank:
            v = int(np.asarray(md.data_of(k).newest_copy().value)[0])
            assert v == -k - 1, (k, v)
    return True


def test_ex05_broadcast_single_rank():
    md = _mydata(1, 0, nodes=3)
    jdf = ptg.load_jdf(JDF_DIR / "Ex05_Broadcast.jdf")
    tp = jdf.build(mydata=md, nodes=3)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)


@pytest.mark.parametrize("fname,check", [
    ("Ex05_Broadcast.jdf", _check_ex05),
    ("Ex06_RAW.jdf", _check_ex0607),
    ("Ex07_RAW_CTL.jdf", _check_ex0607),
])
def test_ex_multirank(fname, check):
    assert all(run_multirank(4, _ex_rank_body_factory(fname, check)))


def test_ex07_ctl_join_single_rank():
    """The counted CTL fan-in: with the join in place every Recv observes
    the pre-update value even single-rank multi-worker."""
    md = _mydata(1, 0, nodes=4)
    jdf = ptg.load_jdf(JDF_DIR / "Ex07_RAW_CTL.jdf")
    tp = jdf.build(mydata=md, nodes=4)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    for k in range(4):
        assert int(np.asarray(md.data_of(k).newest_copy().value)[0]) == -k - 1


# ---------------------------------------------------------------------------
# UD overrides (udf.jdf mirror)
# ---------------------------------------------------------------------------

_UD_JDF = """
%{
calls = {"nb": 0, "key": 0, "deps": 0, "hash": 0, "startup": 0, "eval": 0}

def ud_nb_local_tasks(tp):
    calls["nb"] += 1
    return 2 * tp.globals.N    # N tasks in each of the two classes

def ud_make_key(g, l):
    calls["key"] += 1
    return l.i * 1000 + 7

def ud_find_deps(tp, g, l):
    calls["deps"] += 1
    return ("CHAIN", l.i)

def ud_key_hash(key):
    calls["hash"] += 1
    return hash(key) ^ 0x5bd1e995

def never_here(es, task):
    calls["eval"] += 1
    from parsec_tpu.runtime import HOOK_RETURN_NEXT
    return HOOK_RETURN_NEXT

def ud_startup(tp, context, g):
    calls["startup"] += 1
    return [{"i": i} for i in range(g.N)]
%}

%option nb_local_tasks_fn = ud_nb_local_tasks

V [type = data]
N [type = int]
out [type = object]
ud_hs [type = object]

CHAIN(i) [make_key_fn = ud_make_key  find_deps_fn = ud_find_deps  hash_struct = ud_hs]
  i = 0 .. N - 1
  SIMCOST i + 1
  : V(0)
  RW A <- (i == 0) ? V(0) : A CHAIN(i-1)
       -> (i < N - 1) ? A CHAIN(i+1) : V(0)
BODY [evaluate = never_here]
  out.append(("never", i))
END
BODY
  A[...] += 1
  out.append(("chain", i))
END

FREE(i) [startup_fn = ud_startup]
  i = 0 .. N - 1
  : V(0)
  READ X <- V(0)
BODY
  out.append(("free", i))
END
"""


def test_ud_overrides_full_family():
    from parsec_tpu.runtime.task import KeyHashStruct
    out = []
    N = 5
    V = VectorTwoDimCyclic("V", lm=4, mb=4,
                           init_fn=lambda m, size: np.zeros(size))
    jdf = ptg.parse_jdf(_UD_JDF, "udf")
    ns = {}
    # hash_struct must resolve via build() bindings: pass a KeyHashStruct
    hs_calls = []
    hs = KeyHashStruct(key_hash=lambda k: hs_calls.append(k) or hash(k),
                       key_print=lambda k: f"<udkey {k}>")
    tp = jdf.build(V=V, N=N, out=out, ud_hs=hs)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)

    calls = jdf_prologue_calls(jdf)
    # nb_local_tasks_fn replaced the space scan
    assert calls["nb"] == 1
    # the chain ran in order, through the UD keys/deps/hash
    chain = [i for tag, i in out if tag == "chain"]
    assert chain == list(range(N))
    assert calls["key"] > 0 and calls["deps"] > 0
    assert hs_calls, "user key_hash never consulted"
    # the evaluate hook skipped the first body every time
    assert calls["eval"] == N
    assert not [1 for tag, _ in out if tag == "never"]
    # UD startup enumerated FREE itself
    assert calls["startup"] == 1
    assert sorted(i for tag, i in out if tag == "free") == list(range(N))
    # SIMCOST critical path: chain costs 1+2+...+N
    assert tp.largest_simulation_date == pytest.approx(N * (N + 1) / 2)
    # final chain value wrote back
    assert np.asarray(V.data_of(0).newest_copy().value)[0] == N


def jdf_prologue_calls(jdf):
    """Re-exec the prologue to reach its namespace?  No — bodies closed
    over the ORIGINAL namespace; expose it through a probe build."""
    # The prologue dict is shared by reference inside the built pool's
    # bodies; simplest access: parse_jdf keeps sources, but build() made a
    # fresh ns.  Instead, stash: JDF.build stores the last namespace.
    return jdf._last_ns["calls"]


def test_per_pool_termdet_option():
    src = """
%option termdet = user_trigger
V [type = data]
T(i)
  i = 0 .. 0
  : V(0)
  READ X <- V(0)
BODY
  pass
END
"""
    V = VectorTwoDimCyclic("V", lm=1, mb=1,
                           init_fn=lambda m, size: np.zeros(size))
    jdf = ptg.parse_jdf(src, "td")
    tp = jdf.build(V=V)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        assert isinstance(tp.tdm, UserTriggerTermDet)
        assert not tp.test()       # tasks done but only trigger() terminates
        tp.tdm.trigger()
        ctx.wait(timeout=60)
    assert tp.test()


def test_empty_ranged_fanin_runs_immediately():
    """An active ranged CTL input whose range is EMPTY for these locals
    expects zero arrivals — the task must start, not hang (review r4)."""
    ran = []
    src = """
V [type = data]
out [type = object]
P(i)
  i = 0 .. K - 1
  : V(0)
  CTL c -> c J(0)
BODY
  pass
END
J(z)
  z = 0 .. 0
  : V(0)
  CTL c <- c P(0 .. K - 1)
BODY
  out.append("ran")
END
"""
    V = VectorTwoDimCyclic("V", lm=1, mb=1,
                           init_fn=lambda m, size: np.zeros(size))
    jdf = ptg.parse_jdf("K [type = int]\n" + src, "empty")
    tp = jdf.build(V=V, out=ran, K=0)     # K=0: P's space AND the range empty
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert ran == ["ran"]


def test_dsl_rejects_ranged_data_flow():
    p = ptg.PTGBuilder("bad", N=2)
    t = p.task("T", i=ptg.span(0, 1))
    f = t.flow("X", ptg.READ)
    with pytest.raises(ValueError, match="CTL-only"):
        f.input(pred=("T", "X", lambda g, l: ({"i": 0}, {"i": 1})),
                ranged=True)


def test_ud_jdf_errors():
    with pytest.raises(ptg.JDFError, match="unknown %option"):
        ptg.parse_jdf("%option bogus_fn = x\nV [type = data]\n",
                      "e").build(V=1)
    with pytest.raises(ptg.JDFError, match="does not name"):
        src = """
V [type = data]
T(i) [make_key_fn = missing_fn]
  i = 0 .. 0
  : V(0)
  READ X <- V(0)
BODY
  pass
END
"""
        ptg.parse_jdf(src, "e2").build(V=1)
    with pytest.raises(ptg.JDFError, match="CTL-only"):
        src = """
V [type = data]
A(i)
  i = 0 .. 3
  : V(0)
  RW X <- V(0)
BODY
  pass
END
B(i)
  i = 0 .. 0
  : V(0)
  READ X <- X A(0 .. 3)
BODY
  pass
END
"""
        ptg.parse_jdf(src, "e3").build(V=1)
    with pytest.raises(ptg.JDFError, match="SIMCOST needs"):
        ptg.parse_jdf("V [type = data]\nT(i)\n  i = 0 .. 0\n  SIMCOST\n",
                      "e4")
