"""Compiled-DAG executor (runtime/dagrun.py): the native inner loop.

Adversarial strategy: every test runs the same taskpool twice — once with
``runtime_dag_compile`` on (native select→release) and once forced dynamic —
and asserts identical results.  The compiled path is an incarnation of the
scheduler, so its only observable difference must be speed.
"""

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.core.params import params
from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.runtime import Context
from parsec_tpu.runtime.dagrun import (CompiledDag, VecCompiledDag,
                                       compile_taskpool_dag)


def ep_pool(NT=8, DEPTH=5, trace=None):
    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l:
           trace.append((l.d, l.n)) if trace is not None else None)
    return p.build()


def run_pool(tp, **ctx_kw):
    ctx = Context(**ctx_kw)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.fini()


@pytest.fixture
def dynamic_only():
    old = params.get("runtime_dag_compile")
    params.set("runtime_dag_compile", False)
    yield
    params.set("runtime_dag_compile", old)


class TestVectorPath:
    def test_ep_compiles_vectorized(self):
        tp = ep_pool()
        ctx = Context(nb_cores=0)
        dag = compile_taskpool_dag(tp, ctx)
        assert isinstance(dag, VecCompiledDag)
        assert dag.ntasks == 8 * 5
        ctx.fini()

    def test_ep_executes_every_task_once(self):
        trace = []
        run_pool(ep_pool(trace=trace), nb_cores=0)
        assert sorted(trace) == [(d, n) for d in range(5) for n in range(8)]

    def test_dependency_order_respected(self):
        trace = []
        run_pool(ep_pool(trace=trace), nb_cores=0)
        pos = {t: i for i, t in enumerate(trace)}
        for d in range(1, 5):
            for n in range(8):
                assert pos[(d - 1, n)] < pos[(d, n)], \
                    f"EP({d},{n}) ran before its predecessor"

    def test_threaded_context_drives_compiled_pool(self):
        trace = []
        run_pool(ep_pool(trace=trace), nb_cores=2)
        assert len(trace) == 40

    def test_matches_dynamic(self, dynamic_only):
        trace = []
        run_pool(ep_pool(trace=trace), nb_cores=0)
        assert sorted(trace) == [(d, n) for d in range(5) for n in range(8)]


class TestScalarPath:
    def chain_pool(self, coll, n=6):
        """RW chain over one tile: T(0) -> T(1) -> ... each adds 1."""
        p = ptg.PTGBuilder("chain", N=n, A=coll)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        f = t.flow("V", ptg.RW)
        f.input(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
        f.input(pred=("T", "V", lambda g, l: {"i": l.i - 1}),
                guard=lambda g, l: l.i > 0)
        f.output(succ=("T", "V", lambda g, l: {"i": l.i + 1}),
                 guard=lambda g, l: l.i < g.N - 1)
        f.output(data=("A", lambda g, l: (0,)),
                 guard=lambda g, l: l.i == g.N - 1)

        @t.body
        def body(es, task, g, l):
            c = task.flow_data("V")
            c.value = c.value + 1

        return p.build()

    def test_data_chain_compiles_scalar(self):
        coll = DictCollection("A", dtt=TileType((2,), np.float32),
                              init_fn=lambda *k: np.zeros(2, np.float32))
        tp = self.chain_pool(coll)
        ctx = Context(nb_cores=0)
        dag = compile_taskpool_dag(tp, ctx)
        assert isinstance(dag, CompiledDag) and dag.ntasks == 6
        ctx.fini()

    def test_data_chain_result(self):
        coll = DictCollection("A", dtt=TileType((2,), np.float32),
                              init_fn=lambda *k: np.zeros(2, np.float32))
        run_pool(self.chain_pool(coll), nb_cores=0)
        assert coll.data_of(0).newest_copy().value[0] == 6

    def test_data_chain_matches_dynamic(self, dynamic_only):
        coll = DictCollection("A", dtt=TileType((2,), np.float32),
                              init_fn=lambda *k: np.zeros(2, np.float32))
        run_pool(self.chain_pool(coll), nb_cores=0)
        assert coll.data_of(0).newest_copy().value[0] == 6

    def test_priority_pool_takes_scalar_path(self):
        p = ptg.PTGBuilder("prio", N=4)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        t.flow("ctl", ptg.CTL).output(
            succ=("U", "ctl", lambda g, l: {"i": l.i}))
        t.priority(lambda g, l: l.i)
        t.body(lambda es, task, g, l: None)
        u = p.task("U", i=ptg.span(0, lambda g, l: g.N - 1))
        u.flow("ctl", ptg.CTL).input(
            pred=("T", "ctl", lambda g, l: {"i": l.i}))
        u.body(lambda es, task, g, l: None)
        tp = p.build()
        ctx = Context(nb_cores=0)
        dag = compile_taskpool_dag(tp, ctx)
        assert isinstance(dag, CompiledDag)   # priority -> scalar builder
        ctx.fini()
        run_pool(tp, nb_cores=0)

    def test_triangular_space_takes_scalar_path(self):
        """Dependent ranges (l.i bound in l.j's range) resist vectorizing."""
        seen = []
        p = ptg.PTGBuilder("tri", N=5)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1),
                   j=ptg.span(0, lambda g, l: l.i))
        t.flow("ctl", ptg.CTL)
        t.body(lambda es, task, g, l: seen.append((l.i, l.j)))
        tp = p.build()
        ctx = Context(nb_cores=0)
        dag = compile_taskpool_dag(tp, ctx)
        assert isinstance(dag, CompiledDag) and dag.ntasks == 15
        ctx.fini()
        run_pool(tp, nb_cores=0)
        assert sorted(seen) == [(i, j) for i in range(5)
                                for j in range(i + 1)]


class TestHookProtocol:
    def test_again_is_retried(self):
        from parsec_tpu.runtime.task import HOOK_RETURN_AGAIN
        attempts = {}

        p = ptg.PTGBuilder("again", N=6)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        t.flow("ctl", ptg.CTL)

        @t.body
        def body(es, task, g, l):
            k = attempts.get(l.i, 0)
            attempts[l.i] = k + 1
            if k < 2:
                return HOOK_RETURN_AGAIN
            return None

        run_pool(p.build(), nb_cores=0)
        assert all(v == 3 for v in attempts.values())

    def test_again_with_batch_overflow(self):
        """Retry merge must not overflow the fixed completion buffer: a
        >1024-wide wavefront plus a carried AGAIN task in one pass."""
        from parsec_tpu.runtime.task import HOOK_RETURN_AGAIN
        state = {"again": True, "ran": 0}

        p = ptg.PTGBuilder("wide", N=2200)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        t.flow("ctl", ptg.CTL)

        @t.body
        def body(es, task, g, l):
            state["ran"] += 1
            if l.i == 0 and state["again"]:
                state["again"] = False
                return HOOK_RETURN_AGAIN
            return None

        run_pool(p.build(), nb_cores=0)
        assert state["ran"] == 2201   # 2200 tasks + one retry

    def test_wait_timeout_leaves_pool_resumable(self):
        import time as _t
        p = ptg.PTGBuilder("slow", N=30)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        f = t.flow("ctl", ptg.CTL)   # chain: one task per wavefront, so
        f.input(pred=("T", "ctl", lambda g, l: {"i": l.i - 1}),
                guard=lambda g, l: l.i > 0)   # the per-batch deadline bites
        f.output(succ=("T", "ctl", lambda g, l: {"i": l.i + 1}),
                 guard=lambda g, l: l.i < g.N - 1)
        t.body(lambda es, task, g, l: _t.sleep(0.01))
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(p.build())
        with pytest.raises(TimeoutError):
            ctx.wait(timeout=0.05)
        ctx.wait(timeout=30)   # resumes and finishes
        ctx.fini()

    def test_body_exception_does_not_wedge_fini(self):
        p = ptg.PTGBuilder("boom", N=3)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        t.flow("ctl", ptg.CTL)

        def body(es, task, g, l):
            raise ValueError("body failure")
        t.body(body)

        ctx = Context(nb_cores=0)
        ctx.add_taskpool(p.build())
        with pytest.raises(ValueError):
            ctx.wait(timeout=30)
        ctx.fini()   # must not hang on the aborted pool


class TestFallbacks:
    def test_device_chore_falls_back_to_dynamic(self):
        p = ptg.PTGBuilder("dev", N=2)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        t.flow("ctl", ptg.CTL)
        t.body(lambda es, task, g, l: None)
        t.body(device="tpu", dyld="nonexistent_kernel")
        tp = p.build()
        ctx = Context(nb_cores=0)
        assert compile_taskpool_dag(tp, ctx) is None
        ctx.fini()

    def test_multirank_falls_back(self):
        tp = ep_pool()
        ctx = Context(nb_cores=0)
        ctx.nb_ranks = 2   # simulate distributed: release must route remote
        assert compile_taskpool_dag(tp, ctx) is None
        ctx.nb_ranks = 1
        ctx.fini()

    def test_pins_active_still_compiles_and_fires_events(self):
        """Round-4 contract flip: PINS no longer forces the dynamic
        fallback — the fast path compiles AND emits per-task EXEC plus
        batch-granular DAG_FETCH/DAG_COMPLETE events."""
        from parsec_tpu.prof import pins
        execs, batches = [], []
        cb_e = lambda es, t: execs.append(t.uid)
        cb_b = lambda es, n: batches.append(n)
        pins.register(pins.PinsEvent.EXEC_BEGIN, cb_e)
        pins.register(pins.PinsEvent.DAG_COMPLETE_END, cb_b)
        try:
            tp = ep_pool()
            ctx = Context(nb_cores=0)
            assert compile_taskpool_dag(tp, ctx) is not None
            ctx.fini()
            run_pool(ep_pool(), nb_cores=0)
        finally:
            pins.unregister(pins.PinsEvent.EXEC_BEGIN, cb_e)
            pins.unregister(pins.PinsEvent.DAG_COMPLETE_END, cb_b)
        assert sorted(execs) == list(range(8 * 5))   # every task observed
        assert batches and sum(batches) == 8 * 5     # batch sizes accounted

    def test_param_gate(self, dynamic_only):
        tp = ep_pool()
        ctx = Context(nb_cores=0)
        assert compile_taskpool_dag(tp, ctx) is None
        ctx.fini()
