"""Runtime-core tests: hand-written and PTG DAGs through the full
scheduling loop (analog of reference tests/runtime/ + examples Ex00-Ex04)."""

import threading

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.data import TileType
from parsec_tpu.data_dist import DictCollection
from parsec_tpu.runtime import (Chore, Context, Dep, Flow, Task, TaskClass,
                                Taskpool, compose)


def make_chain_ptg(N, coll, trace=None):
    """Ex04_ChainData shape: T(0..N-1), one datum threading through."""
    p = ptg.PTGBuilder("chain", N=N, A=coll)
    t = p.task("T", k=ptg.span(0, lambda g, l: g.N - 1))
    t.affinity("A", lambda g, l: (0,))
    f = t.flow("A", ptg.RW)
    f.input(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.k == 0)
    f.input(pred=("T", "A", lambda g, l: {"k": l.k - 1}),
            guard=lambda g, l: l.k > 0)
    f.output(succ=("T", "A", lambda g, l: {"k": l.k + 1}),
             guard=lambda g, l: l.k < g.N - 1)
    f.output(data=("A", lambda g, l: (0,)), guard=lambda g, l: l.k == g.N - 1)

    @t.body
    def body(es, task, g, l):
        copy = task.flow_data("A")
        copy.value = copy.value + 1
        if trace is not None:
            trace.append(l.k)

    return p.build()


class TestStartStop:
    def test_init_fini(self):
        # Ex00_StartStop: init + fini with no taskpool
        ctx = Context(nb_cores=0)
        ctx.start()
        ctx.wait()
        ctx.fini()

    def test_repeated_init_fini(self):
        for _ in range(3):
            ctx = Context(nb_cores=0)
            ctx.fini()


class TestChain:
    @pytest.mark.parametrize("nb_cores", [0, 2])
    def test_chain_data_updates_in_order(self, nb_cores):
        N = 16
        coll = DictCollection("A", dtt=TileType((4,), np.float32))
        trace = []
        tp = make_chain_ptg(N, coll, trace)
        ctx = Context(nb_cores=nb_cores)
        ctx.add_taskpool(tp)
        ctx.start()
        tp.wait(timeout=30)
        ctx.fini()
        assert trace == list(range(N))  # strict chain order
        np.testing.assert_allclose(coll.data_of(0).newest_copy().value,
                                   np.full((4,), N, np.float32))

    def test_two_taskpools_same_context(self):
        c1 = DictCollection("A", dtt=TileType((2,), np.float32))
        c2 = DictCollection("B", dtt=TileType((2,), np.float32))
        tp1, tp2 = make_chain_ptg(5, c1), make_chain_ptg(7, c2)
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp1)
        ctx.add_taskpool(tp2)
        ctx.wait(timeout=30)
        ctx.fini()
        assert c1.data_of(0).newest_copy().value[0] == 5
        assert c2.data_of(0).newest_copy().value[0] == 7

    def test_compound_sequential_composition(self):
        coll = DictCollection("A", dtt=TileType((2,), np.float32))
        order = []
        tps = []
        for i in range(3):
            trace = []
            tp = make_chain_ptg(4, coll, trace)
            tp.on_complete = (lambda i: lambda _tp: order.append(i))(i)
            tps.append(tp)
        comp = compose(*tps)
        ctx = Context(nb_cores=2)
        ctx.add_taskpool(comp)
        ctx.start()
        comp.wait(timeout=30)
        ctx.fini()
        assert order == [0, 1, 2]
        assert coll.data_of(0).newest_copy().value[0] == 12


class TestBranchingAndGuards:
    def test_fork_join_diamond(self):
        """A(0) -> B,C (fork) -> D (join): guarded multi-out, multi-in."""
        coll = DictCollection("X", dtt=TileType((1,), np.float32),
                              init_fn=lambda *k: np.zeros(1, np.float32))
        p = ptg.PTGBuilder("diamond", X=coll)
        a = p.task("A", i=lambda g, l: range(1))
        fa = a.flow("V", ptg.RW)
        fa.input(data=("X", lambda g, l: (0,)))
        fa.output(succ=("B", "V", lambda g, l: {"i": 0}))
        fa.output(succ=("C", "V", lambda g, l: {"i": 0}))

        @a.body
        def abody(es, task, g, l):
            c = task.flow_data("V")
            c.value = c.value + 1

        results = {}
        for name, add in (("B", 10), ("C", 100)):
            t = p.task(name, i=lambda g, l: range(1))
            fl = t.flow("V", ptg.READ)
            fl.input(pred=("A", "V", lambda g, l: {"i": 0}))
            ctl = t.flow("done", ptg.CTL)
            ctl.output(succ=("D", "start", lambda g, l: {"i": 0}))

            def mk(nm, addv):
                def b(es, task, g, l):
                    results[nm] = float(task.flow_data("V").value[0]) + addv
                return b

            t.body(mk(name, add))
        d = p.task("D", i=lambda g, l: range(1))
        ctl_in = d.flow("start", ptg.CTL)
        ctl_in.input(pred=("B", "done", lambda g, l: {"i": 0}))
        ctl_in.input(pred=("C", "done", lambda g, l: {"i": 0}))

        joined = []

        @d.body
        def dbody(es, task, g, l):
            joined.append(sorted(results.values()))

        tp = p.build()
        ctx = Context(nb_cores=2)
        ctx.add_taskpool(tp)
        ctx.start()
        tp.wait(timeout=30)
        ctx.fini()
        assert joined == [[11.0, 101.0]]

    def test_guard_excludes_dep(self):
        """Guarded outputs only fire when the predicate holds (branching)."""
        coll = DictCollection("X", dtt=TileType((1,), np.float32))
        seen = []
        p = ptg.PTGBuilder("branch", N=6, X=coll)
        t = p.task("T", k=ptg.span(0, lambda g, l: g.N - 1))
        f = t.flow("V", ptg.RW)
        f.input(data=("X", lambda g, l: (l.k,)))
        # only even k notify the sink
        ctl = t.flow("c", ptg.CTL)
        ctl.output(succ=("S", "in_", lambda g, l: {"k": l.k}),
                   guard=lambda g, l: l.k % 2 == 0)
        t.body(lambda es, task, g, l: None)
        s = p.task("S", k=lambda g, l: range(0, g.N, 2))
        sf = s.flow("in_", ptg.CTL)
        sf.input(pred=("T", "c", lambda g, l: {"k": l.k}))
        s.body(lambda es, task, g, l: seen.append(l.k))
        tp = p.build()
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        ctx.fini()
        assert sorted(seen) == [0, 2, 4]


class TestEP:
    """Embarrassingly-parallel CTL-only DAG (tests/runtime/scheduling/ep.jdf):
    NT chains of DEPTH tasks — the dispatch-overhead microbenchmark."""

    def _build(self, NT, DEPTH, counter):
        p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
        t = p.task("EP",
                   d=ptg.span(0, lambda g, l: g.DEPTH - 1),
                   n=ptg.span(0, lambda g, l: g.NT - 1))
        f = t.flow("ctl", ptg.CTL)
        f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
                guard=lambda g, l: l.d > 0)
        f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
                 guard=lambda g, l: l.d < g.DEPTH - 1)
        t.body(lambda es, task, g, l: counter.append(None))
        return p.build()

    @pytest.mark.parametrize("sched", ["lfq", "ap", "spq", "gd", "rnd", "ip",
                                       "ll", "llp", "pbq", "ltq", "lhq"])
    def test_all_schedulers_run_ep(self, sched):
        from parsec_tpu.core.params import params
        count = []
        tp = self._build(8, 5, count)
        # force the dynamic path: the compiled-DAG incarnation would bypass
        # the scheduler entirely, and this test exists to exercise it
        old = params.get("runtime_dag_compile")
        params.set("runtime_dag_compile", False)
        try:
            ctx = Context(nb_cores=2, scheduler=sched)
            ctx.add_taskpool(tp)
            ctx.start()
            tp.wait(timeout=60)
            ctx.fini()
        finally:
            params.set("runtime_dag_compile", old)
        assert len(count) == 8 * 5

    def test_ep_single_threaded(self):
        count = []
        tp = self._build(4, 3, count)
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        ctx.fini()
        assert len(count) == 12


class TestHandWrittenTaskClass:
    """Layer-2 exit test from SURVEY §7: no DSL, raw TaskClass objects."""

    def test_manual_chain(self):
        N = 5
        log = []
        tc = TaskClass(
            "man",
            params=["k"],
            flows=[Flow("c", "CTL",
                        deps_in=[Dep(guard=lambda l: l["k"] > 0,
                                     target_class="man", target_flow="c",
                                     target_params=lambda l: {"k": l["k"] - 1})],
                        deps_out=[Dep(guard=lambda l: l["k"] < N - 1,
                                      target_class="man", target_flow="c",
                                      target_params=lambda l: {"k": l["k"] + 1})])],
            chores=[Chore("cpu", hook=lambda es, t: log.append(t.locals["k"]) or 0)],
        )

        class ManualTP(Taskpool):
            def nb_local_tasks(self):
                return N

            def startup(self, context):
                t = Task(self, self.task_classes[0], {"k": 0})
                return [t]

        tp = ManualTP(name="manual", task_classes=[tc])
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        ctx.fini()
        assert log == list(range(N))


class TestPriorities:
    def test_priority_order_with_ap(self):
        """With a single worker + ap scheduler, independent ready tasks run
        highest-priority first."""
        seen = []
        p = ptg.PTGBuilder("prio", N=8)
        t = p.task("P", k=ptg.span(0, lambda g, l: g.N - 1))
        t.priority(lambda g, l: l.k)
        t.body(lambda es, task, g, l: seen.append(l.k))
        tp = p.build()
        ctx = Context(nb_cores=0, scheduler="ap")
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        ctx.fini()
        # the keep-highest slot takes one; the rest must be descending
        assert seen[1:] == sorted(seen[1:], reverse=True)
