"""The static-analysis gate: graphcheck over every shipped graph,
runtimelint over the package source, and mutation tests proving the
checker's detection power (a verifier that cannot catch seeded bugs
proves nothing — the ptgpp-error-case suite analog, SURVEY §4).

Runs in tier-1 (no `slow` marker): the graphs are small and the lint is
one AST pass over ~100 files.
"""

import os
import pathlib
import textwrap

import numpy as np
import pytest

from parsec_tpu.analysis import (GraphCheckError, check_dtd, check_jdf,
                                 check_ptg, check_taskpool, lint_file,
                                 lint_self)
from parsec_tpu.analysis.__main__ import _model_graphs, main as cli_main
from parsec_tpu.data.data import ACCESS_READ
from parsec_tpu.data.datatype import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
from parsec_tpu.models.cholesky import tiled_cholesky_ptg
from parsec_tpu.runtime.task import Dep

REPO = pathlib.Path(__file__).parent.parent

pytestmark = pytest.mark.analysis


def _cholesky(nt: int = 5, P: int = 1, Q: int = 1):
    A = SymTwoDimBlockCyclic("A", nt * 16, nt * 16, 16, 16, P=P, Q=Q)
    return tiled_cholesky_ptg(A, devices="cpu")


# ---------------------------------------------------------------------------
# every shipped graph verifies clean
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,tp", list(_model_graphs(5)),
                         ids=lambda v: v if isinstance(v, str) else "")
def test_models_verify_clean(name, tp):
    report = check_ptg(tp)
    assert report.ok, (name, report.findings)
    assert report.ntasks > 0


def test_cholesky_multirank_verifies():
    report = check_ptg(_cholesky(5, P=2, Q=2), nb_ranks=4)
    assert report.ok, report.findings


def test_jdf_examples_verify():
    def dc(name):
        return DictCollection(name, dtt=TileType((4,), np.float32),
                              init_fn=lambda *k: np.zeros(4, np.float32))

    for j in ["Ex05_Broadcast.jdf", "Ex06_RAW.jdf", "Ex07_RAW_CTL.jdf"]:
        r = check_jdf(str(REPO / "examples" / "jdf" / j),
                      mydata=dc("mydata"), nodes=3)
        assert r.ok, (j, r.findings)


def test_raw_vs_ctl_hazard_distinction():
    """Ex06 (deliberately unordered RAW fan-out) draws the shared-write
    hazard warning; Ex07 — the same graph with CTL ordering — is silent.
    The checker reproduces the examples' own documentation."""
    def dc(name):
        return DictCollection(name, dtt=TileType((4,), np.float32),
                              init_fn=lambda *k: np.zeros(4, np.float32))

    raw = check_jdf(str(REPO / "examples/jdf/Ex06_RAW.jdf"),
                    mydata=dc("mydata"), nodes=3)
    ctl = check_jdf(str(REPO / "examples/jdf/Ex07_RAW_CTL.jdf"),
                    mydata=dc("mydata"), nodes=3)
    assert any(f.code == "unordered-shared-write" for f in raw.warnings)
    assert not any(f.code == "unordered-shared-write" for f in ctl.findings)


# ---------------------------------------------------------------------------
# detection power: seeded mutations of a known-good graph
# ---------------------------------------------------------------------------


def test_detects_dropped_input_edge():
    """Mutation class 1 (missing edge): drop GEMM's A input (the TRSM.C
    fan-out target) — the producer's range arrow now lands nowhere."""
    tp = _cholesky()
    fA = next(f for f in tp.task_class("GEMM").flows if f.name == "A")
    fA.deps_in.clear()
    report = check_ptg(tp)
    hits = [f for f in report.errors if f.code == "missing-input-edge"]
    assert hits, report.findings
    # provenance: the finding names the PRODUCER side of the broken edge
    assert hits[0].task_class == "TRSM" and hits[0].flow == "C"
    assert "GEMM" in hits[0].message
    assert hits[0].instance is not None     # concrete locals attached


def test_detects_dropped_output_edge():
    """The symmetric half: drop POTRF's range arrow to TRSM — consumers
    now wait on a producer that never sends."""
    tp = _cholesky()
    fT = next(f for f in tp.task_class("POTRF").flows if f.name == "T")
    fT.deps_out = [d for d in fT.deps_out if d.target_class != "TRSM"]
    report = check_ptg(tp)
    hits = [f for f in report.errors if f.code == "missing-output-edge"]
    assert hits, report.findings
    assert hits[0].task_class == "TRSM" and hits[0].flow == "T"


def test_detects_rw_flipped_to_read():
    """Mutation class 2 (access mismatch): GEMM's accumulation chain
    declared READ — consumers would receive the un-accumulated tile."""
    tp = _cholesky()
    next(f for f in tp.task_class("GEMM").flows
         if f.name == "C").access = ACCESS_READ
    report = check_ptg(tp)
    hits = [f for f in report.errors
            if f.code == "read-chain-never-written"]
    assert hits, report.findings
    assert hits[0].task_class == "GEMM" and hits[0].flow == "C"


def test_detects_out_of_range_tile():
    """Mutation class 3: POTRF's affinity maps outside the tile grid."""
    tp = _cholesky()
    po = tp.task_class("POTRF")
    orig = po.affinity
    po.affinity = lambda l: (orig(l)[0], (l["k"], l["k"] + 99))
    report = check_ptg(tp)
    hits = [f for f in report.errors if f.code == "tile-out-of-range"]
    assert hits, report.findings
    assert hits[0].task_class == "POTRF"
    assert hits[0].instance == {"k": 0}


def test_detects_cycle():
    """Mutation class 4: a backward edge closes a 2-cycle in the GEMM
    k-chain."""
    tp = _cholesky(5)
    fC = next(f for f in tp.task_class("GEMM").flows if f.name == "C")
    fC.deps_out.append(Dep(
        target_class="GEMM", target_flow="C",
        target_params=lambda l: {"m": l["m"], "n": l["n"], "k": l["k"] - 1},
        guard=lambda l: l["k"] > 0))
    report = check_ptg(tp)
    hits = [f for f in report.errors if f.code == "dependency-cycle"]
    assert hits, report.findings
    assert hits[0].task_class == "GEMM"
    assert "GEMM" in hits[0].message and "->" in hits[0].message


def test_detects_unbound_global():
    """Probe evaluation surfaces an unbound name in an edge function as a
    typed finding, not a worker-thread AttributeError."""
    from parsec_tpu import ptg
    p = ptg.PTGBuilder("bad", NB=4)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    f = t.flow("V", ptg.RW)
    f.input(null=True)
    f.output(succ=("T", "V", lambda g, l: {"i": l.i + g.TYPO}),
             guard=lambda g, l: l.i < g.NB - 1)
    t.body(lambda es, task, g, l: None)
    report = check_ptg(p.build())
    hits = [f for f in report.errors if f.code == "edge-eval-error"]
    assert hits and hits[0].task_class == "T"
    assert "TYPO" in hits[0].message


def test_detects_no_startup():
    """A pool whose every instance waits on a predecessor can never
    start — the classic guard-typo hang, caught before enqueue."""
    from parsec_tpu import ptg
    p = ptg.PTGBuilder("stuck", NB=3)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    f = t.flow("V", ptg.RW)
    f.input(pred=("T", "V", lambda g, l: {"i": (l.i - 1) % g.NB}))
    f.output(succ=("T", "V", lambda g, l: {"i": (l.i + 1) % g.NB}))
    t.body(lambda es, task, g, l: None)
    report = check_ptg(p.build())
    codes = {f.code for f in report.errors}
    assert "no-startup-task" in codes
    assert "dependency-cycle" in codes      # the ring is also a cycle


def test_truncated_enumeration_stays_clean():
    """A pool larger than the instance cap verifies a truncated prefix
    without crashing and without false dangling-edge errors (the cap's
    documented contract — membership checks are unreliable mid-prefix)."""
    report = check_ptg(_cholesky(5), max_tasks=3)
    assert report.truncated
    assert report.ok, report.findings
    assert "truncated" in report.summary()


def test_gate_mode_raises_typed_error():
    tp = _cholesky()
    next(f for f in tp.task_class("GEMM").flows
         if f.name == "A").deps_in.clear()
    with pytest.raises(GraphCheckError) as ei:
        check_taskpool(tp, raise_on_error=True)
    assert ei.value.findings
    assert "missing-input-edge" in str(ei.value)


# ---------------------------------------------------------------------------
# the enqueue-time hook (MCA analysis_check=1)
# ---------------------------------------------------------------------------


def test_enqueue_hook_rejects_and_leaves_context_clean(param):
    from parsec_tpu.runtime import Context
    param("analysis_check", 1)
    bad = _cholesky()
    next(f for f in bad.task_class("GEMM").flows
         if f.name == "A").deps_in.clear()
    ctx = Context(nb_cores=0)
    try:
        with pytest.raises(GraphCheckError):
            ctx.add_taskpool(bad)
        assert ctx.test()           # no half-enqueued pool left behind
        from parsec_tpu.models.cholesky import make_spd
        A = SymTwoDimBlockCyclic.from_dense("A", make_spd(48), 16, 16)
        good = tiled_cholesky_ptg(A, devices="cpu")
        ctx.add_taskpool(good)      # the context still works
        ctx.wait(timeout=60)
    finally:
        ctx.abort()


def test_ptg_validate_seam():
    assert _cholesky().validate().ok


# ---------------------------------------------------------------------------
# DTD prong
# ---------------------------------------------------------------------------


def test_dtd_validate(param):
    from parsec_tpu.dtd import INOUT, INPUT, DTDTaskpool
    from parsec_tpu.runtime import Context
    ctx = Context(nb_cores=0)
    try:
        tp = DTDTaskpool("dtd_ok")
        ctx.add_taskpool(tp)
        # a declared (closed) key space: tile (5,) is constructible — the
        # store is lazy — but lies outside the declared bounds, the shape
        # a bad tile_of key takes in practice
        dc = DictCollection("D", dtt=TileType((4,), np.float32),
                            init_fn=lambda *k: np.zeros(4, np.float32),
                            keys=[(0,), (1,)])
        t0 = tp.tile_of(dc, 0)
        t1 = tp.tile_of(dc, 1)
        tp.insert_task(lambda a, c: None, (t0, INPUT), (t1, INOUT),
                       name="ok")
        assert tp.validate().ok
        bad = tp.tile_of(dc, 5)
        tp.insert_task(lambda a: None, (bad, INOUT), name="oob")
        report = check_dtd(tp)
        assert any(f.code == "tile-out-of-range" for f in report.errors)
        tp.close()   # analysis_check is off: close() does not re-validate
        ctx.wait(timeout=60)
    finally:
        ctx.abort()


# ---------------------------------------------------------------------------
# runtimelint
# ---------------------------------------------------------------------------


def test_self_lint_is_green():
    """The concurrency/hygiene lint over parsec_tpu/ holds with an EMPTY
    allowlist: zero errors AND zero warnings (ISSUE 5 acceptance)."""
    report = lint_self()
    assert report.nfiles > 80
    assert not report.findings, [repr(f) for f in report.findings]


def _lint_src(tmp_path, src):
    p = tmp_path / "probe.py"
    p.write_text(textwrap.dedent(src))
    return lint_file(str(p))


def test_lint_unlocked_mutation(tmp_path):
    out = _lint_src(tmp_path, """
        import threading
        _LOCK_PROTECTED = {"Box._items": "_lock"}
        class Box:
            def __init__(self):
                self._items = []          # construction: exempt
                self._lock = threading.Lock()
            def good(self):
                with self._lock:
                    self._items.append(1)
            def bad(self):
                self._items.append(1)
            def waived(self):
                self._items.clear()       # lint: unlocked-ok
            def helper(self):  # lint: holds(_lock)
                self._items.pop()
        """)
    assert [f.code for f in out] == ["unlocked-mutation"]
    assert out[0].line == 12


def test_lint_mutating_call_with_result(tmp_path):
    """Pop-with-result (`v = self.x.pop()`) and call-argument mutations
    are mutations too — the dominant idiom in the runtime itself."""
    out = _lint_src(tmp_path, """
        _LOCK_PROTECTED = {"Box._items": "_lock"}
        class Box:
            def bad_assign(self):
                v = self._items.pop()
                return v
            def bad_nested(self, f):
                return f(self._items.pop(0))
            def good(self):
                with self._lock:
                    return self._items.pop()
        """)
    assert [f.code for f in out] == ["unlocked-mutation"] * 2
    assert [f.line for f in out] == [5, 8]


def test_lint_multi_item_with_order(tmp_path):
    """`with a, b:` acquires in order — an inversion on one line is the
    same deadlock shape as lexical nesting."""
    out = _lint_src(tmp_path, """
        _LOCK_ORDER = ("_outer", "_inner")
        class Box:
            def ok(self):
                with self._outer, self._inner:
                    pass
            def inverted(self):
                with self._inner, self._outer:
                    pass
        """)
    assert [f.code for f in out] == ["lock-order"]


def test_lint_condition_alias(tmp_path):
    out = _lint_src(tmp_path, """
        _LOCK_PROTECTED = {"Box._n": "_lock"}
        _LOCK_ALIASES = {"_cond": "_lock"}
        class Box:
            def ok(self):
                with self._cond:
                    self._n += 1
        """)
    assert not out


def test_lint_lock_order(tmp_path):
    out = _lint_src(tmp_path, """
        _LOCK_ORDER = ("_outer", "_inner")
        class Box:
            def ok(self):
                with self._outer:
                    with self._inner:
                        pass
            def inverted(self):
                with self._inner:
                    with self._outer:
                        pass
        """)
    assert [f.code for f in out] == ["lock-order"]


def test_lint_hygiene(tmp_path):
    out = _lint_src(tmp_path, """
        import pickle
        import os          # never used

        def f(b):
            try:
                return pickle.loads(b)
            except:
                pass
        """)
    codes = sorted(f.code for f in out)
    assert codes == ["bare-except", "bare-pickle-loads", "unused-import"]


def test_lint_quoted_annotation_not_flagged(tmp_path):
    out = _lint_src(tmp_path, """
        from typing import Sequence

        def f(x) -> "Sequence[int]":
            return [x]
        """)
    assert not out


# ---------------------------------------------------------------------------
# CLI + iterators_checker fold
# ---------------------------------------------------------------------------


def test_cli_single_model(capsys):
    assert cli_main(["--graph", "cholesky", "--nt", "4"]) == 0
    assert "graphcheck cholesky: OK" in capsys.readouterr().out


def test_cli_self_lint(capsys):
    assert cli_main(["--self-lint"]) == 0
    assert "runtimelint: OK" in capsys.readouterr().out


def test_iterators_checker_reexport():
    """The dynamic (PINS) successor checker folded into the analysis
    namespace: one entry point for both static and runtime checks."""
    from parsec_tpu import analysis
    from parsec_tpu.prof import iterators_checker
    assert analysis.check_task is iterators_checker.check_task
    assert analysis.IteratorsCheckerError \
        is iterators_checker.IteratorsCheckerError


def test_ruff_clean():
    """Style stage of scripts/check.sh promoted into tier-1 (ISSUE 20):
    ruff must be clean over the whole tree when it is installed; skipped
    (not failed) where the toolchain image lacks it — check.sh prints
    the same skip."""
    import subprocess
    import sys
    probe = subprocess.run([sys.executable, "-m", "ruff", "--version"],
                           capture_output=True)
    if probe.returncode != 0:
        pytest.skip("ruff not installed in this environment")
    root = pathlib.Path(__file__).resolve().parents[1]
    out = subprocess.run(
        [sys.executable, "-m", "ruff", "check",
         "parsec_tpu", "tests", "examples"],
        capture_output=True, text=True, cwd=root)
    assert out.returncode == 0, out.stdout + out.stderr
