"""Multi-rank compiled lowering: one SPMD XLA program from a distributed PTG.

VERDICT r2 item 7: ``lower_taskpool(tp, mesh=...)`` lowers a block-cyclic
distributed taskpool to a single sharded program — tile ownership taken from
the collections' ``rank_of``, collectives inserted by GSPMD.  Adversarial
checks: the lowered result must equal (a) the dense reference, and (b) the
*dynamic* multi-rank runtime executing the same taskpool over the comm
engine.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic, TwoDimBlockCyclic
from parsec_tpu.models.cholesky import make_spd, tiled_cholesky_ptg
from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
from parsec_tpu.ptg.lowering import LoweringError, lower_taskpool


def mesh_of(nranks: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:nranks]), ("ranks",))


def assemble(dc) -> np.ndarray:
    """Full dense matrix from ALL tiles (to_dense() keeps only the local
    rank's tiles on distributed collections; the lowered store holds every
    tile in-process)."""
    out = np.zeros((dc.lm, dc.ln), dtype=dc.dtype)
    for m in range(dc.mt):
        for n in range(dc.nt):
            if not dc.has_tile(m, n):
                continue
            t = np.asarray(dc.data_of(m, n).newest_copy().value)
            out[m * dc.mb:m * dc.mb + t.shape[0],
                n * dc.nb:n * dc.nb + t.shape[1]] = t
    return out


def build_gemm(nranks: int, n=64, nb=16, seed=7):
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=Q)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=Q)
    return a, b, A, B, C


@pytest.mark.parametrize("nranks", [2, 4, 8])
def test_lowered_gemm_matches_dense(nranks):
    a, b, A, B, C = build_gemm(nranks)
    low = lower_taskpool(tiled_gemm_ptg(A, B, C), mesh=mesh_of(nranks))
    assert low.mode == "chain-collapse"
    low.execute()
    np.testing.assert_allclose(assemble(C), a @ b, rtol=1e-4, atol=1e-4)


def test_lowered_gemm_tiles_live_on_owner_ranks():
    """The sharding contract: rank-major slabs — row // cap == rank_of."""
    a, b, A, B, C = build_gemm(4)
    low = lower_taskpool(tiled_gemm_ptg(A, B, C), mesh=mesh_of(4))
    st = low._stores
    for name, rows in st.rows.items():
        dc = st.dcs[name]
        cap = st.nrows[name] // 4
        for key, row in rows.items():
            assert row // cap == dc.rank_of(*key), (name, key)
    sh = low.shardings()
    assert all(s.spec == ("ranks",) or s.spec == () for s in sh.values())


def test_lowered_gemm_matches_dynamic_multirank():
    """The compiled incarnation against the dynamic runtime on 4 inproc
    ranks (same taskpool shape, remote deps through the comm engine)."""
    nranks = 4
    a, b, A, B, C = build_gemm(nranks)
    low = lower_taskpool(tiled_gemm_ptg(A, B, C), mesh=mesh_of(nranks))
    low.execute()
    lowered = assemble(C)

    def body(ctx, rank, nr):
        a2, b2, A2, B2, C2 = build_gemm(nr)
        for dc in (A2, B2, C2):
            dc.myrank = rank
        tp = tiled_gemm_ptg(A2, B2, C2, devices="cpu")
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
        ctx.comm_barrier()
        return C2.to_dense()

    res = run_multirank(nranks, body)
    dynamic = np.zeros_like(lowered)
    for r in res:
        dynamic += r        # each rank contributes only the tiles it owns
    np.testing.assert_allclose(lowered, dynamic, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(lowered, a @ b, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("nranks", [2, 4])
def test_lowered_cholesky_wavefront_multirank(nranks):
    """Four task classes, triangular space, range arrows — the wavefront
    lowering pass, sharded.  POTRF/TRSM/SYRK/GEMM traceables drive it."""
    n, nb = 64, 16
    spd = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense("A", spd, nb, nb,
                                        P=nranks, Q=1)
    tp = tiled_cholesky_ptg(A)
    low = lower_taskpool(tp, mesh=mesh_of(nranks))
    assert low.mode == "wavefront"
    low.execute()
    got = np.tril(assemble(A))
    expect = np.linalg.cholesky(spd.astype(np.float64))
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)


def test_lowered_cholesky_single_rank():
    n, nb = 64, 16
    spd = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense("A", spd, nb, nb)
    low = lower_taskpool(tiled_cholesky_ptg(A))
    assert low.mode == "wavefront"
    low.execute()
    got = np.tril(A.to_dense())
    np.testing.assert_allclose(got, np.linalg.cholesky(spd.astype(np.float64)),
                               rtol=1e-3, atol=1e-4)


def test_mesh_axis_name_is_checked():
    a, b, A, B, C = build_gemm(2)
    bad = Mesh(np.array(jax.devices()[:2]), ("x",))
    with pytest.raises(LoweringError):
        lower_taskpool(tiled_gemm_ptg(A, B, C), mesh=bad)
