"""Irregular / dynamic-graph app tier (reference tests/apps/haar_tree,
merge_sort, all2all): runtime-discovered tree recursion through DTD and
the all-to-all comm cross-product through PTG.
"""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
from parsec_tpu.dtd import DTDTaskpool
from parsec_tpu.models.irregular import (all2all_ptg, haar_project_dtd,
                                         haar_project_reference,
                                         merge_sort_dtd)
from parsec_tpu.runtime import Context


# ---------------------------------------------------------------------------
# adaptive Haar tree: bodies insert their own children
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("nb_cores", [0, 4])
def test_haar_tree_discovery(nb_cores):
    """The DTD-discovered refinement tree matches the sequential oracle —
    including with 4 workers racing their insertions."""
    alpha, thresh = 1.0, 1e-4
    want = haar_project_reference(alpha, thresh, min_depth=4, max_depth=20)
    assert len(want) > 50, "oracle tree unexpectedly small"
    with Context(nb_cores=nb_cores) as ctx:
        tp = DTDTaskpool("haar")
        ctx.add_taskpool(tp)
        tree = haar_project_dtd(tp, alpha, thresh, min_depth=4, max_depth=20)
        tp.wait(timeout=120)
    assert set(tree) == set(want)
    for k in want:
        assert tree[k] == pytest.approx(want[k])


def test_haar_tree_worker_inserters_survive_tiny_window():
    """Backpressure with every inserter a worker executing a body: workers
    must execute-and-come-back, not park (review r4: parking all workers
    above the window deadlocks the run)."""
    from parsec_tpu.core.params import params
    saved = (params.get("dtd_window_size"), params.get("dtd_threshold_size"))
    params.set("dtd_window_size", 8)
    params.set("dtd_threshold_size", 4)
    try:
        want = haar_project_reference(1.0, 1e-4, min_depth=4, max_depth=20)
        with Context(nb_cores=4) as ctx:
            tp = DTDTaskpool("haar_win")
            ctx.add_taskpool(tp)
            tree = haar_project_dtd(tp, 1.0, 1e-4, min_depth=4,
                                    max_depth=20)
            tp.wait(timeout=120)
        assert set(tree) == set(want)
    finally:
        params.set("dtd_window_size", saved[0])
        params.set("dtd_threshold_size", saved[1])


def test_haar_tree_depth_is_data_dependent():
    """Different thresholds give different tree shapes — the structure is
    discovered, not enumerated."""
    with Context(nb_cores=0) as ctx:
        tp = DTDTaskpool("haar1")
        ctx.add_taskpool(tp)
        coarse = haar_project_dtd(tp, 1.0, 1e-2, min_depth=2, max_depth=20)
        tp.wait(timeout=120)
    with Context(nb_cores=0) as ctx:
        tp = DTDTaskpool("haar2")
        ctx.add_taskpool(tp)
        fine = haar_project_dtd(tp, 1.0, 1e-5, min_depth=2, max_depth=20)
        tp.wait(timeout=120)
    assert len(fine) > len(coarse)
    assert set(coarse) < set(fine)


# ---------------------------------------------------------------------------
# merge sort
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,run,nb_cores", [
    (1000, 64, 0),
    (4096, 128, 2),
    (777, 50, 2),        # ragged runs + odd level widths
])
def test_merge_sort(n, run, nb_cores):
    rng = np.random.default_rng(n)
    data = rng.standard_normal(n).astype(np.float32)
    with Context(nb_cores=nb_cores) as ctx:
        tp = DTDTaskpool("msort")
        ctx.add_taskpool(tp)
        out = merge_sort_dtd(tp, data, run=run)
        tp.wait(timeout=120)
    np.testing.assert_array_equal(out, np.sort(data))


def test_merge_sort_int_keys():
    rng = np.random.default_rng(5)
    data = rng.integers(0, 10 ** 6, size=2000).astype(np.int64)
    with Context(nb_cores=0) as ctx:
        tp = DTDTaskpool("msort_i")
        ctx.add_taskpool(tp)
        out = merge_sort_dtd(tp, data, run=37)
        tp.wait(timeout=120)
    np.testing.assert_array_equal(out, np.sort(data))


# ---------------------------------------------------------------------------
# all-to-all
# ---------------------------------------------------------------------------

def _a2a_vectors(nranks, rank, nt, mb, seed=0):
    rng = np.random.default_rng(seed)
    a0 = rng.standard_normal((nt, mb)).astype(np.float32)
    b0 = rng.standard_normal((nt, mb)).astype(np.float32)
    A = VectorTwoDimCyclic("A", lm=nt * mb, mb=mb, P=nranks, myrank=rank,
                           init_fn=lambda m, size: a0[m, :size].copy())
    B = VectorTwoDimCyclic("B", lm=nt * mb, mb=mb, P=nranks, myrank=rank,
                           init_fn=lambda m, size: b0[m, :size].copy())
    return a0, b0, A, B


def test_all2all_single_rank():
    nt, mb, rounds = 4, 8, 3
    a0, b0, A, B = _a2a_vectors(1, 0, nt, mb)
    tp = all2all_ptg(A, B, rounds)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    want = b0 + rounds * a0.sum(axis=0)
    for s in range(nt):
        np.testing.assert_allclose(
            np.asarray(B.data_of(s).newest_copy().value), want[s],
            rtol=1e-5)


def _a2a_rank_body(ctx, rank, nranks):
    nt, mb, rounds = 8, 4, 2
    a0, b0, A, B = _a2a_vectors(nranks, rank, nt, mb, seed=2)
    tp = all2all_ptg(A, B, rounds)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=180)
    ctx.comm_barrier()
    want = b0 + rounds * a0.sum(axis=0)
    for s in range(nt):
        if B.rank_of(s) != rank:
            continue
        np.testing.assert_allclose(
            np.asarray(B.data_of(s).newest_copy().value), want[s],
            rtol=1e-5)
    return True


def test_all2all_multirank():
    """Every tile of every rank reaches every destination each round —
    the comm-engine cross-product stress (a2a.jdf role)."""
    assert all(run_multirank(4, _a2a_rank_body))
