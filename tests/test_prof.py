"""Observability tier: trace well-formedness, converters, grapher, counters.

Mirrors the reference's profiling tests (SURVEY §4.7): run a taskpool with
tracing on, validate event well-formedness (check-async.py analog), read
the binary dump back, convert to pandas; DOT grapher and SDE counters.
"""

import os

import numpy as np
import pytest

from parsec_tpu.core.mca import repository
from parsec_tpu.core.params import params
from parsec_tpu.data_dist.matrix import TiledMatrix
from parsec_tpu.prof.counters import (TASKS_ENABLED, TASKS_RETIRED,
                                      properties, sde)
from parsec_tpu.prof.profiling import Profiling, profiling
from parsec_tpu.runtime import Context

import parsec_tpu.runtime.dagrun  # noqa: F401  registers runtime_dag_compile


@pytest.fixture
def dynamic_path():
    """Full-protocol PINS modules (4-phase trace, grapher, SDE retire
    counts) observe the DYNAMIC scheduling loop; the compiled-DAG executor
    emits only EXEC + batch-level DAG spans (see test_compiled_dag_trace)."""
    old = params.get("runtime_dag_compile")
    params.set("runtime_dag_compile", False)
    yield
    params.set("runtime_dag_compile", old)


def _run_small_gemm(nb_cores=2):
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    rng = np.random.default_rng(0)
    n, nb = 32, 16
    A = rng.standard_normal((n, n)).astype(np.float32)
    B = rng.standard_normal((n, n)).astype(np.float32)
    dA = TiledMatrix.from_dense("A", A, nb, nb)
    dB = TiledMatrix.from_dense("B", B, nb, nb)
    dC = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32), nb, nb)
    ctx = Context(nb_cores=nb_cores)
    ctx.add_taskpool(tiled_gemm_ptg(dA, dB, dC, devices="cpu"))
    ctx.wait(timeout=60)
    ctx.fini()
    np.testing.assert_allclose(dC.to_dense(), A @ B, rtol=1e-4, atol=1e-4)


@pytest.fixture
def traced():
    profiling.init()
    comp = repository.find("pins", "task_profiler")
    mod = comp.open()
    yield profiling
    comp.close(mod)
    profiling.fini()


def test_trace_well_formed_and_converts(tmp_path, traced, dynamic_path):
    _run_small_gemm()
    assert traced.validate() == []
    recs = traced.to_records()
    execs = [r for r in recs if r["name"] == "task_exec"]
    assert len(execs) == 8, len(execs)   # 2x2x2 GEMM tasks
    for r in execs:
        assert r["duration_ns"] > 0
        assert r["info.task"] == "GEMM"
    # the four phases nest sanely: prepare <= exec window exists per task
    names = {r["name"] for r in recs}
    assert {"task_exec", "task_prepare_input", "task_release_deps",
            "task_complete"} <= names

    # binary round-trip (dbp dump + pbt2ptt analog)
    path = str(tmp_path / "trace.ptpb")
    traced.dump(path)
    back = Profiling.load(path)
    assert back.validate() == []
    assert len(back.to_records()) == len(recs)
    df = back.to_pandas()
    assert len(df) == len(recs)
    assert (df[df["name"] == "task_exec"]["duration_ns"] > 0).all()
    # info values round-trip with their types, not as repr strings
    assert (df[df["name"] == "task_exec"]["info.task"] == "GEMM").all()


def test_compiled_dag_trace(tmp_path, traced):
    """VERDICT r3 #4: the compiled-DAG fast path is observable — an EP DAG
    run with runtime_dag_compile=True produces per-task exec events plus
    batch-granular dag_fetch/dag_complete spans, exportable to a Chrome
    trace."""
    import json

    from parsec_tpu import ptg

    assert params.get("runtime_dag_compile")
    NT, DEPTH = 8, 5
    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l: None)
    tp = p.build()
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.fini()
    # the dag_* spans below exist ONLY on the compiled path — their
    # presence proves the pool compiled despite PINS being active
    recs = traced.to_records()
    execs = [r for r in recs if r["name"] == "task_exec"]
    assert len(execs) == NT * DEPTH
    assert all(r["info.task"] == "EP" for r in execs)
    completes = [r for r in recs if r["name"] == "dag_complete"]
    assert completes
    assert sum(r["info.batch"] for r in completes) == NT * DEPTH
    assert {r["name"] for r in recs} >= {"dag_fetch", "dag_complete"}

    trace = traced.to_chrome_trace(str(tmp_path / "ep.json"))
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"task_exec", "dag_fetch", "dag_complete"} <= names
    json.load(open(tmp_path / "ep.json"))   # well-formed on disk


def test_lowered_execute_span(traced):
    """One span per compiled (lowered) taskpool execution."""
    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.ptg.lowering import lower_taskpool

    rng = np.random.default_rng(1)
    a = rng.standard_normal((8, 8)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a, 4, 4)
    B = TiledMatrix.from_dense("B", a.copy(), 4, 4)
    C = TiledMatrix.from_dense("C", np.zeros((8, 8), np.float32), 4, 4)
    low = lower_taskpool(tiled_gemm_ptg(A, B, C))
    low.execute()
    low.execute()
    recs = [r for r in traced.to_records() if r["name"] == "lowered_execute"]
    assert len(recs) == 2
    assert all(r["info.mode"] == low.mode for r in recs)
    assert all(r["duration_ns"] > 0 for r in recs)


def test_standalone_profiling(tmp_path):
    """The sp-demo shape: trace without any runtime."""
    p = Profiling()
    p.init()
    k1, k2 = p.add_dictionary_keyword("phase", "#ff0000", ("step",))
    for i in range(5):
        p.trace(k1, event_id=i, info={"step": i})
        p.trace(k2, event_id=i)
    assert p.validate() == []
    recs = p.to_records()
    assert len(recs) == 5
    assert recs[0]["info.step"] == 0


def test_grapher_dot(tmp_path, dynamic_path):
    comp = repository.find("pins", "grapher")
    mod = comp.open()
    try:
        _run_small_gemm(nb_cores=0)
    finally:
        comp.close(mod)
    path = str(tmp_path / "dag.dot")
    mod.write_dot(path)
    text = open(path).read()
    assert text.startswith("digraph")
    assert '"GEMM_0_0_0"' in text
    # the k-chain edge GEMM(0,0,0) -> GEMM(0,0,1) must be realized
    assert '"GEMM_0_0_0" -> "GEMM_0_0_1"' in text
    assert text.count("->") >= 4


def test_sde_counters(dynamic_path):
    comp = repository.find("pins", "sde")
    mod = comp.open()
    sde.reset()
    try:
        _run_small_gemm(nb_cores=0)
    finally:
        comp.close(mod)
    snap = sde.snapshot()
    assert snap[TASKS_RETIRED] >= 8
    assert snap[TASKS_ENABLED] >= 1


def test_properties_dictionary(tmp_path):
    vals = {"x": 1}
    properties.register("test", "x", lambda: vals["x"])
    try:
        snap = properties.snapshot()
        assert snap["test"]["x"] == 1
        vals["x"] = 7
        stop = properties.stream_to(str(tmp_path / "live.json"),
                                    interval=0.05)
        import json
        import time
        time.sleep(0.15)
        stop()
        data = json.load(open(tmp_path / "live.json"))
        assert data["props"]["test"]["x"] == 7
    finally:
        properties.unregister("test", "x")


def test_chrome_trace_export(tmp_path, traced):
    """The standard-viewer export (profiling_otf2.c role): trace-event
    JSON consumable by Perfetto / chrome://tracing."""
    import json

    _run_small_gemm()
    path = str(tmp_path / "trace.json")
    trace = traced.to_chrome_trace(path)
    on_disk = json.load(open(path))
    assert on_disk == json.loads(json.dumps(trace))
    evs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(evs) == len(traced.to_records())
    execs = [e for e in evs if e["name"] == "task_exec"]
    assert len(execs) == 8
    for e in execs:
        assert e["dur"] > 0
        assert e["args"]["task"] == "GEMM"
    metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert len(metas) >= 1
    assert {m["tid"] for m in metas} >= {e["tid"] for e in evs}
