"""Multi-tenant isolation soak: LLM decode streams sharing one
RuntimeServer with a dense-linear-algebra tenant (ISSUE 6 satellite).

The serving claim under test: WFQ keeps interactive decode responsive
while a batch factorization grinds on the same workers — decode p99
stays bounded, both tenants make progress, and the generated tokens
still match the dense oracle exactly (fairness must never reorder a
sequence's own chain)."""

import threading
import time

import numpy as np

from parsec_tpu.llm import ToyLM
from parsec_tpu.serve import RuntimeServer

MODEL = ToyLM()

# interactive decode gets a 4x fair share over the batch tenant; the
# p99 bound is ~100x the unloaded per-token latency (~5ms on 2 CPU
# workers) — loose enough for CI noise, tight enough that a fairness
# regression that parks decode behind a whole factorization (hundreds
# of ms per pool) trips it
DECODE_P99_S_MAX = 1.0


def _cholesky_pool(n=96, nb=32):
    from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
    from parsec_tpu.models.cholesky import make_spd, tiled_cholesky_ptg
    A = SymTwoDimBlockCyclic.from_dense("A", make_spd(n), nb, nb)
    return tiled_cholesky_ptg(A, devices="cpu"), A


def test_decode_streams_isolated_from_batch_cholesky_tenant():
    with RuntimeServer(nb_cores=2, tenant_weights={"chat": 4.0,
                                                   "batch": 1.0}) as server:
        prompts = [[3, 7, 11, 5], [1, 40], [8, 30, 22]]
        streams = [server.submit_stream(p, max_new_tokens=12,
                                        tenant="chat")
                   for p in prompts]
        # the batch tenant keeps a cholesky pool in flight until every
        # stream finishes — decode always contends with dense work
        done = threading.Event()
        batch_completed = [0]
        batch_errors: list[BaseException] = []

        def batch_client():
            try:
                while not done.is_set():
                    tp, _A = _cholesky_pool()
                    server.submit(tp, tenant="batch").result(timeout=120)
                    batch_completed[0] += 1
            except BaseException as e:      # noqa: BLE001 — surfaced below
                batch_errors.append(e)

        th = threading.Thread(target=batch_client, daemon=True)
        th.start()
        try:
            per_token = []
            for p, tk in zip(prompts, streams):
                r = tk.result(timeout=300)
                assert r["tokens"] == MODEL.reference_generate(p, 12), p
                per_token += r["per_token_s"]
        finally:
            done.set()
            th.join(timeout=300)
        assert not batch_errors, batch_errors
        # both tenants made progress under contention
        assert batch_completed[0] >= 1
        stats = server.stats()
        disp = stats["fair_dispatched"]
        assert disp.get("chat", 0) > 0 and disp.get("batch", 0) > 0, disp
        # decode latency stayed bounded while the batch job ran
        per_token.sort()
        p99 = per_token[min(int(len(per_token) * 0.99),
                            len(per_token) - 1)]
        assert p99 <= DECODE_P99_S_MAX, (p99, stats)
        # WFQ virtual time favored chat 4:1: its decode superpools
        # completed despite the saturating batch tenant.  One pool now
        # carries llm_steps_per_pool tokens for the whole tenant batch
        # (ISSUE 9), so 12 tokens x 3 streams is ceil(12/k) pools, not 36
        from parsec_tpu.core.params import params as _params
        k = max(1, int(_params.get("llm_steps_per_pool")))
        assert stats["per_tenant_completed"].get("chat", 0) >= \
            -(-12 // k), stats["per_tenant_completed"]


def test_drain_finishes_live_streams_then_stops_admission():
    server = RuntimeServer(nb_cores=2)
    tk = server.submit_stream([3, 7, 11], max_new_tokens=6, tenant="chat")
    time.sleep(0.05)                 # let a few iterations land
    server.drain(timeout=120)
    r = tk.result(timeout=5)         # drain waited for the stream
    assert r["tokens"] == MODEL.reference_generate([3, 7, 11], 6)
    assert server.stats()["llm"]["live_streams"] == 0


def test_stream_failure_is_contained_to_its_streams():
    """A poisoned/draining server fails stream tickets promptly instead
    of leaving clients blocked on result()."""
    server = RuntimeServer(nb_cores=1)
    tk = server.submit_stream([1, 2], max_new_tokens=2)
    tk.result(timeout=60)
    # after the graceful drain the batcher thread is gone; a fresh
    # submit_stream sheds instead of queueing forever
    server.drain(timeout=60)
    from parsec_tpu.serve import AdmissionRejected
    import pytest
    with pytest.raises(AdmissionRejected):
        server.submit_stream([1, 2])


def test_forked_prefix_shares_physical_pages_across_streams():
    """Prefix sharing through the batcher's cache: two sequences forked
    from one prompt dedupe their prompt pages (the paged-KV win)."""
    from parsec_tpu.llm import ContinuousBatcher, PagedKVCollection
    with RuntimeServer(nb_cores=2) as server:
        kv = PagedKVCollection("KV", page_size=4,
                               num_heads=MODEL.num_heads,
                               head_dim=MODEL.head_dim)
        b = ContinuousBatcher(server, model=MODEL, kv=kv)
        # materialize a parent sequence's pages via one short stream,
        # then fork the cache state directly (the collection API — the
        # batcher session layer for fork-on-prompt can build on it)
        kv.alloc_seq("p")
        from parsec_tpu.llm import prefill_chunks
        chunks = prefill_chunks(MODEL, kv, "p", [3, 7, 11, 5, 9])
        for (s, c), tile in chunks.items():
            pg = kv.data_of(s, c).get_copy(0)
            pg.value = tile
            pg.version += 1
        kv.fork("p", "q")
        st = kv.stats()
        assert st["logical_pages"] == 4 and st["physical_pages"] == 2
        b.stop()
