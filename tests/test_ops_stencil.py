"""Stencil kernel incarnations (ops/stencil.py): the XLA tap loop and the
VMEM-resident Pallas variant agree with the numpy oracle across shapes,
dtypes, batching, and the fallback paths.
"""

import numpy as np
import pytest

from parsec_tpu.ops.stencil import (_MAX_VMEM_ROW, stencil1d_pallas,
                                    stencil1d_xla)


def _oracle(padded, w):
    n = padded.shape[-1] - len(w) + 1
    out = np.zeros(padded.shape[:-1] + (n,), np.float64)
    for j in range(len(w)):
        out += w[j] * padded[..., j:j + n].astype(np.float64)
    return out


@pytest.mark.parametrize("R", [1, 2, 4])
@pytest.mark.parametrize("n", [16, 128, 1000])
def test_xla_matches_oracle(R, n):
    rng = np.random.default_rng(R * n)
    w = rng.standard_normal(2 * R + 1)
    p = rng.standard_normal(n + 2 * R).astype(np.float32)
    got = np.asarray(stencil1d_xla(p, w))
    np.testing.assert_allclose(got, _oracle(p, w), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("R", [1, 4])
@pytest.mark.parametrize("shape", [(256,), (4, 256), (3, 1000)])
def test_pallas_matches_xla(R, shape):
    """Interpret mode off-TPU: same numerics as the XLA loop."""
    rng = np.random.default_rng(R)
    w = rng.standard_normal(2 * R + 1)
    p = rng.standard_normal(shape[:-1] + (shape[-1] + 2 * R,)).astype(
        np.float32)
    got = np.asarray(stencil1d_pallas(p, w))
    want = np.asarray(stencil1d_xla(p, w))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert got.shape == shape


def test_pallas_large_row_falls_back():
    """Rows beyond the VMEM budget take the XLA path (same numerics)."""
    R = 1
    w = np.array([0.25, 0.5, 0.25])
    n = _MAX_VMEM_ROW + 8
    p = np.linspace(0, 1, n + 2 * R).astype(np.float32)
    got = np.asarray(stencil1d_pallas(p, w))
    assert got.shape == (n,)
    np.testing.assert_allclose(got[:64], _oracle(p, w)[:64], rtol=1e-4,
                               atol=1e-5)


def test_dtype_roundtrip():
    """f32 stays f32 through both kernels; f64 input (downcast under the
    suite's x64-off config) still matches the oracle at f32 tolerance."""
    w = np.array([0.2, 0.6, 0.2])
    p32 = np.ones(66, np.float32)
    assert np.asarray(stencil1d_xla(p32, w)).dtype == np.float32
    got = np.asarray(stencil1d_pallas(p32, w))
    assert got.dtype == np.float32
    p64 = np.linspace(0, 1, 66)
    np.testing.assert_allclose(np.asarray(stencil1d_pallas(p64, w)),
                               _oracle(p64.astype(np.float32), w),
                               rtol=1e-5, atol=1e-5)


def test_pallas_three_dim_batch():
    """Leading dims beyond 2 flatten and restore (same contract as xla)."""
    w = np.array([0.25, 0.5, 0.25])
    p = np.random.default_rng(0).standard_normal((2, 3, 130)).astype(
        np.float32)
    got = np.asarray(stencil1d_pallas(p, w))
    want = np.asarray(stencil1d_xla(p, w))
    assert got.shape == (2, 3, 128)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
