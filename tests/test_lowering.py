"""Taskpool→XLA lowering: the compiled incarnation of regular PTG graphs.

The analog of the reference's chore/incarnation contract
(``parsec_internal.h:396-402``): the same taskpool object that runs through
the dynamic scheduler lowers to one jitted XLA program.  Correctness is
checked against numpy oracles and against the dynamic-runtime execution of
the *same* taskpool.
"""

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.data_dist.matrix import TiledMatrix
from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
from parsec_tpu.ptg.lowering import (LoweringError, lower_taskpool,
                                     register_traceable)
from parsec_tpu.runtime import Context


def _gemm_fixture(n=12, nb=4, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a, nb, nb)
    B = TiledMatrix.from_dense("B", b, nb, nb)
    C = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32), nb, nb)
    return a, b, A, B, C


def test_gemm_lowers_to_chain_collapse():
    """The k-chain of GEMM(m,n,k) collapses to one contraction."""
    a, b, A, B, C = _gemm_fixture()
    low = lower_taskpool(tiled_gemm_ptg(A, B, C))
    assert low.mode == "chain-collapse"
    low.execute()
    np.testing.assert_allclose(C.to_dense(), a @ b, rtol=1e-4, atol=1e-4)


def test_gemm_lowered_matches_dynamic_runtime():
    """Compiled and dynamic incarnations of the SAME taskpool agree."""
    a, b, A, B, C = _gemm_fixture(n=8, nb=4, seed=1)
    lower_taskpool(tiled_gemm_ptg(A, B, C)).execute()

    A2 = TiledMatrix.from_dense("A2", a, 4, 4)
    B2 = TiledMatrix.from_dense("B2", b, 4, 4)
    C2 = TiledMatrix.from_dense("C2", np.zeros((8, 8), np.float32), 4, 4)
    ctx = Context(nb_cores=2)
    try:
        ctx.add_taskpool(tiled_gemm_ptg(A2, B2, C2))
        ctx.wait(timeout=60)
    finally:
        ctx.fini()
    np.testing.assert_allclose(C.to_dense(), C2.to_dense(), rtol=1e-5)


def test_gemm_step_fn_is_pure_and_rerunnable():
    """step_fn is a pure stores->stores function: two applications == C+2AB.
    Identity tile grids select the dense store layout (operands read in
    natural [lm, ln] layout, zero gather traffic)."""
    import jax

    a, b, A, B, C = _gemm_fixture(n=8, nb=4, seed=2)
    low = lower_taskpool(tiled_gemm_ptg(A, B, C))
    st = low.initial_stores()
    assert st["C"].shape == (8, 8)    # dense layout chosen
    fn = jax.jit(low.step_fn)
    st = fn(fn(st))
    np.testing.assert_allclose(np.asarray(st["C"]), 2 * (a @ b),
                               rtol=1e-4, atol=1e-4)


def test_gemm_permuted_operand_uses_stacked_gather():
    """A non-identity tile grid (B stored key-transposed) falls back to the
    stacked-store einsum emission and still computes correctly."""
    n, nb = 8, 4
    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a, nb, nb)
    # tile (i, j) of collection Bt holds logical B block (j, i)
    Bt = TiledMatrix("Bt", n, n, nb, nb, dtype=np.float32,
                     init_fn=lambda i, j, s: b[j * nb:(j + 1) * nb,
                                               i * nb:(i + 1) * nb])
    C = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32), nb, nb)
    MT, NT, KT = C.mt, C.nt, A.nt

    p = ptg.PTGBuilder("gemm_bt", A=A, Bt=Bt, C=C, MT=MT, NT=NT, KT=KT)
    t = p.task("GEMM",
               m=ptg.span(0, lambda g, l: g.MT - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1),
               k=ptg.span(0, lambda g, l: g.KT - 1))
    fa = t.flow("A", ptg.READ)
    fa.input(data=("A", lambda g, l: (l.m, l.k)))
    fb = t.flow("B", ptg.READ)
    fb.input(data=("Bt", lambda g, l: (l.n, l.k)))   # transposed storage
    fc = t.flow("C", ptg.RW)
    fc.input(data=("C", lambda g, l: (l.m, l.n)), guard=lambda g, l: l.k == 0)
    fc.input(pred=("GEMM", "C",
                   lambda g, l: {"m": l.m, "n": l.n, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    fc.output(succ=("GEMM", "C",
                    lambda g, l: {"m": l.m, "n": l.n, "k": l.k + 1}),
              guard=lambda g, l: l.k < g.KT - 1)
    fc.output(data=("C", lambda g, l: (l.m, l.n)),
              guard=lambda g, l: l.k == g.KT - 1)
    t.body(device="tpu", dyld="gemm")

    low = lower_taskpool(p.build())
    assert low.mode == "chain-collapse"
    st = low.initial_stores()
    assert st["Bt"].ndim == 3         # stacked (gather) layout
    low.execute()
    np.testing.assert_allclose(C.to_dense(), a @ b, rtol=1e-4, atol=1e-4)


register_traceable("lower_scale2", lambda x: x * 2.0)


def _scale_chain_ptg(x, nb=4, K=3):
    X = TiledMatrix.from_dense("X", x.copy(), nb, nb)
    p = ptg.PTGBuilder("chain", X=X, K=K, MT=X.mt, NT=X.nt)
    t = p.task("SCALE",
               m=ptg.span(0, lambda g, l: g.MT - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1),
               k=ptg.span(0, lambda g, l: g.K - 1))
    f = t.flow("V", ptg.RW)
    f.input(data=("X", lambda g, l: (l.m, l.n)), guard=lambda g, l: l.k == 0)
    f.input(pred=("SCALE", "V",
                  lambda g, l: {"m": l.m, "n": l.n, "k": l.k - 1}),
            guard=lambda g, l: l.k > 0)
    f.output(succ=("SCALE", "V",
                   lambda g, l: {"m": l.m, "n": l.n, "k": l.k + 1}),
             guard=lambda g, l: l.k < g.K - 1)
    f.output(data=("X", lambda g, l: (l.m, l.n)),
             guard=lambda g, l: l.k == g.K - 1)
    t.body(device="tpu", dyld="lower_scale2")
    return p.build(), X


def test_unrolled_chain_with_pred_edges():
    """A non-bilinear accumulation chain through the forced unrolled pass:
    value forwarding across pred edges, final store writeback only."""
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    tp, X = _scale_chain_ptg(x)
    low = lower_taskpool(tp, passes="unrolled")
    assert low.mode == "unrolled"
    low.execute()
    np.testing.assert_allclose(X.to_dense(), x * 8.0)


def test_wavefront_chain_auto_selected_and_matches():
    """auto picks the wavefront pass for a non-bilinear chain; per-level
    batched emission computes the same result as unrolled."""
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    tp, X = _scale_chain_ptg(x)
    low = lower_taskpool(tp)
    assert low.mode == "wavefront"
    low.execute()
    np.testing.assert_allclose(X.to_dense(), x * 8.0)


def test_read_flow_forwarding_through_two_classes():
    """READ flows forward their input to successors; two classes chain."""
    nb = 4
    x = np.full((4, 4), 3.0, np.float32)
    X = TiledMatrix.from_dense("X", x, nb, nb)
    Y = TiledMatrix.from_dense("Y", np.zeros((4, 4), np.float32), nb, nb)

    p = ptg.PTGBuilder("fwd", X=X, Y=Y)
    t1 = p.task("SRC", z=ptg.span(0, 0))
    f1 = t1.flow("A", ptg.READ)
    f1.input(data=("X", lambda g, l: (0, 0)))
    f1.output(succ=("DST", "B", lambda g, l: {"z": 0}))
    t1.body(device="tpu", dyld="lower_scale2")

    t2 = p.task("DST", z=ptg.span(0, 0))
    f2 = t2.flow("B", ptg.RW)
    f2.input(pred=("SRC", "A", lambda g, l: {"z": 0}))
    f2.output(data=("Y", lambda g, l: (0, 0)))
    t2.body(device="tpu", dyld="lower_scale2")

    low = lower_taskpool(p.build())
    assert low.mode == "wavefront"
    low.execute()
    # SRC's READ flow forwards X unchanged (its result is not a writable
    # flow); DST doubles it once.
    np.testing.assert_allclose(Y.to_dense(), x * 2.0)


def test_wavefront_program_is_level_sized_not_task_sized():
    """The wavefront emission is O(levels·classes): for a K-step chain over
    many tiles its jaxpr is a small multiple of K, far below the unrolled
    pass's O(tasks) trace (the round-3 perf ceiling on Cholesky/stencil)."""
    import jax

    x = np.zeros((32, 32), np.float32)
    tp, X = _scale_chain_ptg(x, nb=4, K=3)        # 64 tasks per level
    wf = lower_taskpool(tp, passes="wavefront")
    un = lower_taskpool(tp, passes="unrolled")
    n_wf = len(jax.make_jaxpr(wf.step_fn)(wf.initial_stores()).eqns)
    n_un = len(jax.make_jaxpr(un.step_fn)(un.initial_stores()).eqns)
    assert n_wf < n_un / 5, (n_wf, n_un)
    assert n_wf < 48, n_wf                        # ~a handful of ops per level
    # (48, not a tighter bound: the exact eqn count drifts a few ops
    # between jax releases — 42 on 0.4.37 — and the level-sized-vs-
    # task-sized claim is carried by the n_un/5 ratio assert above)


def test_wavefront_war_hazard_falls_back_to_unrolled():
    """A version that must survive past a later in-place write cannot run
    through in-place wavefront stores — auto degrades to unrolled and the
    forwarded value is still the ORIGINAL tile."""
    x = np.full((4, 4), 3.0, np.float32)
    X = TiledMatrix.from_dense("X", x, 4, 4)
    Y = TiledMatrix.from_dense("Y", np.zeros((4, 8), np.float32), 4, 4)

    p = ptg.PTGBuilder("war", X=X, Y=Y)
    # SRC reads X(0,0) and forwards it two levels down to DST
    t1 = p.task("SRC", z=ptg.span(0, 0))
    f1 = t1.flow("A", ptg.READ)
    f1.input(data=("X", lambda g, l: (0, 0)))
    f1.output(succ=("MID", "B", lambda g, l: {"z": 0}))
    t1.body(device="tpu", dyld="lower_scale2")
    t2 = p.task("MID", z=ptg.span(0, 0))
    f2 = t2.flow("B", ptg.READ)
    f2.input(pred=("SRC", "A", lambda g, l: {"z": 0}))
    f2.output(succ=("DST", "C", lambda g, l: {"z": 0}))
    t2.body(device="tpu", dyld="lower_scale2")
    t3 = p.task("DST", z=ptg.span(0, 0))
    f3 = t3.flow("C", ptg.RW)
    f3.input(pred=("MID", "B", lambda g, l: {"z": 0}))
    f3.output(data=("Y", lambda g, l: (0, 0)))
    t3.body(device="tpu", dyld="lower_scale2")
    # WRITER updates X(0,0) in place (no collection out-arrow: a scratch
    # write in wavefront terms), racing the forwarded original
    t4 = p.task("WRITER", z=ptg.span(0, 0))
    f4 = t4.flow("V", ptg.RW)
    f4.input(data=("X", lambda g, l: (0, 0)))
    f4.output(succ=("SINK", "W", lambda g, l: {"z": 0}))
    t4.body(device="tpu", dyld="lower_scale2")
    t5 = p.task("SINK", z=ptg.span(0, 0))
    f5 = t5.flow("W", ptg.RW)
    f5.input(pred=("WRITER", "V", lambda g, l: {"z": 0}))
    f5.output(data=("Y", lambda g, l: (0, 1)))
    t5.body(device="tpu", dyld="lower_scale2")

    low = lower_taskpool(p.build())
    assert low.mode == "unrolled"     # wavefront detected the WAR hazard
    low.execute()
    d = Y.to_dense()
    np.testing.assert_allclose(d[:4, :4], x * 2.0)       # original forwarded
    np.testing.assert_allclose(d[:4, 4:8], x * 4.0)      # WRITER·2 then SINK·2


def test_wavefront_scratch_never_shadows_collection_read():
    """An in-place (scratch) version parked on a store row must not be
    visible to a LATER direct ``data=`` read of that row — the source
    program still sees the pristine tile.  The wavefront pass detects the
    shadowing and auto falls back to unrolled."""
    x = np.full((4, 8), 3.0, np.float32)
    X = TiledMatrix.from_dense("X", x, 4, 4)      # tiles (0,0), (0,1)
    Y = TiledMatrix.from_dense("Y", np.zeros((4, 4), np.float32), 4, 4)

    p = ptg.PTGBuilder("shadow", X=X, Y=Y)
    # WRITER doubles X(0,0) in place (succ-only out-arrow: scratch write)
    t1 = p.task("WRITER", z=ptg.span(0, 0))
    f1 = t1.flow("V", ptg.RW)
    f1.input(data=("X", lambda g, l: (0, 0)))
    f1.output(succ=("SINK", "W", lambda g, l: {"z": 0}))
    t1.body(device="tpu", dyld="lower_scale2")
    t2 = p.task("SINK", z=ptg.span(0, 0))
    f2 = t2.flow("W", ptg.READ)
    f2.input(pred=("WRITER", "V", lambda g, l: {"z": 0}))
    t2.body(device="tpu", dyld="lower_scale2")
    # PRE pushes READER to level 1 via a CTL edge; READER then reads X(0,0)
    # directly — AFTER the scratch write has landed on its row
    t3 = p.task("PRE", z=ptg.span(0, 0))
    f3 = t3.flow("P", ptg.READ)
    f3.input(data=("X", lambda g, l: (0, 1)))
    c3 = t3.flow("GO", ptg.CTL)
    c3.output(succ=("READER", "D", lambda g, l: {"z": 0}))
    t3.body(device="tpu", dyld="lower_scale2")
    t4 = p.task("READER", z=ptg.span(0, 0))
    f4 = t4.flow("D", ptg.CTL)
    f4.input(pred=("PRE", "GO", lambda g, l: {"z": 0}))
    f5 = t4.flow("E", ptg.READ)
    f5.input(data=("X", lambda g, l: (0, 0)))
    f5.output(data=("Y", lambda g, l: (0, 0)))
    t4.body(device="tpu", dyld="lower_scale2")

    low = lower_taskpool(p.build())
    assert low.mode == "unrolled"
    low.execute()
    np.testing.assert_allclose(Y.to_dense(), x[:, :4])  # pristine, not 2x


register_traceable("lower_halo_sum",
                   lambda c, l, r: c + (0.0 if l is None else l.sum())
                   + (0.0 if r is None else r.sum()))


def test_wavefront_missing_inputs_pass_none():
    """Flows with no active input arrow (stencil boundaries) reach the
    traceable as ``None``; boundary tasks group separately from interior."""
    nb = 2
    x = np.arange(12, dtype=np.float32).reshape(2, 6)
    X = TiledMatrix.from_dense("X", x.copy(), 2, nb)
    NT = X.nt

    p = ptg.PTGBuilder("halo", X=X, NT=NT)
    t = p.task("H", i=ptg.span(0, lambda g, l: g.NT - 1))
    fc = t.flow("C", ptg.RW)
    fc.input(data=("X", lambda g, l: (0, l.i)))
    fc.output(data=("X", lambda g, l: (0, l.i)))
    fl = t.flow("L", ptg.READ)
    fl.input(data=("X", lambda g, l: (0, l.i - 1)),
             guard=lambda g, l: l.i > 0)
    fr = t.flow("R", ptg.READ)
    fr.input(data=("X", lambda g, l: (0, l.i + 1)),
             guard=lambda g, l: l.i < g.NT - 1)
    t.body(device="tpu", dyld="lower_halo_sum")

    low = lower_taskpool(p.build())
    assert low.mode == "wavefront"
    low.execute()
    tiles = [x[:, 2 * i:2 * i + 2] for i in range(NT)]
    expect = np.hstack([
        tiles[i]
        + (tiles[i - 1].sum() if i > 0 else 0.0)
        + (tiles[i + 1].sum() if i < NT - 1 else 0.0)
        for i in range(NT)])
    np.testing.assert_allclose(X.to_dense(), expect)


def test_python_body_is_not_lowerable():
    X = TiledMatrix.from_dense("X", np.zeros((4, 4), np.float32), 4, 4)
    p = ptg.PTGBuilder("nope", X=X)
    t = p.task("T", z=ptg.span(0, 0))
    f = t.flow("V", ptg.RW)
    f.input(data=("X", lambda g, l: (0, 0)))
    f.output(data=("X", lambda g, l: (0, 0)))
    t.body(lambda es, task, g, l: None)       # python-only body
    with pytest.raises(LoweringError):
        lower_taskpool(p.build())


def test_ragged_tiles_are_not_lowerable():
    a = np.zeros((6, 6), np.float32)          # 6/4 -> ragged edge tiles
    A = TiledMatrix.from_dense("A", a, 4, 4)
    B = TiledMatrix.from_dense("B", a.copy(), 4, 4)
    C = TiledMatrix.from_dense("C", a.copy(), 4, 4)
    with pytest.raises(LoweringError):
        lower_taskpool(tiled_gemm_ptg(A, B, C))


def test_writeback_bumps_versions():
    a, b, A, B, C = _gemm_fixture(n=8, nb=4, seed=3)
    v0 = C.data_of(0, 0).newest_copy().version
    lower_taskpool(tiled_gemm_ptg(A, B, C)).execute()
    assert C.data_of(0, 0).newest_copy().version == v0 + 1


# ---------------------------------------------------------------------------
# persistent lowering/compile cache (ISSUE 2)
# ---------------------------------------------------------------------------

def test_lowering_cache_hit_reuses_executable_and_matches_miss():
    """Two structurally identical lowerings share ONE jitted executable
    (the second invocation pays no trace/compile) and produce identical
    numerics — hit == miss bit-for-bit."""
    from parsec_tpu.ptg.lowering import lowering_cache

    a, b, A, B, C = _gemm_fixture(n=12, nb=4, seed=3)
    low1 = lower_taskpool(tiled_gemm_ptg(A, B, C))
    h0, m0 = lowering_cache.hits, lowering_cache.misses
    jf1 = low1.jitted()
    out1 = np.asarray(jf1(low1.initial_stores())["C"])

    a2, b2, A2, B2, C2 = _gemm_fixture(n=12, nb=4, seed=3)
    low2 = lower_taskpool(tiled_gemm_ptg(A2, B2, C2))
    assert low2.signature == low1.signature
    jf2 = low2.jitted()
    assert jf2 is jf1, "second lowering must hit the executable cache"
    assert lowering_cache.hits >= h0 + 1
    out2 = np.asarray(jf2(low2.initial_stores())["C"])
    np.testing.assert_array_equal(out1, out2)
    # identity tile grids lower to the dense store layout: out IS [n, n]
    np.testing.assert_allclose(out1, a @ b, rtol=1e-4, atol=1e-4)


def test_lowering_cache_second_invocation_compile_is_near_zero():
    """The acceptance pin: a repeat lowered stage in one process shows
    near-zero *_compile_s.  Warm must be at least 10x under cold (cold
    includes a real XLA compile; warm is a dict hit + cached call)."""
    import time

    def once(seed):
        _, _, A, B, C = _gemm_fixture(n=16, nb=4, seed=seed)
        low = lower_taskpool(tiled_gemm_ptg(A, B, C))
        st = low.initial_stores()
        t0 = time.perf_counter()
        out = low.jitted()(st)
        float(np.asarray(out["C"]).reshape(-1)[0])
        return time.perf_counter() - t0

    cold = once(seed=11)
    warm = once(seed=11)
    assert warm <= max(cold / 10.0, 0.05), (cold, warm)


def test_lowering_cache_distinguishes_different_structures():
    """Structurally different programs must carry different signatures
    (no false sharing of executables).  Same kernel + same collection
    names + different wavefront structure (stencil sweep lengths) is the
    sharpest case: only the emitted level plan differs."""
    from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
    from parsec_tpu.models.stencil import stencil_1d_ptg

    def low(iters):
        V = VectorTwoDimCyclic("V", lm=1 << 10, mb=1 << 8, P=1,
                               init_fn=lambda m, size:
                               np.zeros(size, np.float32))
        w = np.full(3, 1.0 / 3.0)
        return lower_taskpool(stencil_1d_ptg(V, w, iters))

    l4, l8 = low(4), low(8)
    assert l4.mode == l8.mode == "wavefront"
    assert l4.signature != l8.signature


def test_lowering_cache_param_disables_sharing(param):
    param("lowering_cache", False)
    _, _, A, B, C = _gemm_fixture(n=12, nb=4, seed=5)
    low1 = lower_taskpool(tiled_gemm_ptg(A, B, C))
    _, _, A2, B2, C2 = _gemm_fixture(n=12, nb=4, seed=5)
    low2 = lower_taskpool(tiled_gemm_ptg(A2, B2, C2))
    assert low1.jitted() is not low2.jitted()


def test_lowered_execute_goes_through_cache():
    """LoweredTaskpool.execute() (the collection-writeback convenience)
    rides the same cached executable."""
    a, b, A, B, C = _gemm_fixture(n=8, nb=4, seed=6)
    low1 = lower_taskpool(tiled_gemm_ptg(A, B, C))
    low1.execute()
    a2, b2, A2, B2, C2 = _gemm_fixture(n=8, nb=4, seed=6)
    low2 = lower_taskpool(tiled_gemm_ptg(A2, B2, C2))
    low2.execute()
    assert low2._jitted is low1._jitted
    np.testing.assert_allclose(C.to_dense(), C2.to_dense(), rtol=1e-5)
    np.testing.assert_allclose(C.to_dense(), a @ b, rtol=1e-4, atol=1e-4)
