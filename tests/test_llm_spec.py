"""Speculative decode (ISSUE 12): the VERIFY kernel trio, both spec
superpool incarnations (per-position predicated branches and the batched
serving path), the paged-KV tail-rollback primitive, and the batcher's
draft/verify/rollback loop with adaptive per-stream spec_k — everything
gated token-for-token against the non-speculative greedy oracle at
acceptance 0, partial, and 1.0 (``docs/LLM.md``)."""

import numpy as np
import pytest
from unittest import mock

from parsec_tpu.data.datatype import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.paged_kv import PagedKVCollection
from parsec_tpu.llm import (NgramDrafter, ToyLM, preallocate_decode_steps,
                            read_spec_batched, read_spec_chain,
                            seed_spec_batched_pool, seed_spec_superpool,
                            spec_batched_ptg, spec_superpool_ptg)
from parsec_tpu.llm.decode import prefill_chunks, seed_spec_batched
from parsec_tpu.ops import ragged_attention as ra
from parsec_tpu.runtime import Context
from parsec_tpu.serve import RuntimeServer

MODEL = ToyLM()
H, D = MODEL.num_heads, MODEL.head_dim


def _kv(page_size=4, **kw):
    return PagedKVCollection("KV", page_size=page_size, num_heads=H,
                             head_dim=D, **kw)


class OracleDrafter(NgramDrafter):
    """Drafts the TRUE continuation (acceptance 1.0): the observed
    history IS the stream's prompt + kept tokens, so the reference
    decode from it is exactly what the target will emit."""

    def __init__(self):
        self.hist = []

    def observe(self, token):
        self.hist.append(int(token))

    def draft(self, cur, k):
        assert self.hist and self.hist[-1] == int(cur)
        return MODEL.reference_generate(self.hist, k)


class GarbageDrafter(NgramDrafter):
    """Always proposes WRONG tokens (acceptance 0): off-by-one of the
    true continuation, padded to the full cap so every pool drafts."""

    def __init__(self):
        self.hist = []

    def observe(self, token):
        self.hist.append(int(token))

    def draft(self, cur, k):
        return [(t + 1) % MODEL.vocab
                for t in MODEL.reference_generate(self.hist, k)]


# ---------------------------------------------------------------------------
# kernels: every incarnation agrees (the VERIFY trio, the batched pair)
# ---------------------------------------------------------------------------

def test_verify_step_incarnations_agree_and_predicate():
    q3t = MODEL.q3_table()
    o = MODEL.q3(13)[2]                       # any (H, D) activation
    for st_prev in ([5.0, 1.0, 0.0, -1.0],    # live, no EOS
                    [5.0, 1.0, 0.0, 7.0],     # live, EOS armed
                    [5.0, 0.0, 0.0, 7.0],     # rejected: dead
                    [5.0, 1.0, 1.0, 7.0]):    # done: dead
        for dtok in (5.0, 6.0):
            prev = np.array(st_prev, np.float32)
            d = np.array([dtok], np.float32)
            want = ra.verify_step_np(o, prev, d, q3t)
            got = np.asarray(ra._verify_jnp(o, prev, d, q3t))
            assert np.abs(got - want).max() < 1e-6, (st_prev, dtok)


def test_verify_eos_inside_rejected_branch_is_invisible():
    """An EOS the target would sample at a DEAD position (rejected
    draft, or already done) must neither surface nor finish the
    stream."""
    q3t = MODEL.q3_table()
    o = MODEL.q3(13)[2]
    tok = ra.verify_step_np(o, np.array([5, 1, 0, -1], np.float32),
                            np.array([5.0], np.float32), q3t)
    eos = tok[0]                               # the token argmax yields
    # same o, but the position is dead (prev live=0): the would-be EOS
    # token is never examined — state holds, done stays 0
    dead = ra.verify_step_np(o, np.array([5, 0, 0, eos], np.float32),
                             np.array([5.0], np.float32), q3t)
    assert dead[1] == 0.0 and dead[2] == 0.0 and dead[0] == 5.0
    # at a LIVE position the same sample finishes the stream
    live = ra.verify_step_np(o, np.array([5, 1, 0, eos], np.float32),
                             np.array([5.0], np.float32), q3t)
    assert live[1] == 1.0 and live[2] == 1.0 and live[0] == eos


def test_spec_attn_page_incarnations_agree_with_serial_chain():
    """The batched multi-query page update must equal S independent
    single-query chains — including zero-limit (padded/empty) rows."""
    tokens = [3, 7, 11, 5, 9, 2, 40]
    page = np.zeros((3, 8, H, D), np.float32)
    for i, t in enumerate(tokens):
        q3 = MODEL.q3(t)
        page[0, i], page[1, i] = q3[1], q3[2]
    page[2, 0, 0, 0] = len(tokens)
    S = 4
    qs = np.zeros((S, 3, H, D), np.float32)
    for i, t in enumerate((13, 22, 8)):
        qs[i] = MODEL.q3(t)
    lim = np.array([3, 7, 5, 0], np.float32)   # ragged causal limits
    acc = np.zeros((S, H, D + 2), np.float32)
    got = ra.spec_attn_page_np(qs, page, lim, acc)
    gotj = np.asarray(ra._spec_attn_page_jnp(qs, page, lim, acc))
    assert np.abs(got - gotj).max() < 1e-5
    for s in range(3):                         # rows with live limits
        pg = np.array(page)
        pg[2, 0, 0, 0] = lim[s]                # single-query fill = limit
        want = ra.attn_page_update_np(qs[s], pg,
                                      np.zeros((H, D + 2), np.float32))
        assert np.abs(got[s] - want).max() < 1e-5, s
    # the padded (all-masked) row stays an EMPTY flash state: zero sum
    # and denominator, so it finalizes to zeros (the running max is a
    # NEG_INF sentinel there — equivalent, never read at l == 0)
    assert np.abs(got[3][:, :D]).max() == 0.0
    assert np.abs(got[3][:, D + 1]).max() == 0.0
    assert np.abs(ra.finalize_acc_np(got[3])).max() == 0.0


def test_spec_verify_incarnations_agree_across_acceptance():
    q3t = MODEL.q3_table()
    rng = np.random.default_rng(7)
    S = 5
    acc = rng.standard_normal((S, H, D + 2)).astype(np.float32)
    acc[:, :, D + 1] = np.abs(acc[:, :, D + 1]) + 0.5
    l = acc[:, :, D + 1]
    o = acc[:, :, :D] / l[:, :, None]
    tgt = np.argmax(o.reshape(S, -1) @ q3t[:, 0].reshape(
        MODEL.vocab, -1).T, axis=1)
    for chain, eos in (
            ([9] + list(tgt[:4]), -1.0),       # full acceptance
            ([9] + list(tgt[:2]) + [63, 63], -1.0),  # reject at pos 3
            ([9, 63, 63, 63, 63], -1.0),       # reject at pos 1
            ([9] + list(tgt[:4]), float(tgt[1])),    # EOS at live pos 1
            ([9, 63, 63, 63, 63], float(tgt[2]))):   # EOS on dead pos
        dt = np.zeros(S + 2, np.float32)
        dt[0], dt[1] = S, eos
        dt[2:2 + S] = chain
        want = ra.spec_verify_np(acc, dt, q3t)
        got = np.asarray(ra._spec_verify_jnp(acc, dt, q3t))
        assert np.abs(got - want).max() < 1e-6, (chain, eos)


# ---------------------------------------------------------------------------
# the pools: acceptance sweep vs the oracle, both incarnations
# ---------------------------------------------------------------------------

def _run_general(prompts, drafts, eos=None):
    kv = _kv()
    DRAFT = DictCollection("DRAFT", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    STOK = DictCollection("STOK", dtt=TileType((4,), np.float32))
    DTOK = DictCollection("DTOK", dtt=TileType((1,), np.float32))
    EMB = DictCollection("EMB", dtt=TileType(MODEL.q3_table().shape,
                                             np.float32))
    npos = seed_spec_superpool(MODEL, kv, DRAFT, DTOK, STOK, EMB,
                               prompts, drafts, eos=eos)
    tp = spec_superpool_ptg(kv, DRAFT, O, STOK, DTOK, EMB, list(prompts),
                            [npos[s] for s in prompts])
    report = tp.validate()
    assert not report.errors and not report.warnings, report
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    return {s: read_spec_chain(STOK, s, npos[s]) for s in prompts}, kv


def _run_batched(prompts, drafts, eos=None):
    kv = _kv()
    pad = max(len(d) for d in drafts.values()) + 1
    QS = DictCollection("QS", dtt=TileType((pad, 3, H, D), np.float32))
    LIM = DictCollection("LIM", dtt=TileType((pad,), np.float32))
    DTOKS = DictCollection("DTOKS", dtt=TileType((pad + 2,), np.float32))
    VOUT = DictCollection("VOUT", dtt=TileType((pad + 2,), np.float32))
    EMB = DictCollection("EMB", dtt=TileType(MODEL.q3_table().shape,
                                             np.float32))
    npos, pad = seed_spec_batched_pool(MODEL, kv, QS, LIM, DTOKS, EMB,
                                       prompts, drafts, pad=pad,
                                       eos=eos)
    tp = spec_batched_ptg(kv, QS, LIM, DTOKS, VOUT, EMB, list(prompts),
                          [npos[s] for s in prompts], pad=pad)
    report = tp.validate()
    assert not report.errors and not report.warnings, report
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    return {s: read_spec_batched(VOUT, s) for s in prompts}, kv


@pytest.mark.parametrize("run", [_run_general, _run_batched],
                         ids=["general", "batched"])
def test_spec_pool_acceptance_sweep_matches_oracle(run):
    """Acceptance 1.0, partial, and 0 — all token-for-token equal to the
    greedy oracle: full drafts emit every position, a mid-chain
    mismatch emits exactly the accepted prefix + the correction token,
    garbage emits position 0 only."""
    prompts = {"a": [3, 7, 11, 5], "b": [1, 40]}
    ref = {s: MODEL.reference_generate(p, 6) for s, p in prompts.items()}
    full = {s: ref[s][:5] for s in prompts}
    got, _ = run(prompts, full)
    for s in prompts:
        assert got[s][0] == ref[s][:6], (s, got[s])
    partial = {s: ref[s][:2] + [(ref[s][2] + 1) % 64,
                                (ref[s][3] + 7) % 64] for s in prompts}
    got, _ = run(prompts, partial)
    for s in prompts:
        assert got[s][0] == ref[s][:3], (s, got[s])
    garbage = {s: [(t + 1) % 64 for t in ref[s][:5]] for s in prompts}
    got, _ = run(prompts, garbage)
    for s in prompts:
        assert got[s][0] == ref[s][:1], (s, got[s])


@pytest.mark.parametrize("run", [_run_general, _run_batched],
                         ids=["general", "batched"])
def test_spec_pool_eos_in_live_vs_rejected_branch(run):
    """EOS at a LIVE position truncates there (done); the same stream
    with the EOS position already rejected must emit the pre-rejection
    prefix with done=False — an EOS inside a rejected branch never
    finishes the stream."""
    prompt = [3, 7, 11, 5]
    ref = MODEL.reference_generate(prompt, 6)
    eos = ref[2]
    want = MODEL.reference_generate(prompt, 6, eos=eos)
    assert 1 <= len(want) < 6 and ref[0] != eos
    toks, done = run({"a": prompt}, {"a": ref[:5]}, eos=eos)[0]["a"]
    assert toks == want and done             # EOS kept, chain cut there
    # reject position 1: positions 1.. are dead, incl. the EOS position
    bad = [(ref[0] + 1) % 64] + ref[1:5]
    toks, done = run({"a": prompt}, {"a": bad}, eos=eos)[0]["a"]
    assert toks == ref[:1] and not done


# ---------------------------------------------------------------------------
# rollback_tail: the version-jump truncation primitive
# ---------------------------------------------------------------------------

def test_rollback_tail_across_page_boundary_and_ledger():
    kv = _kv(page_size=4)
    kv.alloc_seq("s")
    for key, tile in prefill_chunks(MODEL, kv, "s",
                                    [3, 7, 11]).items():
        pg = kv.data_of(*key).get_copy(0)
        pg.value = np.array(tile, copy=True)
        pg.version += 1
    # speculative appends: 5 positions from token 3 -> slots 3..7,
    # crossing from page 0 into page 1 (staged manually — this test is
    # about rollback, not seeding)
    preallocate_decode_steps(kv, "s", 5)
    for t in range(5):
        pg, slot = divmod(3 + t, 4)
        c = kv.data_of("s", pg).get_copy(0)
        c.value[0, slot] = 1.0 + t
        c.value[2, 0, 0, 0] = min(4, 3 + 5 - pg * 4)
        c.version += 1
    kv.note_appended("s", 5)
    assert kv.seq_len("s") == 8
    # roll back to 5 tokens: page 1 keeps 1 slot, page 0 untouched
    rolled = kv.rollback_tail("s", 5)
    assert rolled == 3
    assert kv.seq_len("s") == 5
    p1 = np.asarray(kv.data_of("s", 1).newest_copy().value)
    assert p1[2, 0, 0, 0] == 1                 # boundary fill truncated
    assert p1[0, 0, 0, 0] == 2.0               # kept slot preserved
    assert np.abs(p1[0, 1:]).max() == 0.0      # scrubbed slots zeroed
    p0 = np.asarray(kv.data_of("s", 0).newest_copy().value)
    assert p0[2, 0, 0, 0] == 4                 # full page untouched
    s = kv.stats()
    assert s["tail_rollbacks"] == 1 and s["slots_rolled_back"] == 3
    # bounds are enforced
    with pytest.raises(ValueError):
        kv.rollback_tail("s", 6)
    with pytest.raises(ValueError):
        kv.rollback_tail("s", -1)


def test_rollback_tail_invalidates_stale_device_copies():
    """The recycle-detach discipline (PR 11) extended to rollback: a
    dirty device copy holding the rejected speculative appends must
    never satisfy a later stage-in version check."""
    from parsec_tpu.data.data import DataCopy
    kv = _kv(page_size=4)
    kv.alloc_seq("s")
    kv.alloc_page("s")
    kv.note_appended("s", 3)
    d = kv.data_of("s", 0)
    dev = DataCopy(d, 1, value=np.ones(kv.default_dtt.shape, np.float32))
    dev.version = d.get_copy(0).version + 5      # device runs ahead
    d.attach_copy(dev)
    kv.rollback_tail("s", 1)
    assert d.get_copy(1) is None                 # detached
    host = d.get_copy(0)
    assert host.version > dev.version            # version jumped past
    assert np.asarray(host.value)[2, 0, 0, 0] == 1
    assert kv.seq_len("s") == 1


def test_seed_staging_invalidates_stale_device_copies():
    """Seed-time speculative staging rides the same recycle-detach
    discipline (code-review finding): a dirty device copy running
    ahead of host must be detached and the staged host bytes must
    version-jump past it — otherwise a deferred device writeback would
    silently clobber the staged draft k/v and regress the version."""
    from parsec_tpu.data.data import DataCopy
    kv = _kv(page_size=4)
    pad = 4
    QS = DictCollection("qs", dtt=TileType((pad, 3, H, D), np.float32))
    LIM = DictCollection("lim", dtt=TileType((pad,), np.float32))
    DTOKS = DictCollection("dt", dtt=TileType((pad + 2,), np.float32))
    kv.alloc_seq("s")
    kv.alloc_page("s")
    kv.note_appended("s", 2)
    d = kv.data_of("s", 0)
    dev = DataCopy(d, 1, value=np.full(kv.default_dtt.shape, 7.0,
                                       np.float32))
    dev.version = d.get_copy(0).version + 3      # device runs ahead
    d.attach_copy(dev)
    preallocate_decode_steps(kv, "s", 3)
    seed_spec_batched(MODEL, kv, QS, LIM, DTOKS, "s", 5, [9, 2], pad)
    assert d.get_copy(1) is None                 # detached
    host = d.get_copy(0)
    assert host.version > dev.version            # jumped past
    # the staged bytes sourced the NEWEST copy (the device one)
    assert np.asarray(host.value)[0, 0, 0, 0] == 7.0
    assert np.asarray(host.value)[2, 0, 0, 0] == 4  # staged fill


def test_rollback_tail_refuses_shared_pages():
    """Rollback into a CoW-shared page means the ledger and block table
    disagree — fail loudly instead of corrupting the sibling."""
    kv = _kv(page_size=4)
    kv.alloc_seq("p")
    kv.alloc_page("p")
    kv.note_appended("p", 4)
    kv.fork("p", "c")
    with pytest.raises(RuntimeError, match="shared"):
        kv.rollback_tail("c", 2)


# ---------------------------------------------------------------------------
# the batcher: draft/verify/rollback end to end, adaptive spec_k
# ---------------------------------------------------------------------------

def _serve_all(prompts, max_new, drafter_cls=None, eos=None, tenant_fn=None,
               nb_cores=2):
    patch = mock.patch("parsec_tpu.llm.batcher.NgramDrafter",
                       drafter_cls) if drafter_cls else None
    if patch:
        patch.start()
    try:
        with RuntimeServer(nb_cores=nb_cores) as server:
            tks = [server.submit_stream(
                p, max_new_tokens=max_new, eos=eos,
                tenant=tenant_fn(i) if tenant_fn else "t")
                for i, p in enumerate(prompts)]
            outs = [tk.result(timeout=300)["tokens"] for tk in tks]
            stats = server.stats()["llm"]
            metrics = server.metrics()
        return outs, stats, metrics, tks
    finally:
        if patch:
            patch.stop()


@pytest.mark.parametrize("drafter,accept", [
    (OracleDrafter, 1.0), (NgramDrafter, None), (GarbageDrafter, 0.0)],
    ids=["accept-1.0", "accept-partial", "accept-0"])
def test_batcher_spec_acceptance_sweep_matches_oracle(param, drafter,
                                                      accept):
    """The ISSUE-12 acceptance-criteria sweep at the serving layer:
    whatever the drafter's quality, every stream is token-for-token
    the non-speculative greedy oracle — a rejected token or stale
    rolled-back KV surfacing anywhere breaks equality."""
    param("llm_spec_k", 6)
    param("llm_spec_adaptive", False)
    prompts = [[3, 7, 11, 5], [1, 40], [8, 8, 2, 6], [5, 9]]
    outs, stats, _, _ = _serve_all(prompts, 14, drafter_cls=drafter)
    for p, o in zip(prompts, outs):
        assert o == MODEL.reference_generate(p, 14), (p, o)
    assert stats["spec_submits"] > 0, stats
    if accept is not None:
        assert stats["spec_accept_rate"] == accept, stats
    if accept == 0.0:
        # every drafted position was rejected and rolled back
        assert stats["kv"]["tail_rollbacks"] == stats["spec_submits"]
        assert stats["spec_tokens"] == stats["spec_submits"]


def test_batcher_spec_eos_mid_draft_matches_truncated_oracle(param):
    param("llm_spec_k", 8)
    param("llm_spec_adaptive", False)
    ref = MODEL.reference_generate([3, 7, 11, 5], 16)
    eos = ref[5]
    want = MODEL.reference_generate([3, 7, 11, 5], 16, eos=eos)
    assert 1 <= len(want) < 16
    outs, stats, _, _ = _serve_all([[3, 7, 11, 5], [1, 40]], 16,
                                   drafter_cls=OracleDrafter, eos=eos)
    assert outs[0] == want
    assert outs[1] == MODEL.reference_generate([1, 40], 16, eos=eos)
    assert stats["kv"]["physical_pages"] == 0


def test_batcher_spec_over_trie_forked_prefix(param):
    """Spec decode composes with the PR-11 radix-tree prefix cache: a
    trie adoptee's CoW prompt pages feed the spec pool's frozen-page
    reads, its speculative tail stays private, and tokens stay
    oracle-exact."""
    param("llm_spec_k", 8)
    param("llm_prefix_cache", True)
    shared = [(5 * i + 11) % 64 for i in range(40)]
    with RuntimeServer(nb_cores=2) as server:
        donor = server.submit_stream(shared + [3], max_new_tokens=1,
                                     tenant="p")
        donor.result(timeout=120)         # retires -> donates the prefix
        tks = [server.submit_stream(shared + [3], max_new_tokens=12,
                                    tenant="p") for _ in range(3)]
        want = MODEL.reference_generate(shared + [3], 12)
        for tk in tks:
            assert tk.result(timeout=120)["tokens"] == want
        llm = server.stats()["llm"]
        assert llm["kv"]["prefix_hits"] >= 3, llm["kv"]
        assert llm["spec_submits"] > 0, llm


def test_batcher_spec_with_fork_on_prompt(param):
    """Spec decode composes with fork_from= CoW prompt sharing: the
    fork children's speculative tails privatize away from the shared
    prompt pages and every fork matches the oracle."""
    param("llm_spec_k", 6)
    prompt = list(range(1, 41))
    with RuntimeServer(nb_cores=2) as server:
        t1 = server.submit_stream(prompt, max_new_tokens=8)
        t2 = server.submit_stream(prompt, max_new_tokens=8, fork_from=t1)
        want = MODEL.reference_generate(prompt, 8)
        assert t1.result(timeout=120)["tokens"] == want
        assert t2.result(timeout=120)["tokens"] == want
        assert server.stats()["llm"]["kv"]["physical_pages"] == 0


def test_adaptive_spec_k_converges_off_on_garbage_and_stays_cheap(param):
    """Acceptance-rate-0 pathological traffic: the adaptive controller
    must converge every stream's spec_k to ~0 (the non-speculative
    fallback), the tenant prior must spare LATER streams the descent,
    and the structural cost must stay near the PR-9 path (submits
    within 10% once converged)."""
    param("llm_spec_k", 16)
    param("llm_spec_adaptive", True)
    prompts = [[(7 * i + 3 * j) % 64 for j in range(8)]
               for i in range(4)]
    outs, stats, _, tks = _serve_all(prompts, 64,
                                     drafter_cls=GarbageDrafter)
    for p, o in zip(prompts, outs):
        assert o == MODEL.reference_generate(p, 64), p
    assert stats["spec_accept_rate"] == 0.0, stats
    # every stream converged off (<= 1 means effectively non-spec)
    assert all((tk.spec_k or 0) <= 1 for tk in tks), \
        [tk.spec_k for tk in tks]
    # structural throughput proxy: with k=8 pools the non-spec path
    # needs ceil(64/8)=8 submits per stream; the descent costs a few
    # 1-token spec pools up front, the prior spares later streams —
    # in total within ~10% + the bounded descent overhead
    nonspec_submits = 8 * len(prompts)
    assert stats["decode_submits"] <= nonspec_submits * 1.1 + 6, stats
    # a second wave on the SAME server would start off thanks to the
    # tenant prior; approximated here by the cumulative accept rate
    # staying pinned at 0 with only log2(16)-ish spec pools ever run
    assert stats["spec_submits"] <= 6 * len(prompts), stats


def test_spec_metrics_surface_in_slo_plane_and_runtime_report(param):
    """The satellite surfacing contract: per-tenant spec_accept_rate /
    spec_tokens_per_submit histograms in RuntimeServer.metrics(), the
    cumulative counter pair in batcher stats and in
    runtime_report()["llm"] — surviving batcher retirement."""
    from parsec_tpu.prof.flight_recorder import runtime_report
    import parsec_tpu.llm.batcher as batcher_mod
    param("llm_spec_k", 6)
    param("llm_spec_adaptive", False)
    before = dict(batcher_mod._retired_totals)
    prompts = [[3, 7, 11, 5], [1, 40]]
    outs, stats, metrics, _ = _serve_all(
        prompts, 12, drafter_cls=OracleDrafter,
        tenant_fn=lambda i: f"ten{i}")
    for p, o in zip(prompts, outs):
        assert o == MODEL.reference_generate(p, 12), p
    assert stats["spec_accept_rate"] == 1.0
    assert stats["spec_tokens_per_submit"] > 1.0
    for i in range(len(prompts)):
        ten = metrics["tenants"][f"ten{i}"]
        assert ten["spec_accept_rate_count"] > 0, ten
        assert ten["spec_tokens_per_submit_count"] > 0, ten
        assert ten["spec_tokens_per_submit_p50"] > 1.0, ten
    # the server drained above -> the batcher retired -> its counters
    # folded into the process-cumulative report block
    rep = runtime_report()["llm"]
    d_tokens = rep["spec_tokens"] - before.get("spec_tokens", 0)
    assert d_tokens >= stats["spec_tokens"], (rep, stats)
    assert rep["spec_accept_rate"] > 0.0
    assert rep["spec_tokens_per_submit"] > 0.0


def test_spec_speedup_on_draftable_workload_vs_nonspec(param):
    """A coarse in-suite sanity of the ISSUE-12 speedup claim (the real
    gate is perf_smoke's LLM_SPEC_SPEEDUP_MIN on bench_llm's spec
    axis): on a draftable workload the spec path must emit multiple
    tokens per submit — structurally impossible for the PR-9 path at
    the same k."""
    param("llm_spec_k", 16)
    param("llm_spec_adaptive", True)
    prompts = [[(3 * j) % 64 for j in range(8)],
               [(60 + j) % 64 for j in range(8)]]
    outs, stats, _, _ = _serve_all(prompts, 48)
    for p, o in zip(prompts, outs):
        assert o == MODEL.reference_generate(p, 48), p
    assert stats["spec_tokens_per_submit"] >= 4.0, stats
    assert stats["spec_accept_rate"] >= 0.5, stats
