"""JDF textual front-end tests.

Mirrors the reference's DSL tier (SURVEY §4): working JDFs (chain with
guarded ternary arrows, CTL-only EP, GEMM equivalence against the builder
API) plus the must-fail compilations of the ``ptgpp`` error-case suite.
"""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import TiledMatrix, VectorTwoDimCyclic
from parsec_tpu.ptg import JDFError, parse_jdf
from parsec_tpu.runtime import Context


CHAIN_JDF = """
/* Ex04_ChainData analog: a value threads tile V(0) through NT tasks */
NT   [type = int]
V    [type = data]

T(i)
  i = 0 .. NT-1
  : V(i)
  RW A <- (i == 0) ? V(0) : A T(i-1)
       -> (i < NT-1) ? A T(i+1) : V(0)
BODY
  A += 1
END
"""


def test_chain_jdf_single_rank():
    V = VectorTwoDimCyclic("V", lm=8, mb=2, P=1,
                           init_fn=lambda m, size: np.zeros(size))
    tp = parse_jdf(CHAIN_JDF, name="chain").build(NT=4, V=V)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    np.testing.assert_allclose(V.data_of(0).newest_copy().value,
                               np.full(2, 4.0))


def _chain_jdf_body(ctx, rank, nranks):
    V = VectorTwoDimCyclic("V", lm=12, mb=2, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    tp = parse_jdf(CHAIN_JDF, name="chain").build(NT=6, V=V)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.comm_barrier()
    if rank == 0:
        return np.asarray(V.data_of(0).newest_copy().value).copy()
    return None


def test_chain_jdf_multirank():
    res = run_multirank(3, _chain_jdf_body)
    np.testing.assert_allclose(res[0], np.full(2, 6.0))


EP_JDF = """
NT     [type = int]
DEPTH  [type = int]
V      [type = data]

EP(d, n)
  d = 0 .. DEPTH-1
  n = 0 .. NT-1
  : V(n)
  CTL X <- (d > 0) ? X EP(d-1, n)
        -> (d < DEPTH-1) ? X EP(d+1, n)
BODY
  task.taskpool.counter += 1
END
"""


def test_ep_jdf_ctl_only():
    """The scheduler microbenchmark shape (tests/runtime/scheduling/ep.jdf):
    CTL-only DAG, NT independent depth-DEPTH chains."""
    V = VectorTwoDimCyclic("V", lm=4, mb=1, P=1,
                           init_fn=lambda m, size: np.zeros(size))
    tp = parse_jdf(EP_JDF, name="ep").build(NT=4, DEPTH=5, V=V)
    tp.counter = 0
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert tp.counter == 4 * 5


GEMM_JDF = """
%{
import numpy as np
%}
A [type = data]
B [type = data]
C [type = data]
MT [type = int]
NT [type = int]
KT [type = int]

GEMM(m, n, k)
  m = 0 .. MT-1
  n = 0 .. NT-1
  k = 0 .. KT-1
  : C(m, n)
  READ X <- A(m, k)
  READ Y <- B(k, n)
  RW   Z <- (k == 0) ? C(m, n) : Z GEMM(m, n, k-1)
        -> (k < KT-1) ? Z GEMM(m, n, k+1) : C(m, n)
  ; KT - k
BODY
  Z += X @ Y
END
"""


def test_gemm_jdf_matches_numpy():
    rng = np.random.default_rng(1)
    n, nb = 48, 16
    A = rng.standard_normal((n, n)).astype(np.float32)
    B = rng.standard_normal((n, n)).astype(np.float32)
    dA = TiledMatrix.from_dense("A", A, nb, nb)
    dB = TiledMatrix.from_dense("B", B, nb, nb)
    dC = TiledMatrix.from_dense("C", np.zeros((n, n), np.float32), nb, nb)
    tp = parse_jdf(GEMM_JDF, name="gemm").build(
        A=dA, B=dB, C=dC, MT=dC.mt, NT=dC.nt, KT=dA.nt)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    np.testing.assert_allclose(dC.to_dense(), A @ B, rtol=1e-4, atol=1e-4)


def test_prologue_and_defaults():
    src = """
%{
def double(x):
    return 2 * x
%}
N = double(3) [type = int]
V [type = data]

T(i)
  i = 0 .. N-1
  : V(0)
  RW A <- (i == 0) ? V(0) : A T(i-1)
       -> (i < N-1) ? A T(i+1) : V(0)
BODY
  A += double(1)
END
"""
    V = VectorTwoDimCyclic("V", lm=1, mb=1, P=1,
                           init_fn=lambda m, size: np.zeros(size))
    tp = parse_jdf(src).build(V=V)   # N defaults to double(3) == 6
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    np.testing.assert_allclose(V.data_of(0).newest_copy().value, [12.0])


def test_functional_rebind_body():
    """A body that rebinds a flow name gets the new array written back."""
    src = """
V [type = data]

T(i)
  i = 0 .. 0
  : V(0)
  RW A <- V(0)
       -> V(0)
BODY
  A = A + 41.0
END
"""
    V = VectorTwoDimCyclic("V", lm=1, mb=1, P=1,
                           init_fn=lambda m, size: np.ones(size))
    tp = parse_jdf(src).build(V=V)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    np.testing.assert_allclose(V.data_of(0).newest_copy().value, [42.0])


def test_floor_division_survives_everywhere():
    """'//' is Python floor division in expressions/bodies, never a trailing
    comment; only full-line '//' and '/* */' are comments."""
    src = """
// a full-line comment
/* a block
   comment */
N [type = int]
V [type = data]

T(i)
  i = 0 .. N // 2
  : V(0)
  RW A <- (i == 0) ? V(0) : A T(i-1)
       -> (i < N // 2) ? A T(i+1) : V(0)
BODY
  A += i // 2    # floor division inside a python body
END
"""
    V = VectorTwoDimCyclic("V", lm=1, mb=1, P=1,
                           init_fn=lambda m, size: np.zeros(size))
    tp = parse_jdf(src).build(N=8, V=V)   # i = 0..4
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    expect = sum(i // 2 for i in range(5))
    np.testing.assert_allclose(V.data_of(0).newest_copy().value, [expect])


def test_descending_range_and_comprehension_expr():
    """Negative-step ranges include the low endpoint; comprehensions inside
    expressions can see JDF parameters/globals."""
    src = """
N [type = data]
V [type = data]

T(i)
  i = 3 .. 0 .. -1
  : V(0)
  RW A <- (i == 3) ? V(0) : A T(i+1)
       -> (i > 0) ? A T(i-1) : V(0)
  ; sum(j for j in range(i))
BODY
  A[0] = A[0] * 10 + i
END
"""
    V = VectorTwoDimCyclic("V", lm=1, mb=1, P=1,
                           init_fn=lambda m, size: np.zeros(size))
    jdf = parse_jdf(src)
    tp = jdf.build(N=V, V=V)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    # chain runs i = 3, 2, 1, 0 -> digits appended in that order
    np.testing.assert_allclose(V.data_of(0).newest_copy().value, [3210.0])


def test_global_named_like_body():
    """Identifiers beginning with BODY are not the BODY keyword."""
    src = """
BODY_SIZE [type = int]
V [type = data]

T(i)
  i = 0 .. BODY_SIZE - 1
  : V(0)
  RW A <- V(0)
       -> V(0)
BODY
  A += 1
END
"""
    V = VectorTwoDimCyclic("V", lm=1, mb=1, P=1,
                           init_fn=lambda m, size: np.zeros(size))
    tp = parse_jdf(src).build(BODY_SIZE=1, V=V)
    assert tp.task_class("T") is not None


def test_fail_write_flow_task_input_in_else_branch():
    _must_fail("""
V [type = data]
T(i)
  i = 0 .. 3
  : V(0)
  WRITE A <- (i == 0) ? V(0) : A T(i-1)
        -> V(0)
BODY
END
""", "WRITE flow", V=object())


# ---------------------------------------------------------------------------
# must-fail suite (the ptgpp NODEFAULTBUILD error cases, SURVEY §4)
# ---------------------------------------------------------------------------

def _must_fail(src, match, **bindings):
    with pytest.raises(JDFError, match=match):
        parse_jdf(src).build(**bindings)


def test_fail_unknown_target_class():
    _must_fail("""
V [type = data]
T(i)
  i = 0 .. 3
  : V(0)
  RW A <- V(0) -> A NOPE(i+1)
BODY
END
""", "unknown task class", V=object())


def test_fail_unknown_flow_on_target():
    _must_fail("""
V [type = data]
T(i)
  i = 0 .. 3
  : V(0)
  RW A <- V(0) -> (i < 3) ? B T(i+1) : V(0)
BODY
END
""", "has no flow", V=object())


def test_fail_missing_range():
    _must_fail("""
V [type = data]
T(i, j)
  i = 0 .. 3
  : V(0)
  RW A <- V(0) -> V(0)
BODY
END
""", "has no range", V=object())


def test_fail_ctl_with_data():
    _must_fail("""
V [type = data]
T(i)
  i = 0 .. 3
  : V(0)
  CTL X <- V(0)
BODY
END
""", "CTL flow", V=object())


def test_fail_missing_body():
    _must_fail("""
V [type = data]
T(i)
  i = 0 .. 3
  : V(0)
  RW A <- V(0) -> V(0)
""", "no BODY", V=object())


def test_fail_unbound_global():
    _must_fail("""
N [type = int]
V [type = data]
T(i)
  i = 0 .. N-1
  : V(0)
  RW A <- V(0) -> V(0)
BODY
END
""", "needs a value", V=object())


def test_fail_body_without_end():
    with pytest.raises(JDFError, match="without END"):
        parse_jdf("""
V [type = data]
T(i)
  i = 0 .. 3
  : V(0)
  RW A <- V(0) -> V(0)
BODY
  pass
""")


def test_fail_affinity_not_data():
    _must_fail("""
N [type = int]
T(i)
  i = 0 .. 3
  : N(0)
  RW A <- N(0) -> N(0)
BODY
END
""", "not a .type = data. global", N=4)


def test_fail_write_flow_task_input():
    _must_fail("""
V [type = data]
T(i)
  i = 0 .. 3
  : V(0)
  WRITE A <- A T(i-1)
        -> V(0)
BODY
END
""", "WRITE flow", V=object())


class TestNewNullTargets:
    """JDF NEW/NULL endpoints (reference jdf.h special targets; Ex03's
    `<- NEW` first-link form is the SURVEY §7 step-3 exit shape)."""

    def test_ex03_shape_with_new(self):
        """The reference Ex03_ChainMPI.jdf chain: the first task allocates
        its datum with NEW, every later task receives it from its
        predecessor, incrementing as it goes."""
        import numpy as np

        from parsec_tpu.data.data import TileType
        from parsec_tpu.runtime import Context

        src = """
        NB    [type = int]
        T1    [type = int]
        SINK  [type = int]

        Task(k)
          k = 0 .. NB
          RW A <- (k == 0) ? NEW : A Task(k - 1)  [type = T1]
               -> (k < NB) ? A Task(k + 1)
        BODY
          if k == 0:
              A[...] = 0
          else:
              A[...] = A + 1
          if k == NB:
              SINK.append(float(A[0]))
        END
        """
        sink = []
        tp = parse_jdf(src, "ex03new").build(
            NB=7, T1=TileType((1,), np.float32), SINK=sink)
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        ctx.fini()
        assert sink == [7.0]

    def test_null_input_and_output(self):
        import numpy as np

        from parsec_tpu.data.data import TileType
        from parsec_tpu.data_dist.collection import DictCollection
        from parsec_tpu.runtime import Context

        src = """
        A     [type = data]
        SINK  [type = int]

        T(i)
          i = 0 .. 1
          : A(0)
          RW V <- (i == 0) ? A(0) : NULL
               -> NULL
        BODY
          SINK.append(V is None)
        END
        """
        coll = DictCollection("A", dtt=TileType((1,), np.float32),
                              init_fn=lambda *k: np.zeros(1, np.float32))
        sink = []
        tp = parse_jdf(src, "nulls").build(A=coll, SINK=sink)
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        ctx.fini()
        assert sorted(sink) == [False, True]   # i=0 got data, i=1 NULL

    def test_new_without_type_rejected(self):
        src = """
        NB [type = int]

        T(i)
          i = 0 .. 0
          RW V <- NEW
        BODY
          pass
        END
        """
        with pytest.raises(JDFError, match="NEW needs"):
            parse_jdf(src, "badnew").build(NB=1)

    def test_new_on_ctl_flow_rejected_with_line(self):
        src = """
        NB [type = int]

        T(i)
          i = 0 .. 0
          CTL X <- NEW
        BODY
          pass
        END
        """
        with pytest.raises(JDFError, match=r"line \d+: CTL flow X"):
            parse_jdf(src, "badctlnew").build(NB=1)

    def test_new_as_output_rejected(self):
        src = """
        NB [type = int]

        T(i)
          i = 0 .. 0
          RW V -> NEW
        BODY
          pass
        END
        """
        with pytest.raises(JDFError, match="input-only"):
            parse_jdf(src, "badout").build(NB=1)

    def test_lowering_refuses_new_null_gracefully(self):
        import numpy as np

        from parsec_tpu import ptg
        from parsec_tpu.data.data import TileType
        from parsec_tpu.ptg.lowering import LoweringError, lower_taskpool

        p = ptg.PTGBuilder("nn", N=2)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        f = t.flow("V", ptg.RW)
        f.input(new=True, guard=lambda g, l: l.i == 0,
                dtt=TileType((1,), np.float32))
        f.input(null=True, guard=lambda g, l: l.i > 0)
        t.body(lambda es, task, g, l: None, dyld="gemm")
        with pytest.raises(LoweringError):
            lower_taskpool(p.build())

    def test_dsl_new_without_type_rejected(self):
        from parsec_tpu import ptg

        p = ptg.PTGBuilder("nt", N=1)
        t = p.task("T", i=ptg.span(0, 0))
        f = t.flow("V", ptg.RW)
        with pytest.raises(ValueError, match="NEW needs"):
            f.input(new=True)
