"""Multi-rank protocol tests: chains, broadcast trees, writebacks.

The analog of the reference's distributed test tier (SURVEY §4: shm + MPI
``-np 2/4/8`` variants of the DSL tests; ``examples/Ex03_ChainMPI.jdf``,
``Ex05_Broadcast``): the in-process fabric exercises the full activation /
rendezvous-GET / propagation-tree / termdet-pending-action protocol.
"""

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.comm import run_multirank
from parsec_tpu.comm.remote_dep import tree_children
from parsec_tpu.core.params import params
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic


# ---------------------------------------------------------------------------
# tree unit tests
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["binomial", "chain", "star"])
@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13])
def test_tree_covers_every_node_once(kind, n):
    seen = {0}
    frontier = [0]
    while frontier:
        nxt = []
        for p in frontier:
            for c in tree_children(kind, p, n):
                assert c not in seen, f"{kind} n={n}: node {c} visited twice"
                seen.add(c)
                nxt.append(c)
        frontier = nxt
    assert seen == set(range(n)), f"{kind} n={n}: missing {set(range(n)) - seen}"


@pytest.mark.parametrize("kind", ["binomial", "chain", "star"])
@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13])
def test_tree_parent_inverts_children(kind, n):
    from parsec_tpu.comm.remote_dep import tree_parent
    assert tree_parent(kind, 0, n) is None
    for p in range(n):
        for c in tree_children(kind, p, n):
            assert tree_parent(kind, c, n) == p, (kind, n, p, c)
    for c in range(1, n):
        par = tree_parent(kind, c, n)
        assert c in tree_children(kind, par, n), (kind, n, c, par)


def test_unknown_tree_kind_raises_typed_mca_error():
    """An unknown ``comm_bcast_tree`` value must raise the typed MCA
    domain error naming the knob and its legal set — never silently
    fall through to some default shape."""
    from parsec_tpu.comm.remote_dep import TREE_KINDS, tree_parent
    from parsec_tpu.core.params import MCAParamValueError
    with pytest.raises(MCAParamValueError) as ei:
        tree_children("fibonacci", 0, 8)
    assert ei.value.param == "comm_bcast_tree"
    assert ei.value.value == "fibonacci"
    assert set(ei.value.allowed) == set(TREE_KINDS)
    assert "comm_bcast_tree" in str(ei.value)
    with pytest.raises(MCAParamValueError):
        tree_parent("ring", 3, 8)
    assert isinstance(ei.value, ValueError)   # catchable as plain ValueError


@pytest.mark.parametrize("kind,n,expect", [
    ("chain", 5, {0: [1], 1: [2], 2: [3], 3: [4], 4: []}),
    ("star", 4, {0: [1, 2, 3], 1: [], 2: [], 3: []}),
    ("binomial", 6, {0: [1, 2, 4], 1: [3, 5], 2: [], 3: [], 4: [], 5: []}),
])
def test_tree_shapes_exact(kind, n, expect):
    got = {p: tree_children(kind, p, n) for p in range(n)}
    assert got == expect


# ---------------------------------------------------------------------------
# PTG builders shared by the rank bodies
# ---------------------------------------------------------------------------

def _chain_tp(V, nt: int):
    """T(0) reads V(0); T(i) -> T(i+1) crosses ranks; T(nt-1) writes V(0)
    (a remote writeback for every rank layout with nranks > 1)."""
    p = ptg.PTGBuilder("chain", V=V, NT=nt)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NT - 1))
    t.affinity("V", lambda g, l: (l.i,))
    f = t.flow("A", ptg.RW)
    f.input(data=("V", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "A", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "A", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NT - 1)
    f.output(data=("V", lambda g, l: (0,)),
             guard=lambda g, l: l.i == g.NT - 1)

    def body(es, task, g, l):
        task.flow_data("A").value[...] += 1.0

    t.body(body)
    return p.build()


def _chain_body(ctx, rank, nranks):
    nt = 7
    V = VectorTwoDimCyclic("V", lm=nt * 4, mb=4, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    tp = _chain_tp(V, nt)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    # local termination != global: fence before reading the remote writeback
    ctx.comm_barrier()
    if rank == 0:  # home of V(0): the writeback target
        return np.asarray(V.data_of(0).newest_copy().value).copy()
    return None


@pytest.mark.parametrize("nranks", [2, 4])
def test_chain_across_ranks(nranks):
    """Ex03 shape: a value threads through every rank, +1 per hop, and the
    final version writes back to rank 0's home tile."""
    res = run_multirank(nranks, _chain_body)
    np.testing.assert_allclose(res[0], np.full(4, 7.0))


def _bcast_tp(V, nranks: int, payload: int):
    p = ptg.PTGBuilder("bcast", V=V, NR=nranks, PAY=payload)
    w = p.task("W", z=ptg.span(0, 0))
    w.affinity("V", lambda g, l: (0,))
    fw = w.flow("A", ptg.WRITE,
                dtt=None)
    for r in range(nranks):
        fw.output(succ=("R", "X", lambda g, l, r=r: {"r": r}))

    def wbody(es, task, g, l):
        from parsec_tpu.data.data import data_create
        arr = np.arange(g.PAY, dtype=np.float32)
        task.set_flow_data("A", data_create(arr, key=("w", 0)).get_copy(0))

    w.body(wbody)

    t = p.task("R", r=ptg.span(0, lambda g, l: g.NR - 1))
    t.affinity("V", lambda g, l: (l.r,))
    fx = t.flow("X", ptg.READ)
    fx.input(pred=("W", "A", lambda g, l: {"z": 0}))
    fy = t.flow("Y", ptg.RW)
    fy.input(data=("V", lambda g, l: (l.r,)))
    fy.output(data=("V", lambda g, l: (l.r,)))

    def rbody(es, task, g, l):
        task.flow_data("Y").value[...] = float(task.flow_data("X").value.sum())

    t.body(rbody)
    return p.build()


def _mk_bcast_body(payload):
    def body(ctx, rank, nranks):
        V = VectorTwoDimCyclic("V", lm=nranks, mb=1, P=nranks, myrank=rank,
                               init_fn=lambda m, size: np.zeros(size))
        tp = _bcast_tp(V, nranks, payload)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
        return float(np.asarray(V.data_of(rank).newest_copy().value)[0])
    return body


@pytest.mark.parametrize("nranks,tree", [(2, "binomial"), (4, "binomial"),
                                         (4, "chain"), (4, "star")])
def test_broadcast_inline(nranks, tree):
    """Ex05 shape with a short payload riding inside the activation."""
    params.set("comm_bcast_tree", tree)
    try:
        res = run_multirank(nranks, _mk_bcast_body(8))
    finally:
        params.set("comm_bcast_tree", "binomial")
    expect = float(np.arange(8, dtype=np.float32).sum())
    assert res == [expect] * nranks


@pytest.mark.parametrize("nranks", [4])
def test_broadcast_rendezvous_get(nranks):
    """Payload above comm_short_limit: moves by registered-memory GET and is
    re-registered at every interior tree node."""
    old = params.get("comm_short_limit")
    params.set("comm_short_limit", 64)
    try:
        res = run_multirank(nranks, _mk_bcast_body(4096))
    finally:
        params.set("comm_short_limit", old)
    expect = float(np.arange(4096, dtype=np.float32).sum())
    assert res == [expect] * nranks


def test_single_rank_unaffected():
    """nb_ranks=1 contexts never touch the comm seams."""
    res = run_multirank(1, _chain_body)
    np.testing.assert_allclose(res[0], np.full(4, 7.0))


# ---------------------------------------------------------------------------
# fourcounter distributed termination detection
# ---------------------------------------------------------------------------

def _chain_body_fourcounter(ctx, rank, nranks):
    """Reads the remote writeback right after wait() with NO explicit fence:
    only global (wave-based) termination makes that correct — the local
    detector would release rank 0 before the final writeback lands."""
    nt = 7
    V = VectorTwoDimCyclic("V", lm=nt * 4, mb=4, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    tp = _chain_tp(V, nt)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    if rank == 0:
        return np.asarray(V.data_of(0).newest_copy().value).copy()
    return None


@pytest.mark.parametrize("nranks", [2, 4, 8])
def test_fourcounter_global_termination(nranks):
    params.set("termdet", "fourcounter")
    try:
        res = run_multirank(nranks, _chain_body_fourcounter)
    finally:
        params.set("termdet", "")
    np.testing.assert_allclose(res[0], np.full(4, 7.0))


@pytest.mark.parametrize("nranks", [4])
def test_fourcounter_broadcast(nranks):
    params.set("termdet", "fourcounter")
    try:
        res = run_multirank(nranks, _mk_bcast_body(8))
    finally:
        params.set("termdet", "")
    expect = float(np.arange(8, dtype=np.float32).sum())
    assert res == [expect] * nranks
