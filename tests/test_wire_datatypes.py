"""Partial-tile wire datatypes — the reference's ``[type_remote = LR,
displ_remote = ...]`` dep properties (``tests/apps/stencil/stencil_1D.jdf:
83-92``; MPI derived datatypes + ``parsec_reshape.c`` underneath).

Here the same contract is a :class:`WireRegion` sliced-payload path
through remote_dep: remote neighbor edges ship only the R ghost columns,
local edges still share the full tile, and the consumer body branches on
shape exactly like the reference's ``CORE_copydata_stencil_1D``
displacement logic branches on local-vs-remote buffers.
"""

import pathlib

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.comm.multirank import run_multirank
from parsec_tpu.data.datatype import WireRegion, wire_slice_key

JDF_DIR = pathlib.Path(__file__).parent.parent / "examples" / "jdf"
REF = pathlib.Path("/root/reference")
needs_ref = pytest.mark.skipif(not REF.exists(),
                               reason="reference tree not available")


# ---------------------------------------------------------------------------
# WireRegion displacement arithmetic
# ---------------------------------------------------------------------------

def test_wire_region_slices_follow_column_major_displacement():
    """The reference displaces in BYTES through the tile's column-major
    storage: sizeof*mb*c0 selects column c0 (stencil_1D.jdf:90-92)."""
    mb, R = 8, 2
    lr = WireRegion(mb, R, itemsize=4)
    assert lr.slices(0) == (slice(None), slice(0, R))
    # the AR ghost send: displ sizeof*mb*R -> columns [R, 2R)
    assert lr.slices(4 * mb * R) == (slice(None), slice(R, 2 * R))
    # the AL ghost send: displ sizeof*mb*(nb-2R) -> columns [nb-2R, nb-R)
    nb = 16
    assert lr.slices(4 * mb * (nb - 2 * R)) == \
        (slice(None), slice(nb - 2 * R, nb - R))
    assert lr.nbytes == mb * R * 4


def test_wire_region_rejects_unaligned_displacement():
    with pytest.raises(ValueError):
        WireRegion(8, 2, itemsize=4).slices(6)


def test_prop_values_parse_at_arbitrary_paren_depth():
    """A depth-capped regex once misparsed deep displ_remote formulas as
    bare flags (value True -> displ 1 -> wrong ghost columns, silently).
    The scanner must keep balanced parens whole at any depth."""
    from parsec_tpu.ptg.jdf import _parse_props
    p = _parse_props(
        "type_remote = LR  displ_remote = (sizeof*(mb*(nb-(2*R))))  flag")
    assert p["type_remote"] == "LR"
    assert p["displ_remote"] == "(sizeof*(mb*(nb-(2*R))))"
    assert p["flag"] is True


def test_subst_ids_leaves_attribute_names_alone():
    """A task parameter named like a collection attribute must not
    rewrite the attribute access during read-chain substitution."""
    from parsec_tpu.ptg.jdf_c import _subst_ids
    assert _subst_ids("descA.nb - nb", {"nb": "k+1"}) == \
        "descA.nb - (k+1)"


def test_type_remote_bound_to_tiletype_means_full_tile():
    """The reference's `type = DEFAULT type_remote = DEFAULT` idiom
    (merge_sort.jdf, choice2.jdf): the same arena doubles as the full
    wire datatype — a TileType binding must build as full-tile wire, not
    raise."""
    from parsec_tpu.data.datatype import TileType
    from parsec_tpu.ptg.jdf import parse_jdf

    src = """
D  [type = data]
DEFAULT  [type = object]

T(i)
  i = 0 .. 1
  : D(i)
  RW A <- D(i)  [type = DEFAULT]
       -> D(i)  [type_remote = DEFAULT]
BODY
  pass
END
"""
    jdf = parse_jdf(src, "idiom")
    import numpy as np
    from parsec_tpu.data_dist.collection import DictCollection
    dtt = TileType((1,), np.float32)
    D = DictCollection("D", dtt=dtt,
                       init_fn=lambda *k: np.zeros(1, np.float32))
    tp = jdf.build(D=D, DEFAULT=dtt)
    (dep,) = [d for f in tp.task_class("T").flows for d in f.deps_out]
    assert dep.wire is None


def test_slice_view_rejects_out_of_range_and_owns_bytes():
    """An out-of-range view must error (numpy clamping would ship a
    SMALLER region, misclassified by the consumer's shape branch), and
    the cut must own its bytes even when the slice is contiguous."""
    from parsec_tpu.comm.remote_dep import _slice_view

    tile = np.arange(12, dtype=np.float32).reshape(1, 12)  # 1-row tile:
    out = _slice_view(tile, ((None, None, None), (2, 4, None)))
    assert out.base is None                 # contiguous slice still owned
    tile[0, 2] = 99.0
    assert out[0, 0] == 2.0                 # no aliasing of the live tile
    with pytest.raises(ValueError):
        _slice_view(tile, ((None, None, None), (11, 13, None)))


def test_wire_slice_key_hashable_identity():
    k = wire_slice_key((slice(None), slice(2, 4)))
    assert k == ((None, None, None), (2, 4, None))
    assert hash(k)
    assert wire_slice_key(None) is None


def test_wire_region_slice_roundtrips_binary_codec():
    """Partial-tile wire payloads (the LR ghost columns) must cross the
    binary wire byte-identically: the cut is non-contiguous in the source
    tile, the codec ships it as one contiguous raw segment, and the
    decoded region owns its bytes (ISSUE 4 satellite)."""
    from parsec_tpu.comm import codec
    from parsec_tpu.comm.remote_dep import _slice_view

    mb, nb, R = 8, 34, 2
    tile = np.arange(mb * nb, dtype=np.float32).reshape(mb, nb)
    lr = WireRegion(mb, R, itemsize=4)
    region = _slice_view(tile, wire_slice_key(lr.slices(4 * mb * R)))
    got = codec.roundtrip({"outputs": [{"inline": region,
                                        "wire_view": wire_slice_key(
                                            lr.slices(4 * mb * R))}]})
    out = got["outputs"][0]
    np.testing.assert_array_equal(out["inline"], tile[:, R:2 * R])
    assert out["inline"].dtype == np.float32
    tile[:, R] = -1.0
    np.testing.assert_array_equal(out["inline"][:, 0],
                                  np.arange(mb) * nb + R)


def test_wire_slices_roundtrip_over_socket_fabric():
    """Non-contiguous and partial-tile slices land equal over the real
    binary socket wire (not just the in-memory codec)."""
    import time as _time

    from parsec_tpu.comm.engine import AM_TAG_USER_BASE
    from parsec_tpu.comm.multiproc import _free_port_base
    from parsec_tpu.comm.socket_fabric import (SocketCommEngine,
                                               SocketFabric)

    base = _free_port_base(2)
    f0 = SocketFabric(2, 0, base_port=base)
    f1 = SocketFabric(2, 1, base_port=base)
    e0, e1 = SocketCommEngine(f0), SocketCommEngine(f1)
    try:
        tile = np.arange(16 * 34, dtype=np.float32).reshape(16, 34)
        payloads = {"ghost": tile[:, 1:3], "strided": tile[::2, ::3],
                    "full": tile}
        landed = []
        e1.tag_register(AM_TAG_USER_BASE,
                        lambda eng, src, p: landed.append(p))
        e0.send_am(AM_TAG_USER_BASE, 1, payloads)
        deadline = _time.monotonic() + 30
        while not landed:
            e0.progress()
            e1.progress()
            _time.sleep(0.0005)
            assert _time.monotonic() < deadline
        for k, v in payloads.items():
            np.testing.assert_array_equal(landed[0][k], v)
    finally:
        e0.fini()
        e1.fini()


# ---------------------------------------------------------------------------
# the sliced-payload path, end to end over ranks
# ---------------------------------------------------------------------------

from test_jdf_reference import _stencil_desc, _stencil_oracle  # noqa: E402


def _rank_body(wire_on):
    def body(ctx, rank, nranks):
        from parsec_tpu.core.params import params
        saved = params.get("comm_wire_datatypes")
        params.set("comm_wire_datatypes", wire_on)
        try:
            MB, NB, LMT, LNT, R, iters = 4, 34, 2, 8, 1, 4
            desc, interior = _stencil_desc(nranks, rank, MB, NB, LMT,
                                           LNT, R, seed=7)
            W = np.array([0.25, 0.5, 0.25])
            jdf = ptg.load_jdf(JDF_DIR / "stencil_1D.jdf")
            tp = jdf.build(descA=desc, iter=iters, R=R, W=W, LMT=LMT,
                           LNT=LNT)
            ctx.add_taskpool(tp)
            ctx.wait(timeout=120)
            ctx.comm_barrier()
            want = _stencil_oracle(interior, W, iters)
            m = iters % LMT
            w = NB - 2 * R
            for n in range(LNT):
                if desc.rank_of(m, n) != rank:
                    continue
                tile = np.asarray(desc.data_of(m, n).newest_copy().value)
                np.testing.assert_allclose(
                    tile[:, R:NB - R], want[:, n * w:(n + 1) * w],
                    rtol=1e-4, atol=1e-5)
            return ctx.comm_engine.payload_bytes_staged
        finally:
            params.set("comm_wire_datatypes", saved)
    return body


def test_stencil_wire_datatypes_cut_halo_bytes_multirank():
    """The done-criterion of VERDICT r4 item 3: the translated stencil
    ships R-column payloads on neighbor edges — byte counters prove the
    reduction, numerics stay identical to the full-tile build.

    With NB=34, R=1 every halo edge shrinks 34x; self-edges (A0, FULL)
    still carry whole tiles, so the total shrinks by the halo share."""
    nranks = 4
    with_wire = sum(run_multirank(nranks, _rank_body(True)))
    without = sum(run_multirank(nranks, _rank_body(False)))
    assert with_wire < without * 0.55, (with_wire, without)
    # exact accounting: per iteration each rank boundary moves two
    # (MB, NB) tiles without wire datatypes and two (MB, R) regions with
    # them — the A0 self-edges never cross ranks (column distribution),
    # so the FULL share is zero here and the ratio approaches R/NB
    assert with_wire <= without * (1 / 34) * 1.01, (with_wire, without)


@needs_ref
def test_reference_stencil_jdf_ingests_wire_datatypes():
    """C-syntax ingestion maps the reference's own [type_remote = LR,
    displ_remote = %{...%}] automatically: bind LR to a WireRegion at
    build and the converted deps carry the wire views."""
    from parsec_tpu.ptg.jdf_c import load_c_jdf

    jdf = load_c_jdf(
        REF / "tests" / "apps" / "stencil" / "stencil_1D.jdf",
        bodies={"task": "pass"})
    task = jdf.tasks["task"]
    arrows = [a for f in task.flows for a in f.arrows]
    wired = [a for a in arrows if a.props.get("type_remote") == "LR"]
    # AL in, AR in, and the two neighbor sends
    assert len(wired) == 4
    sends = [a for a in wired if a.direction == "out"]
    assert len(sends) == 2
    assert all("displ_remote" in a.props for a in sends)
    # the displ expressions converted to evaluable Python: check one
    displs = sorted(a.props["displ_remote"] for a in sends)
    assert any("mb" in d for d in displs)
