"""The zero-copy wire data path (ISSUE 4): binary framing, the structured
codec + restricted pickle seam, scatter-gather CTRL frames, and the
windowed fragmented rendezvous across the inproc, socket, and device
fabrics — including the fault paths (partial frames, mid-frame peer
disconnects, transport replays) the TCP tier must absorb invisibly.
"""

import pickle
import socket as socket_mod
import time

import numpy as np
import pytest

from parsec_tpu.comm import codec
from parsec_tpu.comm.engine import (AM_TAG_USER_BASE, InprocFabric)
from parsec_tpu.comm.multiproc import _free_port_base
from parsec_tpu.comm.socket_fabric import SocketCommEngine, SocketFabric
from parsec_tpu.core.params import params


def _wait(engines, pred, timeout=30.0, sleep=0.0005):
    deadline = time.monotonic() + timeout
    while not pred():
        for e in engines:
            e.progress()
        time.sleep(sleep)
        if time.monotonic() > deadline:
            raise TimeoutError("wire test wait timed out")


@pytest.fixture
def socket_pair():
    base = _free_port_base(2)
    f0 = SocketFabric(2, 0, base_port=base)
    f1 = SocketFabric(2, 1, base_port=base)
    e0, e1 = SocketCommEngine(f0), SocketCommEngine(f1)
    yield e0, e1
    e0.fini()
    e1.fini()


# ---------------------------------------------------------------------------
# the codec
# ---------------------------------------------------------------------------

class TestCodec:
    def test_structured_roundtrip(self):
        msg = {"tp": 3, "tc": 0, "locals": {"m": 1, "k": -2},
               "outputs": [(0, 1, 3, 7, np.arange(6, dtype=np.float32))],
               "ranks": [0, 1, 2], "tree": "binomial", "ok": True,
               "none": None, "f": 2.5, "blob": b"xy", "big": b"z" * 4096}
        got = codec.roundtrip(msg)
        assert got["locals"] == msg["locals"]
        assert got["ranks"] == [0, 1, 2] and got["tree"] == "binomial"
        assert got["ok"] is True and got["none"] is None
        assert got["blob"] == b"xy" and got["big"] == msg["big"]
        out = got["outputs"][0]
        assert out[:4] == (0, 1, 3, 7)
        np.testing.assert_array_equal(out[4], msg["outputs"][0][4])

    def test_ndarray_zero_copy_segments_and_ownership(self):
        arr = np.arange(64, dtype=np.float64).reshape(8, 8)
        meta, segs = codec.encode({"a": arr, "n": 1})
        # the tile's bytes ride as ONE out-of-band segment, not in meta
        assert len(segs) == 1 and segs[0] is arr
        assert len(meta) < 64
        got = codec.decode_with_segments(meta, segs)
        np.testing.assert_array_equal(got["a"], arr)
        arr[0, 0] = -1.0                      # decoded copy owns its bytes
        assert got["a"][0, 0] == 0.0

    def test_non_contiguous_and_edge_arrays(self):
        cases = [np.arange(24, dtype=np.float32)[::2],       # strided
                 np.arange(24).reshape(4, 6)[:, 1:3],        # inner slice
                 np.empty((0, 5), np.int32),                 # empty
                 np.array(3.5),                              # 0-d
                 np.arange(6, dtype=">i4")]                  # big-endian
        for c in cases:
            got = codec.roundtrip(c)
            assert got.shape == c.shape and got.dtype == c.dtype
            np.testing.assert_array_equal(got, c)

    def test_numpy_scalars_and_bigints(self):
        assert codec.roundtrip(np.int64(7)) == 7
        assert codec.roundtrip(np.float32(1.5)) == 1.5
        assert codec.roundtrip(1 << 100) == 1 << 100    # pickle fallback

    def test_pickle_fallback_gated_by_param(self, param):
        assert codec.roundtrip(slice(1, 5)) == slice(1, 5)
        param("comm_codec_pickle_fallback", False)
        with pytest.raises(TypeError):
            codec.encode(slice(1, 5))

    def test_restricted_unpickler_blocks_gadgets(self):
        evil = pickle.dumps(getattr(__import__("os"), "system"))
        with pytest.raises(pickle.UnpicklingError):
            codec.restricted_loads(evil)
        # numpy revival stays allowed (the legitimate fallback cargo)
        ok = pickle.dumps(np.arange(3))
        np.testing.assert_array_equal(codec.restricted_loads(ok),
                                      np.arange(3))


# ---------------------------------------------------------------------------
# compact activation wire form
# ---------------------------------------------------------------------------

def test_activation_pack_roundtrip_with_wire_view():
    from parsec_tpu.comm.remote_dep import pack_activation, unpack_activation
    msg = {"tp": 9, "tc": 2, "locals": {"m": 4, "n": 0},
           "outputs": [
               {"flow_index": 0, "writeback": False, "version": 3,
                "wire": (1, 77), "shape": (8, 34), "dtype": "<f4",
                "wire_view": ((None, None, None), (1, 3, None))},
               {"flow_index": 1, "writeback": True},
           ],
           "ranks": [1, 0, 3], "tree": "chain", "priority": 5,
           "seq": 12, "pos": 1}
    packed = pack_activation(msg)
    # the packed form survives the codec (what actually rides the wire)
    got = unpack_activation(codec.roundtrip(packed))
    assert got["outputs"][0]["wire_view"] == msg["outputs"][0]["wire_view"]
    assert got["outputs"][1] == {"flow_index": 1, "writeback": True}
    got["outputs"][0].pop("wire_view")
    msg["outputs"][0].pop("wire_view")
    # tuples may come back as tuples; normalize the containers
    assert got["outputs"][0]["wire"] == (1, 77)
    assert tuple(got["outputs"][0]["shape"]) == (8, 34)
    for k in ("tp", "tc", "locals", "tree", "priority", "seq", "pos"):
        assert got[k] == msg[k], k
    assert list(got["ranks"]) == msg["ranks"]


# ---------------------------------------------------------------------------
# binary CTRL frames over real sockets
# ---------------------------------------------------------------------------

def test_binary_am_roundtrip_with_arrays_and_ledgers(socket_pair):
    e0, e1 = socket_pair
    landed = []
    e1.tag_register(AM_TAG_USER_BASE, lambda eng, src, p: landed.append(p))
    arr = np.arange(5000, dtype=np.float32).reshape(50, 100)
    sliced = arr[:, 3:9]                        # non-contiguous wire slice
    e0.send_am(AM_TAG_USER_BASE, 1, {"tile": arr, "view": sliced, "k": 1})
    _wait((e0, e1), lambda: landed)
    got = landed[0]
    np.testing.assert_array_equal(got["tile"], arr)
    np.testing.assert_array_equal(got["view"], sliced)
    assert got["tile"].flags.owndata or got["tile"].base is None
    # traffic ledgers: sender counted tx to rank 1, receiver rx from 0
    assert e0.fabric.peer_stats()["tx"][1]["bytes"] > arr.nbytes
    _wait((e0, e1), lambda: e1.fabric.bytes_recv > arr.nbytes)
    assert e1.fabric.peer_stats()["rx"][0]["frames"] >= 1


def test_partial_frame_delivery_drops_only_that_connection(socket_pair):
    """A peer that dies mid-frame (or a corrupted stream) must kill only
    that connection; traffic on fresh connections keeps flowing."""
    e0, e1 = socket_pair
    port = e1.fabric.base_port + 1
    # half a header, then EOF
    s = socket_mod.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(b"\x01\x00\x00")
    s.close()
    # a full garbage header (unknown kind), then EOF
    s = socket_mod.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(bytes(range(40)) * 2)
    s.close()
    # a valid CTRL header whose body never arrives
    from parsec_tpu.comm.socket_fabric import _HDR, K_CTRL
    s = socket_mod.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(_HDR.pack(K_CTRL, 0, AM_TAG_USER_BASE, 0, 1, 100, 0, 0))
    s.close()
    time.sleep(0.1)
    landed = []
    e1.tag_register(AM_TAG_USER_BASE, lambda eng, src, p: landed.append(p))
    e0.send_am(AM_TAG_USER_BASE, 1, {"alive": True})
    _wait((e0, e1), lambda: landed)
    assert landed[0] == {"alive": True}


# ---------------------------------------------------------------------------
# fragmented rendezvous GETs
# ---------------------------------------------------------------------------

def test_fragmented_get_inproc_lands_and_cleans_up(param):
    param("comm_get_frag_bytes", 1 << 14)
    param("comm_get_window", 3)
    fab = InprocFabric(2)
    e0, e1 = fab.attach(0), fab.attach(1)
    src = np.random.default_rng(0).standard_normal((128, 130)) \
        .astype(np.float32)
    h = e1.mem_register(src, refcount=1)
    done = []
    e0.get(h.wire(), done.append)
    _wait((e0, e1), lambda: done, sleep=0)
    np.testing.assert_array_equal(done[0], src)
    assert done[0].dtype == src.dtype and done[0].shape == src.shape
    nfrags = -(-src.nbytes // (1 << 14))
    assert e0.frags_in == nfrags and e1.frags_out == nfrags
    assert e0.frag_bytes_in == src.nbytes
    # all state retired: zones, send windows, registrations
    assert not e0._landing and not e1._frag_sends and not e1._mem
    assert e0._frag_active == 0 and e1._frag_active == 0


def test_fragmented_get_fires_pins_events(param):
    from parsec_tpu.prof import pins
    from parsec_tpu.prof.pins import PinsEvent
    param("comm_get_frag_bytes", 1 << 13)
    events = []
    cb = lambda es, p: events.append(p)                    # noqa: E731
    pins.register(PinsEvent.COMM_GET_FRAG_RECV, cb)
    pins.register(PinsEvent.COMM_GET_DONE, cb)
    try:
        fab = InprocFabric(2)
        e0, e1 = fab.attach(0), fab.attach(1)
        src = np.zeros(1 << 15, np.uint8)
        h = e1.mem_register(src, refcount=1)
        done = []
        e0.get(h.wire(), done.append)
        _wait((e0, e1), lambda: done, sleep=0)
    finally:
        pins.unregister(PinsEvent.COMM_GET_FRAG_RECV, cb)
        pins.unregister(PinsEvent.COMM_GET_DONE, cb)
    # 4 fragment landings (byte counts) + one completion (total bytes)
    assert sorted(events)[-1] == 1 << 15
    assert sum(e for e in events) == 2 * (1 << 15)


def test_fragmented_get_over_sockets_recv_into_destination(
        socket_pair, param):
    param("comm_get_frag_bytes", 1 << 16)
    param("comm_get_window", 4)
    e0, e1 = socket_pair
    src = np.random.default_rng(1).standard_normal((512, 300)) \
        .astype(np.float64)                    # ~1.2MiB -> 19 fragments
    h = e1.mem_register(src, refcount=1)
    done = []
    e0.get(h.wire(), done.append)
    _wait((e0, e1), lambda: done)
    np.testing.assert_array_equal(done[0], src)
    nfrags = -(-src.nbytes // (1 << 16))
    assert e0.frags_in == nfrags
    assert e0.fabric.peer_stats()["rx"][1]["frags"] == nfrags
    assert e1.fabric.peer_stats()["tx"][0]["frags"] == nfrags
    assert not e0._landing and not e1._frag_sends


def test_fragmented_get_survives_midstream_disconnects(param):
    """Mid-frame peer disconnects: fault injection hard-breaks the live
    connection across a windowed fragmented GET; reconnect-and-replay
    plus seq/offset dedup must land every byte exactly once."""
    param("comm_socket_fault_p", 0.2)
    param("comm_socket_fault_seed", 11)
    param("comm_get_frag_bytes", 1 << 15)
    param("comm_get_window", 4)
    param("comm_socket_ack_every", 4)
    base = _free_port_base(2)
    f0 = SocketFabric(2, 0, base_port=base)
    f1 = SocketFabric(2, 1, base_port=base)
    e0, e1 = SocketCommEngine(f0), SocketCommEngine(f1)
    try:
        src = np.random.default_rng(2).integers(
            0, 255, size=1 << 20, dtype=np.uint8)
        h = e1.mem_register(src, refcount=1)
        done = []
        e0.get(h.wire(), done.append)
        _wait((e0, e1), lambda: done, timeout=60)
        np.testing.assert_array_equal(done[0], src)
        assert f1.replays > 0          # the fault path actually fired
    finally:
        e0.fini()
        e1.fini()


def test_fragmented_get_device_tier_multi_buffer(param):
    """The device tier keeps jax.device_put but pipelines large pulls as
    a window of device sub-buffers, reassembled on the consumer."""
    import jax

    from parsec_tpu.comm.device_fabric import DeviceFabric, is_device_array
    param("comm_get_frag_bytes", 1 << 14)
    devices = jax.devices()[:2]
    fab = DeviceFabric(2, devices)
    e0, e1 = fab.attach(0), fab.attach(1)
    src = np.random.default_rng(3).standard_normal((120, 120)) \
        .astype(np.float32)                       # 57.6KB -> 4 fragments
    h = e1.mem_register(src, refcount=1)
    assert is_device_array(h.value)
    done = []
    e0.get(h.wire(), done.append)
    _wait((e0, e1), lambda: done, sleep=0)
    got = done[0]
    assert is_device_array(got) and got.device == devices[0]
    np.testing.assert_array_equal(np.asarray(got), src)
    assert e0.frags_in >= 4
    assert e0.bytes_got >= src.nbytes


def test_monolithic_reply_below_threshold_unchanged(param):
    """Payloads at or under comm_get_frag_bytes keep the single-reply
    path (and the last-consumer ownership handover inproc)."""
    param("comm_get_frag_bytes", 1 << 20)
    fab = InprocFabric(2)
    e0, e1 = fab.attach(0), fab.attach(1)
    src = np.arange(64, dtype=np.float32)
    h = e1.mem_register(src, refcount=1)
    done = []
    e0.get(h.wire(), done.append)
    _wait((e0, e1), lambda: done, sleep=0)
    np.testing.assert_array_equal(done[0], src)
    assert e0.frags_in == 0


def test_legacy_pickle_framing_still_works(param):
    """comm_wire_binary=False: the length-prefixed-pickle baseline stays
    a correct transport (it is the measured baseline of bench_comm)."""
    param("comm_wire_binary", True)   # order matters: restore-safe
    param("comm_get_frag_bytes", 0)
    params.set("comm_wire_binary", False)
    base = _free_port_base(2)
    f0 = SocketFabric(2, 0, base_port=base)
    f1 = SocketFabric(2, 1, base_port=base)
    e0, e1 = SocketCommEngine(f0), SocketCommEngine(f1)
    try:
        assert not f0.binary
        src = np.arange(2000, dtype=np.float32)
        h = e1.mem_register(src, refcount=1)
        done = []
        e0.get(h.wire(), done.append)
        _wait((e0, e1), lambda: done)
        np.testing.assert_array_equal(done[0], src)
    finally:
        e0.fini()
        e1.fini()
