"""Static comm-pattern derivation (ISSUE 20, analysis/commcheck.py).

Three tiers, mirroring test_analysis.py's discipline: the model-sweep
classification contract (bcast -> broadcast, reduce -> reduce,
single-rank -> none, every pool non-crashing), seeded-mutation coverage
for each comm-hazard finding class with exact task-class/flow/instance
provenance, and the tree-selection units (``recommend_tree`` /
``resolve_tree_kind`` / the ``comm_bcast_tree=auto`` knob domain).
"""

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.analysis import CommReport, check_comm, recommend_tree
from parsec_tpu.analysis.__main__ import _model_graphs
from parsec_tpu.analysis.commcheck import (PATTERNS, _classify,
                                           agreement_rel_err,
                                           predict_collective_traffic,
                                           report_block)
from parsec_tpu.comm.collectives import bcast_taskpool, reduce_taskpool
from parsec_tpu.comm.remote_dep import (TREE_KINDS, resolve_tree_kind,
                                        tree_children)
from parsec_tpu.core.params import MCAParamValueError, params
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic

pytestmark = pytest.mark.analysis


def _vec(name, n, mb=1024, P=1):
    return VectorTwoDimCyclic(
        name, lm=mb * n, mb=mb, P=P,
        init_fn=lambda m, s: np.zeros(s, np.float32))


# ---------------------------------------------------------------------------
# classification: the model sweep + the canonical pools
# ---------------------------------------------------------------------------

def test_model_sweep_classifies_every_pool():
    """ISSUE-20 acceptance: every model pool gets a non-crashing
    classification at 4 ranks; the collective pools and the single-home
    pools land on their names."""
    want = {"coll_bcast": "broadcast", "coll_reduce": "reduce",
            "cholesky": "none", "stencil1d": "halo", "a2a": "all-to-all"}
    seen = {}
    for name, tp in _model_graphs(5, ranks=4):
        cr = check_comm(tp, nb_ranks=4)
        assert isinstance(cr, CommReport)
        assert cr.pattern in PATTERNS, (name, cr.pattern)
        assert cr.ok, (name, [repr(f) for f in cr.errors])
        seen[cr.name] = cr.pattern
    for pool, pattern in want.items():
        assert seen.get(pool) == pattern, (pool, seen)
    # the derivation feeds runtime_report(): every analyzed pool has a
    # block with the critpath-keyed edge classes
    blk = report_block()
    assert set(want) <= set(blk)
    assert blk["coll_bcast"]["pattern"] == "broadcast"
    assert blk["coll_bcast"]["cross_rank_bytes"] > 0
    assert all(":" in ec for ec in blk["coll_bcast"]["edge_classes"])


def test_single_rank_pool_is_none():
    cr = check_comm(bcast_taskpool(_vec("V", 8), n=8), nb_ranks=1)
    assert cr.pattern == "none" and cr.total_bytes == 0, cr


@pytest.mark.parametrize("n", [2, 4, 8])
def test_bcast_reduce_patterns_and_bytes(n):
    """Distributed collectives classify by name and the derived bytes are
    exactly (n-1) payload transfers — what the wire acceptance measures."""
    mb = 1024
    cr = check_comm(bcast_taskpool(_vec("B", n, mb=mb, P=n), n=n),
                    nb_ranks=n)
    assert cr.pattern == "broadcast", cr
    assert not cr.findings, [repr(f) for f in cr.findings]
    assert cr.total_bytes == (n - 1) * mb * 4, cr.edge_bytes
    # fan-out of the root matches the binomial children count
    root_deg = cr.fan_out.get(0, 0)
    assert root_deg == len(tree_children("binomial", 0, n)), cr.fan_out
    cr = check_comm(reduce_taskpool(_vec("R", n, mb=mb, P=n),
                                    _vec("O", 1, mb=mb), n=n), nb_ranks=n)
    assert cr.pattern == "reduce", cr
    assert cr.total_bytes == (n - 1) * mb * 4, cr.edge_bytes


def test_classify_shapes_directly():
    """The classifier units over synthetic rank-pair matrices."""
    b = 100
    # chain both ways: writeback spread disambiguates
    chain = {(r, r + 1): b for r in range(3)}
    assert _classify(chain, 4, {0}) == "reduce"
    assert _classify(chain, 4, {0, 1, 2, 3}) == "broadcast"
    star = {(0, d): b for d in range(1, 5)}
    assert _classify(star, 5, {0, 1, 2, 3, 4}) == "broadcast"
    gather = {(s, 0): b for s in range(1, 5)}
    assert _classify(gather, 5, {0}) == "reduce"
    ring = {}
    for r in range(4):
        ring[(r, (r + 1) % 4)] = b
        ring[((r + 1) % 4, r)] = b
    assert _classify(ring, 4, set()) == "halo"
    a2a = {(s, d): b for s in range(4) for d in range(4) if s != d}
    assert _classify(a2a, 4, set()) == "all-to-all"
    # two unrelated arrows: neither a unique source nor a unique sink
    assert _classify({(0, 2): b, (3, 1): b}, 4, set()) == "point-to-point"
    assert _classify({}, 4, set()) == "none"


# ---------------------------------------------------------------------------
# seeded mutations: each hazard class detected with provenance
# ---------------------------------------------------------------------------

def test_detects_duplicate_activation():
    """Mutation: duplicate one of B's succ arrows — the same payload now
    activates the same remote consumer twice."""
    n = 4
    tp = bcast_taskpool(_vec("D", n, P=n), n=n)
    fA = next(f for f in tp.task_classes_by_name["B"].flows if f.name == "A")
    fA.deps_out.append(fA.deps_out[0])
    cr = check_comm(tp, nb_ranks=n)
    hits = [f for f in cr.findings if f.code == "duplicate-activation"]
    assert hits, [repr(f) for f in cr.findings]
    # provenance names the PRODUCER side of the doubled edge
    assert hits[0].task_class == "B" and hits[0].flow == "A"
    assert hits[0].instance is not None


def _owner_pool():
    """Two writers W(p) at V(p)'s home rank, two readers R(q) pinned to
    rank 1 reading V(q), CTL-ordered behind their writer — clean: the
    cross-rank read of V(0) is of a tile its owner writes back."""
    V = _vec("V", 2, mb=8, P=2)
    p_ = ptg.PTGBuilder("ownerw", V=V, N=2)
    w = p_.task("W", p=ptg.span(0, lambda g, l: g.N - 1))
    w.affinity("V", lambda g, l: (l.p,))
    fw = w.flow("A", ptg.WRITE)
    fw.input(new=True, dtt=V.default_dtt)
    fw.output(data=("V", lambda g, l: (l.p,)))
    wx = w.flow("X", ptg.CTL)
    wx.output(succ=("R", "X", lambda g, l: {"q": l.p}))

    @w.body
    def wbody(es, task, g, l):
        pass

    r = p_.task("R", q=ptg.span(0, lambda g, l: g.N - 1))
    r.affinity("V", lambda g, l: (1,))
    fr = r.flow("B", ptg.READ)
    fr.input(data=("V", lambda g, l: (l.q,)))
    rx = r.flow("X", ptg.CTL)
    rx.input(pred=("W", "X", lambda g, l: {"p": l.q}))

    @r.body
    def rbody(es, task, g, l):
        pass

    return p_.build()


def test_detects_unowned_remote_read():
    """Mutation (drop an owner write): guard W(0)'s writeback away while
    W(1)'s survives — R(0)'s cross-rank read of V(0) now snapshots a
    home copy nothing produces, in a collection the pool DOES write."""
    clean = check_comm(_owner_pool(), nb_ranks=2)
    assert not [f for f in clean.findings
                if f.code == "unowned-remote-read"], clean.findings

    tp = _owner_pool()
    fw = next(f for f in tp.task_classes_by_name["W"].flows if f.name == "A")
    wb = next(d for d in fw.deps_out if d.data_ref is not None)
    wb.guard = lambda locals_: locals_["p"] != 0
    cr = check_comm(tp, nb_ranks=2)
    hits = [f for f in cr.findings if f.code == "unowned-remote-read"]
    assert hits, [repr(f) for f in cr.findings]
    # provenance names the READER of the never-written tile
    assert hits[0].task_class == "R" and hits[0].flow == "B"
    assert hits[0].instance is not None
    assert "V" in hits[0].message


def _waw_pool():
    """Two writers on DIFFERENT ranks both writing back T(0), serialized
    by a CTL chain W(0) -> W(1) — clean: ordered cross-rank WAW."""
    V = _vec("V", 2, mb=8, P=2)
    T = _vec("T", 1, mb=8, P=2)
    p_ = ptg.PTGBuilder("waw", V=V, T=T, N=2)
    w = p_.task("W", p=ptg.span(0, lambda g, l: g.N - 1))
    w.affinity("V", lambda g, l: (l.p,))
    fw = w.flow("A", ptg.WRITE)
    fw.input(new=True, dtt=T.default_dtt)
    fw.output(data=("T", lambda g, l: (0,)))
    wx = w.flow("X", ptg.CTL)
    wx.output(succ=("W", "Y", lambda g, l: {"p": l.p + 1}),
              guard=lambda g, l: l.p + 1 < g.N)
    wy = w.flow("Y", ptg.CTL)
    wy.input(pred=("W", "X", lambda g, l: {"p": l.p - 1}),
             guard=lambda g, l: l.p > 0)

    @w.body
    def wbody(es, task, g, l):
        pass

    return p_.build()


def test_detects_cross_rank_unordered_write():
    """Mutation (flip a CTL-ordered cross-rank write to unordered): strip
    the CTL chain — the home copy's final state now rests on whichever
    writeback message lands last."""
    clean = check_comm(_waw_pool(), nb_ranks=2)
    assert not [f for f in clean.findings
                if f.code == "cross-rank-unordered-write"], clean.findings

    tp = _waw_pool()
    for f in tp.task_classes_by_name["W"].flows:
        if f.is_ctl:
            f.deps_in.clear()
            f.deps_out.clear()
    cr = check_comm(tp, nb_ranks=2)
    hits = [f for f in cr.errors
            if f.code == "cross-rank-unordered-write"]
    assert hits, [repr(f) for f in cr.findings]
    assert hits[0].task_class == "W" and hits[0].flow == "A"
    assert hits[0].instance is not None
    assert "T" in hits[0].message


def test_detects_tree_shape_mismatch():
    """A star-configured broadcast of payload-heavy tiles over 8 ranks is
    degree-pathological (root serves n-1 copies); binomial is silent."""
    n = 8
    mb = 65536                       # 256 KiB tiles: far past short_limit
    cr = check_comm(bcast_taskpool(_vec("W", n, mb=mb, P=n), n=n,
                                   kind="star"), nb_ranks=n)
    hits = [f for f in cr.warnings if f.code == "tree-shape-mismatch"]
    assert hits, [repr(f) for f in cr.findings]
    assert "star" in hits[0].message and "binomial" in hits[0].message
    cr = check_comm(bcast_taskpool(_vec("W2", n, mb=mb, P=n), n=n),
                    nb_ranks=n)
    assert not [f for f in cr.findings
                if f.code == "tree-shape-mismatch"], cr.findings


# ---------------------------------------------------------------------------
# tree selection: recommend_tree / resolve_tree_kind / the knob domain
# ---------------------------------------------------------------------------

def test_recommend_tree_per_edge_class():
    n = 8
    cr = check_comm(bcast_taskpool(_vec("H", n, mb=65536, P=n), n=n),
                    nb_ranks=n)
    rec = recommend_tree(cr)
    assert rec["overall"] == "binomial", rec
    assert all(k in TREE_KINDS for k in rec["per_class"].values()), rec
    # a short-payload pool on a small mesh recommends the latency star
    cr = check_comm(bcast_taskpool(_vec("S", 4, mb=64, P=4), n=4),
                    nb_ranks=4)
    assert recommend_tree(cr)["overall"] == "star", cr.edge_bytes


def test_resolve_tree_kind_rule():
    short = int(params.get("comm_short_limit"))
    assert resolve_tree_kind("auto", nbytes=short, n=4) == "star"
    assert resolve_tree_kind("auto", nbytes=short + 1, n=4) == "binomial"
    assert resolve_tree_kind("auto", nbytes=64, n=16) == "binomial"
    assert resolve_tree_kind("auto") == "binomial"      # no payload hint
    assert resolve_tree_kind("chain", nbytes=1, n=2) == "chain"
    assert resolve_tree_kind(None, nbytes=1 << 20) == \
        params.get("comm_bcast_tree")
    with pytest.raises(MCAParamValueError) as ei:
        resolve_tree_kind("fanfic")
    assert ei.value.param == "comm_bcast_tree"


def test_auto_is_a_declared_knob_value():
    """The PR-18 loop can search the tree shape: comm_bcast_tree is a
    declared knob whose domain includes auto."""
    spec = params.knob_space().get("comm_bcast_tree")
    assert spec is not None
    assert set(spec.values) == {"binomial", "chain", "star", "auto"}


def test_bcast_pool_accepts_auto_kind():
    """auto resolves at build time — the pool's concrete tree matches
    the payload class, and graph shape follows the resolved kind."""
    tp = bcast_taskpool(_vec("A1", 4, mb=64, P=4), n=4, kind="auto")
    cr = check_comm(tp, nb_ranks=4)
    assert cr.pattern == "broadcast"
    assert cr.fan_out.get(0) == 3           # short payload -> star
    tp = bcast_taskpool(_vec("A2", 4, mb=65536, P=4), n=4, kind="auto")
    cr = check_comm(tp, nb_ranks=4)
    assert cr.fan_out.get(0) == 2           # heavy payload -> binomial


def test_predict_collective_traffic_shape():
    pred = predict_collective_traffic(4, payload_bytes=1 << 16)
    assert pred["bcast_pattern"] == "broadcast"
    assert pred["reduce_pattern"] == "reduce"
    # binomial root serves children(0,4) = {1,2}: exactly two payloads
    assert pred["root_egress_bytes"] == 2 * (1 << 16), pred
    assert pred["total_bytes"] == 3 * (1 << 16) + 3 * 256, pred
    assert agreement_rel_err(100, 110) == pytest.approx(0.1)
    assert agreement_rel_err(0, 50) == 50.0     # degenerate base guarded


def test_runtime_report_carries_comm_pattern_block():
    check_comm(bcast_taskpool(_vec("RB", 4, P=4), n=4), nb_ranks=4)
    from parsec_tpu.prof.flight_recorder import runtime_report
    rep = runtime_report()
    assert "comm_pattern" in rep
    assert rep["comm_pattern"]["coll_bcast"]["pattern"] == "broadcast"
    assert rep["comm_pattern"]["coll_bcast"]["recommended_tree"] \
        in TREE_KINDS


def test_commcheck_cli_and_self_test(capsys):
    from parsec_tpu.analysis.__main__ import main as cli_main
    assert cli_main(["--comm", "--graph", "comm_bcast", "--nt", "4"]) == 0
    out = capsys.readouterr().out
    assert "broadcast" in out
    from parsec_tpu.analysis.commcheck import main as cc_main
    assert cc_main(["--self-test"]) == 0