"""perf_smoke: critical-path regression guards over microbench.py.

Every threshold carries ~10x headroom over the numbers measured at ISSUE-2
time (docs/PERF.md records those), so a pass is timing-flake-safe in CI
while a genuine dispatch-path regression — an accidental allocation in a
PINS site, a lock on the lfq common path, a lost compile-cache hit — still
fails loudly.  The whole module runs in a few seconds on CPU and is part
of tier-1 (it is deliberately NOT marked slow)."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import microbench  # noqa: E402

pytestmark = pytest.mark.perf_smoke

# measured on the ISSUE-2 CPU baseline (docs/PERF.md):  dispatch 1.3-1.6us,
# dynamic 40-50us, steal 0.8us, local pop 0.3us, pins disabled ~30ns
DISPATCH_US_MAX = 16.0
DYNAMIC_DISPATCH_US_MAX = 500.0
RELEASE_TASKS_PER_S_MIN = 2000.0
LOCAL_POP_US_MAX = 4.0
STEAL_US_MAX = 10.0
PINS_DISABLED_NS_MAX = 500.0
# ISSUE-3 serving baseline: ~300-400 submissions/s, p50 ~4-6ms, p99 ~13ms
# for 4 clients x tiny CTL pools on 2 workers (docs/SERVING.md) — same
# ~10x headroom discipline
SERVE_SUBMITS_PER_S_MIN = 25.0
SERVE_P99_MS_MAX = 250.0
# ISSUE-4 comm wire baseline (docs/COMM.md): AM roundtrip ~7µs inproc /
# ~200-500µs localhost socket, coalesced activations ~15-25k/s, 4MiB
# socket GET ~1.3-2 GB/s binary vs ~0.3-0.5 GB/s pickled (3-4.5x),
# overlap efficiency 0.2-0.5 — thresholds keep the same ~10x headroom so
# only a gross wire-path regression (a reintroduced copy, a dead window,
# a lost speedup) fails
COMM_AM_ROUNDTRIP_US_INPROC_MAX = 100.0
COMM_AM_ROUNDTRIP_US_SOCKET_MAX = 5000.0
COMM_ACTIVATIONS_PER_S_MIN = 1500.0
COMM_GET_SOCKET_4MIB_GBPS_MIN = 0.1
COMM_GET_SPEEDUP_VS_PICKLE_MIN = 1.5
# measured 0.2-0.5 on the ISSUE-4 CPU baseline: the dedicated T3
# overlap gate below holds the 10x-headroom line (ROADMAP T3 item);
# a dead fragment-progress path reads ~0 and fails it
COMM_OVERLAP_EFFICIENCY_MIN = 0.02
# ISSUE-6 LLM serving baseline: ~450 tokens/s at 1 stream, ~1300 at 4
# (continuous batching over paged-KV decode pools, 2 CPU workers),
# per-token p50 ~1-2.5ms / p99 ~4ms.  ISSUE 9 (k-step decode superpools,
# in-graph SAMPLE) multiplied the 4-stream smoke point several-fold, so
# the gate is raised to lock in AT LEAST 2x the PR-6 line (its old gate
# was 100 with ~10x headroom): a regression that quietly re-enters the
# host loop per token fails here by name
LLM_TOKENS_PER_S_MIN = 250.0
LLM_P99_MS_MAX = 250.0
# the amortization itself is gated too: k=8 superpools vs k=1 in the
# SAME run must keep a real multiple (measured ~3-6x on 4 streams; the
# ISSUE-9 acceptance line is >= 3x at 8 streams in the full bench)
LLM_SUPERPOOL_SPEEDUP_MIN = 1.8
# ISSUE-11 prefix cache: at 0.9 shared-prefix overlap the trie must
# skip >= 80% of prefill tokens and shared-prompt TTFT p50 must beat
# the trie-off run of the SAME traffic >= 2x (measured ~2.4x on the
# 64-page smoke shape; the ratio is work-structural — both runs share
# one process back to back — so it carries less timing noise than an
# absolute threshold would)
LLM_PREFIX_TTFT_SPEEDUP_MIN = 2.0
LLM_PREFIX_SKIPPED_FRAC_MIN = 0.8
# ISSUE-12 speculative decode: the adaptive drafter on the draftable
# (repetitive) 8-stream workload must beat the PR-9 k=8 path of the
# SAME workload >= 1.5x (measured ~1.6-1.9x on the smoke shape: the
# batched spec superpool collapses ~k*NP+2k tasks per pool to NP+1 and
# emits up to spec_k+1 tokens per submit), and acceptance-rate-0
# traffic (garbage drafts) must converge spec_k to ~0 and stay within
# 10% of the non-speculative path — the second gate lives in
# tests/test_llm_spec.py where the drafter can be forced adversarial
LLM_SPEC_SPEEDUP_MIN = 1.5
# ISSUE-20 commcheck: the static byte prediction for the collective
# rank sweep must agree with the measured peer_stats wire ledger within
# 15% rel (deterministic workload: (n-1) payload transfers + small
# reduction partials; framing and activation frames are the only slack)
COMMCHECK_AGREE_RELERR_MAX = 0.15


def test_compiled_dispatch_latency():
    r = microbench.bench_dispatch_us(ntasks=2000, reps=3)
    assert r["dispatch_us"] <= DISPATCH_US_MAX, r


def test_dynamic_release_throughput():
    r = microbench.bench_release_throughput(ntasks=2000, reps=1)
    assert r["dynamic_dispatch_us"] <= DYNAMIC_DISPATCH_US_MAX, r
    assert r["release_tasks_per_s"] >= RELEASE_TASKS_PER_S_MIN, r


def test_lfq_pop_and_steal_latency():
    r = microbench.bench_steal_us(n=200, reps=20)
    assert r["local_pop_us"] <= LOCAL_POP_US_MAX, r
    assert r["steal_us"] <= STEAL_US_MAX, r


def test_pins_disabled_site_cost():
    r = microbench.bench_pins_disabled_ns(iters=50000)
    # None = a PINS chain was registered by a concurrently-running module;
    # the dedicated allocation test (test_flight_recorder) still guards it
    if r["pins_disabled_ns"] is None:
        pytest.skip("PINS chains registered; disabled site unmeasurable")
    assert r["pins_disabled_ns"] <= PINS_DISABLED_NS_MAX, r


def test_serve_sustained_submission_throughput():
    """The serving path (admission + fair queue + live enqueue + ticket)
    must sustain concurrent submissions without a gross regression —
    tier-1's guard on the RuntimeServer critical path."""
    r = microbench.bench_serve(nsub=16, nthreads=4, depth=4)
    assert r["serve_nsub"] == 16, r
    assert r["serve_submits_per_s"] >= SERVE_SUBMITS_PER_S_MIN, r
    assert r["serve_p99_ms"] <= SERVE_P99_MS_MAX, r


@pytest.fixture(scope="module")
def comm_numbers():
    """One bench_comm run shared by the wire-path and overlap gates —
    the overlap threshold is its own test (a failure must NAME the T3
    regression), but the measurement need not run twice."""
    return microbench.bench_comm(smoke=True)


def test_comm_wire_path_throughput(comm_numbers):
    """The zero-copy wire data path (ISSUE 4): binary framing + windowed
    fragmented GETs must beat the pickled baseline — tier-1's guard on
    the comm critical path."""
    r = comm_numbers
    assert r["comm_am_roundtrip_us_inproc"] <= \
        COMM_AM_ROUNDTRIP_US_INPROC_MAX, r
    assert r["comm_am_roundtrip_us_socket"] <= \
        COMM_AM_ROUNDTRIP_US_SOCKET_MAX, r
    assert r["comm_activations_per_s"] >= COMM_ACTIVATIONS_PER_S_MIN, r
    assert r["comm_get_socket_4mib_gbps"] >= \
        COMM_GET_SOCKET_4MIB_GBPS_MIN, r
    assert r["comm_get_speedup_vs_pickle"] >= \
        COMM_GET_SPEEDUP_VS_PICKLE_MIN, r


def test_comm_overlap_efficiency_threshold(comm_numbers):
    """The T3 overlap gate (ROADMAP): compute retired during a
    saturating fragmented GET must stay above the 10x-headroom line —
    a regression in busy-worker fragment progress (a blocking recv, a
    lost progress interleave) drives the efficiency toward 0 and fails
    HERE, by name, not inside a grab-bag wire assertion."""
    assert comm_numbers["comm_overlap_efficiency"] >= \
        COMM_OVERLAP_EFFICIENCY_MIN, comm_numbers


def test_critpath_agrees_with_measured_overlap(comm_numbers):
    """ISSUE-16 acceptance: the span-plane replay must reconstruct the
    comm stage's overlap efficiency to within 15% relative of the
    inline-measured number — two independent computations of the same
    wall quantity (span interval algebra vs accumulated unit timers) —
    and the report must name the top-3 overlap_lost edge classes with
    nonzero values (the T3 target list)."""
    r = comm_numbers
    assert "comm_critpath_error" not in r, r.get("comm_critpath_error")
    m = r["comm_overlap_efficiency"]
    c = r["comm_critpath_overlap_efficiency"]
    assert abs(c - m) / max(m, 1e-9) < 0.15, (m, c)
    top = r["comm_critpath_top_lost"]
    assert len(top) == 3 and all(ms > 0 for _cls, ms in top), top
    assert r["comm_critpath_overlap_lost_ms"] > 0, r


def test_critpath_replay_fast_and_disabled_path_free(comm_numbers):
    """ISSUE-16 gates: replaying the whole comm stage's spans stays
    under 1s (analysis-time cost only), and the disabled path is free —
    critpath consumes EXISTING spans, so with no recorder installed
    there is nothing to pay and nothing to summarize."""
    assert comm_numbers["comm_critpath_replay_s"] < 1.0, comm_numbers
    from parsec_tpu.prof import spans
    from parsec_tpu.prof.critpath import summarize_recorder
    prev = spans.recorder
    if prev is not None:
        spans.uninstall()
    try:
        assert spans.recorder is None
        assert summarize_recorder() is None
    finally:
        if prev is not None:
            spans.install(recorder_obj=prev)


def test_perfdb_sentinel_roundtrips_synthetic_regression(tmp_path):
    """ISSUE-16 gate: the EWMA drift detector flags a 10x cliff (both
    metric directions) and stays quiet on 5% noise."""
    from parsec_tpu.prof.perfdb import PerfDB, make_key
    db = PerfDB(path=str(tmp_path / "perfdb.jsonl"))
    kd = make_key("smoke", "dispatch_us", backend=["cpu"])
    kt = make_key("smoke", "tokens_per_s", backend=["cpu"])
    for i in range(16):
        db.append(kd, 100.0 + (i % 2))      # latency-like: lower better
        db.append(kt, 1000.0 - (i % 3))     # throughput: higher better
    assert db.check(kd, 105.0)["verdict"] == "ok"       # 5% noise: quiet
    hi = db.check(kd, 1000.0)                           # 10x slowdown
    assert hi["verdict"] == "regressed" and hi["z"] > 0, hi
    assert db.check(kt, 100.0)["verdict"] == "regressed"   # 10x drop
    assert db.check(kt, 10000.0)["verdict"] == "improved"


@pytest.fixture(scope="module")
def llm_numbers():
    """One bench_llm run shared by the decode-throughput and
    speculative-decode gates (the spec axis rides the same bench)."""
    return microbench.bench_llm(smoke=True)


def test_llm_decode_throughput_and_latency(llm_numbers):
    """The LLM serving path (ISSUE 6 + 9): k-step decode superpools over
    the paged KV cache on a hot RuntimeServer must sustain tokens/s with
    bounded per-token p99, and the superpool amortization (one submit
    per k tokens, in-graph SAMPLE) must hold against the k=1 baseline
    measured in the same run — tier-1's guard on the decode critical
    path (admission + WFQ + live enqueue + ragged ATTN chains)."""
    r = llm_numbers
    assert r["llm_tokens_per_s"] >= LLM_TOKENS_PER_S_MIN, r
    assert r["llm_p99_ms"] <= LLM_P99_MS_MAX, r
    # the sweep axes are really swept: all points present and sane
    sweep = r["llm_streams_sweep"]
    assert set(sweep) == {"1", "4"}, r
    assert all(v["tokens_per_s"] > 0 for v in sweep.values()), r
    ksweep = r["llm_steps_sweep"]
    assert set(ksweep) == {"1", "8"}, r
    assert r["llm_superpool_speedup"] >= LLM_SUPERPOOL_SPEEDUP_MIN, r
    # the amortization claim is structural, not just a timing: k=8
    # superpools must submit at most ~1/8 pool per token (one pool can
    # carry a whole tenant batch, so strictly fewer still passes)
    assert ksweep["8"]["submits_per_token"] <= 1.0 / 8 + 1e-9, r
    assert ksweep["1"]["submits_per_token"] > ksweep["8"][
        "submits_per_token"], r


def test_llm_spec_decode_speedup(llm_numbers):
    """The ISSUE-12 speculative-decode gate: on the draftable 8-stream
    workload the adaptive drafter must beat the non-speculative PR-9
    k=8 path of the SAME workload >= 1.5x, with a real acceptance rate
    behind it (a dead drafter, a VERIFY that rejects everything, or a
    spec pool that quietly serializes again all fail here by name).
    The ratio is work-structural — both points run back to back in one
    process — so it carries less timing noise than an absolute
    threshold would."""
    r = llm_numbers
    sweep = r["llm_spec_sweep"]
    assert set(sweep) == {"off", "2", "4", "adaptive"}, r
    assert all(v["tokens_per_s"] > 0 for v in sweep.values()), r
    assert r["llm_spec_speedup"] >= LLM_SPEC_SPEEDUP_MIN, r
    # the speedup must come from accepted drafts, not a measurement
    # artifact: the adaptive point's acceptance is real and its pools
    # carry more tokens per submit than the fixed-2 point's cap allows
    assert sweep["adaptive"]["accept_rate"] >= 0.3, r
    assert sweep["adaptive"]["tokens_per_submit"] > \
        sweep["2"]["tokens_per_submit"], r
    # (zero rollbacks is legitimate here — on a fully draftable
    # workload the transition phase drafts nothing rather than drafts
    # wrong; forced-rejection rollback coverage lives in
    # tests/test_llm_spec.py where the drafter is made adversarial)


def test_llm_prefix_cache_ttft_speedup():
    """The ISSUE-11 prefix-cache gates: with 90% of traffic sharing one
    system prompt, the radix trie must convert >= 80% of prefill tokens
    into copy-on-write page forks (prefill_skipped_frac) and move the
    client-observed TTFT p50 >= 2x vs the identical traffic with the
    cache off — a dead trie (no donations, no matches, or forks that
    re-prefill anyway) fails both by name."""
    r = microbench.bench_llm_prefix(smoke=True)
    hot = r["llm_prefix_sweep"]["0.9"]
    assert hot["prefix_hits"] > 0, r
    assert r["llm_prefill_skipped_frac"] >= LLM_PREFIX_SKIPPED_FRAC_MIN, r
    assert r["llm_prefix_ttft_speedup"] >= LLM_PREFIX_TTFT_SPEEDUP_MIN, r
    # the no-sharing point keeps the cache honest: nothing to hit
    assert r["llm_prefix_sweep"]["0.0"]["prefix_hits"] == 0, r


# ISSUE-10 tracing budget (docs/OBSERVABILITY.md overhead table):
# disabled = the existing PINS one-branch cost, so the dynamic dispatch
# number must stay within 10% of the PR-2 overhead baseline gate;
# enabled = ≤1µs/task budget, gated at 10x headroom plus the noise
# floor of differencing two ~40µs dynamic-dispatch medians (measured
# ±4µs idle, up to ~2x that on a loaded CI box)
TRACING_DISABLED_RATIO_MAX = 1.10
TRACING_ENABLED_DELTA_US_MAX = 20.0
SPAN_RECORD_NS_MAX = 5000.0
HIST_RECORD_NS_MAX = 10000.0


def test_tracing_overhead_within_budget():
    """The ISSUE-10 observability gates: with the span recorder
    UNINSTALLED (the shipped default) the dynamic dispatch path costs
    what it cost at the PR-2 baseline (within the 10% ratio the issue
    pins — tracing added NO new hot-path site, only the existing PINS
    branch); INSTALLED with every pool traced, the per-task delta stays
    inside the ≤1µs budget line held at headroom.  Span and histogram
    record costs are gated directly so a regression names the layer."""
    r = microbench.bench_tracing(smoke=True)
    assert r["tracing_dispatch_off_us"] <= \
        DYNAMIC_DISPATCH_US_MAX * TRACING_DISABLED_RATIO_MAX, r
    assert r["tracing_dispatch_delta_us"] <= \
        TRACING_ENABLED_DELTA_US_MAX, r
    assert r["span_record_ns"] <= SPAN_RECORD_NS_MAX, r
    assert r["hist_record_ns"] <= HIST_RECORD_NS_MAX, r
    # the enabled run really recorded: traced pools span every task
    assert r["tracing_spans_recorded"] > 0, r


def test_lowering_cache_warm_compile_is_near_zero():
    r = microbench.bench_lowering_cache(n=64, nb=32)
    assert r["cache_hits"] >= 1, r
    # warm "compile" is a dict lookup + cached-executable call: even with
    # 10x headroom it must land far under the cold trace+compile
    assert r["compile_warm_s"] <= max(0.1 * r["compile_cold_s"], 0.05), r


# ISSUE-8 region-lowering baseline (docs/PERF.md "Region lowering &
# compile budgets"): on the smoke cholesky DAG (nt=4, 20 tasks across 4
# classes) the measured drop is 20x task-per-dispatch -> region, and the
# warm region compile is ~0.000s — the >=5x gate is the ISSUE-8
# acceptance line, held with the usual headroom discipline (a lost
# grouping or a dead region cache would crater it)
REGION_XLA_CALL_DROP_MIN = 5.0
REGION_COMPILE_WARM_S_MAX = 0.5


def test_region_lowering_xla_call_drop_and_warm_compile():
    """The MPK axis: region-lowered cholesky must issue >= 5x fewer XLA
    dispatches than the task-per-dispatch dynamic path, and a second
    structurally identical plan must compile for ~free through the
    process lowering cache."""
    r = microbench.bench_lowering(smoke=True)
    # the baseline really is task-per-dispatch: one call per task
    assert r["lowering_dispatch_xla_calls"] == r["lowering_tasks_per_dag"], r
    assert r["lowering_region_xla_call_drop"] >= REGION_XLA_CALL_DROP_MIN, r
    assert r["lowering_region_compile_warm_s"] <= \
        REGION_COMPILE_WARM_S_MAX, r


# ISSUE-18 closed-loop autotuner budgets (docs/TUNING.md overhead
# table): a tuning-DB consult sits on Context start and on the first
# submit of every tenant, so the cached lookup must stay deep in the
# noise (measured ~17µs parse-warm over 200 signatures; the issue pins
# the 50µs line).  The search harness itself — scoped overrides, trial
# memo, perfdb prior probe, JSONL note per trial — measured ~59µs/trial
# against a no-op objective; gated at ~30x headroom so only a
# structural regression (re-parsing the DB per trial, re-importing jax
# inside the loop) trips it.
TUNE_DB_LOOKUP_US_MAX = 50.0
TUNE_SEARCH_OVERHEAD_US_PER_TRIAL_MAX = 2000.0
TUNE_SPEEDUP_MIN = 1.2


def test_tune_search_and_db_overhead():
    r = microbench.bench_tune(smoke=True)
    assert r["tune_db_lookup_us"] <= TUNE_DB_LOOKUP_US_MAX, r
    assert r["tune_search_overhead_us_per_trial"] <= \
        TUNE_SEARCH_OVERHEAD_US_PER_TRIAL_MAX, r
    # the lookup gate measured against a real population, not one row
    assert r["tune_db_records"] >= 200, r


def test_tuned_cholesky_recovers_seeded_bad_tile(param, tmp_path):
    """The ISSUE-18 acceptance headline: handed a deliberately
    mis-tiled dynamic Cholesky (nb far too small, dispatch-bound), the
    autotuner must claw back >= 1.2x within its trial budget and leave
    the winner in tunedb.jsonl.  Measured ~10x on the smoke shape — the
    gate only fails if the loop stops moving the knob, scores the wrong
    run, or loses the steady-state warmup discipline."""
    import bench
    from parsec_tpu.core.params import params
    from parsec_tpu.device import registry
    params.register("device_tpu_allow_cpu", False)
    param("device_tpu_allow_cpu", True)
    param("tune_db_path", str(tmp_path / "tunedb.jsonl"))
    param("perfdb", False)
    snapshot = list(registry.devices)
    try:
        r = bench.bench_tuned_cholesky(n=256, nb_bad=32, budget=4)
    finally:
        registry.devices = snapshot
        for i, d in enumerate(registry.devices):
            d.device_index = i
    assert r["tune_speedup"] >= TUNE_SPEEDUP_MIN, r
    assert r["best_nb"] != r["nb_bad"], r
    assert r["tile00_abs_err"] <= 1e-3, r
    assert Path(r["db_path"]).exists(), r


@pytest.mark.parametrize("nranks", [2, 4])
def test_commcheck_static_vs_wire_agreement(nranks):
    """ISSUE-20 agreement gate at the comm_ranks smoke points: commcheck
    predicts the collective sweep's cross-rank bytes WITHOUT executing,
    and the measured socket ledger (summed tx across every rank) must
    land within 15% rel of it — drift on either side (a static model
    that forgot an edge, a wire path that started double-shipping)
    fails here by name."""
    from parsec_tpu.analysis.commcheck import (agreement_rel_err,
                                               predict_collective_traffic)
    from parsec_tpu.comm.multiproc import run_multiproc
    pred = predict_collective_traffic(nranks)
    assert pred["bcast_pattern"] == "broadcast", pred
    assert pred["reduce_pattern"] == "reduce", pred
    res = run_multiproc(
        nranks, "parsec_tpu.comm.collectives:_mp_collective_body",
        timeout=240, nb_cores=1)
    observed = sum(d["bytes"] for r in res
                   for d in r["peer_stats"]["tx"].values())
    err = agreement_rel_err(pred["total_bytes"], observed)
    assert err <= COMMCHECK_AGREE_RELERR_MAX, \
        (pred["total_bytes"], observed, err)
