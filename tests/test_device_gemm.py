"""Device-layer + tiled-GEMM tests (analog of tests/runtime/cuda/stress.jdf,
get_best_device_check.jdf — run against the device module with a virtual
accelerator wrapping a CPU jax device)."""

import numpy as np
import pytest

import jax

from parsec_tpu.data_dist.matrix import (SymTwoDimBlockCyclic, TiledMatrix,
                                         TwoDimBlockCyclic, TwoDimTabular)
from parsec_tpu.device import registry
from parsec_tpu.device.tpu import TPUDevice
from parsec_tpu.models.tiled_gemm import (gemm_flops, tiled_gemm_fused,
                                          tiled_gemm_ptg)
from parsec_tpu.runtime import Context


# accel_device fixture: shared in conftest.py


def _mk_abc(M, N, K, mb, rng):
    a = rng.standard_normal((M, K)).astype(np.float32)
    b = rng.standard_normal((K, N)).astype(np.float32)
    c = rng.standard_normal((M, N)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a, mb, mb)
    B = TiledMatrix.from_dense("B", b, mb, mb)
    C = TiledMatrix.from_dense("C", c, mb, mb)
    return a, b, c, A, B, C


class TestTiledGemmCPU:
    def test_cpu_path_correct(self):
        rng = np.random.default_rng(0)
        a, b, c, A, B, C = _mk_abc(64, 48, 80, 16, rng)
        tp = tiled_gemm_ptg(A, B, C, devices="cpu")
        ctx = Context(nb_cores=2)
        ctx.add_taskpool(tp)
        ctx.start()
        tp.wait(timeout=60)
        ctx.fini()
        np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3,
                                   atol=1e-4)


class TestTiledGemmDevice:
    def test_device_path_correct(self, accel_device):
        rng = np.random.default_rng(1)
        a, b, c, A, B, C = _mk_abc(64, 64, 64, 16, rng)
        tp = tiled_gemm_ptg(A, B, C, devices="tpu")
        ctx = Context(nb_cores=2)
        ctx.add_taskpool(tp)
        ctx.start()
        tp.wait(timeout=120)
        accel_device.sync()
        ctx.fini()
        np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3)
        assert accel_device.executed_tasks == 4 * 4 * 4
        assert accel_device.bytes_in > 0
        # attribution instrumentation: every phase wall + the call counter
        # accumulate during a real run (the bench breakdown's inputs)
        assert accel_device.xla_calls > 0
        assert accel_device.t_manager > 0
        assert accel_device.t_stage_in >= 0 and accel_device.t_dispatch > 0

    def test_best_device_prefers_accel_for_big_tiles(self, accel_device):
        rng = np.random.default_rng(2)
        a, b, c, A, B, C = _mk_abc(32, 32, 32, 32, rng)
        tp = tiled_gemm_ptg(A, B, C, devices="auto")
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
        accel_device.sync()
        ctx.fini()
        np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3)

    def test_lru_flush_writes_back(self, accel_device):
        rng = np.random.default_rng(3)
        a, b, c, A, B, C = _mk_abc(32, 32, 32, 16, rng)
        tp = tiled_gemm_ptg(A, B, C, devices="tpu")
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
        accel_device.sync()
        accel_device.flush_cache()
        ctx.fini()
        # after flush, host copies are plain numpy and correct
        t00 = C.data_of(0, 0).get_copy(0).value
        assert isinstance(t00, np.ndarray)
        np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3)


class TestFused:
    def test_fused_matches_numpy(self):
        rng = np.random.default_rng(4)
        a = rng.standard_normal((128, 64)).astype(np.float32)
        b = rng.standard_normal((64, 96)).astype(np.float32)
        c = np.zeros((128, 96), np.float32)
        out = tiled_gemm_fused(a, b, c)
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-3,
                                   atol=1e-5)

    def test_gemm_flops(self):
        assert gemm_flops(2, 3, 4) == 48


class TestDistributions:
    def test_block_cyclic_rank_map(self):
        m = TwoDimBlockCyclic("M", 64, 64, 8, 8, P=2, Q=2)
        assert m.rank_of(0, 0) == 0
        assert m.rank_of(0, 1) == 1
        assert m.rank_of(1, 0) == 2
        assert m.rank_of(1, 1) == 3
        assert m.rank_of(2, 2) == 0  # cyclic wrap

    def test_supertiles(self):
        m = TwoDimBlockCyclic("M", 64, 64, 8, 8, P=2, Q=1, kp=2)
        assert m.rank_of(0, 0) == m.rank_of(1, 0) == 0
        assert m.rank_of(2, 0) == m.rank_of(3, 0) == 1

    def test_ragged_edge_tiles(self):
        m = TiledMatrix("M", 20, 10, 8, 8)
        assert m.tile_shape(2, 1) == (4, 2)
        d = m.data_of(2, 1)
        assert d.newest_copy().value.shape == (4, 2)

    def test_sym_rejects_wrong_triangle(self):
        m = SymTwoDimBlockCyclic("S", 32, 32, 8, 8, uplo=0)
        m.data_of(2, 1)
        with pytest.raises(KeyError):
            m.data_of(1, 2)

    def test_tabular(self):
        m = TwoDimTabular("T", 32, 32, 8, 8,
                          rank_table=lambda i, j: (i * 7 + j) % 3, nodes=3)
        assert m.rank_of(1, 1) == 8 % 3

    def test_dense_round_trip(self):
        rng = np.random.default_rng(5)
        a = rng.standard_normal((24, 18)).astype(np.float32)
        m = TiledMatrix.from_dense("RT", a, 7, 5)
        np.testing.assert_array_equal(m.to_dense(), a)


class TestVmapBatching:
    """device_tpu_batch stacks same-class pending tasks into ONE vmapped XLA
    dispatch (VERDICT r2 weak #4: the claim is now real)."""

    def _run(self, accel_device, batch_on):
        from parsec_tpu.core.params import params
        old = params.get("device_tpu_batch")
        params.set("device_tpu_batch", batch_on)
        try:
            rng = np.random.default_rng(5)
            a, b, c, A, B, C = _mk_abc(64, 64, 64, 16, rng)
            tp = tiled_gemm_ptg(A, B, C, devices="tpu")
            # nb_cores=0: the caller thread floods the device with every
            # ready task before managing, maximizing batch opportunities
            ctx = Context(nb_cores=0)
            ctx.add_taskpool(tp)
            ctx.wait(timeout=120)
            accel_device.sync()
            ctx.fini()
            np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3)
            return accel_device.batched_dispatches
        finally:
            params.set("device_tpu_batch", old)

    def test_batching_fires_and_is_correct(self, accel_device):
        batched = self._run(accel_device, True)
        assert batched > 0, "no vmapped dispatch serviced a multi-task batch"
        assert accel_device.executed_tasks == 4 * 4 * 4

    def test_batching_off_uses_per_task_path(self, accel_device):
        batched = self._run(accel_device, False)
        assert batched == 0

    def test_non_power_of_two_batches_pad_correctly(self, accel_device):
        """A 3x3x3 GEMM's wavefronts are 9 tasks — the fused dispatch
        pads to 16 lanes with copies of lane 0 and must drop the pad
        outputs (a pad write leaking into a real tile shows up as wrong
        numerics)."""
        rng = np.random.default_rng(6)
        a, b, c, A, B, C = _mk_abc(48, 48, 48, 16, rng)
        tp = tiled_gemm_ptg(A, B, C, devices="tpu")
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
        accel_device.sync()
        ctx.fini()
        np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3,
                                   atol=1e-4)
        assert accel_device.batched_dispatches > 0
        assert accel_device.executed_tasks == 3 * 3 * 3

    def test_fused_batch_is_one_xla_call(self, accel_device):
        """The whole batch — on-device stacking, vmapped exec, per-task
        output slicing — rides ONE enqueue (VERDICT r4 item 5: through a
        high-latency relay the enqueue count IS the dynamic-path wall;
        round 4 paid F stacks + exec + unbind per batch)."""
        self._run(accel_device, True)
        assert accel_device.executed_tasks == 4 * 4 * 4
        assert accel_device.batched_dispatches > 0
        # every task rode a fused batch: calls == batches, not tasks
        assert accel_device.xla_calls == accel_device.batched_dispatches


def test_prefetch_is_idempotent(accel_device):
    """Prefetched stage-in must not double-transfer: bytes_in with the
    lookahead enabled equals a run with it disabled (same tiles, same
    numerics)."""
    from parsec_tpu.core.params import params

    results = {}
    for depth in (0, 8):
        old = params.get("device_tpu_prefetch")
        params.set("device_tpu_prefetch", depth)
        try:
            rng = np.random.default_rng(9)
            a, b, c, A, B, C = _mk_abc(64, 64, 64, 16, rng)
            bytes_before = accel_device.bytes_in
            tp = tiled_gemm_ptg(A, B, C, devices="tpu")
            ctx = Context(nb_cores=0)
            ctx.add_taskpool(tp)
            ctx.wait(timeout=120)
            accel_device.sync()
            accel_device.flush_cache()
            ctx.fini()
            results[depth] = accel_device.bytes_in - bytes_before
            # atol floor: near-zero result elements otherwise fail the
            # relative test on ~1e-6 absolute noise (CPU-backend matmul
            # accumulation-order drift across jax releases)
            np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3,
                                       atol=1e-5)
        finally:
            params.set("device_tpu_prefetch", old)
    assert results[0] == results[8], results


def test_deferred_eviction_under_pressure(accel_device):
    """A tiny HBM budget forces evictions; victims write back through the
    deferred w2r queue between batches, and numerics survive."""
    accel_device._mem_budget = 3 * 16 * 16 * 4   # room for ~3 tiles
    rng = np.random.default_rng(11)
    a, b, c, A, B, C = _mk_abc(64, 64, 64, 16, rng)
    tp = tiled_gemm_ptg(A, B, C, devices="tpu")
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    accel_device.sync()
    accel_device.flush_cache()
    ctx.fini()
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3)
    assert accel_device.deferred_evictions > 0
    assert not accel_device._evict_q


def test_failed_dispatch_demotes_to_cpu(accel_device):
    """A device body that raises must not strand the run: the manager
    salvages resident tiles, disables the device, and the rescheduled
    tasks demote to their CPU incarnation (device_gpu.c:2647 protocol)."""
    from parsec_tpu import ptg
    from parsec_tpu.data.data import TileType
    from parsec_tpu.data_dist.collection import DictCollection

    coll = DictCollection("F", dtt=TileType((4,), np.float32),
                          init_fn=lambda *k: np.zeros(4, np.float32))
    ran = {"cpu": 0, "dev": 0}

    p = ptg.PTGBuilder("demote", F=coll, N=3)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
    f = t.flow("V", ptg.RW)
    f.input(data=("F", lambda g, l: (l.i,)))
    f.output(data=("F", lambda g, l: (l.i,)))

    def dev_body(es, task, device):
        ran["dev"] += 1
        raise RuntimeError("injected device failure")

    from parsec_tpu.device.kernels import register_kernel
    register_kernel("demote_fail", "tpu", dev_body)
    t.body(device="tpu", dyld="demote_fail")

    def cpu_body(es, task, g, l):
        ran["cpu"] += 1
        v = task.flow_data("V")
        v.value = np.asarray(v.value) + 7

    t.body(cpu_body)

    ctx = Context(nb_cores=0)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=60)
    ctx.fini()
    assert ran["dev"] >= 1              # the device was tried...
    assert ran["cpu"] == 3              # ...and every task demoted to CPU
    assert accel_device.enabled is False
    for i in range(3):
        assert float(coll.data_of(i).newest_copy().value[0]) == 7.0
