"""Sharded RuntimeServer (ISSUE 14): one logical serving plane across
ranks — KV-residency placement, cross-rank exactly-merged SLO metrics,
tree-broadcast config, and dead-rank stream requeue.

Inproc multirank (threads) so the test can reach into every rank's
server object: the frontend is rank 0, workers run ``serve_forever``
until the frontend's SHUTDOWN."""

import threading

import numpy as np  # noqa: F401  (kept: parity with the serve test tier)
import pytest

from parsec_tpu.comm.multirank import run_multirank
from parsec_tpu.llm import ToyLM
from parsec_tpu.serve.sharded import ShardedRuntimeServer, merge_planes

MODEL = ToyLM()


def _run_plane(nranks, frontend_fn, timeout=180):
    """Every rank builds a ShardedRuntimeServer; rank 0 runs
    ``frontend_fn(srv, peers)`` (peers: every rank's server, so tests can
    inject faults / read worker state), workers serve until SHUTDOWN."""
    bar = threading.Barrier(nranks)
    peers: dict[int, ShardedRuntimeServer] = {}

    def body(ctx, rank, nranks):
        srv = ShardedRuntimeServer(ctx)
        peers[rank] = srv
        bar.wait()
        if rank == 0:
            try:
                return frontend_fn(srv, peers)
            finally:
                srv.shutdown()
                bar.wait()
        try:
            srv.serve_forever(idle_timeout=timeout)
        finally:
            srv.close()
            bar.wait()
        return None

    return run_multirank(nranks, body, nb_cores=1, timeout=timeout)[0]


def test_two_rank_oracle_equal_and_metrics_merge_exactly():
    prompts = [[3, 7, 11, 5], [1, 40], [8, 30, 22], [9, 2, 4, 6]]

    def frontend(srv, peers):
        hs = [srv.submit_stream(p, max_new_tokens=10,
                                tenant=f"t{i % 2}")
              for i, p in enumerate(prompts)]
        srv.wait(hs, timeout=120)
        for p, h in zip(prompts, hs):
            assert h.result(timeout=1)["tokens"] == \
                MODEL.reference_generate(p, 10), p
        m = srv.metrics(timeout=30)
        # both ranks decoded (least-loaded fallback spreads the burst)
        assert {h.rank for h in hs} == {0, 1}
        # the merged summary IS merge_planes over the per-rank planes:
        # bucket-exact, not an average of per-rank summaries
        raw = [peers[r]._plane_dict() for r in sorted(peers)]
        assert m["tenants"] == merge_planes(raw)
        assert m["ranks"] == 2
        # per-tenant sample counts survived the merge: the merged count
        # is the SUM of the per-rank histogram counts, never a mean
        for t in ("t0", "t1"):
            want = sum(h["count"] for plane in raw
                       for h in [plane.get(t, {}).get("latency_ms")]
                       if h is not None)
            assert want > 0
            assert m["tenants"][t]["latency_ms_count"] == want
        return True

    assert _run_plane(2, frontend) is True


def test_placement_prefers_prefix_residency_then_least_loaded():
    a, b = [3, 7, 11, 5], [21, 22, 23, 24, 25]

    def frontend(srv, peers):
        ha = srv.submit_stream(a, max_new_tokens=6)
        hb = srv.submit_stream(b, max_new_tokens=6)
        # burst placement: tie on residency -> least loaded spreads
        assert ha.rank == 0 and hb.rank == 1, (ha.rank, hb.rank)
        srv.wait([ha, hb], timeout=120)
        # a repeat of b's prompt routes to b's rank: the router history
        # scores its full-prefix match above rank 0's empty residency
        hc = srv.submit_stream(b, max_new_tokens=6)
        assert hc.rank == 1, hc.rank
        srv.wait([hc], timeout=120)
        assert hc.result(timeout=1)["tokens"] == \
            MODEL.reference_generate(b, 6)
        return True

    assert _run_plane(2, frontend) is True


def test_config_broadcast_rides_the_tree():
    """WFQ weights + admission budgets broadcast along the collective
    tree: with 4 ranks (binomial) the frontend serves ranks 1 and 2 only
    and rank 1 re-forwards to rank 3 — every rank still applies it."""
    import time

    def frontend(srv, peers):
        srv.broadcast_config(weights={"pro": 4.0}, max_inflight=32,
                             max_tenant_inflight=8)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            srv.step()
            if all(peers[r]._local._adm.max_inflight == 32
                   for r in peers):
                break
            time.sleep(0.005)
        for r, p in sorted(peers.items()):
            assert p._local._adm.max_inflight == 32, r
            assert p._local._adm.max_tenant_inflight == 8, r
            assert p._local._fair._weights.get("pro") == 4.0, r
        # the tree: rank 0 forwarded twice (children 1, 2), rank 1 once
        # (child 3), leaves not at all
        assert peers[0].config_forwards == 2
        assert peers[1].config_forwards == 1
        assert peers[2].config_forwards == 0
        assert peers[3].config_forwards == 0
        return True

    assert _run_plane(4, frontend) is True


def test_dead_rank_streams_requeue_oracle_exact():
    """Kill the rank mid-generation: its streams resume on a survivor
    from the last shipped token (prompt + prefix re-dispatch), stay
    token-for-token oracle-equal, and the zombie's late duplicate deltas
    are dropped by the handle's index dedup."""
    import time

    prompt, nmax = [5, 9, 13, 2], 12
    oracle = MODEL.reference_generate(prompt, nmax)

    def frontend(srv, peers):
        filler = srv.submit_stream([2, 4], max_new_tokens=4)   # rank 0
        h = srv.submit_stream(prompt, max_new_tokens=nmax)     # rank 1
        assert h.rank == 1
        # let rank 1 ship a few tokens, then it goes dark
        deadline = time.monotonic() + 60
        while len(h.tokens) < 3:
            srv.step()
            assert time.monotonic() < deadline, h.tokens
            time.sleep(0.002)
        peers[1].zombie = True
        k = len(h.tokens)
        srv.fail_rank(1)
        assert h.rank == 0 and h.requeues == 1 and h.ranks == [1, 0]
        srv.wait([h, filler], timeout=120)
        assert h.result(timeout=1)["tokens"] == oracle, \
            (h.tokens, oracle, k)
        # resurrect the zombie: everything it still ships replays
        # below the ledger's high-water mark and is dropped
        peers[1].zombie = False
        deadline = time.monotonic() + 30
        while peers[1]._live and time.monotonic() < deadline:
            srv.step()
            time.sleep(0.005)
        srv.step()
        assert h.tokens == oracle            # dedup: nothing re-landed
        # and a replayed delta through the REAL handler (the zombie may
        # or may not have had unshipped tokens left — this one always
        # replays) is dropped AND counted
        srv._handle(1, {"op": "TOKENS", "sid": h.sid, "base": 0,
                        "toks": list(oracle[:2])})
        assert h.tokens == oracle
        assert h.dup_tokens >= 2
        return True

    assert _run_plane(2, frontend) is True
