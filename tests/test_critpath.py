"""Critical-path attribution engine (ISSUE 16): the replay that turns
the span plane into *where did this request's wall-clock go*.

- the packaged self-test (additive sweep, overlap_lost, chrome
  round-trip, DAG critical path, cycle safety) run as a unit test;
- the accounting identity pinned independently on fresh synthetic
  spans (sum(buckets) + idle == window, exactly);
- the CLI contract (`python -m parsec_tpu.prof.critpath trace.json
  --json`) against a file on disk;
- the ISSUE-16 satellite: a 2-rank ShardedRuntimeServer stream run
  TRACED — critpath must attribute the SUBMIT/TOKENS control-plane
  hops as `serve.submit` / `serve.tokens` edge classes on the
  stream's own trace id.
"""

import json
import subprocess
import sys
import threading

from parsec_tpu.prof import spans
from parsec_tpu.prof.critpath import (attribute, dag_critical_path,
                                      from_chrome, normalize,
                                      summarize_recorder)

MS = 1_000_000


def test_packaged_self_test():
    from parsec_tpu.prof import critpath
    assert critpath.self_test() == 0


def test_perfdb_packaged_self_test(tmp_path, monkeypatch):
    monkeypatch.setenv("PARSEC_TPU_ARTIFACT_DIR", str(tmp_path))
    from parsec_tpu.prof import perfdb
    assert perfdb.self_test() == 0


def test_decomposition_is_an_accounting_identity():
    """Overlapping spans never double-count: each elementary segment is
    charged to exactly one bucket, so the sum reconstructs the window."""
    sp = normalize([
        ("queue_wait", 0x7, 0, 3 * MS, None, None, 1),
        ("exec", 0x7, 1 * MS, 6 * MS, None, "POTRF", 1),       # overlaps q
        ("comm.get", 0x7, 2 * MS, 9 * MS, None, {"bytes": 1 << 16}, 2),
        ("release", 0x7, 9 * MS, 10 * MS, None, None, 1),
        ("exec", 0x7, 12 * MS, 14 * MS, None, "GEMM", 1),      # idle gap
    ])
    rep = attribute(sp)
    rq = rep["requests"]["7"]
    assert abs(sum(rq["buckets_ms"].values()) - rq["window_ms"]) < 1e-9
    # priority: exec shadows queue on [1,3) and comm.get on [2,6)
    bk = rq["buckets_ms"]
    assert bk["exec"] == 7.0 and bk["queue"] == 1.0, bk
    assert bk["comm.get"] == 3.0 and bk["idle"] == 2.0, bk
    # per-task split saw both classes
    assert rep["tasks"]["POTRF"]["count"] == 1
    assert rep["tasks"]["GEMM"]["count"] == 1
    # the GET flew 7ms, 4ms hidden behind POTRF -> 3ms lost
    assert abs(rep["edges"]["comm.get:64kib"]["overlap_lost_ms"] - 3.0) \
        < 1e-9


def test_dag_critical_path_uses_measured_class_costs():
    g = {("A", 0): [("B", 0)], ("B", 0): [("C", 0)], ("C", 0): []}
    dag = dag_critical_path(g, {"A": 2.0, "B": 3.0, "C": 4.0})
    assert dag["length"] == 9.0
    assert [n[0] for n in dag["path"]] == ["A", "B", "C"]


def test_summarize_recorder_disabled_returns_none():
    prev = spans.recorder
    if prev is not None:
        spans.uninstall()
    try:
        assert spans.recorder is None
        assert summarize_recorder() is None
    finally:
        if prev is not None:
            spans.install(recorder_obj=prev)


def test_cli_attributes_a_chrome_trace_on_disk(tmp_path):
    evs = [{"name": "exec", "cat": "span", "ph": "X", "ts": 0.0,
            "dur": 5000.0, "pid": 1, "tid": 1,
            "args": {"trace": "c0de", "task": "GEMM"}},
           {"name": "comm.get", "cat": "span", "ph": "X", "ts": 2000.0,
            "dur": 6000.0, "pid": 1, "tid": 2,
            "args": {"trace": "c0de", "bytes": 4 << 20,
                     "flow": "get:0:1", "flow_side": "recv"}}]
    p = tmp_path / "trace.json"
    p.write_text(json.dumps({"traceEvents": evs}))
    r = subprocess.run(
        [sys.executable, "-m", "parsec_tpu.prof.critpath", str(p),
         "--json"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-1500:]
    rep = json.loads(r.stdout)
    assert rep["spans"] == 2 and "c0de" in rep["requests"]
    assert rep["requests"]["c0de"]["buckets_ms"]["exec"] == 5.0
    assert rep["edges"]["comm.get:4mib"]["overlap_lost_ms"] == 3.0
    # human rendering too (no --json): the panel text
    r2 = subprocess.run(
        [sys.executable, "-m", "parsec_tpu.prof.critpath", str(p)],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0 and "overlap_lost" in r2.stdout


def test_sharded_stream_critpath_attributes_control_plane_hops():
    """ISSUE-16 satellite: a 2-rank traced stream under the sharded
    serving plane — the SUBMIT crossing (frontend -> decode rank) and
    the TOKENS/DONE crossings back must land as `serve.submit` /
    `serve.tokens` edge classes on the stream's trace, charged to the
    comm.activate bucket of that request's decomposition."""
    from parsec_tpu.comm.multirank import run_multirank
    from parsec_tpu.serve.sharded import ShardedRuntimeServer

    bar = threading.Barrier(2)
    prev = spans.recorder
    if prev is not None:
        spans.uninstall()
    rec = spans.install()
    try:
        def body(ctx, rank, nranks):
            srv = ShardedRuntimeServer(ctx)
            bar.wait()
            if rank == 0:
                try:
                    # burst of two: least-loaded placement parks the
                    # second on rank 1 -> a genuinely remote stream
                    ha = srv.submit_stream([3, 7, 11, 5],
                                           max_new_tokens=6)
                    hb = srv.submit_stream([21, 22, 23, 24],
                                           max_new_tokens=6)
                    srv.wait([ha, hb], timeout=120)
                    remote = hb if hb.rank != 0 else ha
                    assert remote.rank != 0, (ha.rank, hb.rank)
                    return remote.trace
                finally:
                    srv.shutdown()
                    bar.wait()
            try:
                srv.serve_forever(idle_timeout=180)
            finally:
                srv.close()
                bar.wait()
            return None

        trace = run_multirank(2, body, nb_cores=1, timeout=180)[0]
        assert trace, "submit_stream minted no trace under the recorder"
        raw = list(rec.spans)
    finally:
        spans.uninstall()
        if prev is not None:
            spans.install(recorder_obj=prev)

    rep = attribute(normalize(raw))
    req = rep["requests"].get(format(trace, "x"))
    assert req, sorted(rep["requests"])
    # both control-plane hop kinds attributed as edge classes
    assert any(c.startswith("serve.submit:") for c in rep["edges"]), \
        sorted(rep["edges"])
    assert any(c.startswith("serve.tokens:") for c in rep["edges"]), \
        sorted(rep["edges"])
    # ...and they charge the traced request's comm.activate bucket
    assert req["buckets_ms"]["comm.activate"] > 0, req
    # the emit/recv pairing really spanned the hop: both sides of at
    # least one ssub flow are present on this trace
    hop = [s for s in raw if s[0] == "serve.submit"
           and int(s[1]) == int(trace)]
    sides = {s[5].get("flow_side") for s in hop if isinstance(s[5], dict)}
    assert sides == {"emit", "recv"}, hop
