"""Stress: the round's features composed (the tests/runtime/stress analog).

Each configuration runs a full block-cyclic GEMM through the dynamic
multi-rank runtime with a different combination of worker threads, the
dedicated comm thread, coalescing, and scheduler modules — the goal is
racing the protocol layers against each other, not numerics novelty.
"""

import numpy as np
import pytest

import parsec_tpu.runtime.dagrun  # noqa: F401  (registers runtime_dag_compile)
from parsec_tpu.comm import run_multirank
from parsec_tpu.core.params import params
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg


def _gemm_body(ctx, rank, nranks):
    n, nb = 96, 16
    rng = np.random.RandomState(41)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q, myrank=rank)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=Q, myrank=rank)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=Q, myrank=rank)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
    ctx.wait(timeout=180)
    ctx.comm_barrier()
    return C.to_dense()


def _check(res):
    n = 96
    rng = np.random.RandomState(41)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    got = np.zeros((n, n), np.float32)
    for part in res:
        got += part
    np.testing.assert_allclose(got, a @ b, rtol=1e-3, atol=1e-3)


CONFIGS = [
    # (nranks, nb_cores, comm_thread, coalesce, sched)
    (8, 0, False, True, "lfq"),      # wide mesh, funneled
    (4, 2, True, True, "lfq"),       # workers + comm thread + coalescing
    (4, 2, True, False, "ll"),       # comm thread, no coalescing, LIFO zoo
    (2, 3, False, True, "pbq"),      # hierarchical scheduler under workers
]


@pytest.mark.parametrize("nranks,cores,cthread,coal,sched", CONFIGS)
def test_gemm_stress(param, nranks, cores, cthread, coal, sched):
    param("comm_thread", cthread)
    param("comm_coalesce", coal)
    param("sched", sched)
    param("runtime_dag_compile", False)   # exercise the dynamic scheduler
    _check(run_multirank(nranks, _gemm_body, nb_cores=cores, timeout=240))


# ---------------------------------------------------------------------------
# round-4 feature interplay: recursive bodies + DTD discovery + live props
# + steal accounting racing on one context
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rep", range(3))
def test_round4_features_race(param, tmp_path, rep):
    """Recursive GEMM (nested pools) and body-driven DTD discovery run
    CONCURRENTLY on one 4-worker context while the properties stream
    writes snapshots and print_steals counts — the protocols must not
    interfere (nested local-only pools, insert locks, PINS chains,
    props registry)."""
    from parsec_tpu.core.mca import repository
    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.dtd import DTDTaskpool
    from parsec_tpu.models.irregular import (haar_project_dtd,
                                             haar_project_reference)
    from parsec_tpu.models.tiled_gemm import tiled_gemm_recursive_ptg
    from parsec_tpu.runtime import Context

    param("props_stream", str(tmp_path / f"props{rep}.json"))
    param("props_stream_interval", 0.02)
    param("runtime_dag_compile", False)
    comp = repository.find("pins", "print_steals")
    mod = comp.open()
    try:
        rng = np.random.default_rng(rep)
        n, nb = 32, 8
        a = rng.standard_normal((n, n)).astype(np.float32)
        b = rng.standard_normal((n, n)).astype(np.float32)
        c = rng.standard_normal((n, n)).astype(np.float32)
        A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
        B = TiledMatrix.from_dense("B", b.copy(), nb, nb)
        C = TiledMatrix.from_dense("C", c.copy(), nb, nb)
        with Context(nb_cores=4) as ctx:
            rec = tiled_gemm_recursive_ptg(A, B, C, sub_mb=4, sub_nb=4)
            ctx.add_taskpool(rec)
            dtd = DTDTaskpool(f"haar{rep}")
            ctx.add_taskpool(dtd)
            tree = haar_project_dtd(dtd, 1.0, 1e-4, min_depth=4,
                                    max_depth=18)
            dtd.wait(timeout=180)
            ctx.wait(timeout=180)
        np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3,
                                   atol=1e-4)
        want = haar_project_reference(1.0, 1e-4, min_depth=4, max_depth=18)
        assert set(tree) == set(want)
        # the observability protocols must have actually observed: the
        # stream wrote snapshots and the steal counter saw the 4 workers
        import json
        snap = json.load(open(tmp_path / f"props{rep}.json"))
        assert "props" in snap and any(
            k.startswith("rank0") for k in snap["props"])
        assert sum(mod.steals.values()) > 0
    finally:
        comp.close(mod)
