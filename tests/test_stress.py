"""Stress: the round's features composed (the tests/runtime/stress analog).

Each configuration runs a full block-cyclic GEMM through the dynamic
multi-rank runtime with a different combination of worker threads, the
dedicated comm thread, coalescing, and scheduler modules — the goal is
racing the protocol layers against each other, not numerics novelty.
"""

import numpy as np
import pytest

import parsec_tpu.runtime.dagrun  # noqa: F401  (registers runtime_dag_compile)
from parsec_tpu.comm import run_multirank
from parsec_tpu.core.params import params
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg


def _gemm_body(ctx, rank, nranks):
    n, nb = 96, 16
    rng = np.random.RandomState(41)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q, myrank=rank)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=Q, myrank=rank)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=Q, myrank=rank)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
    ctx.wait(timeout=180)
    ctx.comm_barrier()
    return C.to_dense()


def _check(res):
    n = 96
    rng = np.random.RandomState(41)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    got = np.zeros((n, n), np.float32)
    for part in res:
        got += part
    np.testing.assert_allclose(got, a @ b, rtol=1e-3, atol=1e-3)


CONFIGS = [
    # (nranks, nb_cores, comm_thread, coalesce, sched)
    (8, 0, False, True, "lfq"),      # wide mesh, funneled
    (4, 2, True, True, "lfq"),       # workers + comm thread + coalescing
    (4, 2, True, False, "ll"),       # comm thread, no coalescing, LIFO zoo
    (2, 3, False, True, "pbq"),      # hierarchical scheduler under workers
]


@pytest.mark.parametrize("nranks,cores,cthread,coal,sched", CONFIGS)
def test_gemm_stress(param, nranks, cores, cthread, coal, sched):
    param("comm_thread", cthread)
    param("comm_coalesce", coal)
    param("sched", sched)
    param("runtime_dag_compile", False)   # exercise the dynamic scheduler
    _check(run_multirank(nranks, _gemm_body, nb_cores=cores, timeout=240))
