"""Rank bodies for the multi-process (socket fabric) tests — kept in a
plain module so subprocess ranks can import them by file path."""

import numpy as np


def chain_body(ctx, rank, nranks):
    """Ex03 chain across PROCESSES: the tile hops rank to rank over TCP."""
    from parsec_tpu import ptg
    from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic

    NB = 2 * nranks
    V = VectorTwoDimCyclic("V", lm=NB, mb=4, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size, np.float32))
    p = ptg.PTGBuilder("chain", V=V, NB=NB)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    t.affinity("V", lambda g, l: (l.i,))
    f = t.flow("A", ptg.RW)
    f.input(data=("V", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "A", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "A", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NB - 1)
    f.output(data=("V", lambda g, l: (0,)),
             guard=lambda g, l: l.i == g.NB - 1)

    @t.body
    def body(es, task, g, l):
        a = task.flow_data("A")
        a.value = np.asarray(a.value) + 1

    ctx.add_taskpool(p.build())
    ctx.wait(timeout=60)
    ctx.comm_barrier()
    if rank == 0:
        return float(np.asarray(V.data_of(0).newest_copy().value)[0])
    return None


def device_bcast_gemm_body(ctx, rank, nranks):
    """Stage-1-equivalent over the device-resident multi-process tier:
    an Ex05-shaped broadcast (payload big enough for the rendezvous GET
    path) followed by a 2-D block-cyclic GEMM, with per-tier byte
    accounting returned for the parent to assert."""
    from parsec_tpu import ptg
    from parsec_tpu.comm.device_socket import DeviceSocketCommEngine
    from parsec_tpu.data.data import data_create
    from parsec_tpu.data_dist.matrix import (TwoDimBlockCyclic,
                                             VectorTwoDimCyclic)
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg

    ce = ctx.comm_engine.ce
    assert isinstance(ce, DeviceSocketCommEngine), type(ce)

    # --- broadcast: one writer, every rank a reader -----------------------
    V = VectorTwoDimCyclic("V", lm=nranks, mb=1, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    p = ptg.PTGBuilder("bcast", V=V, NR=nranks)
    w = p.task("W", z=ptg.span(0, 0))
    w.affinity("V", lambda g, l: (0,))
    fw = w.flow("A", ptg.WRITE)
    for r in range(nranks):
        fw.output(succ=("R", "X", lambda g, l, r=r: {"r": r}))

    def wbody(es, task, g, l):
        arr = np.arange(4096, dtype=np.float32)    # > comm_short_limit
        task.set_flow_data("A", data_create(arr, key=("w", 0)).get_copy(0))

    w.body(wbody)
    t = p.task("R", r=ptg.span(0, lambda g, l: g.NR - 1))
    t.affinity("V", lambda g, l: (l.r,))
    fx = t.flow("X", ptg.READ)
    fx.input(pred=("W", "A", lambda g, l: {"z": 0}))
    fy = t.flow("Y", ptg.RW)
    fy.input(data=("V", lambda g, l: (l.r,)))
    fy.output(data=("V", lambda g, l: (l.r,)))

    def rbody(es, task, g, l):
        y = task.flow_data("Y")
        y.value = np.full_like(np.asarray(y.value),
                               float(np.asarray(
                                   task.flow_data("X").value).sum()))

    t.body(rbody)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=90)
    ctx.comm_barrier()
    bsum = float(np.asarray(V.data_of(rank).newest_copy().value)[0])

    # --- 2-D block-cyclic GEMM over the same engine -----------------------
    n, nb = 64, 16
    rng = np.random.RandomState(23)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q, myrank=rank)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=Q, myrank=rank)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=Q, myrank=rank)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    return {"bsum": bsum, "C": C.to_dense(), "tiers": ce.tier_bytes()}


def gemm_body(ctx, rank, nranks):
    """Block-cyclic GEMM with remote deps over the socket fabric."""
    from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg

    n, nb = 64, 16
    rng = np.random.RandomState(23)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q, myrank=rank)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=Q, myrank=rank)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=Q, myrank=rank)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    return C.to_dense()    # this rank's tiles; caller assembles


def distributed_bootstrap_body(ctx, rank, nranks):
    """VERDICT r4 item 6: the real-pod bootstrap path, exercised.  The
    harness set PARSEC_TPU_COORDINATOR/NUM_PROCS/PROC_ID, so _rank_main's
    maybe_init_distributed() ran jax.distributed.initialize against the
    localhost coordinator before any backend init — this body proves the
    distributed runtime is actually live (process_count spans the ranks)
    and then drives the Ex05 broadcast + block-cyclic GEMM through the
    DeviceSocketCommEngine on top of it."""
    import jax

    assert jax.process_count() == nranks, jax.process_count()
    assert jax.process_index() == rank, (jax.process_index(), rank)
    out = device_bcast_gemm_body(ctx, rank, nranks)
    out["process_count"] = jax.process_count()
    return out


def traced_get_body(ctx, rank, nranks):
    """ISSUE 10: a cross-rank chain with the SPAN recorder observing —
    big tiles force the rendezvous GET path (and, with the parent's
    small ``comm_get_frag_bytes``, FRAGMENTED GETs), so each rank's
    exported Chrome trace carries activation emit/recv spans and GET
    request/serve spans whose flow ids tracemerge stitches across the
    rank boundary.  Both ranks share one deterministic trace id (the
    rank-agreed analog of a server-minted context)."""
    import os

    from parsec_tpu import ptg
    from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
    from parsec_tpu.prof import spans

    spans.install()
    out_dir = os.environ["PARSEC_TEST_TRACE_DIR"]
    MB = 8192            # 32 KiB float32 tiles: > comm_short_limit, and
    NB = 2 * nranks      # > the test's comm_get_frag_bytes (fragmented)
    V = VectorTwoDimCyclic("V", lm=NB * MB, mb=MB, P=nranks, myrank=rank,
                           init_fn=lambda m, size:
                           np.zeros(size, np.float32))
    p = ptg.PTGBuilder("tracedchain", V=V, NB=NB)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NB - 1))
    t.affinity("V", lambda g, l: (l.i,))
    f = t.flow("A", ptg.RW)
    f.input(data=("V", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "A", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "A", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NB - 1)
    f.output(data=("V", lambda g, l: (l.i,)),
             guard=lambda g, l: l.i == g.NB - 1)

    @t.body
    def body(es, task, g, l):
        a = task.flow_data("A")
        a.value = np.asarray(a.value) + 1

    tp = p.build()
    # one trace id agreed by construction on every rank (a server run
    # propagates it over the wire instead)
    tp._trace = spans.TraceContext(0xBEEF01)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=90)
    ctx.comm_barrier()
    spans.export_chrome(os.path.join(out_dir, f"trace-rank{rank}.json"),
                        rank=rank)
    names = {s[0] for s in spans.recorder.spans}
    spans.uninstall()
    return sorted(names)


def traced_chain_body(ctx, rank, nranks):
    """Chain across ranks with the task_profiler + grapher observing:
    each rank dumps its OWN binary trace and DOT fragment (the
    multi-file dbp / per-rank .dot inputs the offline tools consume)."""
    import os

    import parsec_tpu.runtime.dagrun  # noqa: F401  registers the param
    from parsec_tpu.core.mca import repository
    from parsec_tpu.core.params import params
    from parsec_tpu.prof.profiling import profiling

    out_dir = os.environ["PARSEC_TEST_TRACE_DIR"]
    old = params.get("runtime_dag_compile")
    params.set("runtime_dag_compile", False)   # dynamic loop: full PINS
    profiling.init()
    prof_comp = repository.find("pins", "task_profiler")
    prof_mod = prof_comp.open()
    graph_comp = repository.find("pins", "grapher")
    graph_mod = graph_comp.open()
    try:
        chain_body(ctx, rank, nranks)
    finally:
        params.set("runtime_dag_compile", old)
    graph_mod.write_dot(os.path.join(out_dir, f"rank{rank}.dot"))
    graph_comp.close(graph_mod)
    profiling.dump(os.path.join(out_dir, f"rank{rank}.prof"))
    prof_comp.close(prof_mod)
    profiling.fini()
    return True
