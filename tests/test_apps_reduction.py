"""Reference app tier: generalized binomial-tree reduction + pingpong
(tests/apps/generalized_reduction/BT_reduction.jdf, pingpong/rtt.jdf).
"""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
from parsec_tpu.models.pingpong import run_pingpong
from parsec_tpu.models.reduction import (bt_reduction_ptg, count_bits,
                                         index_to_tree, local_index,
                                         tree_bit, tree_offset)
from parsec_tpu.runtime import Context


# ---------------------------------------------------------------------------
# forest arithmetic (count_bits / compute_offset family)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [1, 2, 3, 5, 7, 8, 12, 13, 21, 32, 100])
def test_forest_decomposition_covers_indices(n):
    """Every leaf index lands in exactly one tree at a consistent local
    position; tree sizes are the set bits of n."""
    T = count_bits(n)
    sizes = [1 << tree_bit(n, t) for t in range(1, T + 1)]
    assert sum(sizes) == n
    offs = [tree_offset(n, t) for t in range(1, T + 1)]
    assert offs == sorted(offs)
    for i in range(n):
        t = index_to_tree(n, i)
        li = local_index(n, i)
        assert 1 <= t <= T
        assert 0 <= li < sizes[t - 1]
        assert offs[t - 1] + li == i


def _vec(nt, nranks=1, rank=0, mb=4, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.standard_normal((nt, mb)).astype(np.float32)
    V = VectorTwoDimCyclic("A", lm=nt * mb, mb=mb, P=nranks, myrank=rank,
                           init_fn=lambda m, size: base[m, :size].copy())
    return base, V


@pytest.mark.parametrize("nt", [1, 2, 3, 5, 8, 13, 16, 21])
def test_bt_reduction_sums(nt):
    """The forest reduces NT tiles to their sum in A(0) — every NT shape
    (pure power of 2, odd, multi-tree)."""
    base, V = _vec(nt, seed=nt)
    tp = bt_reduction_ptg(V)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    got = np.asarray(V.data_of(0).newest_copy().value)
    np.testing.assert_allclose(got, base.sum(axis=0), rtol=1e-4,
                               atol=1e-5)


def test_bt_reduction_custom_op():
    base, V = _vec(8, seed=3)
    tp = bt_reduction_ptg(V, op=np.maximum)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    got = np.asarray(V.data_of(0).newest_copy().value)
    np.testing.assert_allclose(got, base.max(axis=0), rtol=1e-6)


def _reduc_rank_body(ctx, rank, nranks):
    nt = 13
    base, V = _vec(nt, nranks=nranks, rank=rank, seed=9)
    tp = bt_reduction_ptg(V)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=180)
    ctx.comm_barrier()
    if V.rank_of(0) == rank:
        got = np.asarray(V.data_of(0).newest_copy().value)
        np.testing.assert_allclose(got, base.sum(axis=0), rtol=1e-4,
                                   atol=1e-5)
    return True


def test_bt_reduction_multirank():
    assert all(run_multirank(4, _reduc_rank_body))


# ---------------------------------------------------------------------------
# pingpong
# ---------------------------------------------------------------------------

def test_pingpong_single_rank():
    _, V = _vec(1, mb=2)
    V.data_of(0).newest_copy().value[...] = 0.0
    with Context(nb_cores=0) as ctx:
        res = run_pingpong(ctx, V, nt=16)
    assert res["hops"] == 16 and res["us_per_hop"] > 0
    got = np.asarray(V.data_of(0).newest_copy().value)
    np.testing.assert_allclose(got, 16.0)


def _ping_rank_body(ctx, rank, nranks):
    nt, mb = 24, 2
    V = VectorTwoDimCyclic("A", lm=nranks * mb, mb=mb, P=nranks,
                           myrank=rank,
                           init_fn=lambda m, size: np.zeros(size,
                                                            np.float32))
    res = run_pingpong(ctx, V, nt)
    ctx.comm_barrier()
    # rank r's home tile holds the chain state after its LAST hop:
    # max{k < nt : k % nranks == r} + 1 increments
    last = max(k for k in range(nt) if k % nranks == rank)
    got = np.asarray(V.data_of(rank).newest_copy().value)
    np.testing.assert_allclose(got, float(last + 1))
    return res["us_per_hop"]


@pytest.mark.parametrize("nranks", [2, 4])
def test_pingpong_multirank(nranks):
    """The rtt shape: every hop crosses ranks; the chain state lands on
    each rank's home tile at its last visit."""
    rtts = run_multirank(nranks, _ping_rank_body)
    assert all(r > 0 for r in rtts)
