"""The bench harness itself is a deliverable (VERDICT r4 item 1: round 4
shipped NO perf numbers because ``bench.py`` could be killed before its
single JSON line printed).  These tests pin the new contract:

- a full cumulative JSON line is printed after EVERY stage, so a driver
  kill at any moment leaves parseable evidence in the stdout tail;
- the headline GEMM runs before any secondary stage;
- a hung stage is abandoned by the thread-join timeout and recorded as a
  degraded stage, never an unreported hole;
- smoke mode completes end-to-end on CPU in seconds, with the dynamic
  device stages exercised through the allow-cpu device registration.

Reference role: the always-printing watchdogged harnesses
(``tests/dsl/dtd/dtd_test_simple_gemm.c:649-667``).
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


@pytest.fixture(scope="module")
def smoke_run(tmp_path_factory):
    """One full BENCH_SMOKE=1 run on CPU, shared by the assertions."""
    env = dict(os.environ)
    env.update(BENCH_SMOKE="1", BENCH_PLATFORM="cpu")
    # run from a scratch cwd so BENCH_partial.json lands there — and
    # point the artifact dir at it so perfdb.jsonl (ISSUE 16) does too
    cwd = tmp_path_factory.mktemp("bench")
    env["PARSEC_TPU_ARTIFACT_DIR"] = str(cwd)
    t0 = time.perf_counter()
    p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, env=env,
                       cwd=str(cwd), timeout=600)
    return p, time.perf_counter() - t0, cwd


def _json_lines(stdout):
    out = []
    for ln in stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            out.append(json.loads(ln))
    return out


def test_smoke_completes_and_last_line_parses(smoke_run):
    p, _dt, _cwd = smoke_run
    assert p.returncode == 0, p.stderr[-2000:]
    lines = _json_lines(p.stdout)
    assert len(lines) >= 10          # one cumulative line per stage
    last = lines[-1]
    assert last["metric"] == "ptg_tiled_gemm_gflops_per_chip"
    assert last["value"] > 0
    assert last["unit"] == "GFLOPS"


def test_every_line_is_full_schema(smoke_run):
    """Any line may be the last one the driver sees: each must carry the
    complete schema, not a stage fragment."""
    p, _dt, _cwd = smoke_run
    for ln in _json_lines(p.stdout):
        assert {"metric", "value", "unit", "vs_baseline",
                "extra"} <= set(ln)
        # the dispatch key is OMITTED when unmeasured (never a -1.0
        # sentinel, ISSUE 2); when present it must be a real reading.
        # In a smoke run the always-first overhead stage supplies it on
        # every line.
        v = ln["extra"].get("task_dispatch_us")
        assert v is None or (isinstance(v, (int, float)) and v >= 0), ln
        assert "task_dispatch_us" in _json_lines(p.stdout)[0]["extra"]


def test_headline_lands_before_secondaries(smoke_run):
    """The fourth JSON line (after overhead + comm + dispatch + gemm) must
    already have a nonzero headline — round 4 ordered it dead last and lost
    the round.  The always-first CPU-safe group (overhead, ISSUE 2; comm,
    ISSUE 4) rides ahead of it because it is relay-independent and runs in
    seconds."""
    p, _dt, _cwd = smoke_run
    lines = _json_lines(p.stdout)
    assert lines[3]["value"] > 0
    assert lines[3]["extra"]["device_kind"] != "pending"
    # the overhead stage's numbers are already on the FIRST line: the perf
    # axis has evidence before any relay-dependent stage can hang
    ov = lines[0]["extra"]["overhead"]
    assert ov["dispatch_us"] > 0
    assert ov["release_tasks_per_s"] > 0
    assert ov["steal_us"] > 0
    # the comm wire-path stage lands on the SECOND line, still before
    # anything that can touch the relay (ISSUE 4): GET throughput, the
    # pickled-framing baseline ratio, and nonzero overlap efficiency
    cm = lines[1]["extra"]["comm"]
    assert cm["comm_am_roundtrip_us_socket"] > 0
    assert cm["comm_get_socket_4mib_gbps"] > 0
    assert cm["comm_get_speedup_vs_pickle"] > 1.0
    assert cm["comm_overlap_efficiency"] > 0


def test_dynamic_stages_exercised_on_cpu(smoke_run):
    """allow-cpu device registration lets smoke cover the dynamic path."""
    p, _dt, _cwd = smoke_run
    last = _json_lines(p.stdout)[-1]
    assert last["extra"]["dynamic_gemm_gflops"] > 0
    assert last["extra"]["dtd_gemm_tpu_gflops"] > 0
    assert last["extra"]["dynamic_gemm_breakdown"].get("xla_calls", 0) > 0


def test_serve_stage_reports_throughput_and_warm_cache(smoke_run):
    """The serving stage (ISSUE 3) ships sustained submissions/s, ticket
    latency percentiles, and the warm-vs-cold lowered split — and the
    warm repeat class really skipped the compile."""
    last = _json_lines(smoke_run[0].stdout)[-1]
    sv = last["extra"]["serve"]
    assert sv["serve_submits_per_s"] > 0
    assert sv["serve_p50_ms"] > 0
    assert sv["serve_p99_ms"] >= sv["serve_p50_ms"]
    assert sv["serve_lowered_cache_hits"] >= 1
    # the cache-hit counter above is the real guard; the wall-clock
    # comparison needs an absolute floor because a populated persistent
    # XLA disk cache (any prior run on this machine) makes the "cold"
    # submission nearly as fast as the warm one — asserting warm < cold
    # outright is then a coin flip on scheduler noise
    assert sv["serve_lowered_warm_s"] <= \
        max(sv["serve_lowered_cold_s"], 0.05)


def test_llm_stage_reports_tokens_per_s_and_sweep(smoke_run):
    """The LLM serving stage (ISSUE 6) ships tokens/s, per-token p50/p99,
    and the concurrent-streams sweep axis."""
    last = _json_lines(smoke_run[0].stdout)[-1]
    llm = last["extra"]["llm"]
    assert llm["llm_tokens_per_s"] > 0
    assert llm["llm_p99_ms"] >= llm["llm_p50_ms"] > 0
    sweep = llm["llm_streams_sweep"]
    assert len(sweep) >= 2 and all(
        v["tokens_per_s"] > 0 for v in sweep.values()), llm


def test_compile_deadline_death_records_typed_partial_entry():
    """The BENCH_r04/r05 failure shape (ISSUE 6 satellite): a stage dying
    on its deadline mid-compile must degrade to a
    ``{"status": "compile_timeout"}`` record carrying the partial
    metrics it flushed — not vanish into a bare timeout."""
    import bench

    def fake_compile_stage():
        bench._note_partial(phase="compile", lowering_mode="wavefront")
        time.sleep(30)

    prior = list(bench._abandoned)
    try:
        res = bench._staged("fakechol", fake_compile_stage, timeout=0.3)
        assert res["status"] == "compile_timeout", res
        assert res["partial"]["lowering_mode"] == "wavefront", res
        assert res["gflops"] == 0.0 and "error" in res

        # past the compile phase, the same death is a plain timeout —
        # but the flushed compile seconds survive into the record
        def fake_measure_stage():
            bench._note_partial(phase="measure", compile_s=3.2)
            time.sleep(30)

        res = bench._staged("fakemeasure", fake_measure_stage, timeout=0.3)
        assert res["status"] == "timeout", res
        assert res["partial"]["compile_s"] == 3.2, res
    finally:
        bench._abandoned[:] = prior


def test_llm_mid_sweep_deadline_keeps_all_completed_points():
    """ISSUE-9 satellite: bench_llm notes every swept (streams, k) point
    under a UNIQUE key (``_note_partial`` merges by dict update), so a
    deadline death mid-sweep degrades to a record carrying ALL the
    completed points — not just the last one."""
    import bench

    def fake_llm_stage():
        bench._note_partial(phase="llm",
                            llm_point_s8_k1={"tokens_per_s": 400.0})
        bench._note_partial(phase="llm",
                            llm_point_s8_k8={"tokens_per_s": 1600.0})
        time.sleep(30)

    prior = list(bench._abandoned)
    try:
        res = bench._staged("fakellm", fake_llm_stage, timeout=0.3)
        assert res["status"] == "timeout", res
        assert res["partial"]["llm_point_s8_k1"]["tokens_per_s"] == 400.0
        assert res["partial"]["llm_point_s8_k8"]["tokens_per_s"] == 1600.0
    finally:
        bench._abandoned[:] = prior


def test_note_partial_flushes_slo_histograms():
    """ISSUE-10 satellite: every ``_note_partial`` flush snapshots the
    live SLO histogram planes as SERIALIZED BUCKET ARRAYS, so a
    deadline death mid-serve/llm stage keeps the latency distribution
    collected so far (reconstructable via ``LogHistogram.from_dict``),
    not just the counters."""
    import bench
    from parsec_tpu.prof.histogram import LogHistogram, SLOPlane

    plane = SLOPlane()              # stays referenced through the stage
    for v in (3.0, 12.5, 40.0):
        plane.observe("tenantX", "ttft_ms", v)

    def fake_slo_stage():
        bench._note_partial(phase="llm", point=1)
        time.sleep(30)

    prior = list(bench._abandoned)
    try:
        res = bench._staged("fakeslo", fake_slo_stage, timeout=0.3)
        assert res["status"] == "timeout", res
        sh = res["partial"]["slo_hist"]
        assert "tenantX" in sh, sh
        h = LogHistogram.from_dict(sh["tenantX"]["ttft_ms"])
        assert h.count == 3
        assert h.quantile(0.5) > 0
    finally:
        bench._abandoned[:] = prior
        plane.reset()


def test_serve_and_llm_stages_emit_per_tenant_slo(smoke_run):
    """ISSUE-10 acceptance: the serve and llm stages emit per-tenant
    quantiles off the histogram plane — the llm stage ttft/tok-latency
    p50/p99 per tenant, the serve stage queue-wait/latency."""
    last = _json_lines(smoke_run[0].stdout)[-1]
    llm_slo = last["extra"]["llm"]["llm_slo"]
    assert llm_slo, last["extra"]["llm"].keys()
    for tenant, d in llm_slo.items():
        assert d["ttft_ms_p50"] > 0, (tenant, d)
        assert d["ttft_ms_p99"] >= d["ttft_ms_p50"], (tenant, d)
        assert d["tok_latency_ms_p99"] >= d["tok_latency_ms_p50"] > 0
    serve_slo = last["extra"]["serve"]["serve_slo"]
    tenants = [t for t in serve_slo if t.startswith("tenant")]
    assert tenants, serve_slo.keys()
    for t in tenants:
        assert serve_slo[t]["latency_ms_p99"] >= \
            serve_slo[t]["latency_ms_p50"] > 0
        assert serve_slo[t]["queue_wait_ms_count"] > 0


def test_lowered_stages_report_compile_seconds(smoke_run):
    last = _json_lines(smoke_run[0].stdout)[-1]
    assert last["extra"]["lowered_cholesky_compile_s"] > 0
    assert last["extra"]["lowered_cholesky_gflops"] > 0
    assert last["extra"]["lowered_lu_gflops"] > 0
    assert last["extra"]["lowered_stencil_gflops"] > 0


def test_partial_file_mirrors_last_line(smoke_run):
    p, _dt, cwd = smoke_run
    with open(os.path.join(str(cwd), "BENCH_partial.json")) as f:
        mirrored = json.loads(f.read())
    last = _json_lines(p.stdout)[-1]
    # elapsed_s differs line to line; compare the stable payload
    mirrored["extra"].pop("elapsed_s"), last["extra"].pop("elapsed_s")
    assert mirrored == last


def test_perfdb_ledger_written_and_verdicts_in_emit(smoke_run):
    """ISSUE-16: a bench run appends every stage's scalars to the
    persistent perf ledger, prints one [perfdb] verdict line per stage,
    and the emit carries the ``perfdb_regressions`` export on EVERY
    cumulative line (any line may be the last one the driver sees)."""
    p, _dt, cwd = smoke_run
    ledger = os.path.join(str(cwd), "perfdb.jsonl")
    assert os.path.exists(ledger), os.listdir(str(cwd))
    recs = [json.loads(ln) for ln in open(ledger) if ln.strip()]
    assert len(recs) > 50, len(recs)        # dozens of metrics x stages
    assert all("key" in r and "value" in r for r in recs)
    assert "[perfdb]" in p.stderr
    for ln in _json_lines(p.stdout):
        assert isinstance(ln["extra"].get("perfdb_regressions"), list), ln


def test_perfdb_accrues_across_invocations_and_verdicts_drift(
        tmp_path, monkeypatch, capsys):
    """ISSUE-16 acceptance, harness form: consecutive invocations of the
    bench perfdb hook accrue history in one ledger file, and once the
    EWMA is warm a 10x cliff in a later invocation is verdicted
    REGRESSED — in the stderr line AND in the ``perfdb_regressions``
    export the next emit would carry."""
    import bench
    monkeypatch.setenv("PARSEC_TPU_ARTIFACT_DIR", str(tmp_path))
    ledger = tmp_path / "perfdb.jsonl"
    prior = dict(bench._perfdb_state)
    try:
        bench._perfdb_state["regressions"] = []
        # invocations 1..3: stable numbers warm the per-key EWMA
        for _ in range(3):
            bench._perfdb_note("fakestage", {"dispatch_us": 100.0})
        n1 = sum(1 for _ in open(ledger))
        assert n1 == 3
        assert bench._perfdb_state["regressions"] == []
        # invocation 4: the 10x cliff
        bench._perfdb_note("fakestage", {"dispatch_us": 1000.0})
        assert sum(1 for _ in open(ledger)) == n1 + 1   # still accruing
        reg = bench._perfdb_state["regressions"]
        assert len(reg) == 1, reg
        assert reg[0]["stage"] == "fakestage"
        assert reg[0]["metric"] == "dispatch_us" and reg[0]["z"] > 0
        err = capsys.readouterr().err
        assert "[perfdb] fakestage" in err and "REGRESSED" in err, err
    finally:
        bench._perfdb_state.clear()
        bench._perfdb_state.update(prior)


def test_deadline_death_flushes_xla_dispatch_ledger():
    """ISSUE-16 satellite: an rc-124-shaped stage death must keep the
    calls-per-DAG axis — every ``_note_partial`` flush snapshots the
    XLA-dispatch ledger total alongside the histogram planes."""
    import bench
    from parsec_tpu.device.device import note_xla_calls, xla_calls_total

    base = xla_calls_total()
    note_xla_calls(7)                      # the stage dispatched work

    def fake_xla_stage():
        bench._note_partial(phase="compile", lowering_mode="region")
        time.sleep(30)

    prior = list(bench._abandoned)
    try:
        res = bench._staged("fakexla", fake_xla_stage, timeout=0.3)
        assert res["status"] == "compile_timeout", res
        assert res["partial"]["xla_calls_total"] >= base + 7, res
    finally:
        bench._abandoned[:] = prior


def test_hung_stage_is_abandoned_not_fatal():
    """A stage that never returns must be timed out, recorded as degraded,
    and must not stop later stages from reporting."""
    import bench
    before = list(bench._abandoned)
    try:
        res = bench._staged("hang", lambda: time.sleep(60), timeout=0.5)
        assert "error" in res and "timeout" in res["error"]
        assert bench._abandoned == before + ["hang"]
        # a later successful stage carries the taint marker
        ok = bench._staged("after", lambda: {"gflops": 1.0}, timeout=5.0)
        assert ok["tainted_by"] == before + ["hang"]
    finally:
        bench._abandoned[:] = before


def test_failing_stage_degrades_with_reason():
    import bench

    def boom():
        raise RuntimeError("relay reset")

    res = bench._staged("boom", boom, timeout=5.0)
    assert res["gflops"] == 0.0
    assert "relay reset" in res["error"]


def test_every_stage_carries_runtime_report(smoke_run):
    """EVERY stage of the output JSON ships a flight-recorder
    self-report — the per-stage runtime evidence the round-5 outage
    proved is needed even (especially) when a stage degrades."""
    p, _dt, _cwd = smoke_run
    last = _json_lines(p.stdout)[-1]
    reports = last["extra"]["runtime_reports"]
    stage_names = {"dispatch", "gemm", "raw_dot", "serve", "stencil",
                   "lowered_cholesky", "lowered_stencil", "lowered_lu",
                   "dynamic_gemm", "dtd_gemm", "lowered_cholesky_16k",
                   "dynamic_cholesky"}
    assert stage_names <= set(reports), sorted(reports)
    for name in stage_names:
        assert "tasks_retired" in reports[name], (name, reports[name])
    # degraded stages (if any) still carry their self-report
    for name in last["extra"].get("degraded_stages", {}):
        assert name in reports
    # the dynamic stages really self-measured: retired counts are live
    assert reports["dynamic_gemm"]["tasks_retired"] > 0


def test_degraded_stages_carry_runtime_report():
    """Timeout, exception, and budget-exhausted degrade paths all embed
    the runtime self-report block (artificially degraded stages)."""
    import bench
    before = list(bench._abandoned)
    try:
        hung = bench._staged("rr-hang", lambda: time.sleep(30), timeout=0.3)
        assert "runtime_report" in hung
        assert "tasks_retired" in hung["runtime_report"]

        def boom():
            raise RuntimeError("relay reset")
        failed = bench._staged("rr-boom", boom, timeout=5.0)
        assert "runtime_report" in failed
    finally:
        bench._abandoned[:] = before


def test_budget_exhausted_logs_and_uses_prior_taint(capsys):
    """The budget-exhausted early return reports like the other degrade
    paths: stderr line + prior-snapshot tainted_by (ADVICE round 5)."""
    import bench
    before = list(bench._abandoned)

    def flaky():
        raise RuntimeError("reset")

    try:
        bench._abandoned[:] = ["earlier-zombie"]
        # timeout < 1s: the retry's remaining budget is under the 1.0s
        # floor, so attempt 2 takes the budget-exhausted early return
        res = bench._staged("rr-budget", flaky, timeout=0.5, retries=3)
        assert "budget" in res["error"]
        # prior snapshot: the pre-existing zombie, never the stage itself
        assert res["tainted_by"] == ["earlier-zombie"]
        assert "runtime_report" in res
        err = capsys.readouterr().err
        assert "budget" in err and "rr-budget" in err
    finally:
        bench._abandoned[:] = before


def test_stage_budget_spec_parses_mca_env_grammar(param):
    """bench_stage_budget_s (ISSUE 8 satellite): '<seconds>' rebudgets
    every stage, 'name=sec' named ones, '*' the default."""
    import bench
    bench._stage_budgets()                 # first call registers the param
    param("bench_stage_budget_s", "gemm=300, lowered_cholesky=240,*=45")
    assert bench._stage_budgets() == {"gemm": 300.0,
                                      "lowered_cholesky": 240.0, "*": 45.0}
    param("bench_stage_budget_s", "75")
    assert bench._stage_budgets() == {"*": 75.0}
    param("bench_stage_budget_s", "")
    assert bench._stage_budgets() == {}
    param("bench_stage_budget_s", "gemm=nonsense")  # malformed: ignored
    assert bench._stage_budgets() == {}


def test_region_stage_budget_shed_completes_instead_of_rc124():
    """ISSUE-8 acceptance, harness form: a region stage whose compile
    budget can afford NOTHING must still complete inside its deadline —
    regions shed to the eager path (stage done, correct result, no
    compile_timeout), and the partial trail names the budget."""
    import bench
    from parsec_tpu.ptg.lowering import lowering_cache

    lowering_cache.clear()                 # force a genuinely cold plan
    res = bench._staged("region-shed", bench.bench_region_cholesky_gflops,
                        n=512, nb=128, budget_s=1e-9, timeout=90.0)
    assert "status" not in res and "error" not in res, res
    assert res["gflops"] > 0
    assert res["regions_eager"] >= 1 and res["regions_compiled"] == 0, res
    assert res["compile_s"] == 0.0
    assert res["tile00_abs_err"] < 1e-4
    # ...and a warm second run compiles for free (the persistent-cache
    # half of the acceptance line): same geometry, same tiny budget,
    # but cache hits are never shed
    res2 = bench._staged("region-warm", bench.bench_region_cholesky_gflops,
                         n=512, nb=128, budget_s=1e-9, timeout=90.0)
    assert "error" not in res2, res2
    # the shed run never compiled, so the in-process cache is still cold
    # for shed regions; a prior COMPILED plan is what warms it
    bench.bench_region_cholesky_gflops(n=512, nb=128, budget_s=60.0)
    res3 = bench._staged("region-warm2", bench.bench_region_cholesky_gflops,
                         n=512, nb=128, budget_s=1e-9, timeout=90.0)
    assert res3["regions_eager"] == 0, res3
    assert res3["compile_s"] <= 0.01, res3


def test_region_stage_lands_in_smoke_emit(smoke_run):
    last = _json_lines(smoke_run[0].stdout)[-1]
    assert last["extra"]["region_cholesky_gflops"] > 0
    assert last["extra"]["region_cholesky_regions"] >= 1
    assert last["extra"]["region_cholesky_eager"] == 0
