"""Unit tests for the foundation layer (analog of reference tests/class/:
lifo.c, hash.c, future.c, future_datacopy.c under thread stress)."""

import threading

import pytest

from parsec_tpu.core import (Backoff, ConcurrentHashTable, CountableFuture,
                             DataCopyFuture, Future, HBBuffer, Mempool)


class TestFuture:
    def test_set_get(self):
        f = Future()
        f.set(42)
        assert f.get() == 42
        assert f.is_ready()

    def test_double_set_raises(self):
        f = Future()
        f.set(1)
        with pytest.raises(RuntimeError):
            f.set(2)

    def test_callbacks_fire(self):
        f = Future()
        seen = []
        f.on_ready(lambda fut: seen.append(fut.get()))
        f.set("x")
        f.on_ready(lambda fut: seen.append("late"))
        assert seen == ["x", "late"]

    def test_threaded_get(self):
        f = Future()
        out = []
        t = threading.Thread(target=lambda: out.append(f.get(timeout=5)))
        t.start()
        f.set(7)
        t.join()
        assert out == [7]

    def test_countable(self):
        f = CountableFuture(3, combine=lambda a, b: a + b)
        f.contribute(1)
        f.contribute(2)
        assert not f.is_ready()
        f.contribute(3)
        assert f.get() == 6

    def test_countable_threaded(self):
        f = CountableFuture(64, combine=lambda a, b: a + b)
        ts = [threading.Thread(target=f.contribute, args=(1,)) for _ in range(64)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert f.get() == 64


class TestDataCopyFuture:
    def test_lazy_trigger_on_get(self):
        calls = []
        f = DataCopyFuture(convert=lambda _: calls.append(1) or "copy")
        assert not f.is_ready()
        assert f.get() == "copy"
        assert calls == [1]

    def test_nested_reshape_chain(self):
        base = DataCopyFuture(convert=lambda _: [1, 2, 3])
        shaped = DataCopyFuture(parent=base, convert=lambda xs: list(reversed(xs)))
        assert shaped.get() == [3, 2, 1]
        assert base.is_ready()

    def test_nested_waits_for_parent(self):
        parent = Future()
        child = DataCopyFuture(parent=parent, convert=lambda v: v * 2)
        out = []
        t = threading.Thread(target=lambda: out.append(child.get(timeout=5)))
        t.start()
        parent.set(21)
        child.trigger()
        t.join()
        assert out == [42]


class TestHashTable:
    def test_basic(self):
        ht = ConcurrentHashTable()
        ht.insert(("tp", 1), "a")
        assert ht.get(("tp", 1)) == "a"
        assert ("tp", 1) in ht
        assert ht.remove(("tp", 1)) == "a"
        assert ht.get(("tp", 1)) is None

    def test_find_or_insert_atomic(self):
        ht = ConcurrentHashTable()
        created = []

        def worker(k):
            for i in range(200):
                ht.find_or_insert((k, i), lambda: created.append(1) or object())

        ts = [threading.Thread(target=worker, args=(j % 4,)) for j in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # every (k, i) pair created exactly once despite 2 threads per k
        assert len(created) == 4 * 200
        assert len(ht) == 4 * 200


class TestMempool:
    def test_reuse(self):
        class Elem:
            pass

        mp = Mempool(Elem)
        a = mp.allocate()
        mp.free(a)
        b = mp.allocate()
        assert a is b

    def test_cross_thread_free_returns_to_owner(self):
        class Elem:
            pass

        mp = Mempool(Elem)
        a = mp.allocate()
        owner = a._mempool_owner

        def free_elsewhere():
            mp.free(a)

        t = threading.Thread(target=free_elsewhere)
        t.start()
        t.join()
        assert a in owner._free

    def test_reset_hook(self):
        class Elem:
            def __init__(self):
                self.v = 0

        mp = Mempool(Elem, reset=lambda e: setattr(e, "v", 0))
        a = mp.allocate()
        a.v = 99
        mp.free(a)
        assert mp.allocate().v == 0


class TestHBBuffer:
    def test_spill_to_parent(self):
        spilled = []
        hb = HBBuffer(2, parent_push=lambda items, d: spilled.extend(items))
        hb.push_all([1, 2, 3, 4])
        assert len(hb) == 2
        assert spilled == [3, 4]

    def test_pop_best_priority(self):
        hb = HBBuffer(8, parent_push=lambda i, d: None)
        hb.push_all([3, 1, 9, 4])
        assert hb.try_pop_best(priority=lambda x: x) == 9
        assert hb.try_pop_best() == 4  # LIFO without priority fn

    def test_steal_from_old_end(self):
        hb = HBBuffer(8, parent_push=lambda i, d: None)
        hb.push_all([1, 2, 3])
        assert hb.steal() == 1


def test_backoff_grows_and_resets():
    b = Backoff(base_ns=10, max_ns=40)
    b.wait()  # first miss only arms it
    assert b._cur_ns == 10
    b.wait()
    b.wait()
    b.wait()
    assert b._cur_ns == 40
    b.reset()
    assert b._cur_ns == 0


def test_top_level_api_lazy_exports():
    """`from parsec_tpu import Context, ...` works, resolved lazily."""
    import parsec_tpu
    for name in ("Context", "PTGBuilder", "span", "lower_taskpool",
                 "DTDTaskpool", "run_multirank", "run_multiproc",
                 "save_collections", "restore_collections"):
        assert getattr(parsec_tpu, name) is not None
    assert "Context" in dir(parsec_tpu)
    with pytest.raises(AttributeError):
        parsec_tpu.no_such_symbol
