"""The LLM inference subsystem: paged KV cache, ragged attention
kernels, prefill/decode task pools, k-step decode superpools with
in-graph sampling, continuous batching (ISSUES 6 + 9;
``docs/LLM.md``)."""

import numpy as np
import pytest

from parsec_tpu.data.datatype import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.paged_kv import PagedKVCollection
from parsec_tpu.llm import (ContinuousBatcher, ToyLM, decode_step_ptg,
                            decode_superpool_ptg, prefill_chunks,
                            prefill_ptg, read_token_chain,
                            seed_decode_superpool)
from parsec_tpu.ops import ragged_attention as ra
from parsec_tpu.runtime import Context
from parsec_tpu.serve import RuntimeServer

MODEL = ToyLM()
H, D = MODEL.num_heads, MODEL.head_dim


def _kv(page_size=4, **kw):
    return PagedKVCollection("KV", page_size=page_size, num_heads=H,
                             head_dim=D, **kw)


def _paged(tokens, page_size=4):
    """Pack a token history into page tiles + a flat k/v oracle view."""
    ks = np.array([MODEL.q3(t)[1] for t in tokens])
    vs = np.array([MODEL.q3(t)[2] for t in tokens])
    pages = []
    for p in range((len(tokens) + page_size - 1) // page_size):
        tile = np.zeros((3, page_size, H, D), np.float32)
        fill = min(page_size, len(tokens) - p * page_size)
        tile[0, :fill] = ks[p * page_size:p * page_size + fill]
        tile[1, :fill] = vs[p * page_size:p * page_size + fill]
        tile[2, 0, 0, 0] = fill
        pages.append(tile)
    return pages, ks, vs


# ---------------------------------------------------------------------------
# PagedKVCollection
# ---------------------------------------------------------------------------

def test_kv_block_table_alloc_and_bounds_oracle():
    kv = _kv()
    kv.alloc_seq("a")
    assert kv.npages("a") == 0 and kv.seq_len("a") == 0
    for _ in range(9):                       # 9 tokens over 4-slot pages
        kv.ensure_tail_slot("a")
        kv.note_appended("a")
    assert kv.npages("a") == 3
    assert kv.page_fill("a", 0) == 4 and kv.page_fill("a", 2) == 1
    # the has_key bounds oracle is CLOSED: live pages only
    assert kv.has_key("a", 0) and kv.has_key("a", 2)
    assert not kv.has_key("a", 3)            # beyond the table
    assert not kv.has_key("b", 0)            # unknown sequence
    assert not kv.has_key("a", -1) and not kv.has_key("a")
    # data_of resolves through the block table to stable physical pages
    d0 = kv.data_of("a", 0)
    assert d0.key == (kv.name, kv.block_table("a")[0])
    assert kv.rank_of("a", 0) == 0


def test_kv_fork_shares_pages_copy_on_write_and_free_recycles():
    kv = _kv()
    kv.alloc_seq("parent")
    for _ in range(6):                       # 1.5 pages
        kv.ensure_tail_slot("parent")
        kv.note_appended("parent")
    kv.data_of("parent", 1).get_copy(0).value[0, 0, 0, 0] = 42.0
    kv.fork("parent", "child")
    assert kv.block_table("child") == kv.block_table("parent")
    assert kv.stats()["shared_pages"] == 2
    # child's tail write privatizes ONLY the partial tail page (CoW)
    kv.ensure_tail_slot("child")
    pt, ct = kv.block_table("parent"), kv.block_table("child")
    assert pt[0] == ct[0] and pt[1] != ct[1]
    assert kv.cow_copies == 1
    # the copy carried the shared contents
    assert kv.data_of("child", 1).get_copy(0).value[0, 0, 0, 0] == 42.0
    # parent's tail stays writable without a copy (it is private again)
    kv.ensure_tail_slot("parent")
    assert kv.cow_copies == 1
    # free both: every physical page returns to the free list
    kv.free_seq("child")
    kv.free_seq("parent")
    s = kv.stats()
    assert s["seqs"] == 0 and s["physical_pages"] == 0
    assert s["free_pages"] == 3
    # recycled pages come back ZEROED with a bumped version
    kv.alloc_seq("next")
    kv.alloc_page("next")
    c = kv.data_of("next", 0).get_copy(0)
    assert float(np.abs(c.value).max()) == 0.0 and c.version >= 2
    assert kv.pages_recycled == 1


def test_recycled_page_invalidates_stale_device_copies():
    """A dirty device copy running AHEAD of host (deferred writeback,
    device/tpu.py) must never satisfy a stage-in version check after its
    page is recycled to a new sequence."""
    from parsec_tpu.data.data import DataCopy
    kv = _kv()
    kv.alloc_seq("a")
    kv.alloc_page("a")
    d = kv.data_of("a", 0)
    dev = DataCopy(d, 1, value=np.ones(kv.default_dtt.shape, np.float32))
    dev.version = d.get_copy(0).version + 1      # ahead of host
    d.attach_copy(dev)
    kv.free_seq("a")
    kv.alloc_seq("b")
    kv.alloc_page("b")
    d2 = kv.data_of("b", 0)
    assert d2 is d                               # the page recycled
    assert d2.get_copy(1) is None                # device copy detached
    host = d2.get_copy(0)
    assert host.version > dev.version            # stale can never win
    assert float(np.abs(host.value).max()) == 0.0


def test_kv_page_budget_and_double_alloc():
    kv = _kv(max_pages=2)
    kv.alloc_seq("a")
    kv.alloc_page("a")
    kv.alloc_page("a")
    with pytest.raises(MemoryError):
        kv.alloc_page("a")
    with pytest.raises(KeyError):
        kv.alloc_seq("a")


# ---------------------------------------------------------------------------
# ragged attention kernels: every incarnation against the dense oracle
# ---------------------------------------------------------------------------

def test_page_chain_matches_dense_reference_all_incarnations():
    tokens = [3, 7, 11, 5, 9, 2, 40, 22, 8]   # 9 tokens: ragged 3rd page
    pages, ks, vs = _paged(tokens)
    q3 = MODEL.q3(13)
    want = ra.ragged_attention_reference(q3[0], ks, vs)
    for name, step in [
            ("numpy", ra.attn_page_update_np),
            ("jnp", lambda q, p, a: np.asarray(ra._page_update_jnp(q, p, a))),
            ("pallas", ra.build_pallas_page_update(interpret=True))]:
        acc = np.zeros((H, D + 2), np.float32)
        for page in pages:
            acc = np.asarray(step(q3, page, acc))
        got = ra.finalize_acc_np(acc)
        assert np.abs(got - want).max() < 1e-5, name


def test_empty_cache_yields_zero_output_not_nan():
    q3 = MODEL.q3(1)
    acc = ra.attn_page_update_np(q3, np.zeros((3, 4, H, D), np.float32),
                                 np.zeros((H, D + 2), np.float32))
    o = ra.finalize_acc_np(acc)
    assert np.all(np.isfinite(o)) and np.abs(o).max() == 0.0


def test_out_update_appends_kv_at_fill_slot():
    pages, _, _ = _paged([3, 7, 11, 5, 9])    # tail fill = 1
    acc = np.zeros((H, D + 2), np.float32)
    acc[:, D + 1] = 1.0
    q3 = MODEL.q3(13)
    new_page, o = ra.attn_out_np(acc, q3, pages[-1])
    assert np.allclose(new_page[0, 1], q3[1])
    assert np.allclose(new_page[1, 1], q3[2])
    assert new_page[2, 0, 0, 0] == 2
    pj, oj = ra._out_update_jnp(acc, q3, pages[-1],
                                np.zeros((H, D), np.float32))
    assert np.abs(np.asarray(pj) - new_page).max() == 0.0
    assert np.abs(np.asarray(oj) - o).max() == 0.0


# ---------------------------------------------------------------------------
# the PTG pools: graphcheck + execution against the oracle
# ---------------------------------------------------------------------------

def _prefilled(kv, seqs_prompts):
    """Prefill every (seq, prompt[:-1]) through the PF pool on a bare
    context; returns the chunk map used."""
    chunks = {}
    for seq, prompt in seqs_prompts:
        kv.alloc_seq(seq)
        chunks.update(prefill_chunks(MODEL, kv, seq, prompt[:-1]))
    T = DictCollection("T", dtt=kv.default_dtt,
                       init_fn=lambda *k: chunks[k], keys=list(chunks))
    ctx = Context(nb_cores=0)
    tp = prefill_ptg(kv, T, [s for s, _ in seqs_prompts])
    tp.validate()                 # graphcheck: zero errors pre-enqueue
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.fini()
    return chunks


def test_prefill_and_decode_pools_match_reference_multi_seq():
    kv = _kv()
    prompts = {"a": [3, 7, 11, 5, 9, 2], "b": [1, 40]}
    _prefilled(kv, list(prompts.items()))
    Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    for seq, prompt in prompts.items():
        assert kv.seq_len(seq) == len(prompt) - 1
        kv.ensure_tail_slot(seq)
        qc = Q.data_of(seq).get_copy(0)
        qc.value = MODEL.q3(prompt[-1])
        qc.version += 1
    tp = decode_step_ptg(kv, Q, O, list(prompts))
    report = tp.validate()
    assert not report.errors and not report.warnings, report
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.fini()
    for seq, prompt in prompts.items():
        _, ks, vs = _paged(prompt[:-1])
        want = ra.ragged_attention_reference(MODEL.q3(prompt[-1])[0],
                                             ks, vs)
        got = np.asarray(O.data_of(seq).newest_copy().value)
        assert np.abs(got - want).max() < 1e-5, seq
        # the OUT task appended the query token's k/v into the tail page
        tail = np.asarray(
            kv.data_of(seq, kv.npages(seq) - 1).newest_copy().value)
        slot = (len(prompt) - 1) % kv.page_size
        assert np.allclose(tail[0, slot], MODEL.q3(prompt[-1])[1])
        assert tail[2, 0, 0, 0] == slot + 1


def test_graphcheck_rejects_out_of_table_page_reference():
    """The has_key bounds oracle in anger: a decode-shaped pool reading
    one page PAST a sequence's block table must draw a bounds error."""
    from parsec_tpu import ptg
    from parsec_tpu.analysis import check_ptg
    kv = _kv()
    kv.alloc_seq("a")
    kv.alloc_page("a")
    p = ptg.PTGBuilder("bad_decode", KV=kv, NP=1)
    t = p.task("R", i=ptg.span(0, lambda g, l: g.NP - 1))
    f = t.flow("KV", ptg.READ)
    f.input(data=("KV", lambda g, l: ("a", l.i + 1)))   # off the table
    t.body(lambda es, task, g, l: None)
    report = check_ptg(p.build())
    assert report.errors, report
    assert any("KV" in str(e) for e in report.errors), report


def test_decode_through_tpu_device_tier_with_lru_residency(accel_device):
    """The device incarnation: ATTN/OUT dispatch through the TPU device
    module — KV pages and flow tiles ride the HBM LRU, and same-class
    decode tasks coalesce into vmapped batched dispatch."""
    kv = _kv()
    prompts = {"a": [3, 7, 11, 5, 9, 2], "b": [1, 40, 8]}
    for seq, prompt in prompts.items():
        kv.alloc_seq(seq)
        chunks = prefill_chunks(MODEL, kv, seq, prompt[:-1])
        for (s, c), tile in chunks.items():      # host-side prefill
            pg = kv.data_of(s, c).get_copy(0)
            pg.value = tile
            pg.version += 1
    Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    for seq, prompt in prompts.items():
        kv.ensure_tail_slot(seq)
        qc = Q.data_of(seq).get_copy(0)
        qc.value = MODEL.q3(prompt[-1])
        qc.version += 1
    tp = decode_step_ptg(kv, Q, O, list(prompts), devices="tpu")
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    accel_device.sync()
    ctx.fini()
    for seq, prompt in prompts.items():
        _, ks, vs = _paged(prompt[:-1])
        want = ra.ragged_attention_reference(MODEL.q3(prompt[-1])[0],
                                             ks, vs)
        got = np.asarray(O.data_of(seq).newest_copy().value)
        assert np.abs(got - want).max() < 1e-4, seq
    assert accel_device.executed_tasks == 5      # 3 + 2 ATTN/OUT chains
    # paged-KV residency: the pages went through the device LRU
    assert accel_device.cache_misses > 0
    assert len(accel_device._mem_lru) > 0


# ---------------------------------------------------------------------------
# k-step decode superpools: in-graph SAMPLE chains (ISSUE 9)
# ---------------------------------------------------------------------------

def _superpool_setup(prompts, steps, devices="cpu", eos=None):
    """Build the side collections and run the library's own
    per-iteration prep (``seed_decode_superpool`` — the batcher's
    seeding contract, stated once) for pool-level tests."""
    kv = _kv()
    Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    TOK = DictCollection("TOK", dtt=TileType((3,), np.float32))
    EMB = DictCollection("EMB", dtt=TileType(MODEL.q3_table().shape,
                                             np.float32))
    seed_decode_superpool(MODEL, kv, Q, TOK, EMB, prompts, steps, eos=eos)
    tp = decode_superpool_ptg(kv, Q, O, TOK, EMB, list(prompts),
                              [steps[s] for s in prompts],
                              devices=devices)
    return kv, TOK, tp


def _tokens_of(TOK, seq, k):
    return read_token_chain(TOK, seq, k)[0]


def test_superpool_matches_reference_mixed_steps_and_page_boundaries():
    """One pool spanning k autoregressive steps per sequence — DIFFERENT
    k per sequence, with the token positions crossing page boundaries
    mid-pool (page_size 4), must equal the dense oracle token for
    token.  This is the ISSUE-9 tentpole contract: SAMPLE threads token
    -> next query in-graph, OUT threads the tail page across steps."""
    prompts = {"a": [3, 7, 11, 5, 9, 2], "b": [1, 40], "c": [8, 8, 2, 6]}
    steps = {"a": 7, "b": 5, "c": 1}
    kv, TOK, tp = _superpool_setup(prompts, steps)
    report = tp.validate()
    assert not report.errors and not report.warnings, report
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    for seq, prompt in prompts.items():
        want = MODEL.reference_generate(prompt, steps[seq])
        assert _tokens_of(TOK, seq, steps[seq]) == want, seq


def test_superpool_eos_mid_pool_predicated_tail_is_discarded():
    """A sequence sampling EOS at an interior step finishes THERE: the
    surfaced tokens equal the EOS-truncated oracle, and the predicated
    tail tasks ran without corrupting the other sequence's chain."""
    ref = MODEL.reference_generate([3, 7, 11, 5], 8)
    eos = ref[1]                       # fires mid-pool
    want = MODEL.reference_generate([3, 7, 11, 5], 8, eos=eos)
    assert 1 <= len(want) < 8
    prompts = {"a": [3, 7, 11, 5], "b": [1, 40]}
    steps = {"a": 8, "b": 8}
    kv, TOK, tp = _superpool_setup(prompts, steps, eos=eos)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    assert _tokens_of(TOK, "a", 8) == want
    # the un-finished stream is untouched by a's early exit (b never
    # samples eos in 8 steps of this prompt — checked via the oracle)
    want_b = MODEL.reference_generate([1, 40], 8, eos=eos)
    assert _tokens_of(TOK, "b", 8) == want_b


def test_superpool_through_device_tier_with_pallas_interpret(
        accel_device, param):
    """The ISSUE-9 satellite gating arxiv 2604.15464 end-to-end off-TPU:
    the FULL k-step pools-vs-oracle token-equality test with the ATTN
    page kernel resolved through the Pallas build (interpret mode) —
    not just the kernel-level incarnation equality."""
    from parsec_tpu.device import kernels as dk
    param("llm_use_pallas", True)
    # re-arm the lazy seam: an earlier device-tier test may have
    # promoted the jnp body already, and the loader reads the param at
    # build time — drop the eager entry so THIS dispatch builds Pallas
    with dk._lock:
        dk._kernels.pop(("ragged_attn_page", "tpu"), None)
    dk.register_lazy_kernel("ragged_attn_page", "tpu", ra._load_page_body)
    try:
        prompts = {"a": [3, 7, 11, 5, 9, 2], "b": [1, 40, 8]}
        steps = {"a": 5, "b": 5}
        kv, TOK, tp = _superpool_setup(prompts, steps, devices="tpu")
        with Context(nb_cores=0) as ctx:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=240)
            accel_device.sync()
        for seq, prompt in prompts.items():
            want = MODEL.reference_generate(prompt, steps[seq])
            assert _tokens_of(TOK, seq, steps[seq]) == want, seq
        assert accel_device.executed_tasks > 0
    finally:
        # leave the seam lazy so later consumers rebuild under the
        # restored llm_use_pallas value
        with dk._lock:
            dk._kernels.pop(("ragged_attn_page", "tpu"), None)
        dk.register_lazy_kernel("ragged_attn_page", "tpu",
                                ra._load_page_body)


# ---------------------------------------------------------------------------
# continuous batching on the RuntimeServer
# ---------------------------------------------------------------------------

def test_stream_generation_matches_reference_token_for_token():
    with RuntimeServer(nb_cores=2) as server:
        prompts = [[3, 7, 11, 5], [1], [40, 2, 9, 9, 9, 30, 22, 8]]
        tks = [server.submit_stream(p, max_new_tokens=10,
                                    tenant=f"t{i % 2}")
               for i, p in enumerate(prompts)]
        for p, tk in zip(prompts, tks):
            r = tk.result(timeout=120)
            assert r["tokens"] == MODEL.reference_generate(p, 10)
            assert len(r["per_token_s"]) == 10
        stats = server.stats()["llm"]
        assert stats["streams_completed"] == 3
        assert stats["tokens_generated"] == 30
        # every retired stream's pages returned to the free list
        assert stats["kv"]["physical_pages"] == 0


def test_streams_join_and_leave_midflight_continuous_batching():
    """A late stream joins while earlier ones decode; short streams
    retire without stalling the batch — and everyone still matches the
    oracle (iteration-level scheduling correctness)."""
    with RuntimeServer(nb_cores=2) as server:
        first = server.submit_stream([3, 7, 11], max_new_tokens=12)
        short = server.submit_stream([5, 9], max_new_tokens=2)
        assert short.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([5, 9], 2)
        late = server.submit_stream([8, 30], max_new_tokens=4)
        assert first.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([3, 7, 11], 12)
        assert late.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([8, 30], 4)
        llm = server.stats()["llm"]
        assert llm["streams_completed"] == 3


def test_batcher_validates_inputs_and_rejects_after_stop():
    with RuntimeServer(nb_cores=1) as server:
        with pytest.raises(ValueError):
            server.submit_stream([], max_new_tokens=2)
        with pytest.raises(ValueError):
            server.submit_stream([1], max_new_tokens=0)
        tk = server.submit_stream([1, 2], max_new_tokens=2)
        tk.result(timeout=60)
    # the server drained: the session API sheds, it does not wedge
    from parsec_tpu.serve import AdmissionRejected
    with pytest.raises(AdmissionRejected):
        server.submit_stream([1, 2], max_new_tokens=2)


def test_page_budget_exhaustion_fails_only_the_oversized_stream():
    """Failure containment: a stream whose prompt blows the KV page
    budget fails ALONE — the other tenants'/streams' generation and the
    batcher loop keep going (code-review finding on the catch-all)."""
    with RuntimeServer(nb_cores=2) as server:
        kv = _kv(page_size=2, max_pages=3)
        b = ContinuousBatcher(server, model=MODEL, kv=kv)
        big = b.submit_stream(list(range(1, 10)), max_new_tokens=2,
                              tenant="big")       # prompt needs 4 pages
        small = b.submit_stream([1, 2], max_new_tokens=2, tenant="small")
        with pytest.raises(MemoryError):
            big.result(timeout=60)
        r = small.result(timeout=60)
        assert r["tokens"] == MODEL.reference_generate([1, 2], 2)
        assert small.generated() == r["tokens"]
        # the failed stream's partial pages were reclaimed
        assert b.stats()["kv"]["physical_pages"] == 0
        b.stop()


def test_batcher_direct_on_server_with_custom_kv_geometry():
    """ContinuousBatcher composes with a caller-owned KV collection
    (page size 2 forces multi-page chains immediately)."""
    with RuntimeServer(nb_cores=2) as server:
        kv = _kv(page_size=2)
        b = ContinuousBatcher(server, model=MODEL, kv=kv)
        tk = b.submit_stream([3, 7, 11, 5, 9], max_new_tokens=6)
        assert tk.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([3, 7, 11, 5, 9], 6)
        assert b.stats()["kv"]["physical_pages"] == 0
        # retired streams leave NO side-collection residue either
        assert b.Q.known_keys() == [] and b.O.known_keys() == []
        b.stop()


def test_stream_eos_stops_early_and_matches_truncated_oracle():
    """EOS sampled mid-superpool (ISSUE 9): the stream finishes at the
    EOS token (inclusive), the predicated tail is never surfaced, and
    pages recycle — while a no-EOS stream in the same batch runs to its
    full budget."""
    ref = MODEL.reference_generate([3, 7, 11, 5], 10)
    eos = ref[1]
    want = MODEL.reference_generate([3, 7, 11, 5], 10, eos=eos)
    assert 1 <= len(want) < 10       # genuinely mid-superpool (k=8)
    with RuntimeServer(nb_cores=2) as server:
        te = server.submit_stream([3, 7, 11, 5], max_new_tokens=10,
                                  eos=eos)
        tf = server.submit_stream([1, 40], max_new_tokens=10)
        re_ = te.result(timeout=120)
        assert re_["tokens"] == want
        assert len(re_["per_token_s"]) == len(want)
        assert tf.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([1, 40], 10)
        assert server.stats()["llm"]["kv"]["physical_pages"] == 0


def test_streams_join_and_leave_between_superpools(param):
    """Iteration-level scheduling at superpool grain (k=4): a short
    stream leaves mid-run, a late one joins at the next superpool
    boundary — and every stream still matches the oracle token for
    token."""
    param("llm_steps_per_pool", 4)
    with RuntimeServer(nb_cores=2) as server:
        first = server.submit_stream([3, 7, 11], max_new_tokens=11)
        short = server.submit_stream([5, 9], max_new_tokens=2)
        assert short.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([5, 9], 2)
        late = server.submit_stream([8, 30], max_new_tokens=6)
        assert first.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([3, 7, 11], 11)
        assert late.result(timeout=120)["tokens"] == \
            MODEL.reference_generate([8, 30], 6)
        llm = server.stats()["llm"]
        assert llm["streams_completed"] == 3
        # 11 tokens at k=4 is 4+4+3: the superpool clips to the budget
        assert llm["decode_submits"] < 11 + 2 + 6, llm


def test_fork_on_prompt_shares_pages_until_first_divergent_write():
    """The ISSUE-9 serving surface for PagedKVCollection.fork: streams
    opened with fork_from= share the parent's prompt pages CoW — full
    prompt pages stay physically shared for the streams' lifetime, only
    the tails privatize (at the first divergent write), and every fork
    still matches the oracle."""
    with RuntimeServer(nb_cores=2) as server:
        prompt = list(range(1, 41))    # 39 cached tokens -> 3 pages @16
        t1 = server.submit_stream(prompt, max_new_tokens=6)
        t2 = server.submit_stream(prompt, max_new_tokens=4, fork_from=t1)
        t3 = server.submit_stream(prompt, max_new_tokens=6, fork_from=t1)
        assert t1.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 6)
        assert t2.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 4)
        assert t3.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 6)
        llm = server.stats()["llm"]
        assert llm["forked_streams"] == 2
        kv = llm["kv"]
        # each fork privatized ONLY its tail page (CoW at the first
        # divergent write); the full prompt pages were never copied, so
        # three streams allocated far less than three prompts' worth
        assert kv["cow_copies"] >= 2, kv
        prompt_pages = (len(prompt) - 1 + 15) // 16
        assert kv["pages_allocated"] < 3 * prompt_pages, kv
        assert kv["physical_pages"] == 0       # everything recycled


def test_fork_from_requires_identical_prompt_and_known_ticket():
    with RuntimeServer(nb_cores=2) as server:
        t1 = server.submit_stream([1, 2, 3], max_new_tokens=2)
        with pytest.raises(ValueError, match="identical prompt"):
            server.submit_stream([1, 2, 4], max_new_tokens=2,
                                 fork_from=t1)
        with pytest.raises(ValueError, match="StreamTicket"):
            server.submit_stream([1, 2, 3], max_new_tokens=2,
                                 fork_from=object())
        # a foreign batcher's ticket must be rejected by IDENTITY: its
        # seq ids collide with ours, so accepting it could fork an
        # unrelated local sequence's pages
        with RuntimeServer(nb_cores=1) as other:
            with pytest.raises(ValueError, match="this batcher"):
                other.submit_stream([1, 2, 3], max_new_tokens=2,
                                    fork_from=t1)
        t1.result(timeout=60)


def test_fork_from_retired_parent_falls_back_to_plain_prefill():
    """A fork whose parent already finished (cache freed) must not fail
    the child: it silently prefills on its own and still matches the
    oracle — sharing is an optimization, never a correctness gate."""
    with RuntimeServer(nb_cores=2) as server:
        t1 = server.submit_stream([3, 7, 11, 5], max_new_tokens=2)
        t1.result(timeout=60)          # parent retires, pages freed
        t2 = server.submit_stream([3, 7, 11, 5], max_new_tokens=3,
                                 fork_from=t1)
        assert t2.result(timeout=60)["tokens"] == \
            MODEL.reference_generate([3, 7, 11, 5], 3)
        assert server.stats()["llm"]["forked_streams"] == 0


def test_fork_from_decoding_parent_forks_early_or_falls_back(param):
    """The classification window (ISSUE 12 closed most of it): a child
    classified against a live parent sitting exactly at the prompt
    boundary now forks AT CLASSIFICATION TIME — before the same
    iteration's decode superpool can advance the parent — and CoW
    privatizes the parent's next append away from the child's
    snapshot.  A child that only classifies AFTER the parent advanced
    still takes the documented silent fallback (its own plain
    prefill).  Either way: oracle-exact tokens, never a stream failure
    from iteration timing."""
    import time as _time
    param("llm_steps_per_pool", 2)
    prompt = [3, 7, 11, 5]
    with RuntimeServer(nb_cores=2) as server:
        t1 = server.submit_stream(prompt, max_new_tokens=6)
        deadline = _time.monotonic() + 60
        # submit the child while the parent PREFILLS: it lands in a
        # LATER iteration's fresh batch, where the parent either still
        # sits at its boundary (early fork) or has decoded (fallback)
        while t1.state == "queued":
            assert _time.monotonic() < deadline, "parent never admitted"
            _time.sleep(0.0002)
        t2 = server.submit_stream(prompt, max_new_tokens=3, fork_from=t1)
        assert t1.result(timeout=60)["tokens"] == \
            MODEL.reference_generate(prompt, 6)
        assert t2.result(timeout=60)["tokens"] == \
            MODEL.reference_generate(prompt, 3)
        # sharing is an optimization whose window depends on iteration
        # timing: both resolutions are legal, failure is not
        assert server.stats()["llm"]["forked_streams"] in (0, 1)


def test_batcher_region_lowered_superpools_match_oracle(param):
    """The llm_lower_regions opt-in: the batcher compiles each decode
    superpool into megakernel regions (PR 8) and submits the REGION
    pool — tokens must still equal the oracle exactly (the serving-path
    incarnation of the eager-vs-region equivalence)."""
    param("llm_lower_regions", True)
    param("llm_steps_per_pool", 2)
    with RuntimeServer(nb_cores=2) as server:
        tk = server.submit_stream([3, 7, 11, 5], max_new_tokens=2)
        assert tk.result(timeout=240)["tokens"] == \
            MODEL.reference_generate([3, 7, 11, 5], 2)
        assert server.stats()["llm"]["kv"]["physical_pages"] == 0


def test_step_timeout_defers_page_release_until_pool_terminates():
    """A timed-out step pool may still be RUNNING (serve tickets cannot
    cancel a live DAG): its streams' pages must not recycle to a new
    tenant until the zombie pool actually terminates."""
    from parsec_tpu.llm.batcher import StreamTicket, _Stream
    from parsec_tpu.runtime.taskpool import Taskpool
    with RuntimeServer(nb_cores=1) as server:
        b = ContinuousBatcher(server, model=MODEL, kv=_kv())
        b.kv.alloc_seq("z")
        b.kv.alloc_page("z")
        st = _Stream("z", "t", 0, [1], 1, StreamTicket("z", "t"))
        zombie = Taskpool(name="zombie_step")
        b._retire_failed([st], TimeoutError("step timeout"),
                         defer_pool=zombie)
        with pytest.raises(TimeoutError):
            st.ticket.result(timeout=1)          # client fails promptly...
        assert b.stats()["kv"]["physical_pages"] == 1   # ...pages held
        zombie.terminated()
        assert b.stats()["kv"]["physical_pages"] == 0   # released now
        b.stop()


def test_fork_from_zombie_parent_is_never_ready():
    """A FAILED parent whose page release is deferred behind a
    timed-out zombie pool still has its seq alive and its host-side
    ledger exactly at the prompt boundary — but the zombie pool may
    still be WRITING those pages.  ``_fork_ready`` must refuse it (the
    child then takes the plain-prefill fallback) rather than CoW-share
    pages mid-write."""
    from parsec_tpu.llm.batcher import StreamTicket, _Stream
    from parsec_tpu.runtime.taskpool import Taskpool
    with RuntimeServer(nb_cores=1) as server:
        b = ContinuousBatcher(server, model=MODEL, kv=_kv())
        prompt = [3, 7, 11, 5]
        b.kv.alloc_seq("p")
        prefill_chunks(MODEL, b.kv, "p", prompt[:-1])
        st = _Stream("p", "t", 0, prompt, 4, StreamTicket("p", "t"))
        assert b._fork_ready(st)         # live parent at its boundary
        zombie = Taskpool(name="zombie_step")
        b._retire_failed([st], TimeoutError("step timeout"),
                         defer_pool=zombie)
        # the ledger alone cannot tell this apart from a healthy parent
        assert b.kv.seq_len("p") == len(prompt) - 1
        assert not b._fork_ready(st)     # retired: never fork it
        zombie.terminated()
        b.stop()
