"""DTD interface tests — the analog of the reference's ``tests/dsl/dtd/``
suite (task insertion/generation, hazard chains, window backpressure,
scratch/value args, data flush, a DTD tiled GEMM)."""

import numpy as np
import pytest

from parsec_tpu.dtd import (DONT_TRACK, INOUT, INPUT, OUTPUT, SCRATCH, VALUE,
                            DTDTaskpool, Scratch)
from parsec_tpu.runtime.context import Context


@pytest.fixture(params=[0, 3], ids=["caller-driven", "3workers"])
def ctx(request):
    c = Context(nb_cores=request.param)
    yield c
    c.fini()


def test_insert_chain_raw(ctx):
    """RAW chain: each task increments the same tile; order must hold."""
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    a = np.zeros((4,), dtype=np.int64)
    trace = []

    def bump(arr, i):
        arr += 1
        trace.append((i, arr[0]))

    for i in range(50):
        tp.insert_task(bump, (a, INOUT), (i, VALUE))
    tp.wait()
    assert a[0] == 50
    assert trace == [(i, i + 1) for i in range(50)]


def test_war_waw_hazards(ctx):
    """Readers between two writers must all run before the second writer
    (WAR), and writers serialize (WAW) — dtd_test_war analog."""
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    a = np.array([7.0])
    reads = []

    def write(arr, v):
        arr[0] = v

    def read(arr):
        reads.append(arr[0])

    tp.insert_task(write, (a, OUTPUT), (1.0, VALUE))
    for _ in range(8):
        tp.insert_task(read, (a, INPUT))
    tp.insert_task(write, (a, OUTPUT), (2.0, VALUE))
    tp.insert_task(read, (a, INPUT))
    tp.wait()
    assert reads[:8] == [1.0] * 8
    assert reads[8] == 2.0
    assert a[0] == 2.0


def test_two_tiles_parallel_then_join(ctx):
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    x = np.array([1.0])
    y = np.array([2.0])
    z = np.array([0.0])

    def scale(arr, s):
        arr *= s

    def add_into(dst, xa, ya):
        dst[0] = xa[0] + ya[0]

    tp.insert_task(scale, (x, INOUT), (10.0, VALUE))
    tp.insert_task(scale, (y, INOUT), (100.0, VALUE))
    tp.insert_task(add_into, (z, OUTPUT), (x, INPUT), (y, INPUT))
    tp.wait()
    assert z[0] == 10.0 + 200.0


def test_scratch_and_value(ctx):
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    out = np.zeros((3,))

    def body(dst, scratch, k):
        scratch[:] = k
        dst[:] = scratch * 2

    tp.insert_task(body, (out, OUTPUT), (Scratch((3,), np.float64), SCRATCH),
                   (21.0, VALUE))
    tp.wait()
    np.testing.assert_allclose(out, 42.0)


def test_functional_update_return(ctx):
    """jax-style bodies return replacement arrays for written flows."""
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    t = tp.tile_of_array(np.array([3.0]), key="t")

    def fbody(arr):
        return arr + 1.0   # replaces, does not mutate

    for _ in range(4):
        tp.insert_task(fbody, (t, INOUT))
    tp.wait()
    assert t.data.newest_copy().value[0] == 7.0


def test_window_backpressure(ctx):
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    tp.window_size, tp.threshold_size = 16, 8
    a = np.zeros((1,), dtype=np.int64)

    def inc(arr):
        arr += 1

    for _ in range(300):
        tp.insert_task(inc, (a, INOUT))
    tp.wait()
    assert a[0] == 300


def test_dont_track(ctx):
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    a = np.zeros((1,))
    seen = []

    def look(arr):
        seen.append(arr[0])

    tp.insert_task(look, (a, INPUT | DONT_TRACK))
    tp.wait()
    assert seen == [0.0]


def test_data_flush(ctx):
    """Flush pushes the final version back to the collection home copy."""
    from parsec_tpu.data_dist.matrix import TiledMatrix

    A = TiledMatrix("A", 8, 8, 4, 4, dtype=np.float64)
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    t = tp.tile_of(A, 0, 0)

    def setv(arr):
        arr[:] = 5.0

    tp.insert_task(setv, (t, INOUT))
    tp.data_flush(t)
    tp.wait()
    assert t.flushed
    np.testing.assert_allclose(A.data_of(0, 0).get_copy(0).value, 5.0)


def test_dtd_gemm_correctness(ctx):
    """DTD tiled GEMM vs numpy — dtd_test_simple_gemm analog (CPU path)."""
    rng = np.random.default_rng(0)
    n, nb = 64, 16
    nt = n // nb
    A = rng.standard_normal((n, n)).astype(np.float32)
    B = rng.standard_normal((n, n)).astype(np.float32)
    C = np.zeros((n, n), dtype=np.float32)

    from parsec_tpu.data_dist.matrix import TiledMatrix
    dA = TiledMatrix.from_dense("A", A, nb, nb)
    dB = TiledMatrix.from_dense("B", B, nb, nb)
    dC = TiledMatrix.from_dense("C", C, nb, nb)

    tp = DTDTaskpool()
    ctx.add_taskpool(tp)

    def gemm(c, a, b):
        c += a @ b

    for m in range(nt):
        for nn in range(nt):
            tc = tp.tile_of(dC, m, nn)
            for k in range(nt):
                tp.insert_task(gemm, (tc, INOUT),
                               (tp.tile_of(dA, m, k), INPUT),
                               (tp.tile_of(dB, k, nn), INPUT))
    tp.data_flush_all()
    tp.wait()
    np.testing.assert_allclose(dC.to_dense(), A @ B, rtol=1e-4, atol=1e-4)


def test_task_class_reuse_and_limit(ctx):
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    a = np.zeros((1,))

    def inc(arr):
        arr += 1

    for _ in range(5):
        tp.insert_task(inc, (a, INOUT))
    tp.wait()
    assert len(tp._classes) == 1  # one dynamic class per (body, arity)


def test_priority_hint(ctx):
    tp = DTDTaskpool()
    ctx.add_taskpool(tp)
    a = np.zeros((1,))

    def inc(arr):
        arr += 1

    t = tp.insert_task(inc, (a, INOUT), priority=7)
    tp.wait()
    assert t.priority == 7 and t.completed
