"""Comm fault robustness: duplicate/unknown acks, peer-death handle GC,
socket reconnect-and-replay under injected connection breaks.

The reference rides MPI, which never drops or duplicates; the TCP tier here
must manufacture those guarantees itself (seq + replay window + cumulative
acks + dedup, :mod:`parsec_tpu.comm.socket_fabric`), and the protocol layer
must tolerate the duplicates a replay can surface (acks, GET replies).
"""

import threading
import time

import numpy as np
import pytest

from parsec_tpu.comm.engine import (AM_TAG_GET_ACK, InprocCommEngine,
                                    InprocFabric)
from parsec_tpu.comm.multiproc import _free_port_base
from parsec_tpu.comm.socket_fabric import SocketFabric
from parsec_tpu.core.params import params


# --------------------------------------------------------------------------
# protocol-layer tolerance
# --------------------------------------------------------------------------

class _FakeTDM:
    def __init__(self):
        self.pa = 0

    def taskpool_addto_nb_pa(self, d):
        self.pa += d


class _FakeTP:
    def __init__(self):
        self.tdm = _FakeTDM()


def test_duplicate_and_unknown_acks_tolerated():
    """A replayed/duplicated GET_ACK must not crash the producer or
    double-settle the termdet pending-action count."""
    from parsec_tpu.comm.remote_dep import RemoteDepEngine

    eng = RemoteDepEngine.__new__(RemoteDepEngine)
    eng._iflock = threading.Lock()
    tp = _FakeTP()
    eng._inflight = {7: tp}
    eng.dup_acks = 0

    eng._on_ack(None, 1, {"seq": 7})
    assert tp.tdm.pa == -1
    eng._on_ack(None, 1, {"seq": 7})       # duplicate: tolerated, counted
    eng._on_ack(None, 1, {"seq": 99})      # unknown: tolerated, counted
    assert tp.tdm.pa == -1
    assert eng.dup_acks == 2


def test_duplicate_get_reply_tolerated():
    fabric = InprocFabric(2)
    e0, e1 = fabric.attach(0), fabric.attach(1)
    h = e1.mem_register(np.arange(4.0), refcount=1)
    landed = []
    e0.get(h.wire(), landed.append)
    for _ in range(4):
        e0.progress()
        e1.progress()
    assert len(landed) == 1
    # forge a duplicate reply (what a transport replay would deliver)
    fabric.deliver(0, 2, 1, {"get_id": 1, "value": np.arange(4.0)})
    e0.progress()
    assert len(landed) == 1
    assert e0.dup_get_replies == 1


# --------------------------------------------------------------------------
# registered-handle GC
# --------------------------------------------------------------------------

def test_peer_death_releases_handle_shares():
    fabric = InprocFabric(4)
    e0 = fabric.attach(0)
    drained = []
    e0.mem_register(np.zeros(4), refcount=2, peers={1, 2},
                    on_drained=lambda: drained.append("a"))
    assert e0.on_peer_failed(1) == 0        # one share left
    assert not drained
    assert e0.on_peer_failed(2) == 1        # last share: drained
    assert drained == ["a"]
    # idempotent: an unrelated/repeat death touches nothing
    assert e0.on_peer_failed(2) == 0


def test_peer_pull_then_death_does_not_double_release():
    """A peer that pulled its share and THEN died must not release twice
    (the serve path clears it from the expected-peer set)."""
    fabric = InprocFabric(3)
    e0, e1 = fabric.attach(0), fabric.attach(1)
    drained = []
    h = e0.mem_register(np.arange(3.0), refcount=2, peers={1, 2},
                        on_drained=lambda: drained.append(1))
    landed = []
    e1.get(h.wire(), landed.append)
    for _ in range(4):
        e1.progress()
        e0.progress()
    assert len(landed) == 1
    assert e0.on_peer_failed(1) == 0        # already consumed its share
    assert e0.mem_retrieve(h.handle_id) is not None
    assert e0.on_peer_failed(2) == 1
    assert drained == [1]


def test_engine_fini_drops_leftover_handles():
    fabric = InprocFabric(2)
    e0 = fabric.attach(0)
    drained = []
    e0.mem_register(np.zeros(2), refcount=3,
                    on_drained=lambda: drained.append(1))
    e0.mem_register(np.zeros(2), refcount=1,
                    on_drained=lambda: drained.append(2))
    e0.fini()
    assert sorted(drained) == [1, 2]


# --------------------------------------------------------------------------
# socket tier: reconnect-and-replay under injected faults
# --------------------------------------------------------------------------

@pytest.fixture
def fabric_pair():
    base = _free_port_base(2)
    params.set("comm_socket_fault_p", 0.05)
    params.set("comm_socket_fault_seed", 1234)
    f0 = SocketFabric(2, 0, base_port=base)
    f1 = SocketFabric(2, 1, base_port=base)
    try:
        yield f0, f1
    finally:
        params.set("comm_socket_fault_p", 0.0)
        f0.close()
        f1.close()


def _drain_until(fabric, want, timeout=30.0):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < want:
        got.extend(fabric.drain(fabric.rank, limit=256))
        if time.monotonic() > deadline:
            raise TimeoutError(f"only {len(got)}/{want} frames arrived")
        time.sleep(0.0005)
    return got


def test_socket_replay_survives_connection_breaks_100_rounds(fabric_pair):
    """100 rounds of numbered traffic with a 5% per-send chance of the
    connection being hard-broken first: every frame still arrives exactly
    once, in order, and replays actually happened."""
    f0, f1 = fabric_pair
    N = 60
    for round_ in range(100):
        for i in range(N):
            f0.deliver(1, tag=10, src=0, payload=(round_, i))
        frames = _drain_until(f1, N)
        assert [p for _, _, p in frames] == [(round_, i) for i in range(N)]
        assert all(tag == 10 and src == 0 for tag, src, _ in frames)
    assert f0.replays > 0          # faults actually fired
    assert f1.dup_frames >= 0      # replay overlap is suppressed, not fatal


def test_socket_replay_bidirectional_under_faults(fabric_pair):
    """Both directions under fault injection concurrently (acks and data
    interleave on the same connections)."""
    f0, f1 = fabric_pair
    N = 400
    err = []

    def pump(src_f, dst_rank):
        try:
            for i in range(N):
                src_f.deliver(dst_rank, tag=11, src=src_f.rank, payload=i)
        except Exception as e:          # pragma: no cover
            err.append(e)

    t0 = threading.Thread(target=pump, args=(f0, 1))
    t1 = threading.Thread(target=pump, args=(f1, 0))
    t0.start()
    t1.start()
    t0.join()
    t1.join()
    assert not err
    for fab in (f0, f1):
        frames = _drain_until(fab, N)
        assert [p for _, _, p in frames] == list(range(N))


def test_socket_clean_path_has_no_replays():
    """With fault injection off, traffic flows with zero replays and zero
    suppressed duplicates (the window machinery is pure bookkeeping)."""
    base = _free_port_base(2)
    f0 = SocketFabric(2, 0, base_port=base)
    f1 = SocketFabric(2, 1, base_port=base)
    try:
        for i in range(200):
            f0.deliver(1, tag=3, src=0, payload=i)
        frames = _drain_until(f1, 200)
        assert [p for _, _, p in frames] == list(range(200))
        assert f0.replays == 0
        assert f1.dup_frames == 0
    finally:
        f0.close()
        f1.close()


# --------------------------------------------------------------------------
# mid-tree rank death: resume a partially-landed GET from a new owner
# --------------------------------------------------------------------------

def test_mid_tree_death_resumes_from_surviving_owner():
    """The collective-tree fault path (ISSUE 14): rank 2 pulls a staged
    payload from its tree parent (rank 1); the parent dies with only part
    of the window landed.  ``resume_get`` retargets the SAME landing zone
    at a surviving holder (the grandparent, rank 0), which serves only
    the missing offsets — and any zombie fragment the dead parent still
    emitted dedups against the zone's landed-offset set exactly once."""
    from parsec_tpu.comm.engine import AM_TAG_GET_FRAG

    old_frag = params.get("comm_get_frag_bytes")
    old_win = params.get("comm_get_window")
    params.set("comm_get_frag_bytes", 64)
    params.set("comm_get_window", 2)
    try:
        fabric = InprocFabric(3)
        e0, e1, e2 = (fabric.attach(r) for r in range(3))
        value = np.arange(64, dtype=np.float64)        # 512 B = 8 frags
        h0 = e0.mem_register(value.copy(), refcount=1)
        h1 = e1.mem_register(value.copy(), refcount=1)  # the staged copy

        landed = []
        gid = e2.get(h1.wire(), landed.append)
        e1.progress()               # serve: first window (2 frags) out
        e2.progress()               # land them; acks queue at rank 1
        with e2._frag_lock:
            zone = e2._landing[gid]
            part = set(zone.landed)
        assert len(part) == 2 and not landed

        # rank 1 dies.  A zombie fragment it already emitted arrives late:
        raw = value.view(np.uint8)
        off = min(part)
        fabric.deliver(2, AM_TAG_GET_FRAG, 1,
                       (gid, off, 64, None, raw[off:off + 64].copy()))
        e2.progress()
        assert e2.dup_frags == 1 and not landed

        # resume against the surviving owner BEFORE sweeping the dead
        # peer (the zone retargets, so the sweep must not reap it)
        assert e2.resume_get(h0.wire(), gid) is True
        e2.on_peer_failed(1)
        with e2._frag_lock:
            assert gid in e2._landing       # retargeted, not reaped

        for _ in range(16):
            e0.progress()
            e2.progress()
            if landed:
                break
        assert len(landed) == 1
        np.testing.assert_array_equal(landed[0], value)
        # the new owner served ONLY the missing offsets (8 total - 2
        # already landed), and the zone retired cleanly
        assert e0.frags_out == 6
        with e2._frag_lock:
            assert gid not in e2._landing
        assert e2._frag_active == 0
        # nothing left to resume once the get completed
        assert e2.resume_get(h0.wire(), gid) is False
    finally:
        params.set("comm_get_frag_bytes", old_frag)
        params.set("comm_get_window", old_win)
