"""Collective-tree taskpools (ISSUE 14): staged broadcast + combining
reduction over the PR-4 wire protocol.

Three tiers: static (graphcheck-clean at every kind x size), inproc
multirank execution against numpy oracles, and the 8-process acceptance
run — a 4 MiB broadcast that must land byte-identical on every rank with
root egress bounded by the root's tree-children count (ceil(log2 8) = 3
payload transfers for binomial), measured off the socket fabric's
per-peer traffic ledger."""

import hashlib

import numpy as np
import pytest

from parsec_tpu.analysis import check_ptg
from parsec_tpu.comm import run_multirank, run_multiproc
from parsec_tpu.comm.collectives import (bcast_taskpool, reduce_op,
                                         reduce_taskpool,
                                         register_reduce_op)
from parsec_tpu.core.params import params
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic

KINDS = ["binomial", "chain", "star"]


def _vec(name, nt, nranks=1, rank=0, init=None):
    return VectorTwoDimCyclic(
        name, lm=nt * 4, mb=4, P=nranks, myrank=rank,
        init_fn=init or (lambda m, s: np.zeros(s, np.float32)))


# ---------------------------------------------------------------------------
# static: every shape is graphcheck-clean
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("n", [1, 2, 3, 5, 8])
def test_collective_pools_graphcheck_clean(kind, n):
    r = check_ptg(bcast_taskpool(_vec("V", n), n=n, kind=kind))
    assert not r.errors, (kind, n, r.errors)
    r = check_ptg(reduce_taskpool(_vec("R", n), _vec("O", 1),
                                  n=n, kind=kind))
    assert not r.errors, (kind, n, r.errors)


def test_reduce_op_registry():
    assert reduce_op("sum") is np.add
    with pytest.raises(KeyError, match="register_reduce_op"):
        reduce_op("xor")
    register_reduce_op("absmax", lambda a, b: np.maximum(np.abs(a),
                                                         np.abs(b)))
    assert reduce_op("absmax") is not None


def test_bad_root_rejected():
    with pytest.raises(ValueError, match="root"):
        bcast_taskpool(_vec("V", 4), n=4, root=4)


# ---------------------------------------------------------------------------
# single-rank execution (tree staging degenerates to local copies)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
def test_bcast_single_rank(kind):
    from parsec_tpu.runtime.context import Context
    n = 5
    V = _vec("V", n, init=lambda m, s:
             np.arange(s, dtype=np.float32) + 9.0 if m == 0
             else np.zeros(s, np.float32))
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(bcast_taskpool(V, n=n, kind=kind))
        ctx.wait(timeout=30)
    want = np.arange(4, dtype=np.float32) + 9.0
    for m in range(n):
        got = np.asarray(V.data_of(m).newest_copy().value)
        np.testing.assert_array_equal(got, want, err_msg=f"tile {m}")


@pytest.mark.parametrize("op,oracle", [
    ("sum", lambda cols: np.sum(cols, axis=0)),
    ("max", lambda cols: np.max(cols, axis=0)),
    ("prod", lambda cols: np.prod(cols, axis=0)),
])
def test_reduce_single_rank_matches_numpy(op, oracle):
    from parsec_tpu.runtime.context import Context
    n = 6
    rng = np.random.RandomState(14)
    cols = rng.uniform(0.5, 1.5, size=(n, 4)).astype(np.float32)
    R = _vec("R", n, init=lambda m, s: cols[m].copy())
    O = _vec("O", 1)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(reduce_taskpool(R, O, op=op, n=n))
        ctx.wait(timeout=30)
    got = np.asarray(O.data_of(0).newest_copy().value)
    np.testing.assert_allclose(got, oracle(cols), rtol=1e-6)


# ---------------------------------------------------------------------------
# inproc multirank: the staged tree across rank boundaries
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("nranks", [2, 4])
def test_bcast_multirank_byte_identical(kind, nranks):
    want = np.arange(4, dtype=np.float32) * 2.0 + 3.0

    def body(ctx, rank, nranks):
        V = _vec("V", nranks, nranks=nranks, rank=rank,
                 init=lambda m, s: (
                     np.arange(s, dtype=np.float32) * 2.0 + 3.0
                     if m == 0 else np.zeros(s, np.float32)))
        ctx.add_taskpool(bcast_taskpool(V, n=nranks, kind=kind))
        ctx.wait(timeout=60)
        ctx.comm_barrier()
        return np.asarray(V.data_of(rank).newest_copy().value).copy()

    res = run_multirank(nranks, body, nb_cores=1, timeout=120)
    for rank, got in enumerate(res):
        np.testing.assert_array_equal(got, want, err_msg=f"rank {rank}")


@pytest.mark.parametrize("nranks", [3, 4])
def test_reduce_multirank_matches_numpy(nranks):
    def body(ctx, rank, nranks):
        R = _vec("R", nranks, nranks=nranks, rank=rank,
                 init=lambda m, s: np.full(s, float(m + 1), np.float32))
        O = _vec("O", 1, nranks=nranks, rank=rank)
        ctx.add_taskpool(reduce_taskpool(R, O, op="sum", n=nranks))
        ctx.wait(timeout=60)
        ctx.comm_barrier()
        if rank == 0:
            return np.asarray(O.data_of(0).newest_copy().value).copy()
        return None

    res = run_multirank(nranks, body, nb_cores=1, timeout=120)
    want = np.full(4, sum(range(1, nranks + 1)), np.float32)
    np.testing.assert_allclose(res[0], want)


# ---------------------------------------------------------------------------
# the 8-process acceptance run: byte-identical + O(log n) root egress
# ---------------------------------------------------------------------------

def test_bcast_8rank_multiproc_root_egress_logn():
    nranks = 8
    payload = int(params.get("comm_coll_bench_bytes"))     # 4 MiB
    res = run_multiproc(
        nranks, "parsec_tpu.comm.collectives:_mp_collective_body",
        timeout=300, nb_cores=1)
    mb = max(payload // 4, 1)
    want = np.arange(mb, dtype=np.float32) * 0.5 + 7.0
    want_digest = hashlib.sha256(want.tobytes()).hexdigest()
    for r in res:
        assert r["digest"] == want_digest, \
            f"rank {r['rank']} broadcast not byte-identical"
    assert res[0]["reduce0"] == pytest.approx(sum(range(1, nranks + 1)))

    # root egress: rank 0 serves at most its tree children — for the
    # binomial default that is ceil(log2(8)) = 3 payload transfers (the
    # activation layer's own staged re-serve may hand some of them to
    # interior ranks, so strictly FEWER is legal too).  Everything else
    # on the ledger (activations, GET control, the small reduction
    # tiles) is noise far under one payload.
    assert res[0]["tree"] == "binomial"
    tx = res[0]["peer_stats"]["tx"]
    egress = sum(d["bytes"] for d in tx.values())
    assert egress <= 3 * payload + (1 << 20), \
        f"root egress {egress} exceeds 3 payloads (+1 MiB slack)"
    heavy = [dst for dst, d in tx.items() if d["bytes"] >= payload]
    assert 1 <= len(heavy) <= 3, \
        (heavy, {k: v["bytes"] for k, v in tx.items()})
    # every non-root rank landed the payload exactly once (one heavy
    # inbound peer): the staged tree never double-delivers
    for r in res[1:]:
        rx = r["peer_stats"]["rx"]
        srcs = [s for s, d in rx.items() if d["bytes"] >= payload]
        assert len(srcs) == 1, (r["rank"], srcs)

    # static-vs-dynamic agreement (ISSUE 20): commcheck's executed-nothing
    # byte prediction for this exact workload must agree with the wire
    # ledger within 15% rel — framing, activations, and the reduction
    # partials are the only slack on top of (n-1) payload transfers
    from parsec_tpu.analysis.commcheck import (agreement_rel_err,
                                               predict_collective_traffic)
    pred = predict_collective_traffic(nranks)
    observed = sum(d["bytes"] for r in res
                   for d in r["peer_stats"]["tx"].values())
    err = agreement_rel_err(pred["total_bytes"], observed)
    assert err <= 0.15, (pred["total_bytes"], observed, err)
    # the root-egress prediction is an UPPER bound on the root's own
    # ledger: the staged re-serve can only shed root load onto interior
    # ranks (see the egress comment above), never add to it
    assert egress <= pred["root_egress_bytes"] + (1 << 20), \
        (pred["root_egress_bytes"], egress)


def test_bcast_4rank_auto_tree_root_egress_bounded():
    """``comm_bcast_tree=auto`` (ISSUE 20): the resolved shape's measured
    root egress must be <= the WORST hand-picked shape on the same
    workload.  The 4 MiB payload is far past comm_short_limit, so auto
    resolves to binomial — root serves children(0, 4) = {1, 2}: 2
    payloads, vs star's worst-case 3; the wire must never carry the
    literal "auto" (every rank's resolved tree is concrete)."""
    nranks = 4
    payload = int(params.get("comm_coll_bench_bytes"))     # 4 MiB
    saved = params.get("comm_bcast_tree")
    params.set("comm_bcast_tree", "auto")
    try:
        res = run_multiproc(
            nranks, "parsec_tpu.comm.collectives:_mp_collective_body",
            timeout=300, nb_cores=1)
    finally:
        params.set("comm_bcast_tree", saved)
    digests = {r["digest"] for r in res}
    assert len(digests) == 1, "auto-tree broadcast not byte-identical"
    assert res[0]["tree"] == "auto"         # the param rode the env
    egress = sum(d["bytes"]
                 for d in res[0]["peer_stats"]["tx"].values())
    # worst hand-picked shape is star: root serves n-1 = 3 payloads
    assert egress <= (nranks - 1) * payload + (1 << 20), \
        f"auto root egress {egress} exceeds the star worst case"
    # and the binomial resolution beats it: 2 children + slack
    assert egress <= 2 * payload + (1 << 20), \
        f"auto did not resolve to the egress-bounding shape: {egress}"
