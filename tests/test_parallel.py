"""Parallelism pack: ring attention, Ulysses all-to-all, composed train step.

Runs on the virtual 8-device CPU mesh (conftest).  These are the compiled
(SPMD) realizations of SURVEY §2.12's strategy inventory; the dynamic-
runtime realizations (halo PTG, redistribute) are tested in
test_apps_stencil.py / test_collections.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parsec_tpu.parallel.alltoall import make_ulysses_attention
from parsec_tpu.parallel.ring import (dense_attention, make_ring_attention)
from parsec_tpu.parallel.train import (init_params, init_transformer_params,
                                       make_train_step,
                                       make_transformer_train_step)


def _mesh(shape: dict) -> Mesh:
    import numpy as np
    devs = np.array(jax.devices()[:int(np.prod(list(shape.values())))])
    return Mesh(devs.reshape(tuple(shape.values())),
                axis_names=tuple(shape.keys()))


def _qkv(key, b=2, h=4, n=16, d=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, n, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_attention_matches_dense(causal, sp):
    mesh = _mesh({"sp": sp})
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ring = make_ring_attention(mesh, causal=causal, batch_axis=None,
                               head_axis=None)
    got = ring(q, k, v)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_full_mesh():
    """dp × tp × sp simultaneously: batch, heads, and sequence all sharded."""
    mesh = _mesh({"dp": 2, "tp": 2, "sp": 2})
    q, k, v = _qkv(jax.random.PRNGKey(1), b=4, h=4, n=16, d=8)
    ring = make_ring_attention(mesh, causal=True)
    got = ring(q, k, v)
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False])
def test_ulysses_matches_dense(causal):
    """All-to-all head re-sharding computes identical attention."""
    mesh = _mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(2), b=2, h=8, n=16, d=4)
    ul = make_ulysses_attention(
        mesh, lambda a, b_, c: dense_attention(a, b_, c, causal=causal),
        batch_axis=None)
    got = ul(q, k, v)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_mlp_train_step_matches_single_device():
    """The dp×tp sharded step computes the same update as unsharded math."""
    mesh = _mesh({"dp": 2, "tp": 4})
    params = init_params(jax.random.PRNGKey(0), 16, 32)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, 16))
    y = jax.random.normal(jax.random.PRNGKey(2), (8, 4, 16))
    step = make_train_step(mesh, lr=0.1)
    p2, loss = step(params, x, y)

    def ref_loss(p):
        h = jax.nn.relu(x @ p["w1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)

    rl, rg = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(p2[k]),
                                   np.asarray(params[k] - 0.1 * rg[k]),
                                   rtol=1e-4, atol=1e-5)


def test_transformer_train_step_matches_single_device():
    """Flagship dp×tp×sp step (ring attention inside) equals unsharded
    transformer-block SGD.

    Params are scaled 25x from init so a missing Megatron f-operator
    (tp-local activation cotangents) shows up orders of magnitude above
    the tolerance instead of hiding in fp32 noise."""
    mesh = _mesh({"dp": 2, "tp": 2, "sp": 2})
    n_heads, d_head, d_model, d_ff = 4, 4, 16, 32
    params = init_transformer_params(jax.random.PRNGKey(0), d_model,
                                     n_heads, d_head, d_ff)
    params = jax.tree.map(lambda p: p * 25.0, params)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, d_model))
    y = jax.random.normal(jax.random.PRNGKey(2), (4, 8, d_model))
    step = make_transformer_train_step(mesh, n_heads, d_head, lr=0.05,
                                       causal=True)
    p2, loss = step(params, x, y)

    def ref_block(p, xx):
        b, s, d = xx.shape
        def heads(t):
            return t.reshape(b, s, n_heads, d_head).transpose(0, 2, 1, 3)
        a = dense_attention(heads(xx @ p["wq"]), heads(xx @ p["wk"]),
                            heads(xx @ p["wv"]), causal=True)
        a = a.transpose(0, 2, 1, 3).reshape(b, s, n_heads * d_head)
        xx = xx + a @ p["wo"]
        return xx + jax.nn.relu(xx @ p["w1"]) @ p["w2"]

    def ref_loss(p):
        return jnp.mean((ref_block(p, x) - y) ** 2)

    rl, rg = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-4)
    for k in params:
        np.testing.assert_allclose(np.asarray(p2[k]),
                                   np.asarray(params[k] - 0.05 * rg[k]),
                                   rtol=1e-3, atol=1e-5)


def test_transformer_loss_decreases():
    mesh = _mesh({"dp": 2, "tp": 2, "sp": 2})
    params = init_transformer_params(jax.random.PRNGKey(0), 16, 4, 4, 32)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))
    y = x * 0.5
    step = make_transformer_train_step(mesh, 4, 4, lr=0.1)
    losses = []
    for _ in range(5):
        params, loss = step(params, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
