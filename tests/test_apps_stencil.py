"""1-D stencil app tests — the halo-exchange tier (tests/apps/stencil analog)."""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
from parsec_tpu.models.stencil import (stencil_1d_ptg, stencil_flops,
                                       stencil_reference)
from parsec_tpu.runtime import Context


def _make_v(base, mb, nranks=1, rank=0):
    return VectorTwoDimCyclic("V", lm=len(base), mb=mb, P=nranks,
                              myrank=rank, dtype=np.float64,
                              init_fn=lambda m, size:
                              base[m * mb:m * mb + size])


@pytest.mark.parametrize("nb_cores", [0, 3])
@pytest.mark.parametrize("radius,iters", [(1, 1), (2, 4), (4, 7)])
def test_stencil_matches_reference(nb_cores, radius, iters):
    rng = np.random.default_rng(0)
    base = rng.standard_normal(64).astype(np.float64)
    V = _make_v(base, mb=16)
    w = rng.standard_normal(2 * radius + 1)
    tp = stencil_1d_ptg(V, w, iters)
    ctx = Context(nb_cores=nb_cores)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.fini()
    got = np.concatenate([V.data_of(i).newest_copy().value
                          for i in range(V.mt)])
    np.testing.assert_allclose(got, stencil_reference(base, w, iters),
                               rtol=1e-10)


def _stencil_rank_body(ctx, rank, nranks):
    rng = np.random.default_rng(0)
    base = rng.standard_normal(48).astype(np.float64)
    V = _make_v(base, mb=8, nranks=nranks, rank=rank)
    w = np.array([0.25, 0.5, 0.25])
    tp = stencil_1d_ptg(V, w, 5)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=90)
    ctx.comm_barrier()
    # gather this rank's tiles
    out = {}
    for i in range(V.mt):
        if V.rank_of(i) == rank:
            out[i] = np.asarray(V.data_of(i).newest_copy().value).copy()
    return out


@pytest.mark.parametrize("nranks", [2, 3])
def test_stencil_multirank(nranks):
    """Ghost regions cross ranks through the activation protocol."""
    res = run_multirank(nranks, _stencil_rank_body, timeout=180)
    rng = np.random.default_rng(0)
    base = rng.standard_normal(48).astype(np.float64)
    want = stencil_reference(base, np.array([0.25, 0.5, 0.25]), 5)
    got = np.zeros_like(want)
    for rank_out in res:
        for i, tile in rank_out.items():
            got[i * 8:(i + 1) * 8] = tile
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_stencil_flops_formula():
    assert stencil_flops(100, 4, 10) == 2.0 * 9 * 100 * 10


@pytest.mark.parametrize("radius,iters", [(1, 1), (2, 4), (4, 7)])
def test_stencil_lowers_to_wavefront(radius, iters):
    """The stencil compiles through the wavefront pass: one batched update
    per iteration (interior group + two boundary groups), ghost reads as
    store gathers — and matches the dense oracle."""
    from parsec_tpu.ptg.lowering import lower_taskpool

    rng = np.random.default_rng(1)
    base = rng.standard_normal(64).astype(np.float64)
    V = _make_v(base, mb=16)
    w = rng.standard_normal(2 * radius + 1)
    low = lower_taskpool(stencil_1d_ptg(V, w, iters))
    assert low.mode == "wavefront"
    low.execute()
    got = np.concatenate([np.asarray(V.data_of(i).newest_copy().value)
                          for i in range(V.mt)])
    np.testing.assert_allclose(got, stencil_reference(base, w, iters),
                               rtol=2e-5, atol=2e-5)


def test_uniform_wavefronts_fold_into_scan(param):
    """Consecutive identical wavefronts (a stencil sweep's iterations)
    fold into ONE lax.scan body — O(1) trace/compile cost instead of
    O(iterations) (VERDICT r4 weak #2: the op count as the next compile
    wall; measured 12x faster jit on the bench stencil shape).  The
    folded program must be numerically IDENTICAL to the unrolled one."""
    from parsec_tpu.ptg.lowering import lower_taskpool

    rng = np.random.default_rng(3)
    base = rng.standard_normal(64).astype(np.float64)
    w = np.array([0.2, 0.6, 0.2])
    outs = {}
    for label, scan_min in (("scan", 4), ("unrolled", 10 ** 9)):
        param("lowering_scan_min", scan_min)
        # fresh tile buffers per run: _make_v hands out views of base,
        # and the first execute()'s writeback must not feed the second
        V = _make_v(base.copy(), mb=16)
        low = lower_taskpool(stencil_1d_ptg(V, w, 12))
        assert low.mode == "wavefront"
        low.execute()
        outs[label] = np.concatenate(
            [np.asarray(V.data_of(i).newest_copy().value)
             for i in range(V.mt)])
    # not bitwise: XLA fuses the scan body differently from the unrolled
    # chain (observed 6e-8 f32 rounding drift) — equivalent, not equal
    np.testing.assert_allclose(outs["scan"], outs["unrolled"],
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(outs["scan"],
                               stencil_reference(base, w, 12),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("nranks", [2, 4])
def test_stencil_wavefront_sharded(nranks):
    """Wavefront-lowered stencil over a ranks mesh: halo gathers become
    GSPMD collectives between per-rank store slabs."""
    import jax
    from jax.sharding import Mesh

    from parsec_tpu.ptg.lowering import lower_taskpool

    rng = np.random.default_rng(2)
    base = rng.standard_normal(64).astype(np.float64)
    V = _make_v(base, mb=8, nranks=nranks)
    w = np.array([0.25, 0.5, 0.25])
    mesh = Mesh(np.array(jax.devices()[:nranks]), ("ranks",))
    low = lower_taskpool(stencil_1d_ptg(V, w, 5), mesh=mesh)
    assert low.mode == "wavefront"
    low.execute()
    got = np.concatenate([np.asarray(V.data_of(i).newest_copy().value)
                          for i in range(V.mt)])
    np.testing.assert_allclose(got, stencil_reference(base, w, 5),
                               rtol=2e-5, atol=2e-5)
