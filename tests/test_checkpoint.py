"""Checkpoint/restore (SURVEY §5.4 — beyond the reference, which has
none): collections are the whole inter-phase program state, so snapshot +
restore + replay is a complete restart story."""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data.checkpoint import (CheckpointError, restore_collections,
                                        save_collections)
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
from parsec_tpu.runtime import Context


def mk(n=32, nb=8, seed=5, **kw):
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, **kw)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, **kw)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, **kw)
    return a, b, A, B, C


def run_gemm(A, B, C):
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
    ctx.wait(timeout=60)
    ctx.fini()


class TestRoundTrip:
    def test_save_restore(self, tmp_path):
        a, b, A, B, C = mk()
        run_gemm(A, B, C)
        p = str(tmp_path / "ck.npz")
        save_collections(p, C, meta={"phase": 1})
        # clobber, then restore
        for m in range(C.mt):
            for n_ in range(C.nt):
                C.data_of(m, n_).newest_copy().value[:] = -1.0
        meta = restore_collections(p, C)
        assert meta == {"phase": 1}
        np.testing.assert_allclose(C.to_dense(), a @ b, rtol=1e-4,
                                   atol=1e-4)

    def test_crash_resume_equals_uninterrupted(self, tmp_path):
        """Two-phase app: C = A·B then D = C·B.  Checkpoint after phase 1,
        'crash' (fresh collections), restore, run phase 2 — the result must
        equal the uninterrupted run."""
        p = str(tmp_path / "phase1.npz")
        a, b, A, B, C = mk()
        run_gemm(A, B, C)
        save_collections(p, C)
        uninterrupted = TwoDimBlockCyclic("D", 32, 32, 8, 8)
        run_gemm(C, B, uninterrupted)

        # crash: all state lost; rebuild collections, restore phase 1
        a2, b2, A2, B2, C2 = mk()
        restore_collections(p, C2)
        D2 = TwoDimBlockCyclic("D2", 32, 32, 8, 8)
        run_gemm(C2, B2, D2)
        np.testing.assert_allclose(D2.to_dense(), uninterrupted.to_dense(),
                                   rtol=1e-4, atol=1e-4)

    def test_versions_roundtrip(self, tmp_path):
        _, _, A, B, C = mk()
        run_gemm(A, B, C)
        ver = C.data_of(0, 0).newest_copy().version
        p = str(tmp_path / "v.npz")
        save_collections(p, C)
        C.data_of(0, 0).newest_copy().version = 999
        restore_collections(p, C)
        assert C.data_of(0, 0).newest_copy().version == ver


class TestValidation:
    def test_geometry_mismatch_refused(self, tmp_path):
        _, _, A, _, _ = mk()
        p = str(tmp_path / "g.npz")
        save_collections(p, A)
        other = TwoDimBlockCyclic("A", 16, 16, 8, 8)   # smaller grid
        with pytest.raises(CheckpointError, match="geometry"):
            restore_collections(p, other)

    def test_missing_collection_refused(self, tmp_path):
        _, _, A, B, _ = mk()
        p = str(tmp_path / "m.npz")
        save_collections(p, A)
        with pytest.raises(CheckpointError, match="no collection"):
            restore_collections(p, B)


class TestMultiRank:
    def test_per_rank_shards(self, tmp_path):
        """Each rank saves/restores only the tiles it owns."""
        p = str(tmp_path / "dist.npz")

        def body(ctx, rank, nranks):
            a, b, A, B, C = mk(P=2, Q=2, myrank=rank)
            ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
            ctx.wait(timeout=60)
            ctx.comm_barrier()
            out = save_collections(p, C)
            # clobber the owned tiles, restore, verify
            for m in range(C.mt):
                for n_ in range(C.nt):
                    if C.rank_of(m, n_) == rank:
                        C.data_of(m, n_).newest_copy().value[:] = -1.0
            restore_collections(p, C)
            return (out, C.to_dense())

        res = run_multirank(4, body)
        paths = {r[0] for r in res}
        assert len(paths) == 4      # one shard file per rank
        a, b, *_ = mk()
        got = np.zeros((32, 32), np.float32)
        for _, part in res:
            got += part
        np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-4)
