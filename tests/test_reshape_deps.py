"""Reshape/datatype-on-deps (VERDICT r2 item 6).

A dep may declare a TileType (``dtt=`` in the DSL, ``[type=NAME]`` in JDF);
the consumer of that edge observes the datum converted — lazily, shared per
(copy, type), on the read side — while the producer's copy stays untouched.
Covers: local task edges, collection reads, writebacks, the remote receive
path on 2 ranks (the reference's remote_read_reshape shape), and the
compiled-path opt-outs.
"""

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.comm import run_multirank
from parsec_tpu.data.data import TileType
from parsec_tpu.data.datatype import register_layout
from parsec_tpu.data.reshape import needs_reshape, reshaped_future
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
from parsec_tpu.runtime import Context

F32 = np.float32

# a transposed layout: canonical <-> transposed via .T (involution)
register_layout("transposed", lambda x: x.T, lambda x: x.T)

VEC8 = TileType((8,), F32)
MAT24 = TileType((2, 4), F32)
MAT42 = TileType((4, 2), F32)
F64_8 = TileType((8,), np.float64)
TRANS = TileType((4, 2), F32, layout="transposed")


def run_pool(tp):
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.fini()


def coll(name, value):
    v = np.asarray(value, F32)
    return DictCollection(name, dtt=TileType(v.shape, v.dtype),
                          init_fn=lambda *k: v.copy())


class TestLocalEdges:
    def build(self, A, in_dtt=None, out_dtt=None, seen=None):
        """P -> C over one tile; the P->C edge may be typed on either end."""
        p = ptg.PTGBuilder("ty", A=A)
        t = p.task("P", i=ptg.span(0, 0))
        f = t.flow("V", ptg.RW)
        f.input(data=("A", lambda g, l: (0,)))
        f.output(succ=("C", "V", lambda g, l: {"i": 0}), dtt=out_dtt)
        t.body(lambda es, task, g, l: None)
        c = p.task("C", i=ptg.span(0, 0))
        fc = c.flow("V", ptg.READ)
        fc.input(pred=("P", "V", lambda g, l: {"i": 0}), dtt=in_dtt)

        def cbody(es, task, g, l):
            seen.append(np.asarray(task.flow_data("V").value))

        c.body(cbody)
        return p.build()

    def test_out_dep_type_reshapes(self):
        seen = []
        A = coll("A", np.arange(8))
        run_pool(self.build(A, out_dtt=MAT24, seen=seen))
        np.testing.assert_array_equal(seen[0],
                                      np.arange(8, dtype=F32).reshape(2, 4))

    def test_in_dep_type_wins_over_out(self):
        seen = []
        A = coll("A", np.arange(8))
        run_pool(self.build(A, out_dtt=MAT24, in_dtt=MAT42, seen=seen))
        assert seen[0].shape == (4, 2)

    def test_dtype_conversion(self):
        seen = []
        A = coll("A", np.arange(8))
        run_pool(self.build(A, in_dtt=F64_8, seen=seen))
        assert seen[0].dtype == np.float64

    def test_layout_conversion(self):
        seen = []
        A = coll("A", np.arange(8))
        run_pool(self.build(A, in_dtt=TRANS, seen=seen))
        # from_canonical of "transposed" transposes the (4,2) reshape
        np.testing.assert_array_equal(
            seen[0], np.arange(8, dtype=F32).reshape(4, 2).T)

    def test_producer_copy_untouched(self):
        seen = []
        A = coll("A", np.arange(8))
        run_pool(self.build(A, out_dtt=MAT24, seen=seen))
        home = np.asarray(A.data_of(0).newest_copy().value)
        assert home.shape == (8,)   # read-side reshape: source unchanged

    def test_conversion_shared_across_consumers(self):
        """Two typed consumers of one copy share a single conversion."""
        A = coll("A", np.arange(8))
        calls = []
        register_layout("counted",
                        lambda x: x,
                        lambda x: (calls.append(1), x)[1])
        CT = TileType((8,), F32, layout="counted")
        p = ptg.PTGBuilder("sh", A=A)
        t = p.task("P", i=ptg.span(0, 0))
        f = t.flow("V", ptg.RW)
        f.input(data=("A", lambda g, l: (0,)))
        f.output(succ=("C", "V", lambda g, l: {"i": 0}), dtt=CT)
        f.output(succ=("D", "V", lambda g, l: {"i": 0}), dtt=CT)
        t.body(lambda es, task, g, l: None)
        for name in ("C", "D"):
            c = p.task(name, i=ptg.span(0, 0))
            c.flow("V", ptg.READ).input(
                pred=("P", "V", lambda g, l: {"i": 0}))
            c.body(lambda es, task, g, l: None)
        run_pool(p.build())
        assert len(calls) == 1

    def test_collection_read_with_type(self):
        seen = []
        A = coll("A", np.arange(8))
        p = ptg.PTGBuilder("cr", A=A)
        t = p.task("T", i=ptg.span(0, 0))
        t.flow("V", ptg.READ).input(data=("A", lambda g, l: (0,)),
                                    dtt=MAT24)
        t.body(lambda es, task, g, l:
               seen.append(np.asarray(task.flow_data("V").value)))
        run_pool(p.build())
        assert seen[0].shape == (2, 4)

    def test_writeback_with_type(self):
        A = coll("A", np.arange(8))
        B = coll("B", np.zeros((2, 4)))
        p = ptg.PTGBuilder("wb", A=A, B=B)
        t = p.task("T", i=ptg.span(0, 0))
        f = t.flow("V", ptg.RW)
        f.input(data=("A", lambda g, l: (0,)))
        f.output(data=("B", lambda g, l: (0,)), dtt=MAT24)
        t.body(lambda es, task, g, l: None)
        run_pool(p.build())
        got = np.asarray(B.data_of(0).newest_copy().value)
        np.testing.assert_array_equal(got,
                                      np.arange(8, dtype=F32).reshape(2, 4))


class TestRemote:
    def test_remote_read_reshape_on_2_ranks(self):
        """The reference's remote_read_reshape shape: rank 0 produces a
        vector tile; rank 1's consumer declares [type=(2,4)] on its input
        dep and must observe the converted matrix."""

        def body(ctx, rank, nranks):
            A = TwoDimBlockCyclic("A8", lm=2 * 8, ln=1, mb=8, nb=1,
                                  P=2, Q=1, myrank=rank,
                                  init_fn=lambda m, n, sh:
                                  np.arange(8, dtype=F32).reshape(sh)
                                  if sh == (8, 1) else np.zeros(sh, F32))
            seen = []
            p = ptg.PTGBuilder("rr", A=A)
            t = p.task("P", i=ptg.span(0, 0))
            t.affinity("A", lambda g, l: (0, 0))
            f = t.flow("V", ptg.RW)
            f.input(data=("A", lambda g, l: (0, 0)))
            f.output(succ=("C", "V", lambda g, l: {"i": 0}))
            t.body(lambda es, task, g, l: None)
            c = p.task("C", i=ptg.span(0, 0))
            c.affinity("A", lambda g, l: (1, 0))   # lives on rank 1
            c.flow("V", ptg.READ).input(
                pred=("P", "V", lambda g, l: {"i": 0}),
                dtt=TileType((2, 4), F32))
            c.body(lambda es, task, g, l:
                   seen.append(np.asarray(task.flow_data("V").value)))
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=60)
            ctx.comm_barrier()
            return seen[0] if seen else None

        res = run_multirank(2, body)
        assert res[0] is None          # consumer ran on rank 1 only
        assert res[1].shape == (2, 4)
        np.testing.assert_array_equal(
            res[1], np.arange(8, dtype=F32).reshape(2, 4))


class TestJDF:
    def test_jdf_type_property(self):
        from parsec_tpu.ptg.jdf import parse_jdf
        src = """
        A   [type = data]
        B   [type = data]
        M24 [type = int]

        T(i)
          i = 0 .. 0
          : A(0)
          RW V <- A(0)
               -> B(0) [type = M24]
        BODY
          pass
        END
        """
        A = coll("A", np.arange(8))
        B = coll("B", np.zeros((2, 4)))
        tp = parse_jdf(src, "ty").build(A=A, B=B, M24=MAT24)
        run_pool(tp)
        got = np.asarray(B.data_of(0).newest_copy().value)
        np.testing.assert_array_equal(got,
                                      np.arange(8, dtype=F32).reshape(2, 4))

    def test_jdf_type_must_be_tiletype(self):
        from parsec_tpu.ptg.jdf import JDFError, parse_jdf
        src = """
        A  [type = data]
        X  [type = int]

        T(i)
          i = 0 .. 0
          : A(0)
          RW V <- A(0)
               -> A(0) [type = X]
        BODY
          pass
        END
        """
        with pytest.raises(JDFError):
            parse_jdf(src, "bad").build(A=coll("A", np.arange(8)), X=7)


class TestOptOuts:
    def mk(self):
        A = coll("A", np.arange(8))
        p = ptg.PTGBuilder("oo", A=A)
        t = p.task("P", i=ptg.span(0, 0))
        f = t.flow("V", ptg.RW)
        f.input(data=("A", lambda g, l: (0,)))
        f.output(succ=("C", "V", lambda g, l: {"i": 0}), dtt=MAT24)
        t.body(lambda es, task, g, l: None)
        c = p.task("C", i=ptg.span(0, 0))
        c.flow("V", ptg.READ).input(pred=("P", "V", lambda g, l: {"i": 0}))
        c.body(lambda es, task, g, l: None)
        return p.build()

    def test_compiled_dag_falls_back(self):
        from parsec_tpu.runtime.dagrun import compile_taskpool_dag
        ctx = Context(nb_cores=0)
        assert compile_taskpool_dag(self.mk(), ctx) is None
        ctx.fini()

    def test_lowering_refuses_typed_edges(self):
        from parsec_tpu.ptg.lowering import LoweringError, lower_taskpool
        with pytest.raises(LoweringError):
            lower_taskpool(self.mk())

    def test_cache_invalidated_on_version_bump(self):
        """A writeback mutates the home copy in place; a later typed read
        must convert the NEW value, not serve the stale cached repack."""
        A = coll("A", np.arange(8))
        copy = A.data_of(0).newest_copy()
        first = reshaped_future(copy, MAT24).get()
        np.testing.assert_array_equal(np.asarray(first.value).ravel(),
                                      np.arange(8, dtype=F32))
        copy.value = np.arange(100, 108, dtype=F32)
        copy.version += 1
        second = reshaped_future(copy, MAT24).get()
        np.testing.assert_array_equal(np.asarray(second.value).ravel(),
                                      np.arange(100, 108, dtype=F32))

    def test_untyped_writeback_restores_home_type(self):
        """A flow whose INPUT was reshaped must not write the converted
        shape back through an untyped output arrow."""
        A = coll("A", np.arange(8))
        p = ptg.PTGBuilder("uwb", A=A)
        t = p.task("T", i=ptg.span(0, 0))
        f = t.flow("V", ptg.RW)
        f.input(data=("A", lambda g, l: (0,)), dtt=MAT24)
        f.output(data=("A", lambda g, l: (0,)))   # untyped writeback

        def body(es, task, g, l):
            v = task.flow_data("V")
            assert np.asarray(v.value).shape == (2, 4)
            v.value = np.asarray(v.value) + 100
            v.version += 1

        t.body(body)
        run_pool(p.build())
        home = np.asarray(A.data_of(0).newest_copy().value)
        assert home.shape == (8,)   # home type restored
        np.testing.assert_array_equal(home,
                                      np.arange(8, dtype=F32) + 100)

    def test_helpers(self):
        A = coll("A", np.arange(8))
        copy = A.data_of(0).newest_copy()
        assert not needs_reshape(copy, None)
        assert not needs_reshape(copy, VEC8)
        assert needs_reshape(copy, MAT24)
        f1 = reshaped_future(copy, MAT24)
        f2 = reshaped_future(copy, MAT24)
        assert f1 is f2                      # shared per (copy, type)
        out = f1.get()
        assert np.asarray(out.value).shape == (2, 4)
