"""Offline trace tooling (VERDICT r4 item 9): the dbpinfos-role stats
CLI (``python -m parsec_tpu.prof.info``) and the parsec-dotmerger-role
multi-rank DOT merger (``python -m parsec_tpu.prof.dotmerge``), both run
against artifacts a REAL 2-process multirank run produced."""

import os
import subprocess
import sys

import pytest

from parsec_tpu.comm.multiproc import run_multiproc

BODIES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mp_bodies.py")


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory):
    """One 2-process run, per-rank .prof + .dot artifacts shared by the
    tool tests."""
    d = tmp_path_factory.mktemp("traces")
    os.environ["PARSEC_TEST_TRACE_DIR"] = str(d)
    try:
        res = run_multiproc(2, f"{BODIES}:traced_chain_body", timeout=120)
    finally:
        os.environ.pop("PARSEC_TEST_TRACE_DIR", None)
    assert res == [True, True]
    for r in range(2):
        assert (d / f"rank{r}.prof").exists()
        assert (d / f"rank{r}.dot").exists()
    return d


def test_info_cli_summarizes_multirank_traces(trace_dir):
    p = subprocess.run(
        [sys.executable, "-m", "parsec_tpu.prof.info", "--validate",
         str(trace_dir / "rank0.prof"), str(trace_dir / "rank1.prof")],
        capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr[-1500:]
    out = p.stdout
    assert "rank0.prof" in out and "rank1.prof" in out
    assert "task_exec" in out
    assert "VALIDATION: ok" in out
    # stats columns present
    assert "count" in out and "mean" in out


def test_info_chrome_export_flag(trace_dir, tmp_path):
    import json
    out = tmp_path / "trace.json"
    p = subprocess.run(
        [sys.executable, "-m", "parsec_tpu.prof.info", "--chrome",
         str(out), str(trace_dir / "rank0.prof")],
        capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr[-1500:]
    trace = json.loads(out.read_text())
    assert any(ev.get("ph") == "X" for ev in trace["traceEvents"])


def test_info_summarize_returns_stats(trace_dir):
    from parsec_tpu.prof.info import summarize
    import io
    buf = io.StringIO()
    res = summarize(str(trace_dir / "rank0.prof"), out=buf, validate=True)
    assert res["problems"] == []
    st = res["classes"]["task_exec"]
    assert st["count"] > 0 and st["total_ns"] > 0
    assert st["min_ns"] <= st["max_ns"]


def test_dotmerge_cli_unions_ranks_and_marks_cross_edges(trace_dir):
    merged = trace_dir / "merged.dot"
    p = subprocess.run(
        [sys.executable, "-m", "parsec_tpu.prof.dotmerge",
         str(trace_dir / "rank0.dot"), str(trace_dir / "rank1.dot"),
         "-o", str(merged)],
        capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr[-1500:]
    from parsec_tpu.prof.dotmerge import parse_dot
    nodes, edges = parse_dot(merged.read_text())
    # the chain has 2*nranks tasks, each executed on exactly one rank
    assert len(nodes) == 4
    ranks = {attrs["ranks"] for attrs in nodes.values()}
    assert ranks == {"0", "1"}              # both ranks contributed
    # chain edges T_i -> T_{i+1}: the rank-boundary hops are cross-rank
    cross = [(s, d) for (s, d, _l), a in edges.items()
             if a.get("style") == "dashed"]
    assert len(cross) >= 1, edges
    # per-rank fragments only see their local halves; the union restores
    # the full chain order
    assert len(edges) >= 3


def test_dotmerge_parse_round_trip(tmp_path):
    """The parser consumes exactly what the grapher emits — including
    PARALLEL edges (one per flow between the same task pair), which are
    distinct dependencies and must both survive the merge."""
    from parsec_tpu.prof.dotmerge import parse_dot, write_merged
    src = tmp_path / "one.dot"
    src.write_text('digraph dag {\n'
                   '  "A_1" [label="A(1)" color="#e6194b"];\n'
                   '  "B_1" [label="B(1)" color="#3cb44b"];\n'
                   '  "A_1" -> "B_1" [label="X"];\n'
                   '  "A_1" -> "B_1" [label="Y"];\n'
                   '}\n')
    stats = write_merged([str(src)], str(tmp_path / "out.dot"))
    assert stats == {"nodes": 2, "edges": 2, "cross_rank_edges": 0}
    nodes, edges = parse_dot((tmp_path / "out.dot").read_text())
    assert nodes["A_1"]["label"] == "A(1)"
    assert nodes["A_1"]["ranks"] == "0"
    assert ("A_1", "B_1", "X") in edges and ("A_1", "B_1", "Y") in edges


def test_dotmerge_rank_tag_from_filename(tmp_path):
    """Shell globs sort rank10 before rank2: the rank tag must come from
    the filename, not the argv position."""
    from parsec_tpu.prof.dotmerge import merge
    for r in (10, 2):
        (tmp_path / f"rank{r}.dot").write_text(
            f'digraph d {{\n  "T_{r}" [label="T({r})"];\n}}\n')
    nodes, _ = merge([str(tmp_path / "rank10.dot"),
                      str(tmp_path / "rank2.dot")])
    assert nodes["T_10"]["ranks"] == "10"
    assert nodes["T_2"]["ranks"] == "2"
