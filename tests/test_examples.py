"""The Ex00–Ex12 examples ladder is living documentation: every script
must keep running and self-checking (reference examples/ + SURVEY §2.11;
Ex11 is the serving-layer demo, parsec_tpu/serve/; Ex12 the LLM
continuous-batching demo, parsec_tpu/llm/)."""

import importlib.util
import pathlib

import pytest

EXAMPLES = sorted(
    (pathlib.Path(__file__).parent.parent / "examples").glob("Ex*.py"))


def load(path):
    spec = importlib.util.spec_from_file_location(path.stem, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ladder_is_complete():
    assert [p.stem.split("_")[0] for p in EXAMPLES] == \
        [f"Ex{i:02d}" for i in range(13)]


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: p.stem)
def test_example_runs(path, param):
    # analysis_check=1: every taskpool any example enqueues (including the
    # multirank and serving ones) passes static verification on the way in
    # (analysis.graphcheck — the ISSUE 5 examples gate), so the ladder run
    # doubles as the graph-correctness sweep
    import parsec_tpu.runtime.context  # noqa: F401 — registers the param
    param("analysis_check", 1)
    mod = load(path)
    mod.main()   # every example self-checks and raises on failure
