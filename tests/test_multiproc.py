"""Multi-process ranks over the TCP socket fabric (the DCN tier): the same
remote-dep protocol the inproc/device tests exercise, but across genuinely
separate interpreters — the mpiexec-analog deployment shape."""

import pathlib

import numpy as np
import pytest

from parsec_tpu.comm.multiproc import run_multiproc

BODIES = str(pathlib.Path(__file__).parent / "mp_bodies.py")


@pytest.mark.parametrize("nranks", [2, 3])
def test_chain_across_processes(nranks):
    res = run_multiproc(nranks, f"{BODIES}:chain_body", timeout=120)
    assert res[0] == 2 * nranks
    assert res[1:] == [None] * (nranks - 1)


def test_gemm_across_processes():
    nranks = 4
    res = run_multiproc(nranks, f"{BODIES}:gemm_body", timeout=180)
    n = 64
    rng = np.random.RandomState(23)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    got = np.zeros((n, n), np.float32)
    for part in res:
        got += part
    np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-4)


def test_device_transport_bcast_and_gemm():
    """The deployable tier (VERDICT r3 missing #1): 4 subprocess ranks,
    each binding one JAX device, broadcast + 2-D block-cyclic GEMM with
    payloads moving through the device-resident GET path — and the bytes
    accounted per tier."""
    nranks = 4
    res = run_multiproc(nranks, f"{BODIES}:device_bcast_gemm_body",
                        timeout=240, transport="device")
    expect = float(np.arange(4096, dtype=np.float32).sum())
    assert [r["bsum"] for r in res] == [expect] * nranks
    n = 64
    rng = np.random.RandomState(23)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    got = np.zeros((n, n), np.float32)
    for part in res:
        got += part["C"]
    np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-4)
    # traffic accounting: the broadcast root served device payloads D2H,
    # every non-root rank landed payloads H2D, and control traffic remains
    # distinct from the payload tier
    tiers = [r["tiers"] for r in res]
    assert tiers[0]["payload_out"] > 0
    assert all(t["payload_in"] > 0 for t in tiers[1:])
    assert all(t["wire_total_sent"] >= t["payload_out"] for t in tiers)
    assert all(t["control_sent"] > 0 for t in tiers)


def test_distributed_bootstrap_two_process_localhost():
    """VERDICT r4 item 6: maybe_init_distributed executed for real — a
    coordinator on 127.0.0.1, 2 CPU processes, jax.distributed live in
    each (process_count == 2 asserted in-rank), Ex05 broadcast +
    block-cyclic GEMM riding DeviceSocketCommEngine on top."""
    nranks = 2
    res = run_multiproc(nranks, f"{BODIES}:distributed_bootstrap_body",
                        timeout=240, transport="device", distributed=True)
    assert [r["process_count"] for r in res] == [nranks] * nranks
    expect = float(np.arange(4096, dtype=np.float32).sum())
    assert [r["bsum"] for r in res] == [expect] * nranks
    n = 64
    rng = np.random.RandomState(23)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    got = np.zeros((n, n), np.float32)
    for part in res:
        got += part["C"]
    np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-4)


def test_failed_rank_surfaces():
    with pytest.raises((RuntimeError, TimeoutError)):
        run_multiproc(2, f"{BODIES}:no_such_body", timeout=60)
