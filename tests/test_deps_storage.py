"""Dep-storage variants (VERDICT r4 missing #5): the hashed tier
(``parsec_hash_find_deps``) vs the index-array tier
(``parsec_default_find_deps`` / ``-M index-array``) — correctness under
both, plus the measurement the fold-in claim needs: on a dense space,
the hashed default is not meaningfully slower than direct indexing."""

import time

import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.runtime import Context

import parsec_tpu.runtime.dagrun  # noqa: F401  registers runtime_dag_compile


def _ep_pool(NT=40, DEPTH=25):
    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l: None)
    return p.build()


def _drain_ep(param, storage, native, NT=40, DEPTH=25):
    param("deps_storage", storage)
    param("runtime_native", native)
    param("runtime_dag_compile", False)   # exercise release_dep itself
    ctx = Context(nb_cores=0)
    tp = _ep_pool(NT, DEPTH)
    t0 = time.perf_counter()
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    dt = time.perf_counter() - t0
    ctx.fini()
    return dt


def test_index_array_tier_selected_for_static_boxes(param):
    param("deps_storage", "index-array")
    param("runtime_dag_compile", False)
    ctx = Context(nb_cores=0)
    assert ctx.deps._index_store is not None
    tp = _ep_pool(8, 6)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    store = ctx.deps._index_store
    # the tier genuinely engaged: one dense array allocated for the EP
    # class, every non-startup task's dep released through it, and the
    # array purged at taskpool termination
    assert store.allocated == 1, "index-array tier never engaged"
    assert store.releases == 8 * (6 - 1)      # DEPTH-1 arrivals per lane
    assert not store._arrays                   # purged at termination
    ctx.fini()


def test_space_extents_captured_for_static_ranges():
    tp = _ep_pool(8, 6)
    tc = tp.task_class("EP")
    assert tc.space_extents == ((0, 6), (0, 8))


def test_gemm_numerics_identical_under_index_array(param):
    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg

    param("deps_storage", "index-array")
    rng = np.random.default_rng(31)
    a = rng.standard_normal((48, 48)).astype(np.float32)
    b = rng.standard_normal((48, 48)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a, 16, 16)
    B = TiledMatrix.from_dense("B", b, 16, 16)
    C = TiledMatrix.from_dense("C", np.zeros((48, 48), np.float32), 16, 16)
    ctx = Context(nb_cores=2)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
    ctx.wait(timeout=60)
    ctx.fini()
    np.testing.assert_allclose(C.to_dense(), a @ b, rtol=1e-4, atol=1e-4)


def test_hashed_fold_in_costs_nothing_on_dense_spaces(param):
    """The measurement itself: drain the same 3000-task dense EP grid
    under direct indexing and under the hashed Python tier.  The claim
    ('folding index-array into the hashed interface costs nothing') holds
    if the hashed drain is within noise of the indexed one — the loose
    2.5x bound keeps CI timing-safe while still catching a real
    asymptotic regression (a hash-cost blowup reads as 10x+)."""
    times = {}
    for storage in ("index-array", "hash"):
        best = min(_drain_ep(param, storage, native=False)
                   for _ in range(3))
        times[storage] = best
    print(f"\n[deps-storage] dense EP drain: "
          f"indexed={times['index-array'] * 1e3:.1f}ms "
          f"hashed={times['hash'] * 1e3:.1f}ms "
          f"ratio={times['hash'] / times['index-array']:.2f}x")
    assert times["hash"] <= times["index-array"] * 2.5 + 0.05, times


def test_triangular_space_falls_back_cleanly(param):
    """A class whose ranges depend on earlier params has no static box:
    the index-array tier must fall back to the hashed tier, silently."""
    param("deps_storage", "index-array")
    param("runtime_dag_compile", False)
    done = []
    p = ptg.PTGBuilder("tri", N=6)
    t = p.task("T",
               i=ptg.span(0, lambda g, l: g.N - 1),
               j=ptg.span(0, lambda g, l: l.i))    # triangular
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("T", "ctl", lambda g, l: {"i": l.i - 1, "j": l.j}),
            guard=lambda g, l: l.i > 0 and l.j <= l.i - 1)
    f.output(succ=("T", "ctl", lambda g, l: {"i": l.i + 1, "j": l.j}),
             guard=lambda g, l: l.i < g.N - 1)
    t.body(lambda es, task, g, l: done.append((l.i, l.j)))
    tp = p.build()
    assert tp.task_class("T").space_extents is None
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.fini()
    assert len(done) == 6 * 7 // 2


def test_oversized_static_box_falls_back_to_hashed_tier(param):
    """A static box bigger than deps_index_array_max_slots must NOT be
    materialized densely (gigabytes of empty slots for a mostly-empty
    space) — the class silently takes the hashed tier instead."""
    param("deps_storage", "index-array")
    param("deps_index_array_max_slots", 16)   # force the guard
    param("runtime_dag_compile", False)
    ctx = Context(nb_cores=0)
    store = ctx.deps._index_store
    assert store is not None
    tp = _ep_pool(8, 6)          # box volume 48 > 16
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    assert store.allocated == 0, "dense array allocated despite the cap"
    assert store.releases == 0
    ctx.fini()
