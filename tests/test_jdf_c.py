"""C-syntax JDF ingestion: the reference's OWN .jdf files, converted
mechanically and executed (bodies supplied in Python — structure, spaces,
guards, ranges, and arrows come straight from the reference text).
"""

import pathlib

import numpy as np
import pytest

from parsec_tpu.data.datatype import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
from parsec_tpu.ptg.jdf_c import convert_expr, load_c_jdf
from parsec_tpu.runtime import Context

REF = pathlib.Path("/root/reference")
needs_ref = pytest.mark.skipif(not REF.exists(),
                               reason="reference tree not available")


# ---------------------------------------------------------------------------
# expression conversion
# ---------------------------------------------------------------------------

def test_convert_expr():
    assert convert_expr("a && b || !c") == "a and b or not c"
    assert convert_expr("x != 1 && !y") == "x != 1 and not y"
    assert convert_expr("descA->lmt - 1") == "descA.mt - 1"
    assert convert_expr("descA->super.myrank") == "descA.myrank"
    assert convert_expr("l/2 + k%3") == "l//2 + k%3"
    assert convert_expr("(1<<n)-1") == "(1<<n)-1"


def test_convert_expr_float_math_keeps_true_division():
    """C's '/' on doubles is float division: an expression doing float
    math must NOT get the integral-index '//' rewrite — flooring
    log(mt)/log(2.0) would drop a reduction-tree level at every
    power-of-two size (the reduce_col.jdf depth default)."""
    got = convert_expr("(int)ceil(log(src->mt) / log(2.0))")
    assert got == "int(ceil(log(src.mt) / log(2.0)))"
    import math
    for mt, want in ((8, 3), (64, 6), (128, 7)):
        env = {"src": type("S", (), {"mt": mt})(), "ceil": math.ceil,
               "log": math.log, "int": int}
        assert eval(got, env) == want
    # pure index math still floors
    assert convert_expr("(m+1)/2") == "(m+1)//2"


def test_line_comments_stripped_outside_strings():
    """A '//' inside a C string literal is not a comment; one outside
    is.  A mangled printf would knock the whole body out of the
    mechanical subset and silently drop its dataflow writes."""
    from parsec_tpu.ptg.jdf_c import _strip_line_comments, convert_c_body
    s = _strip_line_comments('x = 1; // gone\ny = "kept // inside";')
    assert s == 'x = 1; \ny = "kept // inside";'
    # the pipeline strips comments before body conversion: a printf
    # containing '//' must survive the strip and the body still convert
    got = convert_c_body(_strip_line_comments(
        '{ int *A0 = (int*)A;\n'
        '  printf("a // b\\n", k);  // trailing\n'
        '  *A0 = k+1; }'))
    assert got is not None and "A0[0] = k+1" in got
    assert 'a // b' in got          # the format string rode through


# ---------------------------------------------------------------------------
# the reference's own files
# ---------------------------------------------------------------------------

@needs_ref
def test_ex02_chain_runs():
    """examples/Ex02_Chain.jdf: NEW-rooted chain of NB+1 tasks; the C
    body (*A += 1) becomes a Python body; taskdist is declared only in
    the C epilogue and gets synthesized as a data global."""
    jdf = load_c_jdf(REF / "examples" / "Ex02_Chain.jdf", bodies={
        "Task": "A[...] = 0 if k == 0 else A[...] + 1",
    })
    NB = 9
    taskdist = DictCollection("taskdist",
                              dtt=TileType((1,), np.int32),
                              init_fn=lambda *k: np.zeros(1, np.int32))
    tp = jdf.build(taskdist=taskdist, NB=NB,
                   DTT_DEFAULT=TileType((1,), np.int32))
    done = {}

    # capture the final chain value through an extra probe body wrap:
    # simplest is to re-run with a recording body
    jdf2 = load_c_jdf(REF / "examples" / "Ex02_Chain.jdf", bodies={
        "Task": "A[...] = 0 if k == 0 else A[...] + 1\n"
                "out[k] = int(A[0])",
    })
    out = {}
    tp2 = jdf2.build(taskdist=taskdist, NB=NB,
                     DTT_DEFAULT=TileType((1,), np.int32))
    tp2._builder.globals["out"] = out
    jdf2.globals_decl["out"] = {}      # visible to bodies via globals
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp2)
        ctx.wait(timeout=60)
    assert out[NB] == NB               # 0 at k=0, +1 per link


def test_convert_c_body_subset():
    from parsec_tpu.ptg.jdf_c import convert_c_body
    got = convert_c_body("""{
        int *Aint = (int*)A;
        if ( k == 0 ) { *Aint = 0; } else { *Aint += 1; }
        printf("[%d] %d\\n", rank, *Aint);
    }""")
    assert got.splitlines() == [
        "Aint = A",
        "if k == 0:",
        "    Aint[0] = 0",
        "else:",
        "    Aint[0] += 1",
        'pass  # printf("[%d] %d\\n", rank, *Aint)',
    ]
    # outside the subset -> None (caller falls back to pass/override):
    # calls, loops, RHS calls, C ternaries, expression statements
    assert convert_c_body("{ memcpy(A0, AL, n); }") is None
    assert convert_c_body("{ for(i=0;i<n;i++) x+=i; }") is None
    assert convert_c_body("{ int *A0 = (int*)A; *A0 = rand(); }") is None
    assert convert_c_body(
        "{ int *A0 = (int*)A; *A0 = (k==0) ? 1 : 2; }") is None
    assert convert_c_body("{ x == 0; }") is None
    # comment-only / empty bodies are runnable no-ops
    assert convert_c_body("") == "pass"


@needs_ref
def test_ex02_c_body_runs_verbatim():
    """Ex02_Chain.jdf with NO body override: the C body (pointer alias,
    if/else, deref assignment, printf) converts mechanically and the
    chain computes the same values the hand-written Python body did."""
    jdf = load_c_jdf(REF / "examples" / "Ex02_Chain.jdf")
    NB = 9
    taskdist = DictCollection("taskdist",
                              dtt=TileType((1,), np.int32),
                              init_fn=lambda *k: np.zeros(1, np.int32))
    tp = jdf.build(taskdist=taskdist, NB=NB,
                   DTT_DEFAULT=TileType((1,), np.int32))
    # probe the final chain value: wrap the last task's completion
    final = {}
    tc = tp.task_class("Task")
    orig = tc.complete_execution

    def probe(es, task):
        if task.locals["k"] == NB:
            final["v"] = int(np.asarray(
                task.data[0].value)[0])
        if orig is not None:
            orig(es, task)

    tc.complete_execution = probe
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert final["v"] == NB            # 0 at k=0, +1 per link


@needs_ref
def test_ex07_c_bodies_run_verbatim():
    """Ex07_RAW_CTL.jdf with NO body overrides: all three C bodies
    (send k+1, recv printf-only, update -k-1) convert mechanically;
    the final collection state matches the reference semantics."""
    jdf = load_c_jdf(REF / "examples" / "Ex07_RAW_CTL.jdf")
    nodes = 4
    md = VectorTwoDimCyclic("mydata", lm=nodes + 7, mb=1, dtype=np.int32,
                            init_fn=lambda m, s: np.zeros(s, np.int32))
    tp = jdf.build(mydata=md, nodes=nodes, rank=0)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    for k in range(nodes):
        assert int(np.asarray(md.data_of(k).newest_copy().value)[0]) \
            == -k - 1


@needs_ref
def test_reference_jdf_parse_coverage():
    """The converter swallows a broad slice of the reference's own .jdf
    corpus: multi-line ternaries (ep.jdf's else on its own line,
    reduce_col's guard/then/else on three), // line comments, multi-line
    global declarations with C-math defaults, CUDA-era files."""
    expected = {
        "tests/runtime/scheduling/ep.jdf": {"INIT", "TASK"},
        "tests/runtime/multichain.jdf": {"HORIZONTAL", "VERTICAL"},
        "tests/runtime/cuda/stress.jdf":
            {"DISCARD_C", "GEMM", "MAKE_C", "READ_A"},
        "tests/dsl/ptg/complex_deps.jdf":
            {"FCT1", "FCT2", "FCT3", "FCT4", "FCT5"},
        "tests/dsl/ptg/controlgather/ctlgat.jdf": {"TA", "TB", "TC"},
        "parsec/data_dist/matrix/reduce_col.jdf":
            {"reduce_col", "reduce_in_col"},
        "parsec/data_dist/matrix/reduce_row.jdf":
            {"reduce_in_row", "reduce_row"},
        "parsec/data_dist/matrix/apply.jdf":
            {"APPLY_DIAG", "APPLY_L", "APPLY_U"},
        "parsec/data_dist/matrix/broadcast.jdf": {"recv", "send"},
        "examples/Ex01_HelloWorld.jdf": {"HelloWorld"},
        "examples/Ex04_ChainData.jdf": {"Task"},
    }
    for rel, tasks in expected.items():
        jdf = load_c_jdf(REF / rel)
        assert set(jdf.tasks) == tasks, rel


@needs_ref
def test_ep_scheduling_benchmark_runs_verbatim():
    """tests/runtime/scheduling/ep.jdf — the shape behind the
    reference's dispatch benchmark AND this repo's bench_dispatch_us —
    ingests and drains verbatim (empty C bodies auto-convert; the
    multi-line ternary else-branch merges)."""
    from parsec_tpu.data_dist.collection import DictCollection
    jdf = load_c_jdf(REF / "tests" / "runtime" / "scheduling" / "ep.jdf")
    A = DictCollection("A", dtt=TileType((1,), np.float32),
                       init_fn=lambda *k: np.zeros(1, np.float32))
    NT, DEPTH = 20, 15
    done = {"n": 0}
    tp = jdf.build(A=A, NT=NT, DEPTH=DEPTH)
    tc = tp.task_class("TASK")
    orig = tc.complete_execution

    def count(es, task):
        done["n"] += 1
        if orig is not None:
            orig(es, task)

    tc.complete_execution = count
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert done["n"] == NT * DEPTH


@needs_ref
def test_rtt_pingpong_runs():
    """tests/apps/pingpong/rtt.jdf VERBATIM: the `(k < NT) ? T PING(k+1)`
    arrow leaves the execution space at k = NT-1 and relies on the
    generated bounds check — the runtime's space-membership drop."""
    jdf = load_c_jdf(REF / "tests" / "apps" / "pingpong" / "rtt.jdf",
                     bodies={"PING": "T[...] += 1.0"})
    NT = 12
    A = VectorTwoDimCyclic("A", lm=1, mb=1,
                           init_fn=lambda m, s: np.zeros(s, np.float32))
    tp = jdf.build(A=A, NT=NT, WS=1)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert float(np.asarray(A.data_of(0).newest_copy().value)[0]) == NT


@needs_ref
def test_ex05_broadcast_runs():
    """examples/Ex05_Broadcast.jdf verbatim: range arrow fan-out, the
    hidden default NB=(6), derived local loc."""
    jdf = load_c_jdf(REF / "examples" / "Ex05_Broadcast.jdf", bodies={
        "TaskBcast": "A[...] = k",
        "TaskRecv": "assert int(A[0]) == k, (k, n)",
    })
    nodes = 3
    md = VectorTwoDimCyclic("mydata", lm=nodes + 7, mb=1, dtype=np.int32,
                            init_fn=lambda m, s: np.zeros(s, np.int32))
    tp = jdf.build(mydata=md, nodes=nodes, rank=0)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)      # Recv assertions are the check


@needs_ref
def test_ex07_raw_ctl_runs():
    """examples/Ex07_RAW_CTL.jdf verbatim: the counted CTL fan-in
    (`<- ctl TaskRecv(k, 0 .. NB .. 2)`) orders updates after reads."""
    jdf = load_c_jdf(REF / "examples" / "Ex07_RAW_CTL.jdf", bodies={
        "TaskBcast": "A[...] = k + 1",
        "TaskRecv": "assert int(A[0]) == k + 1, (k, n)",
        "TaskUpdate": "A[...] = -k - 1",
    })
    nodes = 4
    md = VectorTwoDimCyclic("mydata", lm=nodes + 7, mb=1, dtype=np.int32,
                            init_fn=lambda m, s: np.zeros(s, np.int32))
    tp = jdf.build(mydata=md, nodes=nodes, rank=0)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    for k in range(nodes):
        assert int(np.asarray(md.data_of(k).newest_copy().value)[0]) \
            == -k - 1


def test_read_chain_resolution_through_another_task():
    """A reciprocal-less input that references ANOTHER task's READ flow
    resolves through that flow's own chain (the recursive branch of the
    fixpoint), and an RW source flow is never resolved (a missing
    reciprocal on an RW flow is a real dataflow break, not a read
    chain)."""
    from parsec_tpu.ptg.jdf import parse_jdf
    from parsec_tpu.ptg.jdf_c import resolve_read_chains

    src = """
D  [type = data]
N  [type = int]

GEN(i)
  i = 0 .. N-1
  : D(i)
  READ A <- (i == 0) ? D(0) : A GEN(i-1)
BODY
  pass
END

USE(i)
  i = 0 .. N-1
  : D(i)
  READ X <- A GEN(i)
BODY
  pass
END
"""
    jdf = parse_jdf(src, "chain")
    notes = resolve_read_chains(jdf)
    # GEN's self chain resolves (base args invariant), and USE's
    # reciprocal-less reference resolves through it
    assert sorted(notes) == [
        "GEN.A <- GEN.A resolved to D(0)",
        "USE.X <- GEN.A resolved to D(0)",
    ]
    (use_in,) = [a for f in jdf.tasks["USE"].flows for a in f.arrows]
    assert use_in.then_tgt == ("data", "D", None, "0")


@needs_ref
def test_a2a_read_chain_is_resolved():
    """The FANOUT round chain (`<- A FANOUT(r-1, t)`, a2a.jdf:58) has no
    reciprocal output arrow — jdf2c forwards such read chains to their
    data origin during its symbolic dataflow pass; resolve_read_chains
    is the post-parse analog.  The else branch must land on descA
    directly, and every OTHER arrow (all reciprocated) stays intact."""
    jdf = load_c_jdf(REF / "tests" / "apps" / "all2all" / "a2a.jdf")
    assert jdf.read_chain_notes == [
        "FANOUT.A <- FANOUT.A resolved to descA(t, 0)"]
    fo = jdf.tasks["FANOUT"]
    (arrow,) = [a for f in fo.flows for a in f.arrows
                if a.direction == "in"]
    assert arrow.else_tgt == ("data", "descA", None, "t, 0")
    # READER_B's round chain has the reciprocal `-> B READER_B(r+1, t)`
    # and must NOT be rewritten
    rb = jdf.tasks["READER_B"]
    (rb_in,) = [a for f in rb.flows for a in f.arrows
                if a.direction == "in"]
    assert rb_in.else_tgt[0] == "task"


@needs_ref
def test_a2a_all_rounds_run_verbatim():
    """tests/apps/all2all/a2a.jdf at NR=3: the ingested file drains ALL
    rounds (VERDICT r4 item 4 — this was a single-round skip), and the
    exchange it performs matches the rebuilt ``all2all_ptg``: every
    RECV(r, s, t) carries descA tile t, so the per-destination
    accumulation equals the B-delta all2all_ptg produces,
    ``NR * sum_t A(t)``."""
    from parsec_tpu.models.irregular import all2all_ptg

    NR, NT = 3, 3
    a_vals = {t: float(t + 1) for t in range(NT)}
    counts = {"READER_B": 0, "FANOUT": 0, "SEND": 0, "RECV": 0,
              "FANIN": 0}
    acc = np.zeros(NT, np.float64)   # ingested RECV accumulation by s

    jdf = load_c_jdf(
        REF / "tests" / "apps" / "all2all" / "a2a.jdf",
        bodies={
            "READER_B": "counts['READER_B'] += 1",
            "FANOUT": "counts['FANOUT'] += 1",
            "SEND": "counts['SEND'] += 1",
            "RECV": ("counts['RECV'] += 1\n"
                     "acc[s] += float(np.asarray(B)[0])"),
            "FANIN": "counts['FANIN'] += 1",
        })

    def mk(nm, vals):
        return DictCollection(
            nm, dtt=TileType((1,), np.float32),
            init_fn=lambda *k: np.full(1, vals.get(k[0], 0.0),
                                       np.float32))

    # instrumentation reaches the bodies as extra pool globals (bodies
    # see vars(g), like any JDF global)
    for extra in ("counts", "acc", "np"):
        jdf.globals_decl.setdefault(extra, {"type": "object"})
    tp = jdf.build(descA=mk("descA", a_vals), descB=mk("descB", {}),
                   NR=NR, NT=NT, counts=counts, acc=acc, np=np)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    assert counts == {"READER_B": NR * NT, "FANOUT": NR * NT,
                      "SEND": NR * NT * NT, "RECV": NR * NT * NT,
                      "FANIN": NR * NT}
    # equivalence with the rebuilt app: all2all_ptg leaves
    # B(s) = B0(s) + NR * sum_t A(t)
    mkv = lambda nm, fill: VectorTwoDimCyclic(
        nm, lm=NT, mb=1, dtype=np.float32,
        init_fn=lambda m, s: np.full(s, fill(m), np.float32))
    A2, B2 = mkv("A", lambda m: a_vals[m]), mkv("B", lambda m: 0.0)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(all2all_ptg(A2, B2, NR))
        ctx.wait(timeout=120)
    for s in range(NT):
        want = float(np.asarray(B2.data_of(s).newest_copy().value)[0])
        assert acc[s] == pytest.approx(want), (s, acc[s], want)
