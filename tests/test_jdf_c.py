"""C-syntax JDF ingestion: the reference's OWN .jdf files, converted
mechanically and executed (bodies supplied in Python — structure, spaces,
guards, ranges, and arrows come straight from the reference text).
"""

import pathlib

import numpy as np
import pytest

from parsec_tpu.data.datatype import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
from parsec_tpu.ptg.jdf_c import convert_expr, load_c_jdf
from parsec_tpu.runtime import Context

REF = pathlib.Path("/root/reference")
needs_ref = pytest.mark.skipif(not REF.exists(),
                               reason="reference tree not available")


# ---------------------------------------------------------------------------
# expression conversion
# ---------------------------------------------------------------------------

def test_convert_expr():
    assert convert_expr("a && b || !c") == "a and b or not c"
    assert convert_expr("x != 1 && !y") == "x != 1 and not y"
    assert convert_expr("descA->lmt - 1") == "descA.mt - 1"
    assert convert_expr("descA->super.myrank") == "descA.myrank"
    assert convert_expr("l/2 + k%3") == "l//2 + k%3"
    assert convert_expr("(1<<n)-1") == "(1<<n)-1"


# ---------------------------------------------------------------------------
# the reference's own files
# ---------------------------------------------------------------------------

@needs_ref
def test_ex02_chain_runs():
    """examples/Ex02_Chain.jdf: NEW-rooted chain of NB+1 tasks; the C
    body (*A += 1) becomes a Python body; taskdist is declared only in
    the C epilogue and gets synthesized as a data global."""
    jdf = load_c_jdf(REF / "examples" / "Ex02_Chain.jdf", bodies={
        "Task": "A[...] = 0 if k == 0 else A[...] + 1",
    })
    NB = 9
    taskdist = DictCollection("taskdist",
                              dtt=TileType((1,), np.int32),
                              init_fn=lambda *k: np.zeros(1, np.int32))
    tp = jdf.build(taskdist=taskdist, NB=NB,
                   DTT_DEFAULT=TileType((1,), np.int32))
    done = {}

    # capture the final chain value through an extra probe body wrap:
    # simplest is to re-run with a recording body
    jdf2 = load_c_jdf(REF / "examples" / "Ex02_Chain.jdf", bodies={
        "Task": "A[...] = 0 if k == 0 else A[...] + 1\n"
                "out[k] = int(A[0])",
    })
    out = {}
    tp2 = jdf2.build(taskdist=taskdist, NB=NB,
                     DTT_DEFAULT=TileType((1,), np.int32))
    tp2._builder.globals["out"] = out
    jdf2.globals_decl["out"] = {}      # visible to bodies via globals
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp2)
        ctx.wait(timeout=60)
    assert out[NB] == NB               # 0 at k=0, +1 per link


@needs_ref
def test_rtt_pingpong_runs():
    """tests/apps/pingpong/rtt.jdf VERBATIM: the `(k < NT) ? T PING(k+1)`
    arrow leaves the execution space at k = NT-1 and relies on the
    generated bounds check — the runtime's space-membership drop."""
    jdf = load_c_jdf(REF / "tests" / "apps" / "pingpong" / "rtt.jdf",
                     bodies={"PING": "T[...] += 1.0"})
    NT = 12
    A = VectorTwoDimCyclic("A", lm=1, mb=1,
                           init_fn=lambda m, s: np.zeros(s, np.float32))
    tp = jdf.build(A=A, NT=NT, WS=1)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert float(np.asarray(A.data_of(0).newest_copy().value)[0]) == NT


@needs_ref
def test_ex05_broadcast_runs():
    """examples/Ex05_Broadcast.jdf verbatim: range arrow fan-out, the
    hidden default NB=(6), derived local loc."""
    jdf = load_c_jdf(REF / "examples" / "Ex05_Broadcast.jdf", bodies={
        "TaskBcast": "A[...] = k",
        "TaskRecv": "assert int(A[0]) == k, (k, n)",
    })
    nodes = 3
    md = VectorTwoDimCyclic("mydata", lm=nodes + 7, mb=1, dtype=np.int32,
                            init_fn=lambda m, s: np.zeros(s, np.int32))
    tp = jdf.build(mydata=md, nodes=nodes, rank=0)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)      # Recv assertions are the check


@needs_ref
def test_ex07_raw_ctl_runs():
    """examples/Ex07_RAW_CTL.jdf verbatim: the counted CTL fan-in
    (`<- ctl TaskRecv(k, 0 .. NB .. 2)`) orders updates after reads."""
    jdf = load_c_jdf(REF / "examples" / "Ex07_RAW_CTL.jdf", bodies={
        "TaskBcast": "A[...] = k + 1",
        "TaskRecv": "assert int(A[0]) == k + 1, (k, n)",
        "TaskUpdate": "A[...] = -k - 1",
    })
    nodes = 4
    md = VectorTwoDimCyclic("mydata", lm=nodes + 7, mb=1, dtype=np.int32,
                            init_fn=lambda m, s: np.zeros(s, np.int32))
    tp = jdf.build(mydata=md, nodes=nodes, rank=0)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    for k in range(nodes):
        assert int(np.asarray(md.data_of(k).newest_copy().value)[0]) \
            == -k - 1


@needs_ref
def test_a2a_structure_parses_and_single_round_runs():
    """tests/apps/all2all/a2a.jdf: five classes, cross-product SEND/RECV
    wiring, a ranged CTL fan-in — ingested structure-only (pass bodies)
    and drained at NR=1 (the full NT x NT exchange plus the counted
    FANIN join).

    KNOWN LIMIT (documented in jdf_c): the reference's READER_B/FANOUT
    round chains declare `<- A FANOUT(r-1, t)` with NO reciprocal output
    arrow — jdf2c's dataflow analysis forwards read-chains to their data
    origin, which this mechanical converter does not replicate, so
    multi-round (NR > 1) needs those arrows made explicit (as
    models/irregular.all2all_ptg does)."""
    jdf = load_c_jdf(REF / "tests" / "apps" / "all2all" / "a2a.jdf")
    assert set(jdf.tasks) == {"READER_B", "FANOUT", "SEND", "RECV",
                              "FANIN"}
    NR, NT = 1, 3
    mk2 = lambda nm: DictCollection(
        nm, dtt=TileType((1,), np.float32),
        init_fn=lambda *k: np.zeros(1, np.float32))
    tp = jdf.build(descA=mk2("descA"), descB=mk2("descB"), NR=NR, NT=NT)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
