"""Device-backed transport tests: the task runtime moving device-resident
tiles across the 8-device virtual mesh.

The analog of the reference's distributed tier run over a *real* transport
(SURVEY §4; ``parsec_mpi_funnelled.c``): the same PTG protocol tests as
``test_comm_multirank.py`` but with rank *i* pinned to JAX device *i*,
``mem_register`` pinning payloads device-resident and GET moving them
device-to-device (``parsec_comm_engine.h:176-199`` vtable contract).
"""

import jax
import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.comm import run_multirank
from parsec_tpu.comm.device_fabric import (DeviceCommEngine, DeviceFabric,
                                           is_device_array)
from parsec_tpu.core.params import params
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic, VectorTwoDimCyclic


# ---------------------------------------------------------------------------
# engine-level unit tests (the dtd_test_ce.c analog)
# ---------------------------------------------------------------------------

def test_mem_register_pins_to_rank_device():
    fab = DeviceFabric(2)
    e0, e1 = fab.attach(0), fab.attach(1)
    h = e0.mem_register(np.arange(8, dtype=np.float32))
    assert is_device_array(h.value)
    assert h.value.device == fab.devices[0]

    landed = []
    e1.get(h.wire(), landed.append)
    e0.progress()   # serve the GET request
    e1.progress()   # land the reply
    assert len(landed) == 1
    assert is_device_array(landed[0])
    assert landed[0].device == fab.devices[1]   # D2D: consumer-side residency
    np.testing.assert_array_equal(np.asarray(landed[0]),
                                  np.arange(8, dtype=np.float32))
    assert e1.bytes_got == 32


def test_device_array_registration_aliases():
    """Immutable device arrays register without a snapshot copy."""
    fab = DeviceFabric(1)
    e0 = fab.attach(0)
    buf = jax.device_put(np.ones(4, np.float32), fab.devices[0])
    h = e0.mem_register(buf)
    assert h.value is buf   # aliased, not copied: jax arrays are immutable


def test_host_array_registration_copies_at_boundary():
    """Mutable host arrays snapshot inside mem_register (owned=False)."""
    fab = DeviceFabric(1)
    e0 = fab.attach(0)
    buf = np.ones(4, np.float32)
    h = e0.mem_register(buf)
    buf[:] = 99.0
    np.testing.assert_array_equal(np.asarray(h.value), np.ones(4))


# ---------------------------------------------------------------------------
# the protocol tests over the device transport
# ---------------------------------------------------------------------------

def _chain_tp(V, nt: int):
    p = ptg.PTGBuilder("chain", V=V, NT=nt)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NT - 1))
    t.affinity("V", lambda g, l: (l.i,))
    f = t.flow("A", ptg.RW)
    f.input(data=("V", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "A", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "A", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NT - 1)
    f.output(data=("V", lambda g, l: (0,)),
             guard=lambda g, l: l.i == g.NT - 1)

    def body(es, task, g, l):
        # functional update: arriving tiles may be immutable device arrays
        c = task.flow_data("A")
        c.value = np.asarray(c.value) + 1.0

    t.body(body)
    return p.build()


def _chain_body(ctx, rank, nranks):
    nt = 7
    V = VectorTwoDimCyclic("V", lm=nt * 4, mb=4, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    tp = _chain_tp(V, nt)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.comm_barrier()
    if rank == 0:
        return np.asarray(V.data_of(0).newest_copy().value).copy()
    return None


@pytest.mark.parametrize("nranks", [2, 4, 8])
def test_chain_across_devices(nranks):
    """Ex03 shape on the device transport: the tile hops device-to-device
    through every rank and writes back to rank 0's home."""
    res = run_multirank(nranks, _chain_body, transport="device")
    np.testing.assert_allclose(res[0], np.full(4, 7.0))


def _gemm_body(ctx, rank, nranks):
    n, nb = 64, 16
    rng = np.random.RandomState(7)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q, myrank=rank)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=Q, myrank=rank)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=Q, myrank=rank)
    tp = tiled_gemm_ptg(A, B, C, devices="cpu")
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    return C.to_dense()   # local tiles only; assembled by the caller


@pytest.mark.parametrize("nranks", [2, 4])
def test_block_cyclic_gemm_on_device_transport(nranks):
    """Distributed GEMM through the task runtime with payloads moving
    device-to-device; every rank's local tiles must match the dense product
    — and must match the single-rank run (the dryrun_multichip contract)."""
    res = run_multirank(nranks, _gemm_body, transport="device", timeout=180)
    n = 64
    rng = np.random.RandomState(7)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    expect = a.astype(np.float32) @ b
    single = run_multirank(1, _gemm_body)[0]
    np.testing.assert_allclose(single, expect, rtol=1e-4)
    # assemble: rank r contributed the tiles it owns; non-owned are zero
    got = np.zeros_like(expect)
    for r in res:
        got += r
    # each tile owned exactly once across ranks
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_rendezvous_get_stays_on_device():
    """A payload above the short limit must ride the registered-memory GET
    path and land as a device array on the consumer."""
    old = params.get("comm_short_limit")
    params.set("comm_short_limit", 8)
    seen = []

    def body(ctx, rank, nranks):
        res = _chain_body(ctx, rank, nranks)
        seen.append(ctx.comm_engine.ce.bytes_got)
        return res

    try:
        res = run_multirank(2, body, transport="device")
    finally:
        params.set("comm_short_limit", old)
    np.testing.assert_allclose(res[0], np.full(4, 7.0))
    assert any(b > 0 for b in seen), "no D2D GET traffic recorded"
