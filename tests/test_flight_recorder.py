"""The always-on runtime flight recorder: ring wraparound, the disabled
path's zero-allocation contract, the stall dump a wedged run must produce
(the round-5 lesson: a hung relay left NO self-reported evidence), the
metrics snapshotter, and the unified run-report export."""

import json
import threading
import time
import tracemalloc

import pytest

from parsec_tpu import ptg
import parsec_tpu.runtime.dagrun  # noqa: F401 — registers runtime_dag_compile
from parsec_tpu.core.params import params  # noqa: F401 — param registry
from parsec_tpu.prof import (export_run_report, flight_recorder, pins,
                             runtime_report, trace_state)
from parsec_tpu.prof.pins import PinsEvent
from parsec_tpu.runtime import Context
from parsec_tpu.runtime.context import ContextWaitTimeout


@pytest.fixture
def fresh_recorder():
    """A private size-8 recorder installed for the test, with whatever
    was installed before (the always-on default) restored after."""
    old_rec, old_hook = flight_recorder.recorder, pins.recorder
    rec = flight_recorder.install(8)
    yield rec
    flight_recorder.recorder, pins.recorder = old_rec, old_hook


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------

def test_ring_wraparound_keeps_last_n(fresh_recorder):
    for i in range(20):
        pins.fire(PinsEvent.EXEC_END, None, i)
    snap = fresh_recorder.snapshot()
    ring = snap[threading.current_thread().name]
    assert ring["total"] == 20
    assert len(ring["events"]) == 8          # fixed-size: last 8 survive
    assert [e["info"] for e in ring["events"]] == list(range(12, 20))
    assert all(e["event"] == "EXEC_END" for e in ring["events"])


def test_counts_survive_wraparound_and_sum_payloads(fresh_recorder):
    for i in range(30):
        pins.fire(PinsEvent.COMPLETE_EXEC_END, None, None)
    pins.fire(PinsEvent.DAG_COMPLETE_END, None, 1000)
    pins.fire(PinsEvent.DAG_COMPLETE_END, None, 24)
    counts, vsums = fresh_recorder.aggregate()
    assert counts[PinsEvent.COMPLETE_EXEC_END] == 30
    assert vsums[PinsEvent.DAG_COMPLETE_END] == 1024
    rep = runtime_report()
    assert rep["dynamic_tasks_retired"] == 30
    assert rep["dag_tasks_completed"] == 1024
    assert rep["tasks_retired"] == 1054   # total = the snapshotter's meaning


def test_idle_selects_become_liveness_ticks_not_ring_spam(fresh_recorder):
    pins.fire(PinsEvent.EXEC_BEGIN, None, 7)
    for _ in range(500):                      # an idle-polling worker
        pins.fire(PinsEvent.SELECT_BEGIN, None, None)
        pins.fire(PinsEvent.SELECT_END, None, None)   # no task: empty
    for _ in range(100):                      # a wedged compiled DAG
        pins.fire(PinsEvent.DAG_FETCH_BEGIN, None, None)
        pins.fire(PinsEvent.DAG_FETCH_END, None, 0)   # empty fetch
    ring = fresh_recorder.snapshot()[threading.current_thread().name]
    assert ring["total"] == 1                 # real history not rotated out
    assert ring["events"][0]["event"] == "EXEC_BEGIN"
    # only EMPTY selects / fetches tick the idle counter: SELECT_BEGIN is
    # payload-free even on productive selects and must not count
    assert ring["idle_selects"] == 600


def test_busy_selects_do_not_count_as_idle(fresh_recorder):
    class _T:
        pass
    task = _T()
    for _ in range(10):                       # a saturated worker
        pins.fire(PinsEvent.SELECT_BEGIN, None, None)
        pins.fire(PinsEvent.SELECT_END, None, task)   # got work
    ring = fresh_recorder.snapshot()[threading.current_thread().name]
    assert ring["idle_selects"] == 0
    assert ring["total"] == 10


def test_recycled_thread_name_keeps_cumulative_counts(fresh_recorder):
    """A later context's worker reusing a thread name must not erase the
    earlier worker's tallies (runtime_report would regress; rates() would
    go negative)."""
    def worker():
        for _ in range(5):
            pins.fire(PinsEvent.COMPLETE_EXEC_END, None, None)
    for _ in range(2):
        t = threading.Thread(target=worker, name="recycled-es")
        t.start()
        t.join()
    counts, _ = fresh_recorder.aggregate()
    assert counts[PinsEvent.COMPLETE_EXEC_END] == 10
    assert len([n for n in fresh_recorder.rings if n == "recycled-es"]) == 1


def test_disabled_path_is_allocation_free():
    """With the recorder uninstalled and no PINS chains, a fire() site
    costs attribute tests only — no allocation (the compiled-out analog
    the perf acceptance criterion pins)."""
    old_rec = pins.recorder
    pins.recorder = None
    try:
        if pins.enabled:
            pytest.skip("a PINS chain is registered by another test")
        payload = object()
        pins.fire(PinsEvent.EXEC_BEGIN, None, payload)     # warm the path
        tracemalloc.start()
        s1 = tracemalloc.take_snapshot()
        for _ in range(1000):
            pins.fire(PinsEvent.EXEC_BEGIN, None, payload)
        s2 = tracemalloc.take_snapshot()
        tracemalloc.stop()
        leaked = [d for d in s2.compare_to(s1, "filename")
                  if d.traceback[0].filename == pins.__file__
                  and d.size_diff > 0]
        assert not leaked, leaked
    finally:
        pins.recorder = old_rec


def test_disabled_dispatch_slot_is_none_and_allocation_free():
    """The ISSUE-2 fast path: hot sites read ``pins.hooks[event]`` — with
    nothing attached the slot IS None, and the slot-pattern loop (index
    load + falsy branch, exactly what scheduling.py compiles in) allocates
    nothing."""
    old_rec = pins.recorder
    pins.recorder = None
    try:
        if pins.enabled:
            pytest.skip("a PINS chain is registered by another test")
        hooks = pins.hooks
        ev = int(PinsEvent.EXEC_BEGIN)
        assert hooks[ev] is None
        payload = object()
        it = range(1000)          # loop machinery allocated up front
        tracemalloc.start()
        before, _ = tracemalloc.get_traced_memory()
        for _ in it:
            h = hooks[ev]
            if h is not None:
                h(None, payload)
        after, _ = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # zero PER-SITE allocation: 1000 disabled sites may not grow the
        # heap by even half a byte per visit
        assert after - before < 512, (before, after)
    finally:
        pins.recorder = old_rec


def test_recorder_assignment_retargets_dispatch_slots():
    """``pins.recorder = fn`` (the PR-1 install contract AND this file's
    fixtures) must retarget the precompiled slots immediately — and the
    hooks LIST identity must never change, since hot sites bind it once
    at import."""
    table_before = pins.hooks
    seen = []
    old_rec = pins.recorder
    pins.recorder = lambda ev, payload: seen.append((ev, payload))
    try:
        h = pins.hooks[int(PinsEvent.EXEC_BEGIN)]
        assert h is not None
        h(None, 42)
        assert seen == [(PinsEvent.EXEC_BEGIN, 42)]
        pins.fire(PinsEvent.DAG_COMPLETE_END, None, 7)   # fire() same table
        assert seen[-1] == (PinsEvent.DAG_COMPLETE_END, 7)
    finally:
        pins.recorder = old_rec
    assert pins.hooks is table_before
    assert pins.recorder is old_rec


def test_chain_registration_compiles_slots_and_unregister_clears():
    calls = []

    def cb(es, payload):
        calls.append(payload)

    old_rec = pins.recorder
    pins.recorder = None
    try:
        ev = PinsEvent.DATA_FLUSH_BEGIN
        if pins.hooks[int(ev)] is not None:
            pytest.skip("another module holds a chain on this event")
        pins.register(ev, cb)
        assert pins.hooks[int(ev)] is not None
        pins.fire(ev, None, "x")
        assert calls == ["x"]
        pins.unregister(ev, cb)
        assert pins.hooks[int(ev)] is None
    finally:
        pins.recorder = old_rec


# ---------------------------------------------------------------------------
# stall dump
# ---------------------------------------------------------------------------

def _hung_pool(ev, n=4):
    p = ptg.PTGBuilder("hangpool", N=n)
    t = p.task("HANG", i=ptg.span(0, lambda g, l: g.N - 1))
    t.body(lambda es, task, g, l: (ev.wait(20), None)[1])
    return p.build()


def test_wait_timeout_raises_typed_and_dumps(tmp_path, param, capsys):
    """A forced Context.wait() timeout on deliberately hung workers
    produces a ContextWaitTimeout (caught by TYPE, not message text) and
    a stall dump naming every worker's last event and the queue depths,
    serialized to stderr and the flightrec-<rank>.json artifact."""
    param("runtime_dag_compile", False)   # dynamic path: per-task PINS
    param("prof_flightrec_dir", str(tmp_path))
    ev = threading.Event()
    ctx = Context(nb_cores=2)
    ctx.add_taskpool(_hung_pool(ev))
    try:
        with pytest.raises(ContextWaitTimeout) as ei:
            ctx.wait(timeout=0.5)
        assert isinstance(ei.value, TimeoutError)   # back-compat contract
        report = ctx.last_stall_report
        assert report is not None
        # every worker is named with its last event
        workers = report["workers"]
        for es_name in ("parsec-es0", "parsec-es1"):
            assert es_name in workers, workers.keys()
            evs = workers[es_name]["events"]
            assert evs, f"{es_name} recorded no events"
            assert evs[-1]["event"] == "EXEC_BEGIN"
            assert evs[-1]["info"] == "HANG"
        # queue depths present (lfq: per-stream + per-VP system queue)
        assert isinstance(report["queue_depths"], dict)
        assert report["queue_depths"], report
        assert "active_taskpools" in report
        # the artifact round-trips as JSON
        art = tmp_path / "flightrec-0.json"
        assert art.exists()
        loaded = json.loads(art.read_text())
        assert loaded["workers"].keys() == workers.keys()
        err = capsys.readouterr().err
        assert "STALL DUMP" in err
        assert "parsec-es0" in err
    finally:
        ev.set()
        ctx.wait(timeout=30)
        ctx.fini()


def test_fini_bounded_drain_aborts_instead_of_hanging(tmp_path, param):
    """fini(timeout=...) on a wedged pool falls through to abort-style
    teardown within the bound instead of blocking forever (ADVICE r5:
    bench.py's 'finally: ctx.fini()' hung in exactly this case)."""
    param("runtime_dag_compile", False)
    param("prof_flightrec_dir", str(tmp_path))
    ev = threading.Event()
    ctx = Context(nb_cores=1)
    ctx.add_taskpool(_hung_pool(ev, n=1))
    ctx.start()
    time.sleep(0.2)                      # let the worker enter the body
    threading.Timer(0.3, ev.set).start()  # unblock during fini's join
    t0 = time.monotonic()
    ctx.fini(timeout=0.2)                # must NOT raise, must NOT hang
    assert time.monotonic() - t0 < 10
    assert ctx.last_stall_report is not None
    assert (tmp_path / "flightrec-0.json").exists()


def test_fini_after_timed_out_wait_dumps_only_once(tmp_path, param, capsys):
    """bench's 'finally: ctx.fini(expired)' after a timed-out wait must
    not produce a second dump — one diagnosis per stall."""
    param("runtime_dag_compile", False)
    param("prof_flightrec_dir", str(tmp_path))
    ev = threading.Event()
    ctx = Context(nb_cores=1)
    ctx.add_taskpool(_hung_pool(ev, n=1))
    with pytest.raises(ContextWaitTimeout):
        ctx.wait(timeout=0.3)
    threading.Timer(0.3, ev.set).start()
    ctx.fini(timeout=0.0)            # expired deadline, abort-style
    assert capsys.readouterr().err.count("STALL DUMP") == 1


def test_wait_timeout_dump_can_be_disabled(param):
    param("runtime_dag_compile", False)
    param("prof_stall_dump", False)
    ev = threading.Event()
    ctx = Context(nb_cores=1)
    ctx.add_taskpool(_hung_pool(ev, n=1))
    try:
        with pytest.raises(ContextWaitTimeout):
            ctx.wait(timeout=0.3)
        assert ctx.last_stall_report is None
    finally:
        ev.set()
        ctx.wait(timeout=30)
        ctx.fini()


# ---------------------------------------------------------------------------
# metrics snapshotter
# ---------------------------------------------------------------------------

def test_snapshotter_samples_counters_and_props(param):
    param("runtime_dag_compile", False)
    param("prof_snapshot_interval", 0.03)
    snap = flight_recorder.snapshotter
    before = len(snap.series)
    p = ptg.PTGBuilder("sleepy", N=60)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
    t.body(lambda es, task, g, l: time.sleep(0.005))
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
    assert len(snap.series) > before, "snapshotter never sampled"
    s = snap.series[-1]
    assert "sde" in s and "props" in s and "tasks_retired" in s
    # the thread refcount released on fini: no further samples accumulate
    # (allow a last in-flight sample to land first)
    time.sleep(0.1)
    n = len(snap.series)
    time.sleep(0.12)
    assert len(snap.series) == n


# ---------------------------------------------------------------------------
# unified export
# ---------------------------------------------------------------------------

def test_export_run_report_roundtrip_chrome(tmp_path, param):
    """Flight-recorder events, counter series, and Profiling streams all
    land in ONE chrome trace that round-trips through JSON."""
    from parsec_tpu.core.mca import repository
    param("runtime_dag_compile", False)
    trace_state.init()
    comp = repository.find("pins", "task_profiler")
    mod = comp.open()
    try:
        p = ptg.PTGBuilder("exp", N=12)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        t.body(lambda es, task, g, l: None)
        with Context(nb_cores=0) as ctx:
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=30)
        flight_recorder.snapshotter.sample()
        flight_recorder.snapshotter.sample()
        path = tmp_path / "report.json"
        out = export_run_report(chrome_path=str(path))
        loaded = json.loads(path.read_text())
        evs = loaded["traceEvents"]
        cats = {e.get("cat") for e in evs}
        phases = {e.get("ph") for e in evs}
        assert "flightrec" in cats           # ring instant events (pid 1)
        assert "parsec" in cats              # profiling spans (pid 0)
        assert "C" in phases                 # counter series (pid 2)
        assert any(e.get("name") == "task_exec" for e in evs)
        summary = out["summary"]
        assert summary["tasks_retired"] >= 12
        assert summary["trace_events"] == len(evs)
        assert summary["workers"]
    finally:
        comp.close(mod)
        trace_state.fini()


def test_runtime_report_is_json_serializable_and_compact():
    rep = runtime_report()
    s = json.dumps(rep)
    assert len(s) < 4096
    assert "tasks_retired" in rep and "workers" in rep
