"""ISSUE 11: the automatic prefix cache (radix trie over prompt pages)
and tiered KV paging (device -> host -> peer), end to end against the
dense oracle.  ``docs/LLM.md``, "Prefix cache & KV tiers"."""

import numpy as np
import pytest

import parsec_tpu.llm.batcher as batcher_mod
from parsec_tpu.data.data import DataCopy
from parsec_tpu.data_dist.kv_tiers import KVTierMap, PeerKVStore
from parsec_tpu.data_dist.paged_kv import PagedKVCollection
from parsec_tpu.llm import ToyLM, prefill_chunks, prefill_ptg
from parsec_tpu.llm.prefix_tree import PrefixTree
from parsec_tpu.runtime import Context
from parsec_tpu.serve import RuntimeServer

MODEL = ToyLM()
H, D = MODEL.num_heads, MODEL.head_dim


def _kv(page_size=4, **kw):
    return PagedKVCollection("KV", page_size=page_size, num_heads=H,
                             head_dim=D, **kw)


def _fill_seq(kv, seq, ntokens):
    """Allocate + ledger-advance a sequence as if prefilled (bytes are
    irrelevant to trie bookkeeping tests)."""
    kv.alloc_seq(seq)
    P = kv.page_size
    for _ in range((ntokens + P - 1) // P):
        kv.alloc_page(seq)
    kv.note_appended(seq, ntokens)


# ---------------------------------------------------------------------------
# the radix tree vs a brute-force longest-common-prefix oracle
# ---------------------------------------------------------------------------

def _lcp(a, b):
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


def test_trie_insert_match_property_vs_lcp_oracle():
    """Randomized donations + matches: adopt must reuse EXACTLY the
    longest full-page common prefix over every retained run — the
    brute-force oracle scans all retained token runs."""
    rng = np.random.default_rng(7)
    kv = _kv(page_size=4, max_pages=2048)
    tree = PrefixTree(kv, budget_bytes=1 << 30)    # no eviction pressure
    P = kv.page_size
    retained_runs: list[tuple] = []
    seqs = 0
    for step in range(120):
        length = int(rng.integers(1, 30))
        prompt = [int(t) for t in rng.integers(0, 4, size=length)]
        if rng.random() < 0.5:
            seq = f"d{seqs}"
            seqs += 1
            _fill_seq(kv, seq, len(prompt) - 1)
            if tree.donate(seq, prompt) is not None:
                retained_runs.append(tuple(prompt[:((len(prompt) - 1)
                                                    // P) * P]))
            kv.free_seq(seq)
        else:
            cacheable = prompt[:-1]
            want = max((_lcp(cacheable, r) // P for r in retained_runs),
                       default=0)
            child = f"c{seqs}"
            seqs += 1
            got = tree.adopt(child, cacheable)
            assert got == want, (step, got, want, cacheable)
            assert kv.seq_len(child) == got * P
            assert kv.npages(child) == got
            kv.free_seq(child)
    s = tree.stats()
    assert s["entries"] == len(set(retained_runs)) == s["donations"]
    assert s["evictions"] == 0


def test_trie_lru_eviction_recycles_pages_and_keeps_warm_entries():
    """Byte budget: donating past it evicts the LEAST recently used
    entry, its pages recycle (free list), and a matched entry is
    touched — so matching keeps an entry alive through later donations."""
    kv = _kv(page_size=2, max_pages=64)
    tree = PrefixTree(kv, budget_bytes=2 * 2 * kv.page_bytes)  # 2 entries
    runs = {}
    for name, base in (("a", 10), ("b", 20), ("c", 30)):
        prompt = [base, base + 1, base + 2, base + 3, 0]   # 2 full pages
        _fill_seq(kv, name, 4)
        runs[name] = tuple(prompt[:4])
        tree.donate(name, prompt)
        kv.free_seq(name)
        if name == "b":
            # touch "a" so "b" is the cold one when "c" arrives
            assert tree.adopt("toucher", list(runs["a"])) == 2
            kv.free_seq("toucher")
    assert tree.stats()["evictions"] == 1
    live = tree.live_entries()
    kept = {e[0] for e in live.values()}
    assert runs["a"] in kept and runs["c"] in kept
    assert runs["b"] not in kept                     # LRU victim
    assert tree.adopt("miss", list(runs["b"])) == 0  # really gone
    # the victim's pages went back to the free list (nothing leaks)
    assert kv.stats()["free_pages"] >= 2


def test_trie_adopt_pins_entry_against_concurrent_eviction_semantics():
    """An adopted child survives eviction of its donor entry: the CoW
    refcounts — not trie residency — keep the shared pages alive."""
    kv = _kv(page_size=2)
    tree = PrefixTree(kv, budget_bytes=1 << 30)
    _fill_seq(kv, "donor", 4)
    d0 = kv.data_of("donor", 0)
    d0.get_copy(0).value[0, 0, 0, 0] = 7.0
    tree.donate("donor", [1, 2, 3, 4, 9])
    kv.free_seq("donor")
    assert tree.adopt("child", [1, 2, 3, 4]) == 2
    tree.clear()                                   # evict everything
    assert tree.stats()["entries"] == 0
    # the child still reads the donated bytes; pages were never recycled
    assert kv.data_of("child", 0).get_copy(0).value[0, 0, 0, 0] == 7.0
    assert kv.data_of("child", 0) is d0


# ---------------------------------------------------------------------------
# fork-under-eviction: CoW privatize must copy the NEWEST bytes and
# version-jump past every stale copy (the ISSUE-11 regression)
# ---------------------------------------------------------------------------

def test_cow_privatize_copies_newest_device_bytes_not_stale_host():
    """A shared tail page whose device copy runs AHEAD of host (deferred
    write-back, device/tpu.py) is privatized by a fork child: the copy
    must source the device bytes, and the private page's version must
    jump past the shared page's every version."""
    kv = _kv(page_size=4)
    kv.alloc_seq("parent")
    for _ in range(2):
        kv.ensure_tail_slot("parent")
        kv.note_appended("parent")
    d = kv.data_of("parent", 0)
    host = d.get_copy(0)
    stale = np.array(host.value, copy=True)
    fresh = np.array(host.value, copy=True)
    fresh[0, 0, 0, 0] = 99.0
    dev = DataCopy(d, 1, value=fresh)
    dev.version = host.version + 3        # device ran ahead of host
    d.attach_copy(dev)
    kv.fork("parent", "child")
    kv.ensure_tail_slot("child")          # privatizes the shared tail
    c = kv.data_of("child", 0).get_copy(0)
    assert c.value[0, 0, 0, 0] == 99.0, "fork copied stale host bytes"
    assert c.version > dev.version, "no version jump past the device copy"
    assert np.array_equal(np.asarray(host.value), stale)  # parent intact


def test_fork_under_device_eviction_end_to_end_oracle(accel_device,
                                                     param):
    """The regression in anger: a tiny device budget keeps KV pages
    cycling through eviction/write-back while trie-forked streams
    privatize shared tails mid-decode — every stream must still equal
    the dense oracle token for token."""
    param("llm_prefix_cache", True)
    accel_device._mem_budget = 3 * 6144    # ~3 pages of (3,16,4,8)·f32
    with RuntimeServer(nb_cores=2) as server:
        from parsec_tpu.llm import ContinuousBatcher
        b = ContinuousBatcher(server, model=MODEL, devices="tpu")
        prompt = list(range(1, 40))        # 2 full pages + partial @16
        t1 = b.submit_stream(prompt, max_new_tokens=5)
        assert t1.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 5)
        # same prompt twice: both adopt the donated prefix
        t2 = b.submit_stream(prompt, max_new_tokens=6)
        t3 = b.submit_stream(prompt, max_new_tokens=4)
        assert t2.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 6)
        assert t3.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 4)
        s = b.stats()
        assert s["kv"]["prefix_hits"] == 2
        assert s["kv"]["prefix_pages_reused"] == 4
        assert accel_device.deferred_evictions > 0, \
            "budget never forced an eviction — the test lost its point"
        b.stop()


# ---------------------------------------------------------------------------
# trie-forked streams vs the oracle through the full serving stack
# ---------------------------------------------------------------------------

def test_trie_streams_match_oracle_mixed_hit_lengths(param):
    """Shared-system-prompt traffic with NO fork_from wiring: full-hit,
    mid-page hit, and miss streams interleave — token-for-token oracle
    equality plus the prefill-skip ledger."""
    param("llm_prefix_cache", True)
    with RuntimeServer(nb_cores=2) as server:
        sysprompt = list(range(1, 34))     # 33 tokens: 2 full pages @16
        cases = [
            sysprompt,                          # exact repeat (full hit)
            sysprompt + [40, 41, 42],           # extension (full-page hit)
            sysprompt[:20] + [50, 51],          # diverges mid page 2
            [60, 61, 62, 63],                   # miss
        ]
        donor = server.submit_stream(sysprompt, max_new_tokens=3)
        assert donor.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(sysprompt, 3)
        tks = [server.submit_stream(p, max_new_tokens=4) for p in cases]
        for p, tk in zip(cases, tks):
            assert tk.result(timeout=120)["tokens"] == \
                MODEL.reference_generate(p, 4), p
        llm = server.stats()["llm"]
        # full hit (2 pages) + extension (2 pages) + mid-page (1 page:
        # LCP 20 tokens -> 1 full page); the miss and the donor hit nothing
        assert llm["kv"]["prefix_hits"] == 3
        assert llm["kv"]["prefix_pages_reused"] == 5
        assert llm["prefill_tokens_skipped"] == 5 * 16
        assert llm["prefix"]["donations"] >= 1
        # per-tenant SLO counters carry the same wins (PR-10 plane)
        t = server.metrics()["tenants"]["default"]
        assert t["prefix_hits"] == 3 and t["prefix_pages_reused"] == 5


def test_trie_disabled_by_default_keeps_pr9_behavior():
    """llm_prefix_cache defaults OFF: no trie, no retained pages — the
    PR-6/9 contract (every page recycles at stream retirement) holds."""
    with RuntimeServer(nb_cores=2) as server:
        prompt = list(range(1, 40))
        t1 = server.submit_stream(prompt, max_new_tokens=3)
        t2 = server.submit_stream(prompt, max_new_tokens=3)
        for tk in (t1, t2):
            assert tk.result(timeout=120)["tokens"] == \
                MODEL.reference_generate(prompt, 3)
        llm = server.stats()["llm"]
        assert llm["kv"]["prefix_hits"] == 0
        assert llm["kv"]["physical_pages"] == 0
        assert "prefix" not in llm


def test_trie_and_explicit_fork_from_compose(param):
    """fork_from is now optional but still honored: an explicit fork
    rides the parent's live pages; a trie hit serves everyone else."""
    param("llm_prefix_cache", True)
    with RuntimeServer(nb_cores=2) as server:
        prompt = list(range(1, 41))
        t1 = server.submit_stream(prompt, max_new_tokens=6)
        t2 = server.submit_stream(prompt, max_new_tokens=4, fork_from=t1)
        assert t1.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 6)
        assert t2.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 4)
        llm = server.stats()["llm"]
        assert llm["forked_streams"] == 1          # the explicit fork
        # after both retire, a third stream hits the donated prefix
        t3 = server.submit_stream(prompt, max_new_tokens=3)
        assert t3.result(timeout=120)["tokens"] == \
            MODEL.reference_generate(prompt, 3)
        assert server.stats()["llm"]["kv"]["prefix_hits"] >= 1


# ---------------------------------------------------------------------------
# tail-only prefill (the PF starts seam)
# ---------------------------------------------------------------------------

def test_prefill_chunks_continue_past_shared_prefix_pages():
    kv = _kv(page_size=4)
    _fill_seq(kv, "donor", 8)
    tree = PrefixTree(kv, budget_bytes=1 << 30)
    tree.donate("donor", [1, 2, 3, 4, 5, 6, 7, 8, 9])
    assert tree.adopt("child", [1, 2, 3, 4, 5, 6, 7, 8, 11, 12]) == 2
    chunks = prefill_chunks(MODEL, kv, "child", [11, 12])
    assert list(chunks) == [("child", 2)]          # chunk index continues
    assert kv.seq_len("child") == 10 and kv.npages("child") == 3


def test_tail_prefill_pool_writes_only_tail_pages_and_graphchecks():
    """prefill_ptg(starts=) must neither redo nor overwrite the shared
    prefix pages — and the pool is graphcheck-clean."""
    kv = _kv(page_size=4)
    _fill_seq(kv, "donor", 8)
    sentinel = kv.data_of("donor", 0).get_copy(0)
    sentinel.value[0, 0, 0, 0] = 123.0
    tree = PrefixTree(kv, budget_bytes=1 << 30)
    tree.donate("donor", [1, 2, 3, 4, 5, 6, 7, 8, 9])
    kv.free_seq("donor")
    tree.adopt("child", [1, 2, 3, 4, 5, 6, 7, 8, 11])
    chunks = prefill_chunks(MODEL, kv, "child", [11])
    from parsec_tpu.data_dist.collection import DictCollection
    T = DictCollection("T", dtt=kv.default_dtt,
                       init_fn=lambda *k: chunks[k], keys=list(chunks))
    tp = prefill_ptg(kv, T, ["child"], starts=[2])
    report = tp.validate()
    assert not report.errors and not report.warnings, report
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert kv.data_of("child", 0).get_copy(0).value[0, 0, 0, 0] == 123.0
    tail = kv.data_of("child", 2).get_copy(0).value
    assert np.allclose(tail[0, 0], MODEL.q3(11)[1])   # the tail landed
    with pytest.raises(ValueError):
        prefill_ptg(kv, T, ["child"], starts=[7])     # out of range


# ---------------------------------------------------------------------------
# KV tiering: spill accounting, prefetch, and the peer hop
# ---------------------------------------------------------------------------

def test_hbm_budget_below_working_set_decodes_oracle_equal(accel_device,
                                                           param):
    """The tier soak: device budget far below the live-KV working set;
    pages spill HBM -> host continuously, the batcher prefetches them
    back one superpool ahead — decode completes oracle-equal and the
    tier ledger shows real traffic."""
    param("llm_prefetch_ahead", True)
    accel_device._mem_budget = 4 * 6144    # ~4 pages; WS is ~4x that
    with RuntimeServer(nb_cores=2) as server:
        from parsec_tpu.llm import ContinuousBatcher
        b = ContinuousBatcher(server, model=MODEL, devices="tpu")
        prompts = [list(range(1, 50)), list(range(2, 51)),
                   [7, 9, 11] * 16]
        # 20 tokens at k=8 = 3 superpool iterations per stream: spills
        # from iteration N are in the host ledger when iteration N+1's
        # prefetch runs (a 1-iteration run would race the deferred
        # write-back drain and measure nothing)
        tks = [b.submit_stream(p, max_new_tokens=20) for p in prompts]
        for p, tk in zip(prompts, tks):
            assert tk.result(timeout=240)["tokens"] == \
                MODEL.reference_generate(p, 20), p
        s = b.stats()
        assert s["tiers"]["spills"] > 0
        assert s["tiers"]["prefetched_pages"] > 0
        assert s["kv"]["host_tier_bytes"] >= 0     # key present + sane
        assert "prefetch_inflight" in s["kv"]
        # the aggregate surfaces in runtime_report()["llm"] (satellite)
        from parsec_tpu.prof import runtime_report
        rep = runtime_report().get("llm", {})
        assert "host_tier_bytes" in rep and "prefetch_inflight" in rep
        assert rep["prefix_hits"] >= 0
        b.stop()


def test_peer_tier_spill_and_prefetch_get_roundtrip(param):
    """Host budget pressure pushes a cold page one hop further over the
    comm engine (AM spill -> registered MemHandle), and prefetch pulls
    it back over the GET path with its bytes and version intact."""
    from parsec_tpu.comm.engine import InprocFabric
    param("kv_host_tier_bytes", 1)         # any spill exceeds the budget
    fab = InprocFabric(2)
    e0, e1 = fab.attach(0), fab.attach(1)
    store = PeerKVStore(e1)
    kv = _kv()
    tiers = KVTierMap(kv)
    tiers.attach_peer(e0, 1)
    kv.alloc_seq("a")
    kv.alloc_page("a")
    kv.note_appended("a", 4)
    d = kv.data_of("a", 0)
    host = d.get_copy(0)
    host.value[:] = np.arange(host.value.size,
                              dtype=np.float32).reshape(host.value.shape)
    host.version = 5
    orig = np.array(host.value)
    tiers.note_spill(d, host.value.nbytes)     # as the device hook would
    for _ in range(20):
        e0.progress()
        e1.progress()
    assert d.get_copy(0).value is None          # host bytes released
    assert store.stats()["pages_held"] == 1
    assert tiers.stats()["peer_tier_pages"] == 1
    tiers.prefetch_seqs(["a"])                  # issues the prefetch GET
    for _ in range(20):
        e0.progress()
        e1.progress()
    back = d.get_copy(0)
    assert back.value is not None and np.array_equal(back.value, orig)
    assert back.version == 5
    assert tiers.stats()["peer_fetches"] == 1
    assert store.stats()["pages_held"] == 0     # handle drained
    assert getattr(e0, "prefetch_gets", 0) == 1


def test_peer_spill_keeps_local_bytes_until_ack(param):
    """Until the peer acknowledges custody, the local host copy is the
    page's ONLY copy: a lost spill AM must degrade to 'page stayed
    local', never to 'page gone'."""
    from parsec_tpu.comm.engine import InprocFabric
    param("kv_host_tier_bytes", 1)
    fab = InprocFabric(2)
    e0 = fab.attach(0)
    fab.attach(1)                      # peer rank exists, NO store: the
    kv = _kv()                         # spill AM is never consumed
    tiers = KVTierMap(kv)
    tiers.attach_peer(e0, 1)
    kv.alloc_seq("a")
    kv.alloc_page("a")
    d = kv.data_of("a", 0)
    tiers.note_spill(d, d.get_copy(0).value.nbytes)
    e0.progress()                      # no ACK will ever arrive
    assert d.get_copy(0).value is not None     # bytes stayed reachable
    assert tiers.stats()["peer_tier_pages"] == 1   # address pending


def test_runtime_report_llm_block_survives_batcher_retirement(param):
    """The cumulative-since-process-start contract: a drained server's
    batcher folds its counters into the aggregate, so a bench stage's
    post-run report still shows the cache effectiveness."""
    import parsec_tpu.llm.batcher as bmod
    param("llm_prefix_cache", True)
    before = bmod.aggregate_report()
    with RuntimeServer(nb_cores=2) as server:
        prompt = list(range(1, 41))
        for _ in range(2):
            server.submit_stream(prompt, max_new_tokens=2) \
                .result(timeout=120)
    after = bmod.aggregate_report()
    assert after.get("prefix_hits", 0) - before.get("prefix_hits", 0) == 1
    assert after.get("tokens_generated", 0) \
        - before.get("tokens_generated", 0) == 4


def test_kv_stats_carries_the_issue11_keys_without_tiers():
    kv = _kv()
    s = kv.stats()
    for key in ("prefix_hits", "prefix_pages_reused", "host_tier_bytes",
                "prefetch_inflight"):
        assert key in s and s[key] == 0


def test_fork_prefix_validates_bounds_and_page_alignment():
    kv = _kv(page_size=4)
    _fill_seq(kv, "p", 6)                  # 2 pages, ledger 6
    with pytest.raises(ValueError):
        kv.fork_prefix("p", "c", 3)        # past the table
    with pytest.raises(ValueError):
        kv.fork_prefix("p", "c", 2)        # page 2 only 2 tokens full
    kv.fork_prefix("p", "c", 1)
    assert kv.seq_len("c") == 4 and kv.npages("c") == 1
    with pytest.raises(KeyError):
        kv.fork_prefix("p", "c", 1)        # child exists
