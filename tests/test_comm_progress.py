"""Comm progress discipline (VERDICT r2 item 9): per-peer coalescing with
priority ordering on the outgoing activation stage, and the optional
dedicated comm-progress thread."""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.comm.engine import AM_TAG_ACTIVATE, InprocFabric
from parsec_tpu.comm.remote_dep import RemoteDepEngine
from parsec_tpu.core.params import params
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
from parsec_tpu.runtime import Context  # noqa: F401 (e2e bodies)


class _SpyEngine:
    """Captures send_am calls; quacks enough of CommEngine for the stage."""

    def __init__(self):
        self.sent = []
        self.rank, self.nranks = 0, 4

    def send_am(self, tag, dst, payload, trace_id=0):
        self.sent.append((tag, dst, payload))

    def tag_register(self, tag, cb):
        pass


def mk_engine(spy):
    """A bare outgoing stage: the coalescing tests need no live Context."""
    import itertools
    import threading
    eng = RemoteDepEngine.__new__(RemoteDepEngine)
    eng.ce = spy
    eng._outq = {}
    eng._outq_lock = threading.Lock()
    eng._flush_serial = threading.Lock()
    eng._outseq = itertools.count()
    return eng


class TestCoalescing:
    def test_same_peer_batches_priority_ordered(self, param):
        param("comm_coalesce", True)
        spy = _SpyEngine()
        eng = mk_engine(spy)
        eng._post_activate(1, {"priority": 1, "id": "low"})
        eng._post_activate(1, {"priority": 9, "id": "high"})
        eng._post_activate(1, {"priority": 5, "id": "mid"})
        eng._post_activate(2, {"priority": 0, "id": "other-peer"})
        assert spy.sent == []           # staged, nothing on the wire yet
        n = eng.flush_outgoing()
        assert n == 4
        by_dst = {dst: p for tag, dst, p in spy.sent}
        # coalesced aggregates ride as the flat ("B", [msgs]) wire form
        assert [m["id"] for m in by_dst[1][1]] == ["high", "mid", "low"]
        assert by_dst[2]["id"] == "other-peer"   # singletons ride unbatched
        assert all(tag == AM_TAG_ACTIVATE for tag, _, _ in spy.sent)
        assert eng.flush_outgoing() == 0

    def test_fifo_within_equal_priority(self, param):
        param("comm_coalesce", True)
        spy = _SpyEngine()
        eng = mk_engine(spy)
        for i in range(3):
            eng._post_activate(1, {"priority": 7, "id": i})
        eng.flush_outgoing()
        assert [m["id"] for m in spy.sent[0][2][1]] == [0, 1, 2]

    def test_disabled_sends_immediately(self, param):
        param("comm_coalesce", False)
        spy = _SpyEngine()
        eng = mk_engine(spy)
        eng._post_activate(1, {"priority": 1})
        assert len(spy.sent) == 1


def _gemm_body(ctx, rank, nranks):
    n, nb = 64, 16
    rng = np.random.RandomState(11)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    P = 2 if nranks % 2 == 0 else 1
    A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=nranks // P,
                                     myrank=rank)
    B = TwoDimBlockCyclic.from_dense("B", b, nb, nb, P=P, Q=nranks // P,
                                     myrank=rank)
    C = TwoDimBlockCyclic("C", n, n, nb, nb, P=P, Q=nranks // P, myrank=rank)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    return C.to_dense()


def _check(res):
    n = 64
    rng = np.random.RandomState(11)
    a = rng.randn(n, n).astype(np.float32)
    b = rng.randn(n, n).astype(np.float32)
    got = np.zeros((n, n), np.float32)
    for part in res:
        got += part
    np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-4)


class TestEndToEnd:
    def test_gemm_with_comm_thread(self, param):
        param("comm_thread", True)
        _check(run_multirank(4, _gemm_body))

    def test_gemm_without_coalescing(self, param):
        param("comm_coalesce", False)
        _check(run_multirank(4, _gemm_body))

    def test_gemm_comm_thread_with_workers(self, param):
        """Comm thread + worker threads racing the protocol."""
        param("comm_thread", True)
        _check(run_multirank(2, _gemm_body, nb_cores=2))
