"""Tiled Cholesky: the irregular-guard PTG over the symmetric distribution.

The analog of the reference's DPLASMA-style ``dpotrf`` tests over
``sym_two_dim_rectangle_cyclic.c`` (BASELINE.md staged config #5): four task
classes with a triangular execution space and range arrows — the task-class
mix changes with ``k``, which is exactly what chain-collapse cannot swallow
(VERDICT r2, missing #4).
"""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
from parsec_tpu.models.cholesky import (cholesky_flops, make_spd,
                                        tiled_cholesky_ptg)
from parsec_tpu.runtime import Context


def _run_single(n, nb, nb_cores=0):
    a = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb)
    tp = tiled_cholesky_ptg(A, devices="cpu")
    with Context(nb_cores=nb_cores) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=300)
    got = np.tril(A.to_dense())
    expect = np.linalg.cholesky(a.astype(np.float64)).astype(np.float32)
    return got, expect


@pytest.mark.parametrize("n,nb", [(64, 16), (96, 32), (128, 32)])
def test_cholesky_small(n, nb):
    got, expect = _run_single(n, nb)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)


def test_cholesky_ragged_edge():
    """Edge tiles smaller than nb."""
    got, expect = _run_single(80, 32)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)


def test_cholesky_n2048_workers():
    """The VERDICT-mandated size: N >= 2048, single rank, worker threads."""
    got, expect = _run_single(2048, 256, nb_cores=2)
    np.testing.assert_allclose(got, expect, rtol=5e-2, atol=5e-3)


def _mk_body(a, nb, P, Q):
    def body(ctx, rank, nranks):
        A = SymTwoDimBlockCyclic.from_dense("A", a, nb, nb, P=P, Q=Q,
                                            myrank=rank)
        tp = tiled_cholesky_ptg(A, devices="cpu")
        ctx.add_taskpool(tp)
        ctx.wait(timeout=240)
        ctx.comm_barrier()
        # sum-assembly: each tile owned exactly once across ranks
        return np.tril(A.to_dense())
    return body


@pytest.mark.parametrize("nranks,transport", [(2, "inproc"), (4, "inproc"),
                                              (4, "device")])
def test_cholesky_multirank(nranks, transport):
    n, nb = 192, 32
    a = make_spd(n)
    P = 2 if nranks % 2 == 0 else 1
    Q = nranks // P
    parts = run_multirank(nranks, _mk_body(a, nb, P, Q),
                          transport=transport, timeout=240)
    got = np.zeros((n, n), np.float32)
    for p in parts:
        got += p
    expect = np.linalg.cholesky(a.astype(np.float64)).astype(np.float32)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)


def test_cholesky_flops_model():
    assert cholesky_flops(1000) == pytest.approx(1e9 / 3, rel=0.01)
