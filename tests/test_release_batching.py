"""Hot-path equivalence: batched dep-release vs per-task release.

ISSUE 2 rebuilt ``release_deps`` to accumulate one completing task's
successor releases and push them through ``DependencyTracking.release_many``
(grouped, one lock per dense-tier class group).  These tests pin the
contract over RANDOM layered DAGs:

- the completion SET equals the execution space exactly (nothing lost,
  nothing duplicated) under every storage tier and worker count;
- the ordering CONSTRAINT holds: every task completes strictly after each
  of its DAG predecessors (bodies append to a shared log; a successor's
  body cannot run before the release its predecessor's completion issued);
- the hashed tier (record-at-a-time through ``release_dep``) and the
  dense index-array tier (grouped batch path) drain identical DAGs to
  identical completion sets — the batched path IS the per-task path's
  semantics.

The DAG generator gives every in-edge slot its own CTL flow, so each
arrival lands on a distinct dep bit (the mask protocol's requirement), and
edge tables are plain dict lookups inside guards — exercising guard-driven
``input_dep_mask`` with 0..K_IN active inputs per task.
"""

import random
import threading

import pytest

from parsec_tpu import ptg
from parsec_tpu.runtime import Context

import parsec_tpu.runtime.dagrun  # noqa: F401 — registers runtime_dag_compile

K_IN = 3     # max in-edges per node (one CTL flow per slot)


def _random_dag(rng, layers, width):
    """in_edges[(d, n)] = list of source idx at layer d-1 (slot order)."""
    in_edges = {}
    for d in range(1, layers):
        for n in range(width):
            k = rng.randint(0, K_IN)
            in_edges[(d, n)] = rng.sample(range(width), k) if k else []
    return in_edges


def _build_pool(in_edges, layers, width, log, lock):
    """One task class T(d, n) on a (layers x width) grid; slot-k input flow
    ``in<k>`` fed by T(d-1, src) when the edge table says so."""
    out_edges = {}   # (d, n) -> list of (succ_n, slot)
    for (d, n), srcs in in_edges.items():
        for k, s in enumerate(srcs):
            out_edges.setdefault((d - 1, s), []).append((n, k))

    p = ptg.PTGBuilder("randdag", L=layers, W=width)
    t = p.task("T",
               d=ptg.span(0, lambda g, l: g.L - 1),
               n=ptg.span(0, lambda g, l: g.W - 1))
    for k in range(K_IN):
        f = t.flow(f"in{k}", ptg.CTL)
        f.input(pred=("T", f"in{k}",
                      lambda g, l, k=k:
                      {"d": l.d - 1, "n": in_edges[(l.d, l.n)][k]}),
                guard=lambda g, l, k=k:
                l.d > 0 and k < len(in_edges.get((l.d, l.n), ())))
        # the producing side of slot k: every out-edge of (d, n) that lands
        # in some successor's slot k
        for m in range(width):
            f.output(succ=("T", f"in{k}",
                           lambda g, l, m=m:
                           {"d": l.d + 1, "n": m}),
                     guard=lambda g, l, m=m, k=k:
                     (m, k) in [(sn, sk) for sn, sk
                                in out_edges.get((l.d, l.n), ())])

    def body(es, task, g, l):
        with lock:
            log.append((l.d, l.n))

    t.body(body)
    return p.build()


def _drain(param, in_edges, layers, width, storage, nb_cores):
    param("deps_storage", storage)
    param("runtime_dag_compile", False)   # exercise release_deps itself
    log, lock = [], threading.Lock()
    tp = _build_pool(in_edges, layers, width, log, lock)
    ctx = Context(nb_cores=nb_cores)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    ctx.fini()
    return log


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("storage,nb_cores", [
    ("index-array", 0), ("index-array", 2), ("hash", 0), ("hash", 2),
])
def test_random_dag_completion_set_and_ordering(param, seed, storage,
                                                nb_cores):
    rng = random.Random(seed)
    layers, width = 6, 7
    in_edges = _random_dag(rng, layers, width)
    log = _drain(param, in_edges, layers, width, storage, nb_cores)
    # completion set: the whole space, exactly once
    expect = {(d, n) for d in range(layers) for n in range(width)}
    assert len(log) == len(expect), f"{len(log)} != {len(expect)}"
    assert set(log) == expect
    # ordering constraint: every task after each of its predecessors
    pos = {t: i for i, t in enumerate(log)}
    for (d, n), srcs in in_edges.items():
        for s in srcs:
            assert pos[(d - 1, s)] < pos[(d, n)], \
                f"T({d},{n}) completed before its predecessor T({d - 1},{s})"


@pytest.mark.parametrize("seed", [5, 6])
def test_batched_tier_matches_per_record_tier(param, seed):
    """The dense tier's grouped batch release and the hashed tier's
    record-at-a-time release drain one identical DAG to the same set."""
    rng = random.Random(seed)
    layers, width = 5, 6
    in_edges = _random_dag(rng, layers, width)
    a = _drain(param, in_edges, layers, width, "index-array", 0)
    b = _drain(param, in_edges, layers, width, "hash", 0)
    assert set(a) == set(b)
    assert len(a) == len(b)


def test_release_many_groups_take_one_path(param):
    """A wide fan-out (one completion releasing many same-class deps) goes
    through the index-array tier's batch path and still accounts every
    release (the SDE-style engagement proof the dense tier keeps)."""
    param("deps_storage", "index-array")
    param("runtime_dag_compile", False)
    width = 16
    # FAN(0) -> every SINK(n): one completing task, 16 same-class records
    in_edges = {(1, n): [0] for n in range(width)}
    log, lock = [], threading.Lock()
    tp = _build_pool(in_edges, 2, width, log, lock)
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    store = ctx.deps._index_store
    assert store is not None
    assert store.releases == width     # every fan edge through the tier
    ctx.fini()
    assert len(log) == 2 * width
