"""Test-pyramid round-out (SURVEY §4 items without a prior analog):
custom device stage hooks (``stage_custom.jdf``), DTD allreduce
(``dtd_test_allreduce.c``), and the independent-chain scheduler stress
(``multichain.jdf``).
"""

import time

import jax
import numpy as np
import pytest

from parsec_tpu import ptg
from parsec_tpu.data_dist.matrix import TiledMatrix
from parsec_tpu.device import registry
from parsec_tpu.device.tpu import TPUDevice
from parsec_tpu.dtd import DTDTaskpool, INOUT, INPUT, OUTPUT
from parsec_tpu.runtime import Context


# accel_device fixture: shared in conftest.py


# ---------------------------------------------------------------------------
# custom stage hooks (stage_custom.jdf / device_gpu.h:61-77)
# ---------------------------------------------------------------------------

def test_custom_stage_hooks_drive_transfers(accel_device):
    """A class's stage_in_hook/stage_out_hook replace the default
    versioned staging: the custom stage-in doubles the tile on the way to
    the device, the custom stage-out records itself, and the vmapped
    batch path stands aside (custom hooks own data placement)."""
    calls = {"in": 0, "out": 0}
    n, nb = 32, 16
    a = np.random.default_rng(0).standard_normal((n, n)).astype(np.float32)
    A = TiledMatrix.from_dense("A", a.copy(), nb, nb)

    def my_stage_in(device, task):
        calls["in"] += 1
        import jax as _jax
        c = task.data[0]
        # custom transfer: land the tile on the device DOUBLED (a stand-in
        # for any user-owned packing/layout logic)
        c.value = _jax.device_put(np.asarray(c.value) * 2.0,
                                  device.jax_device)

    def my_stage_out(device, task):
        calls["out"] += 1

    p = ptg.PTGBuilder("stagec", A=A, MT=A.mt, NT=A.nt)
    t = p.task("T",
               m=ptg.span(0, lambda g, l: g.MT - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("X", ptg.RW)
    f.input(data=("A", lambda g, l: (l.m, l.n)))
    f.output(data=("A", lambda g, l: (l.m, l.n)))
    t.stage_hooks(stage_in=my_stage_in, stage_out=my_stage_out)

    def body(es, task, device):
        c = task.data[0]
        c.value = c.value + 1.0
        c.version += 1
        return c.value

    t.body(body, device="tpu")
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
    accel_device.sync()
    accel_device.flush_cache()
    ntiles = A.mt * A.nt
    assert calls["in"] == ntiles and calls["out"] == ntiles
    np.testing.assert_allclose(A.to_dense(), 2.0 * a + 1.0, rtol=1e-5)


def test_lowering_refuses_stage_hooked_classes():
    """Custom stage hooks own data placement: the compiled lowering must
    refuse (fall back dynamic), never silently drop the user's transfer
    logic."""
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.ptg.lowering import LoweringError, lower_taskpool
    n, nb = 32, 16
    a = np.ones((n, n), np.float32)
    A = TiledMatrix.from_dense("A", a, nb, nb)
    B = TiledMatrix.from_dense("B", a, nb, nb)
    C = TiledMatrix("C", n, n, nb, nb)
    tp = tiled_gemm_ptg(A, B, C)
    tp.task_class("GEMM").stage_in_hook = lambda device, task: None
    with pytest.raises(LoweringError, match="stage hooks"):
        lower_taskpool(tp)


# ---------------------------------------------------------------------------
# DTD allreduce (dtd_test_allreduce.c)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k,nb_cores", [(4, 0), (7, 2), (16, 2)])
def test_dtd_allreduce(k, nb_cores):
    """Reduce K tiles into tile 0, then broadcast the result back: after
    the pool drains every tile holds the elementwise sum of all K."""
    rng = np.random.default_rng(k)
    arrs = [rng.standard_normal(8).astype(np.float32) for _ in range(k)]
    want = np.sum(arrs, axis=0)

    def add_into(acc, x):
        acc[...] += x

    def copy_from(dst, src):
        dst[...] = src

    with Context(nb_cores=nb_cores) as ctx:
        tp = DTDTaskpool("allreduce")
        ctx.add_taskpool(tp)
        tiles = [tp.tile_of_array(a, key=("t", i))
                 for i, a in enumerate(arrs)]
        for i in range(1, k):
            tp.insert_task(add_into, (tiles[0], INOUT), (tiles[i], INPUT),
                           name="REDUCE")
        for i in range(1, k):
            tp.insert_task(copy_from, (tiles[i], OUTPUT),
                           (tiles[0], INPUT), name="BCAST")
        tp.wait(timeout=120)
    for a in arrs:
        np.testing.assert_allclose(a, want, rtol=1e-5)


# ---------------------------------------------------------------------------
# multichain (multichain.jdf): independent chains racing the scheduler
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sched", ["lfq", "ltq", "lhq"])
def test_multichain_ordering(sched):
    """NT independent chains of DEPTH tasks: every chain executes in
    order whatever the scheduler interleaves across workers."""
    NT, DEPTH = 8, 24
    seen: list[list[int]] = [[] for _ in range(NT)]

    p = ptg.PTGBuilder("multichain", NT=NT, D=DEPTH)
    t = p.task("T",
               c=ptg.span(0, lambda g, l: g.NT - 1),
               d=ptg.span(0, lambda g, l: g.D - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("T", "ctl", lambda g, l: {"c": l.c, "d": l.d - 1}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("T", "ctl", lambda g, l: {"c": l.c, "d": l.d + 1}),
             guard=lambda g, l: l.d < g.D - 1)

    def body(es, task, g, l):
        seen[l.c].append(l.d)
        if l.d % 7 == 0:
            time.sleep(0.001)     # jitter the interleaving

    t.body(body)
    with Context(nb_cores=4, scheduler=sched) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
    for c in range(NT):
        assert seen[c] == list(range(DEPTH)), f"chain {c} out of order"
