"""Collections tier: operators, redistribution, band/subtile variants.

Mirrors the reference's ``tests/collections/`` (SURVEY §4.5): redistribute
block↔block correctness (aligned and unaligned), map/reduce/broadcast
operator taskpools, band storage, recursive sub-tiling.
"""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import (SubtileCollection,
                                         SymTwoDimBlockCyclic, TiledMatrix,
                                         TwoDimBlockCyclic,
                                         TwoDimBlockCyclicBand,
                                         VectorTwoDimCyclic)
from parsec_tpu.data_dist.operators import (broadcast_taskpool, map_taskpool,
                                            reduce_taskpool)
from parsec_tpu.data_dist.redistribute import redistribute_taskpool
from parsec_tpu.runtime import Context
from parsec_tpu.runtime.taskpool import compose


@pytest.fixture
def ctx():
    c = Context(nb_cores=0)
    yield c
    c.fini()


# ---------------------------------------------------------------------------
# operators
# ---------------------------------------------------------------------------

def test_map_operator(ctx):
    a = np.arange(36, dtype=np.float32).reshape(6, 6)
    dA = TiledMatrix.from_dense("A", a, 2, 3)
    tp = map_taskpool(dA, lambda key, t: t * 2.0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    np.testing.assert_allclose(dA.to_dense(), a * 2.0)


def test_map_operator_inplace(ctx):
    a = np.ones((4, 4), dtype=np.float32)
    dA = TiledMatrix.from_dense("A", a, 2, 2)

    def bump(key, t):
        t += key[0] + key[1]

    ctx.add_taskpool(map_taskpool(dA, bump))
    ctx.wait(timeout=30)
    expect = np.ones((4, 4), np.float32)
    expect[:2, 2:] += 1
    expect[2:, :2] += 1
    expect[2:, 2:] += 2
    np.testing.assert_allclose(dA.to_dense(), expect)


@pytest.mark.parametrize("mt", [1, 2, 3, 5, 8])
def test_reduce_operator(ctx, mt):
    n = mt * 2
    a = np.arange(n * n, dtype=np.float64).reshape(n, n)
    dA = TiledMatrix.from_dense("A", a, 2, 2)
    out = {}
    tp = reduce_taskpool(dA, lambda x, y: x + y, out=out)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    # sum of all tiles == elementwise sum over the tile grid
    expect = sum(a[m * 2:(m + 1) * 2, k * 2:(k + 1) * 2]
                 for m in range(mt) for k in range(mt))
    np.testing.assert_allclose(out["value"], expect)
    # source tiles must be untouched by the reduction chain
    np.testing.assert_allclose(dA.to_dense(), a)


def test_reduce_ragged_with_transform(ctx):
    """Ragged edge tiles reduce through a per-tile transform (scalar sum)."""
    a = np.arange(70, dtype=np.float64).reshape(7, 10)
    dA = TiledMatrix.from_dense("A", a, 3, 4)   # ragged: 7x10 in 3x4 tiles
    out = {}
    tp = reduce_taskpool(dA, lambda x, y: x + y, out=out, transform=np.sum)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    np.testing.assert_allclose(out["value"], a.sum())


def test_broadcast_operator(ctx):
    src = VectorTwoDimCyclic("S", lm=4, mb=4, P=1,
                             init_fn=lambda m, size: np.arange(4.0))
    dst = VectorTwoDimCyclic("D", lm=4, mb=4, P=1,
                             init_fn=lambda m, size: np.zeros(size))
    tp = broadcast_taskpool(src, (0,), dst)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    np.testing.assert_allclose(dst.data_of(0).newest_copy().value,
                               np.arange(4.0))


def test_map_over_band_and_sym(ctx):
    """Operators enumerate only materialized tiles of sparse storages."""
    dB = TwoDimBlockCyclicBand("B", 8, 8, 2, 2, band_size=2)
    ctx.add_taskpool(map_taskpool(dB, lambda key, t: t + 1.0))
    ctx.wait(timeout=30)
    assert dB.data_of(1, 0).newest_copy().value[0, 0] == 1.0
    dS = SymTwoDimBlockCyclic("S", 8, 8, 2, 2, uplo=0)
    ctx.add_taskpool(map_taskpool(dS, lambda key, t: t + 1.0, name="map2"))
    ctx.wait(timeout=30)
    assert dS.data_of(3, 0).newest_copy().value[0, 0] == 1.0


def test_broadcast_multi_segment_dst(ctx):
    """Fan-out is sized by the destination, not the source."""
    src = VectorTwoDimCyclic("S2", lm=4, mb=4, P=1,
                             init_fn=lambda m, size: np.arange(4.0))
    dst = VectorTwoDimCyclic("D2", lm=12, mb=4, P=3, nodes=1,
                             init_fn=lambda m, size: np.zeros(size))
    ctx.add_taskpool(broadcast_taskpool(src, (0,), dst))
    ctx.wait(timeout=30)
    for r in range(3):
        np.testing.assert_allclose(dst.data_of(r).newest_copy().value,
                                   np.arange(4.0))


def test_broadcast_2d_dst(ctx):
    """A 2-D tiled-matrix destination works (keys come from the collection's
    own key space, not an assumed 1-D ``(r,)``)."""
    src = VectorTwoDimCyclic("S3", lm=4, mb=2, P=1,
                             init_fn=lambda m, size: np.full(size, 7.0))
    dst = TiledMatrix.from_dense("D3", np.zeros((4, 4)), 2, 2)
    ctx.add_taskpool(broadcast_taskpool(src, (0,), dst))
    ctx.wait(timeout=30)
    for i in range(2):
        for j in range(2):
            np.testing.assert_allclose(
                dst.data_of(i, j).newest_copy().value, np.full((2, 2), 7.0))


def _reduce_multirank_body(ctx, rank, nranks):
    n = 8
    a = np.arange(n * n, dtype=np.float64).reshape(n, n)
    dA = TwoDimBlockCyclic("A", n, n, 2, 2, P=nranks, Q=1, myrank=rank,
                           init_fn=lambda m, nn, shape:
                           a[m * 2:m * 2 + shape[0], nn * 2:nn * 2 + shape[1]])
    out = {}
    tp = reduce_taskpool(dA, lambda x, y: x + y, out=out)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    ctx.comm_barrier()
    return out.get("value")


def test_reduce_multirank():
    res = run_multirank(2, _reduce_multirank_body)
    n = 8
    a = np.arange(n * n, dtype=np.float64).reshape(n, n)
    expect = sum(a[m * 2:(m + 1) * 2, k * 2:(k + 1) * 2]
                 for m in range(4) for k in range(4))
    got = [r for r in res if r is not None]
    assert got, "no rank produced the reduction result"
    np.testing.assert_allclose(got[0], expect)


# ---------------------------------------------------------------------------
# redistribute
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("src_nb,dst_nb", [(4, 4), (4, 6), (6, 4), (5, 3)])
def test_redistribute_full(ctx, src_nb, dst_nb):
    """block <-> block correctness across aligned and unaligned tilings."""
    a = np.arange(144, dtype=np.float32).reshape(12, 12)
    dS = TiledMatrix.from_dense("S", a, src_nb, src_nb)
    dT = TiledMatrix.from_dense("T", np.zeros((12, 12), np.float32),
                                dst_nb, dst_nb)
    tp = redistribute_taskpool(dS, dT)
    ctx.add_taskpool(tp)
    tp.wait(timeout=30)
    np.testing.assert_allclose(dT.to_dense(), a)


def test_redistribute_submatrix(ctx):
    """Shifted submatrix copy with unaligned offsets."""
    a = np.arange(100, dtype=np.float32).reshape(10, 10)
    dS = TiledMatrix.from_dense("S", a, 4, 4)
    dT = TiledMatrix.from_dense("T", np.zeros((10, 10), np.float32), 3, 3)
    tp = redistribute_taskpool(dS, dT, size_row=5, size_col=6,
                               disi_src=2, disj_src=1,
                               disi_dst=3, disj_dst=4)
    ctx.add_taskpool(tp)
    tp.wait(timeout=30)
    expect = np.zeros((10, 10), np.float32)
    expect[3:8, 4:10] = a[2:7, 1:7]
    np.testing.assert_allclose(dT.to_dense(), expect)


def test_redistribute_composes(ctx):
    """Two redistributes sequenced through compose() round-trip the data."""
    a = np.arange(64, dtype=np.float32).reshape(8, 8)
    dS = TiledMatrix.from_dense("S", a, 4, 4)
    dT = TiledMatrix.from_dense("T", np.zeros((8, 8), np.float32), 3, 3)
    dU = TiledMatrix.from_dense("U", np.zeros((8, 8), np.float32), 5, 5)
    comp = compose(redistribute_taskpool(dS, dT, name="r1"),
                   redistribute_taskpool(dT, dU, name="r2"))
    ctx.add_taskpool(comp)
    ctx.wait(timeout=30)
    np.testing.assert_allclose(dU.to_dense(), a)


# ---------------------------------------------------------------------------
# band + subtile variants
# ---------------------------------------------------------------------------

def test_band_storage():
    dB = TwoDimBlockCyclicBand("B", 8, 8, 2, 2, P=2, Q=1, band_size=2,
                               nodes=2)
    assert dB.rank_of(0, 0) == 0
    assert dB.rank_of(2, 1) == 1   # min(2,1)=1 -> 1 % 2
    with pytest.raises(KeyError):
        dB.data_of(0, 3)
    assert dB.data_of(1, 0).newest_copy().value.shape == (2, 2)


def test_sym_band_storage():
    dB = SymTwoDimBlockCyclic("B", 8, 8, 2, 2, P=1, Q=1, uplo=0)
    assert dB.data_of(3, 1) is not None
    with pytest.raises(KeyError):
        dB.data_of(1, 3)


def test_subtile_recursive(ctx):
    """A nested taskpool over one parent tile's sub-tiling writes through
    (in-place bodies: sub-tiles are views into the parent)."""
    from parsec_tpu.data_dist.operators import map_taskpool
    a = np.zeros((8, 8), dtype=np.float32)
    dA = TiledMatrix.from_dense("A", a, 8, 8)   # one big tile
    sub = SubtileCollection(dA, 0, 0, 2, 2)
    assert (sub.mt, sub.nt) == (4, 4)

    def bump(key, t):
        t += key[0] * 4 + key[1]   # in-place: writes through the view

    ctx.add_taskpool(map_taskpool(sub, bump))
    ctx.wait(timeout=30)
    parent = dA.data_of(0, 0).newest_copy().value
    assert parent[0, 0] == 0 and parent[2, 0] == 4 and parent[7, 7] == 15
