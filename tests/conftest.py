"""Test harness configuration.

Multi-chip paths are tested on a virtual 8-device CPU mesh (the analog of the
reference's oversubscribed ``mpiexec -np 8`` CI runs, SURVEY §4).  The session
environment may pin JAX to a real TPU backend (JAX_PLATFORMS=axon via
sitecustomize), so we both set the env *and* override the config after import
— tests must be deterministic and must not occupy the bench chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# the autotuner consult (parsec_tpu/tune) must be hermetic under test: a
# leftover /tmp/tunedb.jsonl from a bench run on the same box must never
# steer test Contexts.  env-level default, so tests that probe the
# consult path still override it with params.set / their own stores.
if "PARSEC_MCA_tune_db_path" not in os.environ:
    import tempfile

    os.environ["PARSEC_MCA_tune_db_path"] = os.path.join(
        tempfile.mkdtemp(prefix="parsec_test_tune_"), "tunedb.jsonl")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

import pytest  # noqa: E402


@pytest.fixture
def accel_device():
    """A TPUDevice wrapping the host CPU jax device, registered for the
    test and restored after (shared by the device/pressure suites)."""
    from parsec_tpu.device import registry
    from parsec_tpu.device.tpu import TPUDevice

    snapshot = list(registry.devices)
    dev = TPUDevice(jax.devices()[0])
    registry.add(dev)
    yield dev
    registry.devices = snapshot
    for i, d in enumerate(registry.devices):
        d.device_index = i


@pytest.fixture
def param():
    """Scoped MCA-parameter override: set through the registry, restored
    at test exit (shared by every test module)."""
    from parsec_tpu.core.params import params
    saved = {}

    def set_(name, value):
        if name not in saved:       # keep the ORIGINAL for restore when a
            saved[name] = params.get(name)   # test overrides twice
        params.set(name, value)

    yield set_
    for name, value in saved.items():
        params.set(name, value)
