"""Live properties export: an external observer reads runtime gauges
mid-run (the ``dictionary.c`` + ``tools/aggregator_visu`` pair, VERDICT r3
missing #4): the context registers its scheduler depth / task gauges in
the properties dictionary and, with ``props_stream`` set, tails JSON
snapshots to a file while taskpools execute.
"""

import threading
import time

import numpy as np

from parsec_tpu import ptg
import parsec_tpu.runtime.dagrun  # noqa: F401  (registers runtime_dag_compile)
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
from parsec_tpu.prof.counters import properties, read_live_snapshot, sde
from parsec_tpu.runtime import Context


def _slow_chain(V, nt, delay):
    p = ptg.PTGBuilder("slow", V=V, NT=nt, D=delay)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.NT - 1))
    t.affinity("V", lambda g, l: (0,))
    f = t.flow("A", ptg.RW)
    f.input(data=("V", lambda g, l: (0,)), guard=lambda g, l: l.i == 0)
    f.input(pred=("T", "A", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "A", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.NT - 1)
    f.output(data=("V", lambda g, l: (0,)),
             guard=lambda g, l: l.i == g.NT - 1)

    def body(es, task, g, l):
        time.sleep(g.D)
        task.flow_data("A").value[...] += 1.0

    t.body(body)
    return p.build()


def test_snapshot_readable_during_run(tmp_path, param):
    """The acceptance gate: a reader thread observes a streamed snapshot
    WHILE the taskpool is still executing, and the snapshot carries the
    context gauges."""
    path = str(tmp_path / "props.json")
    param("props_stream", path)
    param("props_stream_interval", 0.02)
    param("runtime_dag_compile", False)   # keep the dynamic path visible

    V = VectorTwoDimCyclic("V", lm=4, mb=4,
                           init_fn=lambda m, size: np.zeros(size))
    tp = _slow_chain(V, nt=12, delay=0.05)
    seen: list[dict] = []
    ctx = Context(nb_cores=1)

    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                snap = read_live_snapshot(path)
            except (FileNotFoundError, ValueError):
                time.sleep(0.01)
                continue
            if not tp.test():          # captured strictly mid-run
                seen.append(snap)
            time.sleep(0.01)

    th = threading.Thread(target=reader)
    th.start()
    try:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    finally:
        stop.set()
        th.join(timeout=10)
        ctx.fini()

    assert seen, "no snapshot observed while the taskpool was running"
    snap = seen[-1]
    assert "ts" in snap
    r0 = snap["props"]["rank0"]
    assert r0["active_taskpools"] >= 1
    assert r0["nb_tasks"] >= 1          # tasks still outstanding mid-run
    assert "sched_pending" in r0 and "sde" in r0


def test_properties_registry_lifecycle(param):
    """Context registration appears in the dictionary and is removed at
    fini (no leakage across contexts)."""
    ctx = Context(nb_cores=0)
    snap = properties.snapshot()
    assert "rank0" in snap and "sched_pending" in snap["rank0"]
    ctx.fini()
    snap = properties.snapshot()
    assert "rank0" not in snap


def test_custom_property_and_sde_in_snapshot(param):
    properties.register("app", "phase", lambda: "factorize")
    try:
        sde.inc("app::custom", 3)
        snap = properties.snapshot()
        assert snap["app"]["phase"] == "factorize"
        assert sde.get("app::custom") >= 3
    finally:
        properties.unregister("app", "phase")


def test_dashboard_renders_snapshot():
    """The aggregator_visu consumer: a snapshot becomes a readable table
    with one column per rank namespace and sde dicts expanded to rows."""
    from parsec_tpu.prof.dashboard import render_snapshot
    snap = {"ts": 1000.0, "props": {
        "rank0": {"sched_pending": 3, "nb_tasks": 7,
                  "sde": {"parsec::steals": 2}},
        "rank1": {"sched_pending": 0, "nb_tasks": 4,
                  "sde": {"parsec::steals": 9}},
    }}
    text = render_snapshot(snap)
    assert "rank0" in text and "rank1" in text
    assert "sched_pending" in text and "sde:parsec::steals" in text
    lines = text.splitlines()
    row = next(l for l in lines if l.startswith("nb_tasks"))
    assert "7" in row and "4" in row


def test_dashboard_watch_live(tmp_path, param):
    """watch() renders frames from the live stream while a pool runs."""
    import io
    from parsec_tpu.prof.dashboard import watch
    path = str(tmp_path / "props.json")
    param("props_stream", path)
    param("props_stream_interval", 0.02)
    V = VectorTwoDimCyclic("V", lm=4, mb=4,
                           init_fn=lambda m, size: np.zeros(size))
    tp = _slow_chain(V, nt=6, delay=0.03)
    ctx = Context(nb_cores=1)
    try:
        ctx.add_taskpool(tp)
        ctx.start()              # opens the props stream
        time.sleep(0.15)
        buf = io.StringIO()
        watch(path, interval=0.02, iterations=3, out=buf)
        ctx.wait(timeout=60)
    finally:
        ctx.fini()
    text = buf.getvalue()
    assert "rank0" in text and "sched_pending" in text
