"""Megakernel region lowering (ISSUE 8): graphcheck-driven region
selection, one jitted program per convex subgraph, runtime scheduling at
region boundaries only, all under an explicit compile budget.

Covers the ISSUE-8 acceptance criteria on CPU:
- region-lowered cholesky (the irregular 4-class POTRF/TRSM/SYRK/GEMM
  DAG) and the LLM decode step match the eager runtime path across
  nb/nt sweeps;
- the region pool itself passes graphcheck (regions must not hide
  WAR/WAW hazards the whole-pool analysis proved ordered);
- XLA dispatches per DAG drop >= 5x vs task-per-dispatch;
- a compile budget the plan cannot afford sheds regions to the eager
  path (the stage completes — no rc-124 death), while a warm second
  plan reports compile_s <= 0.01 via the process lowering cache.
"""

import json

import numpy as np
import pytest

from parsec_tpu.analysis import GraphCheckError, select_regions, task_levels
from parsec_tpu.analysis.regions import regions_of_report
from parsec_tpu.data.datatype import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.data_dist.matrix import SymTwoDimBlockCyclic
from parsec_tpu.data_dist.paged_kv import PagedKVCollection
from parsec_tpu.llm import ToyLM, decode_step_ptg, prefill_chunks
from parsec_tpu.models.cholesky import make_spd, tiled_cholesky_ptg
from parsec_tpu.ptg import lowering
from parsec_tpu.ptg.lowering import lower_regions, lowering_cache
from parsec_tpu.runtime import Context


# ---------------------------------------------------------------------------
# region selection (analysis.regions)
# ---------------------------------------------------------------------------

def _diamond():
    # a -> b, c -> d  plus an isolated 2-chain x -> y (second component)
    return {
        ("A", (0,)): [("B", (0,)), ("C", (0,))],
        ("B", (0,)): [("D", (0,))],
        ("C", (0,)): [("D", (0,))],
        ("D", (0,)): [],
        ("X", (0,)): [("Y", (0,))],
        ("Y", (0,)): [],
    }


def test_task_levels_are_longest_path():
    lv = task_levels(_diamond())
    assert lv[("A", (0,))] == 0
    assert lv[("B", (0,))] == lv[("C", (0,))] == 1
    assert lv[("D", (0,))] == 2
    assert lv[("X", (0,))] == 0 and lv[("Y", (0,))] == 1


def test_select_regions_unbounded_is_one_per_component():
    regs = select_regions(_diamond())
    assert len(regs) == 2
    sizes = sorted(r.ntasks for r in regs)
    assert sizes == [2, 4]
    # independent components share no region-DAG edges
    assert all(not r.preds and not r.succs for r in regs)


def test_select_regions_cap_splits_on_band_boundaries():
    adj = _diamond()
    regs = select_regions(adj, max_tasks=2)
    # regions partition the node set exactly
    assign = {}
    for r in regs:
        for node in r.members:
            assert node not in assign
            assign[node] = r.index
    assert set(assign) == set(adj)
    # bounded size: a region only exceeds the cap when a single level
    # band is itself larger (bands never split)
    for r in regs:
        assert r.ntasks <= 2 or r.level_lo == r.level_hi
    # convexity: every task edge crossing regions matches a region-DAG
    # edge, and region edges always point to later level bands
    for v, succs in adj.items():
        for s in succs:
            if assign[v] != assign[s]:
                assert assign[s] in regs[assign[v]].succs
                assert assign[v] in regs[assign[s]].preds
    for r in regs:
        for p in r.preds:
            assert regs[p].level_lo <= r.level_lo


def test_task_levels_raises_on_cycle():
    adj = {("A", (0,)): [("B", (0,))], ("B", (0,)): [("A", (0,))]}
    with pytest.raises(ValueError, match="cycle"):
        task_levels(adj)


def test_regions_of_report_rejects_truncated_and_failing():
    class FakeReport:
        truncated = True
        ok = True
        name = "fake"
        graph = {}
        ntasks = 0
    with pytest.raises(ValueError, match="truncated"):
        regions_of_report(FakeReport())


def test_regions_of_report_rejects_graphless_nonempty_report():
    """Only check_ptg retains the concrete graph; a DTD/JDF report must
    refuse loudly instead of yielding zero regions for a live pool."""
    class DTDShapedReport:
        truncated = False
        ok = True
        name = "dtd"
        graph = {}
        ntasks = 7
    with pytest.raises(ValueError, match="no concrete task graph"):
        regions_of_report(DTDShapedReport())


# ---------------------------------------------------------------------------
# cholesky: the irregular 4-class DAG, region-lowered vs the eager runtime
# ---------------------------------------------------------------------------

def _chol_eager(a, nb):
    """The eager runtime path: numpy bodies, task-grained scheduling."""
    A = SymTwoDimBlockCyclic.from_dense("A", a.copy(), nb, nb)
    tp = tiled_cholesky_ptg(A, devices="cpu")
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    return np.tril(A.to_dense())


@pytest.mark.parametrize("n,nb,max_tasks", [
    (64, 16, 0),        # nt=4, one region per component
    (96, 32, 0),        # nt=3
    (128, 32, 6),       # nt=4, forced multi-region (band splits)
    (160, 32, 8),       # nt=5, multi-region with cross-band conflicts
])
def test_region_cholesky_matches_eager_runtime(n, nb, max_tasks):
    a = make_spd(n)
    want = _chol_eager(a, nb)
    A = SymTwoDimBlockCyclic.from_dense("A", a.copy(), nb, nb)
    plan = lower_regions(tiled_cholesky_ptg(A), max_tasks=max_tasks)
    plan.execute()
    got = np.tril(A.to_dense())
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
    # ... and against the dense oracle, so both paths can't be wrong
    expect = np.linalg.cholesky(a.astype(np.float64)).astype(np.float32)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)


def test_region_cholesky_xla_call_drop_vs_task_per_dispatch():
    """ISSUE-8 acceptance: on the 4-class DAG the region path must issue
    >= 5x fewer XLA dispatches than task-per-dispatch (one call per task
    — the dynamic-path lower bound without vmapped batching)."""
    n, nb = 160, 32                       # nt=5 -> 35 tasks
    a = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a.copy(), nb, nb)
    plan = lower_regions(tiled_cholesky_ptg(A))
    plan.execute()
    st = plan.stats()
    assert st["ntasks"] == 35
    assert st["xla_calls"] >= 1
    assert st["ntasks"] / st["xla_calls"] >= 5.0, st


def test_region_pool_passes_graphcheck():
    """The region pool (one REGION task per region, CTL fan-in edges
    mirroring the region DAG) is a plain PTG pool — graphcheck must
    prove it clean, or region scheduling hides hazards."""
    a = make_spd(128)
    A = SymTwoDimBlockCyclic.from_dense("A", a.copy(), 32, 32)
    plan = lower_regions(tiled_cholesky_ptg(A), max_tasks=6)
    assert len(plan.regions) > 1
    plan.compile()
    table = plan.materialize_table()
    pool = plan.taskpool(table)
    report = pool.validate()
    assert not report.errors, report.summary()
    assert pool.region_plan is plan


def test_region_program_size_is_grouped_not_per_task():
    """O(wavefronts x classes) program size: the region emission groups
    same-class tasks into vmapped calls, so a region's spec count stays
    far below its task count."""
    a = make_spd(256)
    A = SymTwoDimBlockCyclic.from_dense("A", a.copy(), 32, 32)
    plan = lower_regions(tiled_cholesky_ptg(A))     # nt=8 -> 120 tasks
    st = plan.stats()
    assert st["ntasks"] == 120
    assert st["regions"] == 1
    # one program, 120 tasks: the signature's runs payload carries one
    # spec list per (folded) level, not one entry per task
    reg = next(r for r in plan.regions if r.step_fn is not None)
    nspecs = sum(len(specs) for _reps, specs in reg.signature[-1])
    assert nspecs < st["ntasks"] / 2, nspecs


# ---------------------------------------------------------------------------
# compile budget: shed to eager, warm hits are free
# ---------------------------------------------------------------------------

def _fresh_chol_plan(n=160, nb=32, max_tasks=8):
    a = make_spd(n)
    A = SymTwoDimBlockCyclic.from_dense("A", a.copy(), nb, nb)
    return a, A, lower_regions(tiled_cholesky_ptg(A), max_tasks=max_tasks)


def test_compile_budget_sheds_to_eager_and_still_completes():
    lowering_cache.clear()
    a, A, plan = _fresh_chol_plan()
    notes = []
    st = plan.compile(budget_s=1e-9,
                      note=lambda **kw: notes.append(kw))
    data_regions = [r for r in plan.regions if r.step_fn is not None]
    assert st["regions_compiled"] == 0
    assert st["regions_eager"] == len(data_regions)
    assert any(n_.get("eager") for n_ in notes)
    # the stage still completes (no rc-124 compile death) and is correct
    plan.execute()
    got = np.tril(A.to_dense())
    expect = np.linalg.cholesky(a.astype(np.float64)).astype(np.float32)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)
    assert plan.stats()["xla_calls"] == 0
    assert plan.stats()["eager_runs"] == len(data_regions)


def test_compile_budget_warm_run_is_free():
    """ISSUE-8 acceptance: a warm second run reports compile_s <= 0.01 —
    cache hits are never shed, even under a budget no compile could fit."""
    _a, _A, plan = _fresh_chol_plan()
    plan.compile()                        # cold: pays trace + compile
    assert plan.stats()["regions_compiled"] > 0
    _a2, _A2, plan2 = _fresh_chol_plan()  # structurally identical
    notes = []
    st = plan2.compile(budget_s=1e-9,
                       note=lambda **kw: notes.append(kw))
    assert st["regions_eager"] == 0
    assert st["regions_compiled"] == plan.stats()["regions_compiled"]
    assert st["compile_s"] <= 0.01, st
    assert st["trace_s"] <= 0.01, st
    assert all(n_.get("cached") for n_ in notes)


def test_budget_staged_compile_is_ascending_and_sheds_monotonically():
    """Staged compile runs SMALLEST region first: the cheap compiles
    bootstrap the per-task cost rate that guards the expensive ones, so
    the largest region sheds BEFORE burning the budget (the 141s
    BENCH_r04/r05 compile could never be the first thing attempted).
    Mixed compiled/eager execution stays correct."""
    lowering_cache.clear()
    a, A, plan = _fresh_chol_plan(max_tasks=6)
    assert len([r for r in plan.regions if r.step_fn is not None]) >= 3
    notes = []
    st = plan.compile(budget_s=3.0,       # CPU compiles are ~0.1-0.5s each
                      note=lambda **kw: notes.append(kw))
    # processing order is ascending by region size
    sizes = [n_["ntasks"] for n_ in notes]
    assert sizes == sorted(sizes), notes
    # shedding is monotone: once the budget stops affording a region,
    # every later (>= as large) region sheds too (cache is cold, so no
    # free hits can interleave)
    eager_flags = [bool(n_.get("eager")) for n_ in notes]
    if any(eager_flags):
        first = eager_flags.index(True)
        assert all(eager_flags[first:]), notes
    assert st["regions_compiled"] >= 1
    plan.execute()
    got = np.tril(A.to_dense())
    expect = np.linalg.cholesky(a.astype(np.float64)).astype(np.float32)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)


def test_lower_regions_truncated_report_raises_lowering_error(param):
    """A truncated graphcheck enumeration (analysis_max_tasks) cannot
    produce sound regions — and it must surface as LoweringError, the
    documented contract, so callers' fallback paths engage."""
    from parsec_tpu.ptg.lowering import LoweringError

    param("analysis_max_tasks", 5)
    a = make_spd(160)
    A = SymTwoDimBlockCyclic.from_dense("A", a, 32, 32)   # 35 tasks > 5
    with pytest.raises(LoweringError, match="truncated"):
        lower_regions(tiled_cholesky_ptg(A))


# ---------------------------------------------------------------------------
# LLM decode step: parallel per-sequence components, open collections
# ---------------------------------------------------------------------------

MODEL = ToyLM()
H, D = MODEL.num_heads, MODEL.head_dim
PROMPTS = {"a": [3, 7, 11, 5, 9, 2], "b": [1, 40], "c": [8, 8, 2, 6]}


def _decode_setup(devices):
    """One decode-step geometry: pages prefilled host-side (the PF pool's
    straight page copy, done directly), Q loaded with the query token."""
    kv = PagedKVCollection("KV", page_size=4, num_heads=H, head_dim=D)
    Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    for seq, prompt in PROMPTS.items():
        kv.alloc_seq(seq)
        chunks = prefill_chunks(MODEL, kv, seq, prompt[:-1])
        for (s, c), tile in chunks.items():
            copy = kv.data_of(s, c).newest_copy()
            copy.value = np.array(tile, copy=True)
            copy.version += 1
        kv.ensure_tail_slot(seq)
        qc = Q.data_of(seq).get_copy(0)
        qc.value = MODEL.q3(prompt[-1])
        qc.version += 1
    return kv, Q, O, decode_step_ptg(kv, Q, O, list(PROMPTS),
                                     devices=devices)


@pytest.mark.parametrize("max_tasks", [0, 4])
def test_region_llm_decode_matches_eager_runtime(max_tasks):
    kv_e, _Qe, O_e, tp_e = _decode_setup("cpu")
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp_e)
        ctx.wait(timeout=120)

    kv_r, _Qr, O_r, tp_r = _decode_setup("auto")
    plan = lower_regions(tp_r, max_tasks=max_tasks)
    if max_tasks == 0:
        # per-sequence ATTN chains are independent components -> the
        # runtime may execute them as parallel regions
        assert len(plan.regions) == len(PROMPTS)
    plan.execute()

    for seq, prompt in PROMPTS.items():
        got = np.asarray(O_r.data_of(seq).newest_copy().value)
        want = np.asarray(O_e.data_of(seq).newest_copy().value)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # the OUT task's tail-page append (KV writeback) must match too
        pe = np.asarray(
            kv_e.data_of(seq, kv_e.npages(seq) - 1).newest_copy().value)
        pr = np.asarray(
            kv_r.data_of(seq, kv_r.npages(seq) - 1).newest_copy().value)
        np.testing.assert_allclose(pr, pe, rtol=1e-5, atol=1e-6)


def test_parallel_identical_regions_share_one_executable():
    """Structurally identical regions (same grouped runs, same avals —
    the decode step's parallel per-seq chains at equal page counts) must
    share ONE compiled executable: the cache key covers what the traced
    program depends on, not the global boundary rows."""
    kv = PagedKVCollection("KV", page_size=4, num_heads=H, head_dim=D)
    Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    seqs = [f"s{i}" for i in range(4)]
    for s in seqs:                        # equal geometry: 2 pages each
        kv.alloc_seq(s)
        chunks = prefill_chunks(MODEL, kv, s, [3, 7, 11, 5])
        for (sq, c), tile in chunks.items():
            copy = kv.data_of(sq, c).newest_copy()
            copy.value = np.array(tile, copy=True)
            copy.version += 1
        kv.ensure_tail_slot(s)
        qc = Q.data_of(s).get_copy(0)
        qc.value = MODEL.q3(9)
        qc.version += 1
    plan = lower_regions(decode_step_ptg(kv, Q, O, seqs, devices="auto"))
    assert len(plan.regions) == len(seqs)
    h0, m0 = lowering_cache.hits, lowering_cache.misses
    st = plan.compile()
    assert st["regions_compiled"] == len(seqs)
    assert lowering_cache.misses - m0 <= 1, (
        lowering_cache.misses - m0, "identical regions re-compiled")
    assert lowering_cache.hits - h0 >= len(seqs) - 1


def test_region_llm_decode_pool_passes_graphcheck():
    _kv, _Q, _O, tp = _decode_setup("auto")
    plan = lower_regions(tp)
    plan.compile()
    table = plan.materialize_table()
    pool = plan.taskpool(table)
    report = pool.validate()
    assert not report.errors, report.summary()


# ---------------------------------------------------------------------------
# LLM k-step decode superpool: the ISSUE-9 multi-step generalization
# ---------------------------------------------------------------------------

def _superpool_setup(steps, devices):
    """k-step geometry over PROMPTS, prepped by the library's own
    ``seed_decode_superpool`` (the batcher's seeding contract)."""
    from parsec_tpu.llm import decode_superpool_ptg, seed_decode_superpool
    kv = PagedKVCollection("KV", page_size=4, num_heads=H, head_dim=D)
    Q = DictCollection("Q", dtt=TileType((3, H, D), np.float32))
    O = DictCollection("O", dtt=TileType((H, D), np.float32))
    TOK = DictCollection("TOK", dtt=TileType((3,), np.float32))
    EMB = DictCollection("EMB", dtt=TileType(MODEL.q3_table().shape,
                                             np.float32))
    seed_decode_superpool(MODEL, kv, Q, TOK, EMB, PROMPTS, steps)
    tp = decode_superpool_ptg(kv, Q, O, TOK, EMB, list(PROMPTS),
                              [steps[s] for s in PROMPTS],
                              devices=devices)
    return kv, TOK, tp


@pytest.mark.parametrize("max_tasks", [0, 8])
def test_region_llm_superpool_k_steps_matches_eager_runtime(max_tasks):
    """The ISSUE-9 acceptance: the 1-step eager-vs-region equivalence
    generalizes to k > 1 — cross-step tail-page dataflow, in-graph
    SAMPLE chains, mixed per-seq step counts, page boundaries crossed
    mid-pool — and both paths equal the dense token oracle."""
    steps = {"a": 5, "b": 4, "c": 2}
    kv_e, TOK_e, tp_e = _superpool_setup(steps, "cpu")
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp_e)
        ctx.wait(timeout=120)

    kv_r, TOK_r, tp_r = _superpool_setup(steps, "auto")
    plan = lower_regions(tp_r, max_tasks=max_tasks)
    if max_tasks == 0:
        # per-sequence chains stay independent components across steps
        assert len(plan.regions) == len(PROMPTS)
    plan.execute()

    from parsec_tpu.llm import read_token_chain

    def toks(TOK, seq, k):
        return read_token_chain(TOK, seq, k)[0]

    for seq, prompt in PROMPTS.items():
        want = MODEL.reference_generate(prompt, steps[seq])
        assert toks(TOK_e, seq, steps[seq]) == want, ("eager", seq)
        assert toks(TOK_r, seq, steps[seq]) == want, ("region", seq)
        # the tail page (appended k/v of every step) must agree too
        pe = np.asarray(
            kv_e.data_of(seq, kv_e.npages(seq) - 1).newest_copy().value)
        pr = np.asarray(
            kv_r.data_of(seq, kv_r.npages(seq) - 1).newest_copy().value)
        np.testing.assert_allclose(pr, pe, rtol=1e-5, atol=1e-6)


def test_region_llm_superpool_pool_passes_graphcheck():
    """The region pool built from a k-step superpool is itself a clean
    PTG pool (region scheduling must not hide the cross-step WAR/WAW
    hazards the whole-pool analysis proved ordered)."""
    steps = {"a": 4, "b": 3, "c": 2}
    _kv, _TOK, tp = _superpool_setup(steps, "auto")
    plan = lower_regions(tp)
    plan.compile()
    table = plan.materialize_table()
    pool = plan.taskpool(table)
    report = pool.validate()
    assert not report.errors, report.summary()


# ---------------------------------------------------------------------------
# graphcheck gating: an unverifiable pool never region-lowers
# ---------------------------------------------------------------------------

def test_lower_regions_refuses_failing_graphcheck():
    from parsec_tpu import ptg

    # a pool whose edge symmetry is broken: A declares a successor edge
    # that B never declares as input
    p = ptg.PTGBuilder("bad", N=2)
    ta = p.task("A", i=ptg.span(0, lambda g, l: g.N - 1))
    fa = ta.flow("ctl", ptg.CTL)
    fa.output(succ=("B", "ctl", lambda g, l: {"i": l.i}))
    ta.body(lambda es, task, g, l: None)
    tb = p.task("B", i=ptg.span(0, lambda g, l: g.N - 1))
    tb.flow("ctl", ptg.CTL)             # no matching input edge
    tb.body(lambda es, task, g, l: None)
    with pytest.raises(GraphCheckError):
        lower_regions(p.build())


# ---------------------------------------------------------------------------
# AOT cache warming CLI
# ---------------------------------------------------------------------------

def test_warm_cache_cli_region_mode(capsys):
    rc = lowering._main(["--warm", "cholesky", "--n", "128", "--nb", "32",
                         "--modes", "region"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["workload"] == "cholesky"
    assert out["region"]["regions"] >= 1
    assert out["region"]["regions_eager"] == 0
    assert "backend" in out                   # the cross-backend cache key


def test_warm_cache_cli_llm_decode_k_workload(capsys):
    """The ISSUE-9 AOT entry: the k-step decode superpool's region
    programs warm through the CLI (scripts/warm_cache.sh ships it in
    the default workload set)."""
    rc = lowering._main(["--warm", "llm_decode_k", "--n", "2", "--nb",
                         "2", "--modes", "region"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["workload"] == "llm_decode_k"
    assert out["nseqs"] == 2 and out["steps"] == 2
    assert out["region"]["regions"] >= 1
    assert out["region"]["regions_eager"] == 0


def test_warm_cache_traces_against_avals_without_executing():
    """warm_cache compiles AOT — collection tiles must stay untouched."""
    out = lowering.warm_cache("cholesky", n=96, nb=32, modes=("region",))
    assert out["region"]["regions_compiled"] >= 1
    # a second warm at the same geometry is a pure cache hit
    out2 = lowering.warm_cache("cholesky", n=96, nb=32, modes=("region",))
    assert out2["region"]["compile_s"] <= 0.01, out2
