"""Closed-loop autotuner (ISSUE 18): knob declarations and scoped
overrides, structural signatures, the tuning DB (cross-process), the
budgeted search, ambient consults at Context start / per-tenant submit,
and the live per-tenant adaptation controller.

The acceptance e2e lives here too: a seeded-bad knob vector on a small
decode workload is recovered by ``tune.search`` within a bounded
budget, the winner persists to ``tunedb.jsonl``, a fresh ``Context``
picks it up, and the per-tenant adapter stays oracle-equal
token-for-token while converging."""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from parsec_tpu.core.params import KnobSpec, params
from parsec_tpu.tune import (TuneDB, ambient_signature, apply_ambient,
                             consult_ambient, workload_signature)
from parsec_tpu.tune import db as tunedb_mod
from parsec_tpu.tune.adaptive import GARBAGE_LIMIT, KnobController
from parsec_tpu.tune.search import declared_space, search

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# knob space + scoped overrides (core/params.py)
# ---------------------------------------------------------------------------

def test_knobspec_moves_and_domain():
    s = KnobSpec(name="k", lo=1, hi=8, scale="log2")
    assert s.neighbors(2) == [4, 1]
    assert s.neighbors(8) == [4]            # hi clamp folds the up move
    assert s.contains(8) and not s.contains(9)
    e = KnobSpec(name="m", values=("a", "b", "c"))
    assert e.neighbors("b") == ["a", "c"]
    assert e.neighbors("zz") == ["a", "b", "c"]   # off-domain: full reset
    lin = KnobSpec(name="n", lo=0, hi=10, step=2.0)
    assert lin.neighbors(4) == [6, 2]


def test_declare_knob_idempotent_and_declared_space():
    params.register("tune_t_knob", 4, "test knob")
    s1 = params.declare_knob("tune_t_knob", lo=1, hi=16, scale="log2")
    s2 = params.declare_knob("tune_t_knob", lo=2, hi=999)
    assert s1 is s2 and s2.hi == 16         # first declaration wins
    assert "tune_t_knob" in declared_space(["tune_t_knob"])
    with pytest.raises(KeyError):
        declared_space(["definitely_not_declared"])


def test_overrides_scoped_and_atomic():
    params.register("tune_t_ov", 3, "test")
    with params.overrides({"tune_t_ov": 7}):
        assert params.get("tune_t_ov") == 7
        assert params.lookup("tune_t_ov").source == "set"
    assert params.get("tune_t_ov") == 3
    assert params.lookup("tune_t_ov").source == "default"
    # an unregistered name fails BEFORE anything is applied
    with pytest.raises(KeyError):
        with params.overrides({"tune_t_ov": 9, "tune_t_missing": 1}):
            pass
    assert params.get("tune_t_ov") == 3


def test_runtime_report_carries_knob_vector(param):
    from parsec_tpu.prof.flight_recorder import runtime_report
    params.register("tune_t_rep", 5, "test")
    params.declare_knob("tune_t_rep", lo=1, hi=8)
    param("tune_t_rep", 6)
    rep = runtime_report()
    kn = rep["knobs"]
    assert kn["tune_t_rep"] == 6            # non-default value resolved
    snap = params.snapshot()
    for name in params.knob_space():        # every declared knob rides
        if name in snap:
            assert name in kn, name


# ---------------------------------------------------------------------------
# structural signatures (tune/signature.py over ptg/lowering.py)
# ---------------------------------------------------------------------------

def _gemm_pool(n=12, nb=4, seed=0, tag="x"):
    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(np.float32)
    A = TiledMatrix.from_dense(f"A{tag}", a, nb, nb)
    B = TiledMatrix.from_dense(f"B{tag}", a.T.copy(), nb, nb)
    C = TiledMatrix.from_dense(f"C{tag}", np.zeros((n, n), np.float32),
                               nb, nb)
    return tiled_gemm_ptg(A, B, C)


def test_equal_structure_equal_signature_equal_db_key():
    """The property test: two separately built pools with the same
    structure (different data, different collection names) sign
    identically, so their tuning-DB keys collide — which is the point."""
    s1 = workload_signature(_gemm_pool(seed=0, tag="p"))
    s2 = workload_signature(_gemm_pool(seed=9, tag="q"))
    assert s1 == s2
    be = ["0.4.30", "cpu", ""]
    assert tunedb_mod.make_key(s1, backend=be) == \
        tunedb_mod.make_key(s2, backend=be)


def test_backend_change_different_key_same_signature():
    """Backend is the key's second column, NOT part of the signature: a
    vector tuned on TPU must never apply on CPU, but the structural
    identity survives the port."""
    s = workload_signature(_gemm_pool())
    k_cpu = tunedb_mod.make_key(s, backend=["0.4.30", "cpu", ""])
    k_tpu = tunedb_mod.make_key(s, backend=["0.4.30", "tpu", "v5e"])
    assert k_cpu != k_tpu
    assert json.loads(k_cpu)["sig"] == json.loads(k_tpu)["sig"]


def test_different_structure_different_signature():
    assert workload_signature(_gemm_pool(n=12, nb=4)) != \
        workload_signature(_gemm_pool(n=16, nb=4))
    # explicit size hint separates size classes of one structure
    tp = _gemm_pool()
    assert workload_signature(tp, size_hint=512) != \
        workload_signature(tp, size_hint=8192)


# ---------------------------------------------------------------------------
# the tuning DB (tune/db.py)
# ---------------------------------------------------------------------------

def test_tunedb_best_direction_per_objective(tmp_path):
    db = TuneDB(str(tmp_path / "t.jsonl"))
    be = ["j", "cpu", ""]
    db.note("s", {"k": 1}, 10.0, objective="tokens_per_s", backend=be)
    db.note("s", {"k": 2}, 90.0, objective="tokens_per_s", backend=be)
    db.note("s", {"k": 3}, 5.0, objective="tok_latency_ms", backend=be)
    db.note("s", {"k": 4}, 1.0, objective="tok_latency_ms", backend=be)
    assert db.best("s", objective="tokens_per_s",
                   backend=be)["knobs"] == {"k": 2}
    assert db.best("s", objective="tok_latency_ms",
                   backend=be)["knobs"] == {"k": 4}
    assert db.best("s", objective="wall_s", backend=be) is None
    with pytest.raises(ValueError):
        db.note("s", {"k": 5}, float("nan"))


def test_tunedb_cross_process_roundtrip(tmp_path):
    """A vector noted here is the `best` answer in a fresh interpreter,
    and a vector the CHILD appends is visible to the parent's CACHED
    consult path (the (mtime_ns, size) generation moved)."""
    path = str(tmp_path / "tunedb.jsonl")
    be = ["j", "cpu", ""]
    TuneDB(path).note("wl:x", {"nb": 128, "sched": "spq"}, 1.25,
                      objective="wall_s", backend=be)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    code = (
        "import json\n"
        "from parsec_tpu.tune.db import TuneDB\n"
        f"db = TuneDB({path!r})\n"
        f"rec = db.best('wl:x', objective='wall_s', backend={be!r})\n"
        "print(json.dumps(rec['knobs']))\n"
        f"db.note('wl:x', {{'nb': 256}}, 0.5, objective='wall_s',"
        f" backend={be!r})\n")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         cwd=str(REPO), capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout.strip()) == {"nb": 128, "sched": "spq"}
    rec = tunedb_mod.cached_db(path).best("wl:x", objective="wall_s",
                                          backend=be)
    assert rec["knobs"] == {"nb": 256}      # 0.5 < 1.25: wall_s is lower


def test_tunedb_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "t.jsonl")
    db = TuneDB(path)
    db.note("s", {"k": 1}, 1.0, backend=["j", "cpu", ""])
    with open(path, "a") as f:
        f.write('{"key": "torn half-line')
    rec = TuneDB(path).best("s", backend=["j", "cpu", ""])
    assert rec is not None and rec["knobs"] == {"k": 1}


# ---------------------------------------------------------------------------
# ambient consult + apply (tune/__init__.py)
# ---------------------------------------------------------------------------

def test_consult_ambient_filters_to_declared_domain(tmp_path, param):
    path = str(tmp_path / "tunedb.jsonl")
    param("tune_db_path", path)
    params.register("tune_t_consult", 2, "test")
    params.declare_knob("tune_t_consult", lo=1, hi=8)
    TuneDB(path).note(ambient_signature("t_gate"),
                      {"tune_t_consult": 4, "undeclared_thing": 9}, 1.0)
    TuneDB(path).note(ambient_signature("t_oob"),
                      {"tune_t_consult": 99}, 1.0)
    assert consult_ambient("t_gate") == {"tune_t_consult": 4}
    assert consult_ambient("t_oob") is None     # out-of-domain dropped
    param("tune_db", False)
    assert consult_ambient("t_gate") is None    # the gate


def test_apply_ambient_respects_operator_pins(tmp_path, param):
    path = str(tmp_path / "tunedb.jsonl")
    param("tune_db_path", path)
    params.register("tune_t_apply", 2, "test")
    params.declare_knob("tune_t_apply", lo=1, hi=8)
    TuneDB(path).note(ambient_signature("t_apply"),
                      {"tune_t_apply": 8}, 1.0)
    p = params.lookup("tune_t_apply")
    src = p.source
    p.source = "env"                    # simulate an operator env pin
    try:
        assert apply_ambient("t_apply") is None
        assert params.get("tune_t_apply") == 2
    finally:
        p.source = src
    assert apply_ambient("t_apply") == {"tune_t_apply": 8}
    assert params.get("tune_t_apply") == 8
    params.set("tune_t_apply", 2)


# ---------------------------------------------------------------------------
# the search (tune/search.py)
# ---------------------------------------------------------------------------

def test_search_prunes_known_bad_points_from_ledger(tmp_path, param):
    """The perfdb EWMA seeds the search: a vector whose recorded
    history is far worse than the incumbent never spends a trial."""
    from parsec_tpu.prof import perfdb as perfdb_mod
    param("perfdb", True)
    param("perfdb_path", str(tmp_path / "perfdb.jsonl"))
    perf = perfdb_mod.PerfDB()
    space = {"x": KnobSpec(name="x", lo=1, hi=4, step=1.0)}
    sig = "t:prune"
    # known-bad history for x=2 (the only neighbor of the start point)
    bad_key = perfdb_mod.make_key(f"tune.{sig}", "cost_s",
                                  knobs={"x": 2})
    for _ in range(4):
        perf.append(bad_key, 1000.0, run="tune")
    ran: list = []

    def fn(knobs):
        ran.append(dict(knobs))
        return 1.0

    out = search(fn, signature=sig, space=space, budget=8, restarts=1,
                 objective="cost_s", start={"x": 1},
                 db=TuneDB(str(tmp_path / "t.jsonl")), persist=False)
    assert out["pruned"] >= 1, out
    assert {"x": 2} not in ran              # never re-measured
    assert out["best"] == {"x": 1}


def test_search_persists_winner_and_reseeds_from_it(tmp_path, param):
    param("perfdb", False)
    db = TuneDB(str(tmp_path / "t.jsonl"))
    space = {"x": KnobSpec(name="x", lo=1, hi=16, scale="log2")}
    cost = {1: 9.0, 2: 5.0, 4: 2.0, 8: 1.0, 16: 3.0}
    out = search(lambda k: cost[k["x"]], signature="t:seed", space=space,
                 budget=10, restarts=1, objective="cost_s",
                 start={"x": 1}, db=db)
    assert out["best"] == {"x": 8} and out["best_score"] == 1.0
    assert db.best("t:seed", objective="cost_s")["knobs"] == {"x": 8}
    # a later budget-1 search starts FROM the persisted winner
    out2 = search(lambda k: cost[k["x"]], signature="t:seed",
                  space=space, budget=1, restarts=1, objective="cost_s",
                  db=db)
    assert out2["trials"][0]["knobs"] == {"x": 8}


# ---------------------------------------------------------------------------
# the adaptive controller (tune/adaptive.py)
# ---------------------------------------------------------------------------

def _drive(c: KnobController, cost: dict, n: int) -> None:
    for _ in range(n):
        c.observe(cost[c.value])
    while c._probing is not None:           # settle any probe in flight
        c.observe(cost[c.value])


def test_controller_probes_and_adopts_better_value():
    c = KnobController("k", default=4, lo=1, hi=16, probe_every=4,
                       probe_len=2)
    cost = {1: 40.0, 2: 20.0, 4: 10.0, 8: 5.0, 16: 2.0}
    _drive(c, cost, 200)
    assert c._incumbent == 16 and c.adoptions >= 2, c.stats()
    wb = c.take_writeback()
    assert wb == 16
    assert c.take_writeback() is None       # exactly once per adoption


def test_controller_hysteresis_rejects_noise():
    c = KnobController("k", default=4, lo=1, hi=16, probe_every=4,
                       probe_len=2)
    for i in range(300):                    # flat objective, 5% wobble
        c.observe(10.0 + 0.5 * (i % 2))
    while c._probing is not None:
        c.observe(10.0)
    assert c.adoptions == 0 and c._incumbent == 4, c.stats()


def test_controller_garbage_objective_falls_back_bounded():
    """The acceptance property: a garbage objective (non-finite /
    non-positive) kills adaptation within GARBAGE_LIMIT probes and the
    knob returns to its default — and stays there."""
    c = KnobController("k", default=8, lo=1, hi=32, probe_every=4,
                       probe_len=2)
    c.observe(5.0)                          # healthy first sample
    seen = 0
    for x in [float("nan"), float("inf"), -1.0, 0.0] * 4:
        c.observe(x)
        seen += 1
        if c.dead:
            break
    assert c.dead and seen <= GARBAGE_LIMIT, (seen, c.stats())
    assert c.value == 8
    assert c.observe(123.0) == 8            # dead stays pinned to default
    assert c.converged


def test_adaptive_writeback_persists_tenant_vector(tmp_path, param):
    from parsec_tpu.tune import adaptive
    path = str(tmp_path / "t.jsonl")
    param("tune_db_path", path)
    adaptive.writeback("acme", 16, 3.2)
    rec = TuneDB(path).best(ambient_signature("tenant:acme"),
                            objective="tok_latency_ms")
    assert rec["knobs"] == {"llm_steps_per_pool": 16}
    assert rec["source"] == "adaptive"


# ---------------------------------------------------------------------------
# the closed loop, end to end (acceptance)
# ---------------------------------------------------------------------------

def test_closed_loop_decode_search_persist_context_pickup(tmp_path,
                                                          param):
    """Seeded-bad ``llm_steps_per_pool=1`` on a small decode workload:
    ``tune.search`` recovers a deeper superpool within 5 trials, the
    winner lands in tunedb.jsonl under the workload signature AND the
    ambient context tag, and a FRESH Context applies it at start."""
    import parsec_tpu.llm.batcher  # noqa: F401 — registers the knob
    from parsec_tpu.runtime import Context
    from parsec_tpu.serve import RuntimeServer
    path = str(tmp_path / "tunedb.jsonl")
    param("tune_db_path", path)
    param("perfdb", False)
    param("llm_steps_per_pool", 1)          # the seeded-bad vector
    db = TuneDB(path)

    def decode(_knobs):
        with RuntimeServer(nb_cores=2) as srv:
            t0 = time.perf_counter()
            ts = [srv.submit_stream([3, 7, 11], max_new_tokens=12)
                  for _ in range(2)]
            for t in ts:
                t.result(timeout=120)
            return time.perf_counter() - t0

    out = search(decode, signature="wl:test:decode",
                 space=declared_space(["llm_steps_per_pool"]), budget=5,
                 restarts=1, objective="wall_s",
                 start={"llm_steps_per_pool": 1}, db=db,
                 ambient_tag="context")
    assert out["evals"] <= 5
    assert out["best"]["llm_steps_per_pool"] >= 2, out
    assert db.best("wl:test:decode") is not None
    # the override was scoped: the live param still holds the bad seed
    assert params.get("llm_steps_per_pool") == 1
    # a fresh Context consults ambient:context and applies the winner
    ctx = Context(nb_cores=0)
    try:
        assert ctx.tuned_knobs is not None
        assert ctx.tuned_knobs.get("llm_steps_per_pool", 0) >= 2
        assert params.get("llm_steps_per_pool") == \
            ctx.tuned_knobs["llm_steps_per_pool"]
    finally:
        ctx.fini()


def test_adaptive_oracle_equal_and_server_pickup(tmp_path, param):
    """Live adaptation must move BATCHING, never tokens: the adaptive
    run's streams are token-for-token equal to the default run's, while
    the per-tenant controller is live and seeded from the tuning DB."""
    from parsec_tpu.serve import RuntimeServer
    path = str(tmp_path / "tunedb.jsonl")
    param("tune_db_path", path)
    prompts = [[3, 7, 11, 5], [1, 40, 8]]

    def run():
        with RuntimeServer(nb_cores=2) as srv:
            ts = [srv.submit_stream(p, max_new_tokens=16, tenant="acme")
                  for p in prompts]
            toks = [t.result(timeout=120)["tokens"] for t in ts]
            return toks, (srv._llm._k_seed.get("acme"),
                          srv._llm._k_ctl.get("acme"))

    param("tune_adaptive", False)
    oracle, (seed0, ctl0) = run()
    assert seed0 is None and ctl0 is None   # plane fully dormant when off
    # a persisted per-tenant vector the next server must pick up
    TuneDB(path).note(ambient_signature("tenant:acme"),
                      {"llm_steps_per_pool": 2}, 1.0,
                      objective="tok_latency_ms", source="adaptive")
    param("tune_adaptive", True)
    adapted, (seed, ctl) = run()
    assert seed == 2                        # DB -> server -> batcher seed
    assert ctl is not None and ctl.value >= 1
    assert adapted == oracle                # oracle-equal token-for-token
