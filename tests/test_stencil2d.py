"""2-D 5-point stencil PTG (BASELINE.json staged config #2): dynamic
path, wavefront lowering, and the 4-neighbor halo over ranks.
"""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
from parsec_tpu.models.stencil2d import (stencil2d_reference, stencil_2d_ptg)
from parsec_tpu.runtime import Context

W = (0.5, 0.15, 0.15, 0.1, 0.1)


def _grid(rows, cols, mb, nb, nranks=1, rank=0, P=1, Q=1, seed=0):
    rng = np.random.default_rng(seed)
    dense = rng.standard_normal((rows, cols)).astype(np.float32)
    M = TwoDimBlockCyclic.from_dense("M", dense.copy(), mb, nb,
                                     P=P, Q=Q, myrank=rank)
    return dense, M


@pytest.mark.parametrize("shape,tile,iters", [
    ((24, 24), (8, 8), 1),
    ((24, 24), (8, 8), 5),
    ((16, 32), (8, 8), 4),
    ((24, 24), (24, 24), 3),       # single tile: every ghost flow inactive
])
def test_stencil2d_dynamic(shape, tile, iters):
    dense, M = _grid(*shape, *tile)
    tp = stencil_2d_ptg(M, W, iters)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    want = stencil2d_reference(dense, W, iters)
    np.testing.assert_allclose(M.to_dense(), want, rtol=1e-4, atol=1e-5)


def test_stencil2d_workers():
    dense, M = _grid(32, 32, 8, 8, seed=3)
    tp = stencil_2d_ptg(M, W, 6)
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
    np.testing.assert_allclose(M.to_dense(),
                               stencil2d_reference(dense, W, 6),
                               rtol=1e-4, atol=1e-5)


def test_stencil2d_lowered_wavefront():
    """The compiled incarnation through the wavefront pass matches."""
    import jax
    from parsec_tpu.ptg.lowering import lower_taskpool
    dense, M = _grid(24, 24, 8, 8, seed=5)
    iters = 4
    low = lower_taskpool(stencil_2d_ptg(M, W, iters))
    assert low.mode == "wavefront", low.mode
    out = low.execute()
    got = np.zeros_like(dense)
    rows = low._stores.rows["M"]
    mv = np.asarray(out["M"])
    for (i, j), r in rows.items():
        got[i * 8:(i + 1) * 8, j * 8:(j + 1) * 8] = mv[r]
    np.testing.assert_allclose(got, stencil2d_reference(dense, W, iters),
                               rtol=1e-4, atol=1e-5)


def _rank_body(ctx, rank, nranks):
    P = 2
    Q = nranks // P
    dense, M = _grid(16, 16, 4, 4, nranks=nranks, rank=rank, P=P, Q=Q,
                     seed=7)
    tp = stencil_2d_ptg(M, W, 4)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=180)
    ctx.comm_barrier()
    want = stencil2d_reference(dense, W, 4)
    for i in range(M.mt):
        for j in range(M.nt):
            if M.rank_of(i, j) != rank:
                continue
            got = np.asarray(M.data_of(i, j).newest_copy().value)
            np.testing.assert_allclose(
                got, want[i * 4:(i + 1) * 4, j * 4:(j + 1) * 4],
                rtol=1e-4, atol=1e-5)
    return True


def test_stencil2d_multirank_2x2():
    """The 2-D halo over a 2x2 rank grid: every ghost edge crosses a
    rank boundary somewhere (and, since round 5, carries only its ghost
    row/column over the wire)."""
    assert all(run_multirank(4, _rank_body))


def _band_body(wire_on):
    def body(ctx, rank, nranks):
        from parsec_tpu.core.params import params
        saved = params.get("comm_wire_datatypes")
        params.set("comm_wire_datatypes", wire_on)
        try:
            # row bands (P=4, Q=1): every N/S halo edge crosses ranks,
            # every E/W edge stays local — the wire views are unique per
            # receiving rank, so no conflict-degrade to full tiles
            mb = 8
            dense, M = _grid(4 * mb, 2 * mb, mb, mb, nranks=nranks,
                             rank=rank, P=4, Q=1, seed=9)
            tp = stencil_2d_ptg(M, W, 3)
            ctx.add_taskpool(tp)
            ctx.wait(timeout=180)
            ctx.comm_barrier()
            want = stencil2d_reference(dense, W, 3)
            for i in range(M.mt):
                for j in range(M.nt):
                    if M.rank_of(i, j) != rank:
                        continue
                    got = np.asarray(M.data_of(i, j).newest_copy().value)
                    np.testing.assert_allclose(
                        got, want[i * mb:(i + 1) * mb,
                                  j * mb:(j + 1) * mb],
                        rtol=1e-4, atol=1e-5)
            return ctx.comm_engine.payload_bytes_staged
        finally:
            params.set("comm_wire_datatypes", saved)
    return body


def test_stencil2d_halo_wire_views_cut_bytes():
    """Each cross-rank halo edge ships one mb-element ghost row instead
    of the mb x mb tile: byte counters prove the exact mb-fold cut,
    numerics identical to the full-tile build."""
    with_wire = sum(run_multirank(4, _band_body(True)))
    without = sum(run_multirank(4, _band_body(False)))
    assert with_wire * 7 < without, (with_wire, without)
    # exact: every remote payload is one 8-element row vs an 8x8 tile
    assert with_wire == without // 8, (with_wire, without)
