"""Perf-observability polish: print_steals + alperf PINS modules, the
CPU cache-topology feed (hwloc distance role), and the JDF unparser
round-trip (jdf_unparse role).
"""

import time

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.core.mca import repository
from parsec_tpu.core.topology import (core_of_stream, distance, llc_group_of,
                                      llc_groups)
from parsec_tpu.prof.counters import sde
from parsec_tpu.runtime import Context


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------

def test_llc_groups_cover_and_agree():
    groups = llc_groups()
    assert groups, "no topology groups at all"
    seen = set()
    for g in groups:
        assert not (seen & g), "a cpu in two LLC groups"
        seen |= g
    for cpu in list(seen)[:8]:
        assert cpu in groups[llc_group_of(cpu)]


def test_distance_properties():
    c0 = core_of_stream(0)
    assert distance(c0, c0) == 0
    c1 = core_of_stream(1)
    assert distance(c0, c1) == distance(c1, c0)
    assert distance(c0, c1) in (0, 1, 2)


def test_lhq_topology_groups_schedule_correctly(param):
    """lhq with real LLC-derived groups still runs a pool to completion."""
    param("sched", "lhq")
    done = []
    p = ptg.PTGBuilder("lhq_topo", N=64)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
    t.body(lambda es, task, g, l: done.append(l.i))
    with Context(nb_cores=4, scheduler="lhq") as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
    assert sorted(done) == list(range(64))


# ---------------------------------------------------------------------------
# print_steals + alperf
# ---------------------------------------------------------------------------

def _sleepy_pool(n, delay=0.002):
    p = ptg.PTGBuilder("steals", N=n, D=delay)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
    t.body(lambda es, task, g, l: time.sleep(g.D))
    return p.build()


def _fanout_tree(depth, delay=0.002):
    """Binary task tree: each completion releases two children into the
    completing worker's own queues — the shape that makes idle siblings
    STEAL (system-queue pops don't count; distance sentinel 99)."""
    p = ptg.PTGBuilder("tree", D=depth, S=delay)
    t = p.task("T",
               d=ptg.span(0, lambda g, l: g.D - 1),
               i=ptg.span(0, lambda g, l: (1 << l.d) - 1))
    f = t.flow("c", ptg.CTL)
    f.input(pred=("T", "c", lambda g, l: {"d": l.d - 1, "i": l.i // 2}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("T", "c",
                   lambda g, l: ({"d": l.d + 1, "i": 2 * l.i},
                                 {"d": l.d + 1, "i": 2 * l.i + 1})),
             guard=lambda g, l: l.d < g.D - 1)
    t.body(lambda es, task, g, l: time.sleep(g.S))
    return p.build()


def test_print_steals_counts(param):
    param("runtime_dag_compile", False)   # keep selects on the dynamic path
    comp = repository.find("pins", "print_steals")
    mod = comp.open()
    try:
        before = sde.get("parsec::steals")
        with Context(nb_cores=4, scheduler="pbq") as ctx:
            ctx.add_taskpool(_fanout_tree(8))
            ctx.wait(timeout=60)
        assert sum(mod.steals.values()) > 0, \
            "no sibling steals observed with 4 workers on a fanout tree"
        assert sde.get("parsec::steals") > before
        assert sum(mod.distance.values()) >= sum(mod.steals.values())
    finally:
        comp.close(mod)


def test_alperf_samples_rate(param):
    param("runtime_dag_compile", False)
    param("pins_alperf_interval", 0.05)
    comp = repository.find("pins", "alperf")
    mod = comp.open()
    try:
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(_sleepy_pool(120, delay=0.005))
            ctx.wait(timeout=60)
        time.sleep(0.12)           # at least one sample window
        assert mod.samples, "alperf never sampled"
        assert max(r for _, r in mod.samples) > 0
    finally:
        comp.close(mod)


# ---------------------------------------------------------------------------
# JDF unparser round-trip
# ---------------------------------------------------------------------------

def test_unparse_roundtrip_stencil(tmp_path):
    """parse -> unparse -> parse: the re-parsed template builds and runs
    to the same result as the original (jdf_unparse contract)."""
    import pathlib
    from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
    from parsec_tpu.models.stencil import stencil_reference

    src_path = (pathlib.Path(__file__).resolve().parent.parent
                / "examples" / "jdf" / "stencil_1D.jdf")
    jdf1 = ptg.load_jdf(src_path)
    text2 = ptg.unparse_jdf(jdf1)
    jdf2 = ptg.parse_jdf(text2, "stencil_rt")

    MB, NB, LMT, LNT, R, iters = 2, 8, 2, 3, 2, 4
    rng = np.random.default_rng(4)
    interior = rng.standard_normal((MB, LNT * (NB - 2 * R))).astype(
        np.float32)

    def run(jdf):
        def init(m, n, shape):
            tile = np.zeros(shape, np.float32)
            if m == 0:
                w = NB - 2 * R
                tile[:, R:NB - R] = interior[:, n * w:(n + 1) * w]
            return tile
        desc = TwoDimBlockCyclic("descA", lm=LMT * MB, ln=LNT * NB,
                                 mb=MB, nb=NB, P=1, Q=1, init_fn=init)
        W = np.array([0.1, 0.2, 0.4, 0.2, 0.1])
        tp = jdf.build(descA=desc, iter=iters, R=R, W=W, LMT=LMT, LNT=LNT)
        with Context(nb_cores=0) as ctx:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=120)
        m = iters % LMT
        return np.concatenate(
            [np.asarray(desc.data_of(m, n).newest_copy().value)[:, R:NB - R]
             for n in range(LNT)], axis=1)

    got1, got2 = run(jdf1), run(jdf2)
    np.testing.assert_allclose(got1, got2, rtol=0, atol=0)
    want = np.stack([stencil_reference(row, np.array([0.1, 0.2, 0.4, 0.2,
                                                      0.1]), iters)
                     for row in interior])
    np.testing.assert_allclose(got1, want, rtol=1e-4, atol=1e-5)


def test_unparse_preserves_ud_surface():
    """%option, task props, SIMCOST, ranged arrows, dep [type=] props and
    NULL targets survive the round trip structurally."""
    src = """
%option termdet = local
V [type = data]
T(i) [make_key_fn = mk]
  i = 0 .. 3
  j = i * 2
  : V(0)
  SIMCOST i + 1
  READ X <- (i > 0) ? X T(i-1) : NULL
  CTL c <- c S(0 .. 3)
BODY
  pass
END
S(k)
  k = 0 .. 3
  : V(0)
  CTL c -> c T(0 .. 3)
BODY
  pass
END
"""
    jdf1 = ptg.parse_jdf(src, "ud")
    jdf2 = ptg.parse_jdf(ptg.unparse_jdf(jdf1), "ud2")
    assert jdf2.options == jdf1.options
    t1, t2 = jdf1.tasks["T"], jdf2.tasks["T"]
    assert t2.props == t1.props
    assert t2.simcost_src == t1.simcost_src
    assert t2.derived == t1.derived
    assert t2.ranges == t1.ranges
    for f1, f2 in zip(t1.flows, t2.flows):
        assert f2.name == f1.name and f2.access == f1.access
        assert len(f2.arrows) == len(f1.arrows)
        for a1, a2 in zip(f1.arrows, f2.arrows):
            assert a2.direction == a1.direction
            assert a2.then_tgt == a1.then_tgt
            assert a2.else_tgt == a1.else_tgt
            assert (a2.guard_src or "").replace(" ", "") == \
                (a1.guard_src or "").replace(" ", "")
