"""Recursive task bodies: nested taskpools over sub-tiled flow data.

The analog of the reference's recursive apps
(``parsec/recursive.h``, ``tests/apps/recursive/``): an outer task's body
spawns a nested taskpool over a :class:`SubtileCollection` of its RW tile,
detaches, and completes when the sub-DAG drains — so outer successors see
the sub-writes exactly as if the body had produced them.
"""

import numpy as np

from parsec_tpu import ptg
from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import SubtileCollection, TiledMatrix, \
    TwoDimBlockCyclic
from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg, \
    tiled_gemm_recursive_ptg
from parsec_tpu.runtime import Context, recursive_call


def _mats(n, nb, nranks=1, rank=0, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    c = rng.standard_normal((n, n)).astype(np.float32)
    # tile COPIES: home tiles are views into the source array, and the run
    # mutates C in place — the dense references must stay pristine
    if nranks == 1:
        A = TiledMatrix.from_dense("A", a.copy(), nb, nb)
        B = TiledMatrix.from_dense("B", b.copy(), nb, nb)
        C = TiledMatrix.from_dense("C", c.copy(), nb, nb)
    else:
        mk = lambda nm, arr: TwoDimBlockCyclic.from_dense(
            nm, arr.copy(), nb, nb, P=nranks, Q=1, myrank=rank)
        A, B, C = mk("A", a), mk("B", b), mk("C", c)
    return a, b, c, A, B, C


# ---------------------------------------------------------------------------
# single rank
# ---------------------------------------------------------------------------

def test_recursive_gemm_single_rank():
    """Outer 2x2 tiles, inner 4x4 sub-tiles: C += A@B exact."""
    a, b, c, A, B, C = _mats(32, 16)          # 2x2 outer tiles of 16
    tp = tiled_gemm_recursive_ptg(A, B, C, sub_mb=4, sub_nb=4)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3, atol=1e-4)


def test_recursive_cutoff_falls_to_cpu_chore():
    """min_tile >= tile size: the evaluate hook skips the recursive chore
    and the plain CPU incarnation runs (reference evaluate protocol)."""
    a, b, c, A, B, C = _mats(16, 8, seed=1)
    tp = tiled_gemm_recursive_ptg(A, B, C, sub_mb=4, sub_nb=4, min_tile=8)
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3, atol=1e-4)


def test_recursive_with_worker_threads():
    a, b, c, A, B, C = _mats(32, 16, seed=2)
    tp = tiled_gemm_recursive_ptg(A, B, C, sub_mb=8, sub_nb=8)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3, atol=1e-4)


def test_recursive_depth_two():
    """A nested pool whose bodies recurse again (depth-2 sub-tiling)."""
    a, b, c, A, B, C = _mats(32, 16, seed=3)

    p = ptg.PTGBuilder("rec2", A=A, B=B, C=C, MT=C.mt, NT=C.nt, KT=A.nt)
    t = p.task("GEMM",
               m=ptg.span(0, lambda g, l: g.MT - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1),
               k=ptg.span(0, lambda g, l: g.KT - 1))
    t.affinity("C", lambda g, l: (l.m, l.n))
    fa = t.flow("A", ptg.READ)
    fa.input(data=("A", lambda g, l: (l.m, l.k)))
    fb = t.flow("B", ptg.READ)
    fb.input(data=("B", lambda g, l: (l.k, l.n)))
    fc = t.flow("C", ptg.RW)
    fc.input(data=("C", lambda g, l: (l.m, l.n)), guard=lambda g, l: l.k == 0)
    fc.input(pred=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n, "k": l.k - 1}),
             guard=lambda g, l: l.k > 0)
    fc.output(succ=("GEMM", "C", lambda g, l: {"m": l.m, "n": l.n, "k": l.k + 1}),
              guard=lambda g, l: l.k < g.KT - 1)
    fc.output(data=("C", lambda g, l: (l.m, l.n)),
              guard=lambda g, l: l.k == g.KT - 1)

    def body(es, task, g, l):
        asub = SubtileCollection.of_copy(task.data[0], 8, 8)
        bsub = SubtileCollection.of_copy(task.data[1], 8, 8)
        csub = SubtileCollection.of_copy(task.data[2], 8, 8)
        # the inner pool itself recurses once more, to 4x4 sub-sub-tiles
        inner = tiled_gemm_recursive_ptg(asub, bsub, csub, sub_mb=4, sub_nb=4)
        return recursive_call(es, task, inner, collections=(csub,))

    t.body(body, device="recursive")
    tp = p.build()
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3, atol=1e-4)


def test_recursive_callback_and_async_protocol():
    """The completion chain fires callback before outer successors run."""
    order = []
    a, b, c, A, B, C = _mats(16, 16, seed=4)   # one outer tile

    p = ptg.PTGBuilder("rcb", A=A, B=B, C=C)
    t = p.task("G", z=ptg.span(0, 0))
    t.affinity("C", lambda g, l: (0, 0))
    fc = t.flow("C", ptg.RW)
    fc.input(data=("C", lambda g, l: (0, 0)))
    fc.output(succ=("S", "X", lambda g, l: {"z": 0}))

    def gbody(es, task, g, l):
        sub = SubtileCollection.of_copy(task.data[0], 8, 8)
        asub = SubtileCollection.of_copy(
            A.data_of(0, 0).newest_copy(), 8, 8)
        bsub = SubtileCollection.of_copy(
            B.data_of(0, 0).newest_copy(), 8, 8)
        inner = tiled_gemm_ptg(asub, bsub, sub, devices="cpu")
        return recursive_call(
            es, task, inner,
            callback=lambda tp_, outer: order.append("callback"),
            collections=(sub,))

    t.body(gbody, device="recursive")

    s = p.task("S", z=ptg.span(0, 0))
    s.affinity("C", lambda g, l: (0, 0))
    fx = s.flow("X", ptg.READ)
    fx.input(pred=("G", "C", lambda g, l: {"z": 0}))

    def sbody(es, task, g, l):
        order.append("successor")

    s.body(sbody)
    tp = p.build()
    with Context(nb_cores=0) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert order == ["callback", "successor"]


# ---------------------------------------------------------------------------
# 8-rank mesh
# ---------------------------------------------------------------------------

def _rec_rank_body(ctx, rank, nranks):
    n, nb = 32, 4            # 8x1 block-cyclic outer tiles, one row per rank
    a, b, c, A, B, C = _mats(n, nb, nranks=nranks, rank=rank, seed=7)
    tp = tiled_gemm_recursive_ptg(A, B, C, sub_mb=2, sub_nb=2)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    # every rank checks its own home tiles against the dense result
    want = c + a @ b
    for m in range(C.mt):
        for nn in range(C.nt):
            if C.rank_of(m, nn) != rank:
                continue
            got = np.asarray(C.data_of(m, nn).newest_copy().value)
            np.testing.assert_allclose(
                got, want[m * nb:(m + 1) * nb, nn * nb:(nn + 1) * nb],
                rtol=1e-3, atol=1e-4)
    return True


def test_recursive_gemm_8rank_mesh():
    """Outer tiles block-cyclic over 8 ranks; every rank's bodies spawn
    rank-private nested pools (different counts per rank) without
    desynchronizing the collective taskpool id sequence."""
    res = run_multirank(8, _rec_rank_body)
    assert all(res)
