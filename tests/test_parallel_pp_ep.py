"""PP + EP recipes (VERDICT r2 item 8): pipeline-chain PTG across ranks
(Ex03 shape) and expert routing over the TwoDimTabular distribution — each
in both incarnations (dataflow core on 4 inproc ranks, and the mesh)."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic, TwoDimTabular
from parsec_tpu.parallel.expert import make_moe_step, moe_ptg, reference_moe
from parsec_tpu.parallel.pipeline import make_pipeline_step, pipeline_ptg
from parsec_tpu.runtime import Context


# ---------------------------------------------------------------------------
# PP — dataflow core
# ---------------------------------------------------------------------------

def _stage_fns(S):
    """Distinct, non-commuting stages so ordering bugs surface."""
    return [lambda x, s=s: x * (s + 2) + s for s in range(S)]


def _expect_pipeline(x, fns):
    for f in fns:
        x = f(x)
    return x


def _pp_body(ctx, rank, nranks):
    S, M, nb = 4, 6, 8
    fns = _stage_fns(S)
    X = TwoDimBlockCyclic("Xpp", lm=M * nb, ln=1, mb=nb, nb=1, P=1, Q=1,
                          myrank=rank, nodes=nranks,
                          init_fn=lambda m, n, sh:
                          np.full(sh, float(m + 1), np.float32))
    tp = pipeline_ptg(X, fns, nranks)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    if rank == 0:
        return np.stack([np.asarray(X.data_of(m, 0).newest_copy().value)
                         for m in range(M)])
    return None


@pytest.mark.parametrize("nranks", [1, 4])
def test_pipeline_ptg_across_ranks(nranks):
    res = run_multirank(nranks, _pp_body)
    fns = _stage_fns(4)
    for m in range(6):
        expect = _expect_pipeline(np.full((8, 1), float(m + 1), np.float32),
                                  fns)
        np.testing.assert_allclose(res[0][m], expect)


def test_pipeline_ptg_stage_placement():
    """Affinity contract: stage s runs on rank s % nranks."""
    seen = {}

    def body(ctx, rank, nranks):
        S, M, nb = 4, 2, 4
        fns = [lambda x, s=s: (seen.setdefault((s, rank), True), x)[1]
               for s in range(S)]
        X = TwoDimBlockCyclic("Xsp", lm=M * nb, ln=1, mb=nb, nb=1,
                              P=1, Q=1, myrank=rank, nodes=nranks,
                              init_fn=lambda m, n, sh:
                              np.zeros(sh, np.float32))
        ctx.add_taskpool(pipeline_ptg(X, fns, nranks))
        ctx.wait(timeout=120)
        ctx.comm_barrier()

    run_multirank(4, body)
    assert set(seen) == {(s, s % 4) for s in range(4)}


# ---------------------------------------------------------------------------
# PP — mesh (shard_map + ppermute GPipe rotation)
# ---------------------------------------------------------------------------

def test_pipeline_mesh_matches_sequential():
    import jax.numpy as jnp
    S, M, nb, d = 4, 6, 2, 8
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
    rng = np.random.RandomState(0)
    w = rng.randn(S, d, d).astype(np.float32) * 0.3
    xs = rng.randn(M, nb, d).astype(np.float32)

    def stage_fn(wl, x):
        return jnp.tanh(x @ wl)

    run = make_pipeline_step(mesh, stage_fn, S, M)
    ys = np.asarray(run(w, xs))

    expect = xs.copy()
    for s in range(S):
        expect = np.tanh(expect @ w[s])
    np.testing.assert_allclose(ys, expect, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# EP — dataflow core over TwoDimTabular
# ---------------------------------------------------------------------------

def _ep_setup(seed=0, B=2, E=4, ntok=16, d=8):
    rng = np.random.RandomState(seed)
    x = rng.randn(B * ntok, d).astype(np.float32)
    wg = rng.randn(d, E).astype(np.float32)
    we = rng.randn(E, d, d).astype(np.float32)
    return x, wg, we


def _ep_body(ctx, rank, nranks):
    B, E, ntok, d = 2, 4, 16, 8
    x, wg, we = _ep_setup()
    X = TwoDimBlockCyclic.from_dense("Xep", x, ntok, d, P=1, Q=1,
                                     myrank=rank, nodes=nranks)
    W = TwoDimTabular("Wep", lm=E * d, ln=d, mb=d, nb=d,
                      rank_table=lambda m, n: m % nranks,
                      nodes=nranks, myrank=rank,
                      init_fn=lambda m, n, sh: we[m])
    ctx.add_taskpool(moe_ptg(X, W, wg, E))
    ctx.wait(timeout=120)
    ctx.comm_barrier()
    if rank == 0:
        return np.concatenate(
            [np.asarray(X.data_of(b, 0).newest_copy().value)
             for b in range(B)])
    return None


@pytest.mark.parametrize("nranks", [1, 4])
def test_moe_ptg_over_tabular(nranks):
    x, wg, we = _ep_setup()
    res = run_multirank(nranks, _ep_body)
    expect = np.concatenate(
        [reference_moe(x[b * 16:(b + 1) * 16], wg, we) for b in range(2)])
    np.testing.assert_allclose(res[0], expect, rtol=1e-5, atol=1e-5)


def test_moe_ptg_expert_placement():
    """EXPERT(e) must execute on rank_table(e) — the tabular contract."""
    seen = {}
    B, E, ntok, d = 2, 4, 8, 4

    def body(ctx, rank, nranks):
        x, wg, we = _ep_setup(B=B, E=E, ntok=ntok, d=d)
        X = TwoDimBlockCyclic.from_dense("Xpl", x, ntok, d, P=1, Q=1,
                                         myrank=rank, nodes=nranks)
        W = TwoDimTabular("Wpl", lm=E * d, ln=d, mb=d, nb=d,
                          rank_table=lambda m, n: (m * 2 + 1) % nranks,
                          nodes=nranks, myrank=rank,
                          init_fn=lambda m, n, sh: we[m])
        tp = moe_ptg(X, W, wg, E)
        tc = tp.task_class("EXPERT")
        orig = tc.chores[0].hook

        def spy(es, task):
            seen[(task.locals["e"], rank)] = True
            return orig(es, task)
        tc.chores[0].hook = spy
        ctx.add_taskpool(tp)
        ctx.wait(timeout=120)
        ctx.comm_barrier()

    run_multirank(4, body)
    assert set(seen) == {(e, (e * 2 + 1) % 4) for e in range(4)}


# ---------------------------------------------------------------------------
# EP — mesh (dense dispatch einsums over "ep")
# ---------------------------------------------------------------------------

def test_moe_mesh_matches_reference():
    E = 4
    x, wg, we = _ep_setup(B=1, E=E, ntok=32, d=8)
    mesh = Mesh(np.array(jax.devices()[:E]), ("ep",))
    step = make_moe_step(mesh)
    got = np.asarray(step(x, wg, we))
    np.testing.assert_allclose(got, reference_moe(x, wg, we),
                               rtol=1e-5, atol=1e-5)
