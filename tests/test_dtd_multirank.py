"""DTD across ranks: shells, AFFINITY routing, pushes, flushes.

The analog of the reference's MPI-variant DTD tests
(``tests/dsl/dtd/Testings.cmake`` running each test at -np 2/4/8; remote
shells ``insert_function.c:821,866``; flush-to-owner
``parsec_dtd_data_flush.c``).
"""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import VectorTwoDimCyclic
from parsec_tpu.dtd.insert import (AFFINITY, INOUT, INPUT, DTDTaskpool)
from parsec_tpu.dtd.multirank_check import dtd_gemm_multirank_check


def _inc(x):
    return np.asarray(x) + 1.0


def _chain_body(ctx, rank, nranks):
    """A value hops rank-to-rank: task i runs on rank i%n (AFFINITY on a
    per-rank anchor tile), INOUT on the shared tile X — every hop is a
    cross-rank RAW push."""
    nt = 6
    X = VectorTwoDimCyclic("X", lm=1, mb=1, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    anchors = VectorTwoDimCyclic("W", lm=nranks, mb=1, P=nranks, myrank=rank,
                                 init_fn=lambda m, size: np.zeros(size))
    tp = DTDTaskpool("chain")
    ctx.add_taskpool(tp)
    tX = tp.tile_of(X, 0)

    def hop(anchor, x):
        return np.asarray(x) + 1.0

    for i in range(nt):
        tA = tp.tile_of(anchors, i % nranks)
        tp.insert_task(hop, (tA, INPUT | AFFINITY), (tX, INOUT), name="hop")
    tp.data_flush_all()
    tp.wait(timeout=60)
    ctx.comm_barrier()
    if rank == 0:   # X's home rank
        return float(np.asarray(X.data_of(0).newest_copy().value)[0])
    return None


@pytest.mark.parametrize("nranks", [2, 4])
def test_dtd_chain_across_ranks(nranks):
    res = run_multirank(nranks, _chain_body)
    assert res[0] == 6.0


@pytest.mark.parametrize("nranks", [2, 4, 8])
def test_dtd_gemm_multirank(nranks):
    dtd_gemm_multirank_check(nranks)


def test_dtd_gemm_multirank_device_transport():
    dtd_gemm_multirank_check(4, transport="device")


def test_dtd_single_rank_still_clean():
    """nb_ranks=1 must not touch shells/pushes (regression guard)."""
    res = run_multirank(1, _chain_body)
    assert res[0] == 6.0


def _war_body(ctx, rank, nranks):
    """WAR across ranks: rank 0 writes X, a remote rank reads it, rank 0
    overwrites it — the remote reader must see the FIRST version (snapshot
    pushes, not live aliases)."""
    X = VectorTwoDimCyclic("X", lm=1, mb=1, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    R = VectorTwoDimCyclic("R", lm=nranks, mb=1, P=nranks, myrank=rank,
                           init_fn=lambda m, size: np.zeros(size))
    tp = DTDTaskpool("war")
    ctx.add_taskpool(tp)
    tX = tp.tile_of(X, 0)
    tR = tp.tile_of(R, 1 % nranks)

    def write7(x):
        return np.full_like(np.asarray(x), 7.0)

    def capture(r, x):
        return np.asarray(x).copy()

    def write9(x):
        return np.full_like(np.asarray(x), 9.0)

    tp.insert_task(write7, (tX, INOUT | AFFINITY), name="w7")       # rank 0
    tp.insert_task(capture, (tR, INOUT | AFFINITY), (tX, INPUT),
                   name="cap")                                      # rank 1
    tp.insert_task(write9, (tX, INOUT | AFFINITY), name="w9")       # rank 0
    tp.data_flush_all()
    tp.wait(timeout=60)
    ctx.comm_barrier()
    if rank == 1 % nranks:
        return float(np.asarray(R.data_of(1 % nranks)
                                .newest_copy().value)[0])
    return None


@pytest.mark.parametrize("nranks", [2, 4])
def test_dtd_war_across_ranks(nranks):
    res = run_multirank(nranks, _war_body)
    assert res[1 % nranks] == 7.0
