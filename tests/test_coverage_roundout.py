"""Coverage round-out (VERDICT r2 table): rwlock, show_help aggregation,
vpmap specs, debug marks, iterators_checker, ptg_to_dtd, paranoid mode."""

import threading

import numpy as np
import pytest

from parsec_tpu import ptg
import parsec_tpu.runtime.dagrun  # noqa: F401  (registers runtime_dag_compile)
from parsec_tpu.core.params import params
from parsec_tpu.core.rwlock import RWLock
from parsec_tpu.data.data import TileType
from parsec_tpu.data_dist.collection import DictCollection
from parsec_tpu.runtime import Context


class TestRWLock:
    def test_readers_share_writers_exclude(self):
        lk = RWLock()
        state = {"readers": 0, "max_readers": 0, "writer_during_read": False}
        stop = threading.Event()

        def reader():
            for _ in range(200):
                with lk.read():
                    state["readers"] += 1
                    state["max_readers"] = max(state["max_readers"],
                                               state["readers"])
                    state["readers"] -= 1

        def writer():
            for _ in range(50):
                with lk.write():
                    if state["readers"]:
                        state["writer_during_read"] = True

        ts = [threading.Thread(target=reader) for _ in range(4)] + \
             [threading.Thread(target=writer) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        stop.set()
        assert not state["writer_during_read"]

    def test_writer_preference(self):
        lk = RWLock()
        lk.acquire_read()
        got_write = threading.Event()

        def w():
            lk.acquire_write()
            got_write.set()
            lk.release_write()

        t = threading.Thread(target=w)
        t.start()
        import time
        time.sleep(0.05)
        # a waiting writer blocks NEW readers
        blocked = threading.Event()

        def r():
            lk.acquire_read()
            blocked.set()
            lk.release_read()

        t2 = threading.Thread(target=r)
        t2.start()
        time.sleep(0.05)
        assert not blocked.is_set()
        lk.release_read()
        t.join(5)
        t2.join(5)
        assert got_write.is_set() and blocked.is_set()


class TestShowHelp:
    def test_dedup_and_flush(self):
        from parsec_tpu.core.output import show_help, show_help_flush
        show_help_flush()
        assert show_help("topic", "sec", "message %d", 1) is True
        assert show_help("topic", "sec", "message %d", 2) is False
        assert show_help("topic", "sec", "message %d", 3) is False
        assert show_help("topic", "other", "different") is True
        counts = show_help_flush()
        assert counts[("topic", "sec")] == 3
        assert counts[("topic", "other")] == 1
        # flushed: the topic prints again
        assert show_help("topic", "sec", "again") is True
        show_help_flush()


class TestVPMap:
    def test_specs(self):
        from parsec_tpu.runtime.vpmap import parse_vpmap
        assert parse_vpmap("", 4, 2) == [0, 1, 0, 1]
        assert parse_vpmap("flat", 4, 2) == [0, 0, 0, 0]
        assert parse_vpmap("rr:3", 6, 1) == [0, 1, 2, 0, 1, 2]
        assert parse_vpmap("list:2,1", 3, 1) == [0, 0, 1]
        with pytest.raises(ValueError):
            parse_vpmap("bogus:1", 2, 1)
        with pytest.raises(ValueError):
            parse_vpmap("list:0", 2, 1)

    def test_file_spec(self, tmp_path, param):
        from parsec_tpu.runtime.vpmap import parse_vpmap
        p = tmp_path / "vpmap"
        p.write_text("# comment\n2\n2\n")
        assert parse_vpmap(f"file:{p}", 4, 1) == [0, 0, 1, 1]

    def test_context_honors_spec(self, param):
        param("runtime_vpmap", "list:2,2")
        ctx = Context(nb_cores=4)
        assert len(ctx.virtual_processes) == 2
        assert [len(vp.execution_streams)
                for vp in ctx.virtual_processes] == [2, 2]
        ctx.fini()


def _small_pool(trace=None):
    p = ptg.PTGBuilder("t", N=4)
    t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("T", "ctl", lambda g, l: {"i": l.i - 1}),
            guard=lambda g, l: l.i > 0)
    f.output(succ=("T", "ctl", lambda g, l: {"i": l.i + 1}),
             guard=lambda g, l: l.i < g.N - 1)
    t.body(lambda es, task, g, l:
           trace.append(l.i) if trace is not None else None)
    return p.build()


class TestDebugMarks:
    def test_ring_captures_events(self, param):
        from parsec_tpu.core.mca import repository
        from parsec_tpu.prof import debug_marks
        param("runtime_dag_compile", False)   # marks watch the full loop
        comp = repository.find("pins", "debug_marks")
        mod = comp.open()   # install re-creates the module-level ring
        ring = debug_marks.ring
        try:
            run = []
            ctx = Context(nb_cores=0)
            ctx.add_taskpool(_small_pool(run))
            ctx.wait(timeout=30)
            ctx.fini()
        finally:
            comp.close(mod)
        kinds = {k for _, _, k, _ in ring.snapshot()}
        assert {"exec_begin", "exec_end", "release_deps"} <= kinds
        assert "T(i=0)" in ring.dump()

    def test_ring_is_bounded(self):
        from parsec_tpu.prof.debug_marks import MarkRing
        r = MarkRing(8)
        for i in range(100):
            r.mark("k", str(i))
        snap = r.snapshot()
        assert len(snap) == 8
        assert snap[-1][3] == "99"


class TestIteratorsChecker:
    def test_consistent_graph_passes(self):
        from parsec_tpu.core.mca import repository
        comp = repository.find("pins", "iterators_checker")
        mod = comp.open()
        try:
            ctx = Context(nb_cores=0)
            ctx.add_taskpool(_small_pool())
            ctx.wait(timeout=30)
            ctx.fini()
        finally:
            checked = mod.checked_edges
            comp.close(mod)
        assert checked == 3     # chain of 4: three forward edges

    def test_inconsistent_arrow_is_caught(self):
        from parsec_tpu.prof.iterators_checker import (IteratorsCheckerError,
                                                       check_task)
        from parsec_tpu.runtime.task import Task
        p = ptg.PTGBuilder("bad", N=2)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        f = t.flow("ctl", ptg.CTL)
        # out-arrow claims an edge the successor's in-deps don't declare
        f.output(succ=("T", "ctl", lambda g, l: {"i": l.i + 1}),
                 guard=lambda g, l: l.i < g.N - 1)
        t.body(lambda es, task, g, l: None)
        tp = p.build()
        task = Task(tp, tp.task_class("T"), {"i": 0})
        with pytest.raises(IteratorsCheckerError):
            check_task(task)


class TestPtgToDtd:
    def test_gemm_through_dtd(self):
        from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
        from parsec_tpu.dtd import ptg_to_dtd
        from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
        n, nb = 32, 8
        rng = np.random.RandomState(3)
        a = rng.randn(n, n).astype(np.float32)
        b = rng.randn(n, n).astype(np.float32)
        A = TwoDimBlockCyclic.from_dense("A", a, nb, nb)
        B = TwoDimBlockCyclic.from_dense("B", b, nb, nb)
        C = TwoDimBlockCyclic("C", n, n, nb, nb)
        tp = tiled_gemm_ptg(A, B, C, devices="cpu")
        ctx = Context(nb_cores=0)
        ptg_to_dtd(tp, ctx)
        ctx.fini()
        np.testing.assert_allclose(C.to_dense(), a @ b, rtol=1e-4,
                                   atol=1e-4)

    def test_ctl_pool_rejected(self):
        from parsec_tpu.dtd import ptg_to_dtd
        from parsec_tpu.dtd.from_ptg import PTGToDTDError
        ctx = Context(nb_cores=0)
        with pytest.raises(PTGToDTDError):
            ptg_to_dtd(_small_pool(), ctx)
        ctx.fini()


class TestParanoid:
    def test_unordered_writebacks_caught(self, param):
        from parsec_tpu.runtime.scheduling import apply_writeback_to_home
        param("debug_paranoid", True)
        coll = DictCollection("P", dtt=TileType((1,), np.float32),
                              init_fn=lambda *k: np.zeros(1, np.float32))
        from parsec_tpu.data.data import data_create
        c1 = data_create(np.ones(1, np.float32), key="a").get_copy(0)
        c2 = data_create(np.ones(1, np.float32), key="b").get_copy(0)
        c1.version = 3
        c2.version = 2   # strictly older after newer: must be a race
        apply_writeback_to_home(coll, (0,), c1, owner=7)
        with pytest.raises(AssertionError, match="unordered writebacks"):
            apply_writeback_to_home(coll, (0,), c2, owner=7)

    def test_equal_version_writebacks_warn_not_raise(self, param):
        """Two fresh copies at the same version may be legally CTL-ordered:
        the paranoid mode warns instead of rejecting a legal program."""
        from parsec_tpu.core.output import show_help_flush
        from parsec_tpu.data.data import data_create
        from parsec_tpu.runtime.scheduling import apply_writeback_to_home
        param("debug_paranoid", True)
        coll = DictCollection("R", dtt=TileType((1,), np.float32),
                              init_fn=lambda *k: np.zeros(1, np.float32))
        show_help_flush()
        c1 = data_create(np.ones(1, np.float32), key="e1").get_copy(0)
        c2 = data_create(np.ones(1, np.float32), key="e2").get_copy(0)
        apply_writeback_to_home(coll, (0,), c1, owner=8)
        apply_writeback_to_home(coll, (0,), c2, owner=8)   # no raise
        counts = show_help_flush()
        assert counts.get(("paranoid", "equal-version-writeback"), 0) >= 1

    def test_ordered_writebacks_pass(self, param):
        from parsec_tpu.runtime.scheduling import apply_writeback_to_home
        param("debug_paranoid", True)
        coll = DictCollection("Q", dtt=TileType((1,), np.float32),
                              init_fn=lambda *k: np.zeros(1, np.float32))
        from parsec_tpu.data.data import data_create
        for v in (1, 2, 3):
            c = data_create(np.ones(1, np.float32), key=f"v{v}").get_copy(0)
            c.version = v
            apply_writeback_to_home(coll, (0,), c, owner=9)

    def test_normal_run_clean_under_paranoid(self, param):
        param("debug_paranoid", True)
        param("runtime_dag_compile", False)   # exercise the dynamic path
        trace = []
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(_small_pool(trace))
        ctx.wait(timeout=30)
        ctx.fini()
        assert len(trace) == 4


class TestThreadBinding:
    def test_bound_workers_run(self, param):
        """runtime_bind_threads pins workers round-robin (best-effort);
        the run must complete and execute every task either way."""
        param("runtime_bind_threads", True)
        param("runtime_dag_compile", False)
        trace = []
        ctx = Context(nb_cores=2)
        ctx.add_taskpool(_small_pool(trace))
        ctx.start()
        ctx.wait(timeout=30)
        ctx.fini()
        assert len(trace) == 4
