"""Pressure/fault harness for the TPU device module (VERDICT r4 item 7).

The reference exercises its 700-line GPU edge-case surface on real
hardware in CI (``device_gpu.c:845-1528``, ``tests/CMakeLists.txt:70-72``
gating); here the same paths are driven by *injected* faults against a
TPUDevice wrapping the host CPU jax device — the module's logic is
platform-independent XLA, so this coverage is real:

- OOM during stage-in -> LRU eviction + deferred w2r drain, with the
  byte-accounting invariants checked at every drain;
- an XLA dispatch raising MID-RUN (relay reset) after earlier batches
  left dirty device tiles -> salvage-writeback + demote + requeue, with
  the salvaged values verified against the partial computation;
- a salvage that cannot write back a newer-than-host tile -> fail-stop
  escalation (wrong answers are worse than stopping);
- the relay dying during stage-in (``device_put`` raising) -> the same
  demote protocol from the H2D boundary.
"""

import numpy as np
import pytest

import jax

from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
from parsec_tpu.runtime import Context


@pytest.fixture
def dev(accel_device):
    return accel_device    # shared conftest fixture, local name


def _mk_abc(n, mb, seed):
    from parsec_tpu.data_dist.matrix import TiledMatrix
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    c = rng.standard_normal((n, n)).astype(np.float32)
    return (a, b, c, TiledMatrix.from_dense("A", a, mb, mb),
            TiledMatrix.from_dense("B", b, mb, mb),
            TiledMatrix.from_dense("C", c, mb, mb))


def test_eviction_accounting_invariants_hold_at_every_drain(dev):
    """Under a 3-tile budget the w2r queue churns constantly; at every
    drain boundary the byte ledgers must agree with the structures they
    describe (a drift here is silent HBM over/under-subscription)."""
    checks = {"n": 0}
    real_drain = dev._drain_evictions

    def checked_drain():
        real_drain()
        with dev._lru_lock:
            assert dev._mem_bytes == sum(
                getattr(c.value, "nbytes", 0)
                for c in dev._mem_lru.values()), "LRU ledger drift"
            assert dev._evict_bytes == sum(
                getattr(c.value, "nbytes", 0) for c in dev._evict_q), \
                "w2r ledger drift"
            assert dev._mem_bytes >= 0 and dev._evict_bytes >= 0
        checks["n"] += 1

    dev._drain_evictions = checked_drain
    dev._mem_budget = 3 * 16 * 16 * 4
    a, b, c, A, B, C = _mk_abc(64, 16, 21)
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="tpu"))
    ctx.wait(timeout=120)
    dev.sync()
    dev._drain_evictions = real_drain
    dev.flush_cache()
    ctx.fini()
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3,
                               atol=1e-4)
    assert checks["n"] > 0 and dev.deferred_evictions > 0
    # post-flush: everything accounted down to zero
    assert dev._mem_bytes == 0 and dev._evict_bytes == 0
    assert not dev._mem_lru and not dev._evict_q


def test_mid_run_dispatch_failure_salvages_dirty_tiles_and_requeues(
        dev, param):
    """Batches 1..k succeed and leave dirty C tiles device-resident; then
    the relay 'resets' (the vmapped XLA call raises).  The manager must
    salvage the PARTIAL results back to host copies, disable the device,
    and requeue the uncompleted tasks onto the CPU incarnation — final
    numerics prove both the salvage values and the requeue set were
    exact (a dropped dirty tile or a double-run task shows up as a wrong
    product)."""
    a, b, c, A, B, C = _mk_abc(64, 16, 22)
    tp = tiled_gemm_ptg(A, B, C, devices="auto")

    # several small batches so failures land mid-run with dirty residue
    param("device_tpu_batch_max", 8)
    calls = {"n": 0}

    def hook(batch):
        calls["n"] += 1
        if calls["n"] > 2:
            raise ConnectionResetError("relay reset mid-batch")

    dev._dispatch_hook = hook
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    dev.sync()
    ctx.fini()
    assert calls["n"] > 2, "the failure was never injected"
    assert dev.enabled is False
    assert dev.executed_tasks > 0, "no batch succeeded before the reset"
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3,
                               atol=1e-4)


def test_unsalvageable_dirty_tile_fails_stop(dev, param):
    """A dirty device tile newer than its host copy that cannot write
    back must STOP the run (recomputing on stale inputs silently
    corrupts results — device_gpu.c's fail-stop discipline)."""
    a, b, c, A, B, C = _mk_abc(32, 16, 23)
    tp = tiled_gemm_ptg(A, B, C, devices="auto")

    param("device_tpu_batch_max", 4)
    calls = {"n": 0}

    def hook(batch):
        calls["n"] += 1
        if calls["n"] > 1:
            raise ConnectionResetError("relay reset")

    dev._dispatch_hook = hook

    def broken_writeback(copy):
        raise OSError("D2H path down")

    dev._writeback = broken_writeback
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    with pytest.raises(RuntimeError, match="could not be salvaged"):
        ctx.wait(timeout=120)
        dev.sync()
    ctx.fini()


def test_fini_reraises_never_surfaced_background_failure():
    """A worker death recorded while the caller never wait()s must not
    read as clean success: fini() tears down, then re-raises.  A failure
    the caller already saw (raised from wait) is NOT raised twice."""
    import time

    from parsec_tpu import ptg

    def mk_ctx():
        p = ptg.PTGBuilder("boom", N=1)
        t = p.task("T", i=ptg.span(0, 0))
        t.flow("ctl", ptg.CTL)

        def body(es, task, g, l):
            raise ValueError("worker death")
        t.body(body)
        ctx = Context(nb_cores=1)
        ctx.add_taskpool(p.build())
        return ctx

    # never-surfaced: poll without wait(), then fini raises
    ctx = mk_ctx()
    ctx.start()
    deadline = time.monotonic() + 30
    while ctx._worker_error is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ctx._worker_error is not None
    with pytest.raises(RuntimeError, match="background thread failed"):
        ctx.fini()

    # surfaced through wait() (as the raw body error on the caller-driven
    # path, or wrapped when a worker recorded it first): fini stays silent
    ctx = mk_ctx()
    with pytest.raises((RuntimeError, ValueError)):
        ctx.wait(timeout=30)
    ctx.fini()


def test_relay_disconnect_during_stage_in_demotes(dev, monkeypatch, param):
    """The H2D boundary dies (device_put raises after N transfers): the
    demote protocol must fire from the stage-in phase too, and the CPU
    incarnations must finish with exact numerics."""
    a, b, c, A, B, C = _mk_abc(64, 16, 24)
    tp = tiled_gemm_ptg(A, B, C, devices="auto")

    param("device_tpu_batch_max", 8)   # several batched transfers
    real_put = jax.device_put
    calls = {"n": 0}

    def flaky_put(x, device=None, **kw):
        calls["n"] += 1
        if calls["n"] > 1:
            raise ConnectionResetError("relay reset during H2D")
        return real_put(x, device, **kw)

    monkeypatch.setattr(jax, "device_put", flaky_put)
    ctx = Context(nb_cores=0)
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    dev.sync()
    ctx.fini()
    monkeypatch.undo()
    assert calls["n"] > 1, "the H2D failure was never injected"
    assert dev.enabled is False
    np.testing.assert_allclose(C.to_dense(), c + a @ b, rtol=1e-3,
                               atol=1e-4)
