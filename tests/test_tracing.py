"""Request-scoped tracing + the per-tenant SLO metrics plane (ISSUE 10):

- histogram property tests: merge associativity (exact bucket-wise),
  quantile error bound vs numpy on random distributions, serialization
  round trip;
- span recorder unit tests + the allocation-free disabled pin (same
  style as ``test_disabled_path_is_allocation_free``);
- server-level SLO: ``RuntimeServer.metrics()`` per-tenant quantiles,
  admission-shed counters, drain time, and the stall-dump section that
  names WHOSE request is stuck (per-tenant inflight + oldest trace id);
- tracemerge: the self-test, and THE acceptance run — a 2-rank
  multiproc run whose activation and fragmented-GET spans stitch into
  one Chrome trace with cross-rank flow arrows.
"""

import json
import os
import threading
import time
import tracemalloc

import numpy as np
import pytest

from parsec_tpu.prof import spans
from parsec_tpu.prof.histogram import LogHistogram, SLOPlane

BODIES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mp_bodies.py")


# ---------------------------------------------------------------------------
# histogram properties
# ---------------------------------------------------------------------------

def _hist_of(values):
    h = LogHistogram()
    for v in values:
        h.record(v)
    return h


def test_histogram_merge_is_associative_and_exact():
    """(a ⊎ b) ⊎ c == a ⊎ (b ⊎ c) == hist(all) — bucket-exact, so
    per-rank / per-stage histograms combine without loss."""
    rng = np.random.default_rng(42)
    xs = rng.lognormal(1.0, 1.5, 3000) * 5
    a, b, c = xs[:1000], xs[1000:1700], xs[1700:]
    left = _hist_of(a).merge(_hist_of(b)).merge(_hist_of(c))
    right = _hist_of(a).merge(_hist_of(b).merge(_hist_of(c)))
    whole = _hist_of(xs)
    assert left.counts == right.counts == whole.counts
    assert left.count == whole.count == len(xs)
    assert abs(left.total - whole.total) < 1e-6 * whole.total


@pytest.mark.parametrize("dist", ["lognormal", "exponential", "uniform"])
def test_histogram_quantile_error_is_bounded(dist):
    """A reported quantile is the geometric midpoint of its bucket:
    within a factor sqrt(growth) of the empirical quantile.  Tested
    against numpy at the (growth - 1) line — looser than the midpoint
    bound to absorb rank-convention differences at bucket edges."""
    rng = np.random.default_rng(7)
    xs = {"lognormal": rng.lognormal(1.0, 1.0, 5000) * 3,
          "exponential": rng.exponential(20.0, 5000) + 0.01,
          "uniform": rng.uniform(0.5, 400.0, 5000)}[dist]
    h = _hist_of(xs)
    bound = h.growth - 1.0          # ~0.19 at the default 2**0.25
    for q in (0.5, 0.9, 0.99):
        hq = h.quantile(q)
        nq = float(np.percentile(xs, q * 100))
        assert abs(hq - nq) / nq <= bound, (dist, q, hq, nq)


def test_histogram_serialization_round_trip():
    rng = np.random.default_rng(3)
    h = _hist_of(rng.exponential(5.0, 2000))
    h2 = LogHistogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert h2.counts == h.counts
    assert h2.count == h.count
    for q in (0.5, 0.99):
        assert h2.quantile(q) == h.quantile(q)
    # the serialized form really is a (sparse) bucket array
    d = h.to_dict()
    assert all(isinstance(i, int) and c > 0 for i, c in d["counts"])


def test_histogram_extremes_and_empty():
    h = LogHistogram()
    assert h.quantile(0.5) == 0.0
    h.record(0.0)                     # underflow bucket
    h.record(1e12)                    # overflow bucket
    assert h.count == 2
    assert h.quantile(0.01) == h.lo
    assert h.quantile(0.99) == h._bucket_value(h.nbuckets - 1)
    with pytest.raises(ValueError):
        h.merge(LogHistogram(lo=1.0))


def test_histogram_quantile_clamps_racy_count_divergence():
    """The lock-free record path can lose a bucket increment while
    ``count`` advances (racing completion listeners): quantile must
    clamp its rank to the buckets actually present, never fall through
    to the ~4.6e7 ms overflow midpoint."""
    h = _hist_of([1.0, 2.0, 3.0])
    h.count += 2            # simulate two lost bucket increments
    assert h.quantile(0.99) < 10.0
    empty = LogHistogram()
    empty.count = 5         # pathological: counts all lost
    assert empty.quantile(0.5) == 0.0


def test_slo_plane_summary_and_counters():
    p = SLOPlane()
    for v in (1.0, 2.0, 100.0):
        p.observe("tenantA", "ttft_ms", v)
    p.inc("tenantA", "admission_sheds", 3)
    s = p.summary()
    assert s["tenantA"]["ttft_ms_count"] == 3
    assert s["tenantA"]["ttft_ms_p50"] > 0
    assert s["tenantA"]["ttft_ms_p99"] >= s["tenantA"]["ttft_ms_p50"]
    assert s["tenantA"]["admission_sheds"] == 3
    d = p.to_dict()
    assert "ttft_ms" in d["tenantA"]
    assert d["_counters"]["tenantA"]["admission_sheds"] == 3


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------

@pytest.fixture
def installed_spans():
    rec = spans.install()
    try:
        yield rec
    finally:
        spans.uninstall()


def test_disabled_span_path_is_allocation_free():
    """The comm/serve hot-site pattern (``r = spans.recorder; if r is
    not None: ...``) with the recorder uninstalled: zero allocation —
    the same pin as the flight recorder's disabled path."""
    assert spans.recorder is None, "a test left the recorder installed"
    payload = spans  # any attr holder; warm the path
    r = spans.recorder
    if r is not None:
        r.record("x", 0, 0, 0)
    it = range(1000)
    tracemalloc.start()
    before, _ = tracemalloc.get_traced_memory()
    for _ in it:
        r = spans.recorder
        if r is not None:
            r.record("x", 0, 0, 0)
    after, _ = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert after - before < 512, (before, after)
    assert payload is spans


def test_trace_ids_are_unique_and_64bit():
    seen = {spans.new_trace().trace_id for _ in range(1000)}
    assert len(seen) == 1000
    assert all(0 < t < 2 ** 64 for t in seen)


def test_traced_pool_records_task_spans(installed_spans):
    """A traced pool decomposes into queue_wait/schedule/exec/release
    spans; an untraced pool records NOTHING (the per-task getattr
    filter)."""
    from parsec_tpu import ptg
    from parsec_tpu.runtime import Context

    def pool():
        p = ptg.PTGBuilder("chainp", N=6)
        t = p.task("T", i=ptg.span(0, lambda g, l: g.N - 1))
        f = t.flow("ctl", ptg.CTL)
        f.input(pred=("T", "ctl", lambda g, l: {"i": l.i - 1}),
                guard=lambda g, l: l.i > 0)
        f.output(succ=("T", "ctl", lambda g, l: {"i": l.i + 1}),
                 guard=lambda g, l: l.i < g.N - 1)
        t.body(lambda es, task, g, l: None)
        return p.build()

    import parsec_tpu.runtime.dagrun  # noqa: F401 — registers the param
    from parsec_tpu.core.params import params
    saved = params.get("runtime_dag_compile")
    params.set("runtime_dag_compile", False)    # dynamic: full PINS
    try:
        tp = pool()
        tr = spans.new_trace()
        tp._trace = tr
        tp._trace_enq_ns = time.perf_counter_ns()
        with Context(nb_cores=0) as ctx:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=60)
            n_traced = len(installed_spans.by_trace(tr.trace_id))
            untraced = pool()
            before = len(installed_spans.spans)
            ctx.add_taskpool(untraced)
            ctx.wait(timeout=60)
            assert len(installed_spans.spans) == before
    finally:
        params.set("runtime_dag_compile", saved)
    names = {s[0] for s in installed_spans.by_trace(tr.trace_id)}
    assert {"exec", "release", "queue_wait"} <= names, names
    assert n_traced >= 6 * 2 + 1    # exec+release per task + queue_wait
    # exec spans carry the task-class name (string hot-path form)
    ev = [e for e in spans.to_chrome_events(pid=0)
          if e.get("name") == "exec"]
    assert ev and ev[0]["args"]["task"] == "T"


def test_span_recorder_bounds_memory(installed_spans):
    rec = spans.SpanRecorder(max_spans=100)
    for i in range(500):
        rec.record("x", 1, i, i + 1)
    assert len(rec.spans) <= 100
    assert rec.dropped > 0


def test_bench_tracing_preserves_installed_recorder():
    """bench_tracing's enabled/disabled measurement must hand back the
    USER-INSTALLED recorder object — spans accumulated before the bench
    and a custom capacity both survive."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import microbench

    rec = spans.install(max_spans=123)
    rec.record("keepme", 7, 0, 1)
    try:
        microbench.bench_tracing(smoke=True)
        assert spans.recorder is rec
        assert rec.max == 123
        assert any(s[0] == "keepme" for s in rec.spans)
    finally:
        spans.uninstall()


# ---------------------------------------------------------------------------
# server SLO + stall sections
# ---------------------------------------------------------------------------

def _ctl_pool(depth=4, lanes=4, body=None):
    from parsec_tpu import ptg
    p = ptg.PTGBuilder("slopool", NT=lanes, DEPTH=depth)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(body or (lambda es, task, g, l: None))
    return p.build()


def test_server_metrics_live_and_after_drain():
    """metrics() mid-run returns per-tenant quantiles off the histogram
    plane; drain stamps the drain time."""
    from parsec_tpu.serve import RuntimeServer

    server = RuntimeServer(nb_cores=2)
    tks = [server.submit(_ctl_pool(), tenant=f"t{i % 2}")
           for i in range(8)]
    for tk in tks:
        tk.result(timeout=60)
    m = server.metrics()          # LIVE: the server is still hot
    for tenant in ("t0", "t1"):
        d = m["tenants"][tenant]
        assert d["latency_ms_count"] == 4
        assert d["latency_ms_p99"] >= d["latency_ms_p50"] > 0
        assert d["queue_wait_ms_count"] == 4
        assert d["admission_wait_ms_count"] == 4
    assert m["drain_s"] is None
    server.drain(timeout=60)
    assert server.metrics()["drain_s"] is not None
    # every ticket carried a distinct trace context
    assert len({tk.trace.trace_id for tk in tks}) == len(tks)


def test_admission_sheds_counted_per_tenant():
    from parsec_tpu.serve import RuntimeServer
    from parsec_tpu.serve.admission import (AdmissionController,
                                            AdmissionRejected)

    server = RuntimeServer(
        nb_cores=1, admission=AdmissionController(max_inflight=1))
    gate = threading.Event()

    def slow_body(es, task, g, l):
        gate.wait(10)       # a body must return None (hook rc protocol)

    slow = _ctl_pool(body=slow_body)
    tk = server.submit(slow, tenant="busy")
    try:
        with pytest.raises(AdmissionRejected):
            server.submit(_ctl_pool(), tenant="shed", block=False)
        m = server.metrics()
        assert m["tenants"]["shed"]["admission_sheds"] == 1
    finally:
        gate.set()
        tk.result(timeout=60)
        server.drain(timeout=60)


def test_stall_section_names_stuck_tenant_and_trace():
    """The ISSUE-10 satellite: a stall report carries per-tenant
    inflight counts and the oldest live trace id, so a wedged serve run
    names WHOSE request is stuck."""
    from parsec_tpu.prof import flight_recorder
    from parsec_tpu.serve import RuntimeServer

    server = RuntimeServer(nb_cores=1)
    gate = threading.Event()

    def slow_body(es, task, g, l):
        gate.wait(10)       # a body must return None (hook rc protocol)

    tk = server.submit(_ctl_pool(body=slow_body), tenant="victim")
    try:
        report = flight_recorder.build_stall_report(
            server.context, reason="test")
        sec = [v for k, v in report["sections"].items()
               if k.startswith("serve")]
        assert sec, report.get("sections")
        victim = sec[0]["victim"]
        assert victim["inflight"] == 1
        assert victim["oldest_trace_id"] == format(tk.trace.trace_id,
                                                   "x")
        assert victim["oldest_age_s"] >= 0
        assert victim["oldest_pool"] == tk.name
    finally:
        gate.set()
        tk.result(timeout=60)
        server.drain(timeout=60)
    # the section unregisters with the server: later dumps are clean
    report = flight_recorder.build_stall_report(None, reason="after")
    assert not any(k.startswith("serve")
                   for k in (report.get("sections") or {}))


def test_llm_stream_slo_ttft_and_token_latency():
    """The LLM plane: per-tenant TTFT + inter-token latency quantiles
    from the histogram plane, identical live (metrics()) and after."""
    from parsec_tpu.serve import RuntimeServer

    server = RuntimeServer(nb_cores=2)
    try:
        tks = [server.submit_stream([3, 5, 7], max_new_tokens=4,
                                    tenant=f"u{i}") for i in range(2)]
        for tk in tks:
            tk.result(timeout=120)
        m = server.metrics()
        for i in range(2):
            d = m["tenants"][f"u{i}"]
            assert d["ttft_ms_count"] == 1
            assert d["ttft_ms_p50"] > 0
            assert d["tok_latency_ms_count"] == 4
            assert d["tok_latency_ms_p99"] >= d["tok_latency_ms_p50"] > 0
        # streams carry trace contexts too
        assert len({tk.trace.trace_id for tk in tks}) == 2
    finally:
        server.drain(timeout=60)


def test_runtime_report_carries_slo_block():
    from parsec_tpu.prof import runtime_report
    p = SLOPlane()
    p.observe("reportme", "latency_ms", 5.0)
    rep = runtime_report()
    assert "reportme" in rep["slo"]
    assert rep["slo"]["reportme"]["latency_ms_count"] >= 1


# ---------------------------------------------------------------------------
# tracemerge
# ---------------------------------------------------------------------------

def test_tracemerge_self_test():
    from parsec_tpu.prof import tracemerge
    assert tracemerge.self_test() == 0


def test_tracemerge_unmatched_flows_are_not_stitched(tmp_path):
    from parsec_tpu.prof import tracemerge
    p = tmp_path / "trace-rank0.json"
    p.write_text(json.dumps({"traceEvents": [
        {"name": "comm.get", "ph": "X", "ts": 1.0, "dur": 1.0, "pid": 0,
         "tid": 0, "args": {"flow": "get:0:1", "flow_side": "recv"}}]}))
    stats = tracemerge.merge_traces([str(p)], str(tmp_path / "out.json"))
    assert stats["flows_matched"] == 0
    assert stats["cross_rank_flows"] == 0


def test_two_rank_spans_stitch_across_ranks(tmp_path):
    """THE acceptance run: a 2-rank multiproc chain over the binary
    socket wire produces per-rank Chrome traces that tracemerge
    stitches into ONE trace with cross-rank flow arrows for at least
    one activation AND one fragmented GET (viewable in Perfetto)."""
    from parsec_tpu.comm.multiproc import run_multiproc
    from parsec_tpu.core.params import params
    from parsec_tpu.prof import tracemerge

    os.environ["PARSEC_TEST_TRACE_DIR"] = str(tmp_path)
    saved = params.get("comm_get_frag_bytes")
    # 8 KiB fragments over 32 KiB tiles: every tile hop is a FRAGMENTED
    # GET (the param is forwarded to the subprocess ranks by multiproc)
    params.set("comm_get_frag_bytes", 8192)
    try:
        res = run_multiproc(2, f"{BODIES}:traced_get_body", timeout=180)
    finally:
        params.set("comm_get_frag_bytes", saved)
        os.environ.pop("PARSEC_TEST_TRACE_DIR", None)
    # each rank recorded comm spans (names returned by the body)
    for names in res:
        assert "comm.activate" in names, res
    paths = [str(tmp_path / f"trace-rank{r}.json") for r in (0, 1)]
    for p in paths:
        assert os.path.exists(p)
    merged = tmp_path / "merged_trace.json"
    stats = tracemerge.merge_traces(paths, str(merged))
    # at least one activation hop and one GET stitched ACROSS ranks
    assert stats["cross_rank_flows"] >= 2, stats
    assert stats["flows_by_kind"].get("act", 0) >= 1, stats
    assert stats["flows_by_kind"].get("get", 0) >= 1, stats
    trace = json.loads(merged.read_text())
    evs = trace["traceEvents"]
    s_evs = [e for e in evs if e.get("ph") == "s"]
    f_evs = [e for e in evs if e.get("ph") == "f"]
    assert s_evs and f_evs
    # arrows connect DIFFERENT rank pid namespaces
    assert any(a["pid"] // 100 != b["pid"] // 100
               for a in s_evs for b in f_evs
               if a.get("id") == b.get("id"))
    # the shared trace id survived the wire: traced spans on both ranks
    traced = [e for e in evs
              if (e.get("args") or {}).get("trace") == "beef01"]
    assert {e["pid"] // 100 for e in traced} == {0, 1}
