"""Native (C++) runtime-core tier: build, bindings, hot-path integration.

The analog of the reference's ``tests/class/`` thread-stress suite
(SURVEY §4.1) for the ctypes-bound structures, plus integration checks that
the dispatch hot path actually goes through the native dep table and that
native and Python tiers agree.
"""

import threading

import numpy as np
import pytest

from parsec_tpu import native
from parsec_tpu.runtime.deps import _pack_key64

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native tier not buildable")


def test_ensure_built_returns_lib():
    assert native.ensure_built() is not None


def test_lifo_threaded_stress():
    lifo = native.NativeLifo()
    N, T = 2000, 4
    seen = []
    seen_lock = threading.Lock()

    def worker(base):
        got = []
        for i in range(N):
            lifo.push(base + i)
            if i % 3 == 0:
                v = lifo.pop()
                if v is not None:
                    got.append(v)
        while True:
            v = lifo.pop()
            if v is None:
                break
            got.append(v)
        with seen_lock:
            seen.extend(got)

    ts = [threading.Thread(target=worker, args=(t * N,)) for t in range(T)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # drain leftovers (races can leave items pushed after a worker's drain)
    while (v := lifo.pop()) is not None:
        seen.append(v)
    assert sorted(seen) == list(range(N * T))
    assert len(lifo) == 0


def test_deque_two_ended():
    dq = native.NativeDeque()
    dq.push_back(1)
    dq.push_back(2)
    dq.push_front(0)
    assert len(dq) == 3
    assert dq.pop_front() == 0
    assert dq.pop_back() == 2
    assert dq.pop_front() == 1
    assert dq.pop_front() is None


def test_heap_priority_order():
    h = native.NativeHeap()
    for prio, v in [(1, 10), (5, 50), (3, 30)]:
        h.push(prio, v)
    assert [h.pop(), h.pop(), h.pop()] == [50, 30, 10]
    assert h.pop() is None


def test_deptable_mask_protocol():
    t = native.NativeDepTable(64)
    assert not t.release(7, 0b001, 0b111)
    assert not t.release(7, 0b100, 0b111)
    assert len(t) == 1
    assert t.release(7, 0b010, 0b111)       # ready, entry removed
    assert len(t) == 0
    # the key is reusable after readiness (freelist recycling)
    assert t.release(7, 0b1, 0b1)


def test_deptable_double_release_raises():
    t = native.NativeDepTable(64)
    t.release(9, 0b01, 0b11)
    with pytest.raises(AssertionError):
        t.release(9, 0b01, 0b11)


def test_deptable_threaded_stress():
    t = native.NativeDepTable(256)
    NKEYS, NBITS = 500, 8
    required = (1 << NBITS) - 1
    ready_counts = [0] * NBITS

    def worker(bit):
        n = 0
        for k in range(NKEYS):
            if t.release(k, 1 << bit, required):
                n += 1
        ready_counts[bit] = n

    ts = [threading.Thread(target=worker, args=(b,)) for b in range(NBITS)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert sum(ready_counts) == NKEYS       # each key ready exactly once
    assert len(t) == 0


def test_counter():
    c = native.NativeCounter(2)
    assert c.add(-1) == 1
    assert c.add(-1) == 0
    assert c.get() == 0


def test_pack_key64_is_exact_or_refused():
    assert _pack_key64(1, 2, (3, 4, 5)) is not None
    # injective on a sample grid
    seen = set()
    for m in range(8):
        for n in range(8):
            for k in range(8):
                seen.add(_pack_key64(1, 2, (m, n, k)))
    assert len(seen) == 512
    # refusals: negative, huge, non-int, too many ids
    assert _pack_key64(1, 2, (-1,)) is None
    assert _pack_key64(1, 2, (1 << 50,)) is None
    assert _pack_key64(1, 2, ("x",)) is None
    assert _pack_key64(1 << 12, 2, (0,)) is None
    assert _pack_key64(1, 1 << 8, (0,)) is None


def _run_ep(nb_cores, sched=None):
    from parsec_tpu import ptg
    from parsec_tpu.runtime import Context

    NT, DEPTH = 10, 20
    done = []
    p = ptg.PTGBuilder("ep", NT=NT, DEPTH=DEPTH, DONE=done)
    t = p.task("EP",
               d=ptg.span(0, lambda g, l: g.DEPTH - 1),
               n=ptg.span(0, lambda g, l: g.NT - 1))
    f = t.flow("ctl", ptg.CTL)
    f.input(pred=("EP", "ctl", lambda g, l: {"d": l.d - 1, "n": l.n}),
            guard=lambda g, l: l.d > 0)
    f.output(succ=("EP", "ctl", lambda g, l: {"d": l.d + 1, "n": l.n}),
             guard=lambda g, l: l.d < g.DEPTH - 1)
    t.body(lambda es, task, g, l: g.DONE.append((l.d, l.n)))
    ctx = Context(nb_cores=nb_cores, scheduler=sched) if sched else \
        Context(nb_cores=nb_cores)
    try:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
    finally:
        ctx.fini()
    return done


def test_ep_dag_runs_through_native_deptable():
    from parsec_tpu.runtime import Context
    ctx = Context(nb_cores=0)
    try:
        assert ctx.deps.native_enabled
    finally:
        ctx.fini()
    done = _run_ep(nb_cores=2)
    assert len(done) == 200
    assert sorted(done) == sorted((d, n) for d in range(20) for n in range(10))


def test_native_and_python_tiers_agree_on_gemm():
    from parsec_tpu.core.params import params
    from parsec_tpu.data_dist.matrix import TiledMatrix
    from parsec_tpu.models.tiled_gemm import tiled_gemm_ptg
    from parsec_tpu.runtime import Context

    rng = np.random.default_rng(5)
    a, b = rng.standard_normal((8, 8)), rng.standard_normal((8, 8))
    outs = []
    for native_on in (True, False):
        params.set("runtime_native", native_on)
        try:
            A = TiledMatrix.from_dense(f"A{native_on}", a, 4, 4)
            B = TiledMatrix.from_dense(f"B{native_on}", b, 4, 4)
            C = TiledMatrix.from_dense(f"C{native_on}",
                                       np.zeros((8, 8)), 4, 4)
            ctx = Context(nb_cores=2)
            try:
                assert ctx.deps.native_enabled == native_on
                # pin the cpu incarnation: best-device selection is load-
                # dependent and the tpu body computes in f32 — incarnation
                # variance would mask what this test compares (dep tiers)
                ctx.add_taskpool(tiled_gemm_ptg(A, B, C, devices="cpu"))
                ctx.wait(timeout=60)
            finally:
                ctx.fini()
            outs.append(C.to_dense())
        finally:
            params.set("runtime_native", True)
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)
    # the cpu body contracts in f32 (gemm_cpu_body): f32-level oracle check
    np.testing.assert_allclose(outs[0], a @ b, atol=1e-5)


def test_ll_scheduler_uses_native_lifo():
    done = _run_ep(nb_cores=2, sched="ll")
    assert len(done) == 200
