"""Tiled LU (nopiv): the second dense factorization, on all three tiers —
dynamic single-rank, dynamic multi-rank over the comm engine, and the
compiled lowering (single-rank and sharded)."""

import numpy as np
import pytest

from parsec_tpu.comm import run_multirank
from parsec_tpu.data_dist.matrix import TwoDimBlockCyclic
from parsec_tpu.models.lu import make_dd, tiled_lu_ptg, unpack_lu
from parsec_tpu.runtime import Context


def assemble(dc) -> np.ndarray:
    out = np.zeros((dc.lm, dc.ln), dtype=dc.dtype)
    for m in range(dc.mt):
        for n in range(dc.nt):
            t = np.asarray(dc.data_of(m, n).newest_copy().value)
            out[m * dc.mb:(m + 1) * dc.mb, n * dc.nb:(n + 1) * dc.nb] = t
    return out


def check_factors(packed: np.ndarray, a: np.ndarray, tol=2e-3):
    L, U = unpack_lu(packed)
    np.testing.assert_allclose(L @ U, a, rtol=tol, atol=tol)


class TestDynamic:
    @pytest.mark.parametrize("n,nb", [(32, 8), (64, 16)])
    def test_single_rank(self, n, nb):
        a = make_dd(n)
        A = TwoDimBlockCyclic.from_dense("A", a, nb, nb)
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tiled_lu_ptg(A, devices="cpu"))
        ctx.wait(timeout=60)
        ctx.fini()
        check_factors(assemble(A), a)

    def test_matches_numpy_packed(self):
        """Tile algorithm == straight nopiv elimination."""
        from parsec_tpu.models.lu import _getrf_nopiv_np
        n, nb = 32, 8
        a = make_dd(n, seed=3)
        A = TwoDimBlockCyclic.from_dense("A", a, nb, nb)
        ctx = Context(nb_cores=0)
        ctx.add_taskpool(tiled_lu_ptg(A, devices="cpu"))
        ctx.wait(timeout=60)
        ctx.fini()
        np.testing.assert_allclose(assemble(A), _getrf_nopiv_np(a),
                                   rtol=2e-3, atol=2e-3)

    @pytest.mark.parametrize("nranks", [4])
    def test_multirank(self, nranks):
        def body(ctx, rank, nr):
            a = make_dd(64)
            A = TwoDimBlockCyclic.from_dense("A", a, 16, 16, P=2, Q=2,
                                             myrank=rank)
            ctx.add_taskpool(tiled_lu_ptg(A, devices="cpu"))
            ctx.wait(timeout=120)
            ctx.comm_barrier()
            return A.to_dense()   # local tiles only

        res = run_multirank(nranks, body)
        packed = np.zeros((64, 64), np.float32)
        for part in res:
            packed += part
        check_factors(packed, make_dd(64))


class TestLowered:
    def test_lowered_single(self):
        from parsec_tpu.ptg.lowering import lower_taskpool
        n, nb = 64, 16
        a = make_dd(n)
        A = TwoDimBlockCyclic.from_dense("A", a, nb, nb)
        low = lower_taskpool(tiled_lu_ptg(A))
        assert low.mode == "wavefront"
        low.execute()
        check_factors(assemble(A), a)

    def test_lowered_sharded(self):
        import jax
        from jax.sharding import Mesh

        from parsec_tpu.ptg.lowering import lower_taskpool
        n, nb = 64, 16
        a = make_dd(n)
        A = TwoDimBlockCyclic.from_dense("A", a, nb, nb, P=2, Q=1)
        mesh = Mesh(np.array(jax.devices()[:2]), ("ranks",))
        low = lower_taskpool(tiled_lu_ptg(A), mesh=mesh)
        low.execute()
        check_factors(assemble(A), a)
