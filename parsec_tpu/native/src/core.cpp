// Native runtime core: concurrent queues, dependency table, counters.
//
// Rebuild of the reference's foundation-class tier in C++ (SURVEY §2.1:
// parsec/class/{lifo,dequeue,parsec_hash_table,maxheap} and the atomic
// counter discipline of parsec_internal.h:124-144), exposed through a C ABI
// for ctypes.  These are the dispatch hot-path structures: scheduler queues
// hold opaque uint64 task handles; the dependency table implements the
// satisfied-mask protocol of parsec_update_deps_with_mask (parsec.c:1577)
// with per-bucket locks (the hashed variant, parsec.c:1501).
//
// Design notes (not a translation):
// - LIFO push/pop use a 128-bit CAS {head, aba} pair to defeat ABA, the
//   same trick the reference's lifo.h uses, implemented with GCC __int128
//   atomics instead of hand-rolled asm.
// - The dep table is a fixed-power-of-two bucket array with chaining and a
//   spinlock per bucket; entries free-list onto a per-table LIFO.
// - Handles are uint64 so Python can map them to task objects; the native
//   layer never owns Python state.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <queue>
#include <utility>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// spinlock
// ---------------------------------------------------------------------------
struct Spin {
    std::atomic_flag f = ATOMIC_FLAG_INIT;
    void lock() {
        while (f.test_and_set(std::memory_order_acquire)) {
#if defined(__x86_64__)
            __builtin_ia32_pause();
#endif
        }
    }
    void unlock() { f.clear(std::memory_order_release); }
};

// ---------------------------------------------------------------------------
// lock-free LIFO with ABA counter (cf. class/lifo.h's 128-bit CAS design)
// ---------------------------------------------------------------------------
struct LifoNode {
    LifoNode* next;
    uint64_t value;
};

struct alignas(16) LifoHead {
    LifoNode* ptr;
    uint64_t aba;
};

struct Lifo {
    std::atomic<__int128> head;   // {ptr, aba} packed
    std::atomic<long> size;
    // node freelist to avoid malloc per push
    std::atomic<__int128> freelist;

    static __int128 pack(LifoNode* p, uint64_t aba) {
        __int128 v = (unsigned __int128)(uintptr_t)p;
        v |= ((unsigned __int128)aba) << 64;
        return v;
    }
    static LifoNode* ptr_of(__int128 v) {
        return (LifoNode*)(uintptr_t)(uint64_t)(unsigned __int128)v;
    }
    static uint64_t aba_of(__int128 v) {
        return (uint64_t)(((unsigned __int128)v) >> 64);
    }
};

static void lifo_stack_push(std::atomic<__int128>* stack, LifoNode* n) {
    __int128 old = stack->load(std::memory_order_relaxed);
    for (;;) {
        n->next = Lifo::ptr_of(old);
        __int128 desired = Lifo::pack(n, Lifo::aba_of(old) + 1);
        if (stack->compare_exchange_weak(old, desired,
                                         std::memory_order_release,
                                         std::memory_order_relaxed))
            return;
    }
}

static LifoNode* lifo_stack_pop(std::atomic<__int128>* stack) {
    __int128 old = stack->load(std::memory_order_acquire);
    for (;;) {
        LifoNode* n = Lifo::ptr_of(old);
        if (!n) return nullptr;
        __int128 desired = Lifo::pack(n->next, Lifo::aba_of(old) + 1);
        if (stack->compare_exchange_weak(old, desired,
                                         std::memory_order_acquire,
                                         std::memory_order_relaxed))
            return n;
    }
}

void* pt_lifo_new() {
    Lifo* l = new Lifo();
    l->head.store(0);
    l->freelist.store(0);
    l->size.store(0);
    return l;
}

void pt_lifo_free(void* h) {
    Lifo* l = (Lifo*)h;
    LifoNode* n;
    while ((n = lifo_stack_pop(&l->head))) delete n;
    while ((n = lifo_stack_pop(&l->freelist))) delete n;
    delete l;
}

void pt_lifo_push(void* h, uint64_t value) {
    Lifo* l = (Lifo*)h;
    LifoNode* n = lifo_stack_pop(&l->freelist);
    if (!n) n = new LifoNode();
    n->value = value;
    lifo_stack_push(&l->head, n);
    l->size.fetch_add(1, std::memory_order_relaxed);
}

// returns 1 and sets *out on success, 0 when empty
int pt_lifo_pop(void* h, uint64_t* out) {
    Lifo* l = (Lifo*)h;
    LifoNode* n = lifo_stack_pop(&l->head);
    if (!n) return 0;
    *out = n->value;
    l->size.fetch_sub(1, std::memory_order_relaxed);
    lifo_stack_push(&l->freelist, n);
    return 1;
}

long pt_lifo_size(void* h) {
    return ((Lifo*)h)->size.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// dequeue (cf. class/dequeue.h): two-ended, spinlocked
// ---------------------------------------------------------------------------
struct Deque {
    Spin lock;
    std::deque<uint64_t> q;
};

void* pt_deque_new() { return new Deque(); }
void pt_deque_free(void* h) { delete (Deque*)h; }

void pt_deque_push_back(void* h, uint64_t v) {
    Deque* d = (Deque*)h;
    d->lock.lock();
    d->q.push_back(v);
    d->lock.unlock();
}

void pt_deque_push_front(void* h, uint64_t v) {
    Deque* d = (Deque*)h;
    d->lock.lock();
    d->q.push_front(v);
    d->lock.unlock();
}

int pt_deque_pop_front(void* h, uint64_t* out) {
    Deque* d = (Deque*)h;
    d->lock.lock();
    if (d->q.empty()) { d->lock.unlock(); return 0; }
    *out = d->q.front();
    d->q.pop_front();
    d->lock.unlock();
    return 1;
}

int pt_deque_pop_back(void* h, uint64_t* out) {
    Deque* d = (Deque*)h;
    d->lock.lock();
    if (d->q.empty()) { d->lock.unlock(); return 0; }
    *out = d->q.back();
    d->q.pop_back();
    d->lock.unlock();
    return 1;
}

long pt_deque_size(void* h) {
    Deque* d = (Deque*)h;
    d->lock.lock();
    long n = (long)d->q.size();
    d->lock.unlock();
    return n;
}

// ---------------------------------------------------------------------------
// priority heap (cf. class/maxheap.c): (priority, handle) max-heap
// ---------------------------------------------------------------------------
struct Heap {
    Spin lock;
    std::priority_queue<std::pair<int64_t, uint64_t>> q;
};

void* pt_heap_new() { return new Heap(); }
void pt_heap_free(void* h) { delete (Heap*)h; }

void pt_heap_push(void* h, int64_t priority, uint64_t v) {
    Heap* p = (Heap*)h;
    p->lock.lock();
    p->q.emplace(priority, v);
    p->lock.unlock();
}

int pt_heap_pop(void* h, uint64_t* out) {
    Heap* p = (Heap*)h;
    p->lock.lock();
    if (p->q.empty()) { p->lock.unlock(); return 0; }
    *out = p->q.top().second;
    p->q.pop();
    p->lock.unlock();
    return 1;
}

long pt_heap_size(void* h) {
    Heap* p = (Heap*)h;
    p->lock.lock();
    long n = (long)p->q.size();
    p->lock.unlock();
    return n;
}

// ---------------------------------------------------------------------------
// dependency table: key -> {required_mask, satisfied_mask}
// (parsec_update_deps_with_mask, parsec.c:1577; hashed storage :1501)
// ---------------------------------------------------------------------------
struct DepEntry {
    uint64_t key;
    uint64_t required;
    uint64_t satisfied;
    DepEntry* next;
};

struct DepTable {
    size_t nbuckets;           // power of two
    std::vector<DepEntry*> buckets;
    std::vector<Spin> locks;
    std::atomic<long> count;
    std::atomic<__int128> freelist;   // of DepEntry via LifoNode-compatible
                                      // layout (next is first member? no —
                                      // use own simple spinlocked freelist)
    Spin flock;
    DepEntry* free_head = nullptr;

    explicit DepTable(size_t n) : nbuckets(n), buckets(n, nullptr),
                                  locks(n), count(0) {}
};

void* pt_deptable_new(uint64_t nbuckets_pow2) {
    size_t n = 1;
    while (n < nbuckets_pow2) n <<= 1;
    return new DepTable(n);
}

void pt_deptable_free(void* h) {
    DepTable* t = (DepTable*)h;
    for (size_t i = 0; i < t->nbuckets; i++) {
        DepEntry* e = t->buckets[i];
        while (e) { DepEntry* nx = e->next; delete e; e = nx; }
    }
    DepEntry* e = t->free_head;
    while (e) { DepEntry* nx = e->next; delete e; e = nx; }
    delete t;
}

static inline size_t dep_bucket(DepTable* t, uint64_t key) {
    // fibonacci hashing spreads sequential task keys
    return (size_t)((key * 0x9E3779B97F4A7C15ull) >> 32) & (t->nbuckets - 1);
}

// Record satisfied bits for `key`; required_mask is idempotently installed
// on first touch.  Returns 1 when the task just became ready (entry is
// removed), 0 otherwise.  Asserting a bit twice aborts (the double-release
// paranoia check, PARSEC_DEBUG_PARANOID analog) — returns -1 instead.
int pt_deptable_release(void* h, uint64_t key, uint64_t bits,
                        uint64_t required_mask) {
    DepTable* t = (DepTable*)h;
    size_t b = dep_bucket(t, key);
    t->locks[b].lock();
    DepEntry** slot = &t->buckets[b];
    DepEntry* e = *slot;
    while (e && e->key != key) { slot = &e->next; e = e->next; }
    if (!e) {
        t->flock.lock();
        e = t->free_head;
        if (e) t->free_head = e->next;
        t->flock.unlock();
        if (!e) e = new DepEntry();
        e->key = key;
        e->required = required_mask;
        e->satisfied = 0;
        e->next = t->buckets[b];
        t->buckets[b] = e;
        slot = &t->buckets[b];
        t->count.fetch_add(1, std::memory_order_relaxed);
    }
    if (e->satisfied & bits) {
        t->locks[b].unlock();
        return -1;                       // double release
    }
    e->satisfied |= bits;
    int ready = (e->satisfied == e->required);
    if (ready) {
        *slot = e->next;
        t->count.fetch_sub(1, std::memory_order_relaxed);
        t->flock.lock();
        e->next = t->free_head;
        t->free_head = e;
        t->flock.unlock();
    }
    t->locks[b].unlock();
    return ready;
}

long pt_deptable_count(void* h) {
    return ((DepTable*)h)->count.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// DAG executor: the select→release inner loop of a *compiled* task graph
// (the jdf2c stance applied to the scheduler: scheduling.c:562-575's hot loop
// over a concretely-enumerated DAG).  Python hands over indegree counts and a
// CSR successor table once, then ping-pongs batches: pt_dag_fetch fills a
// buffer of ready task ids (priority order when priorities exist), Python
// runs the chore bodies, pt_dag_complete releases all successors of the
// batch natively and banks the newly-ready set.  Per-task native cost is a
// few array ops; Python appears only at the chore boundary.
// ---------------------------------------------------------------------------
struct Dag {
    Spin lock;
    int32_t ntasks;
    int64_t remaining;           // tasks not yet completed
    std::vector<int32_t> indeg;  // live remaining-input counters
    std::vector<int32_t> succ_off;
    std::vector<int32_t> succ;
    std::vector<int64_t> prio;
    bool use_prio;
    std::vector<int32_t> ready;                          // LIFO when !use_prio
    std::priority_queue<std::pair<int64_t, int32_t>> pready;
};

void* pt_dag_new(int32_t ntasks, const int32_t* indeg,
                 const int32_t* succ_off, const int32_t* succ,
                 const int64_t* prio) {
    Dag* d = new Dag();
    d->ntasks = ntasks;
    d->remaining = ntasks;
    d->indeg.assign(indeg, indeg + ntasks);
    d->succ_off.assign(succ_off, succ_off + ntasks + 1);
    d->succ.assign(succ, succ + succ_off[ntasks]);
    d->use_prio = (prio != nullptr);
    if (prio) d->prio.assign(prio, prio + ntasks);
    for (int32_t i = 0; i < ntasks; i++) {
        if (d->indeg[i] == 0) {
            if (d->use_prio) d->pready.emplace(d->prio[i], i);
            else d->ready.push_back(i);
        }
    }
    return d;
}

void pt_dag_free(void* h) { delete (Dag*)h; }

// Fill out[0..cap) with ready task ids; returns the count (0 = none ready).
int32_t pt_dag_fetch(void* h, int32_t* out, int32_t cap) {
    Dag* d = (Dag*)h;
    d->lock.lock();
    int32_t n = 0;
    if (d->use_prio) {
        while (n < cap && !d->pready.empty()) {
            out[n++] = d->pready.top().second;
            d->pready.pop();
        }
    } else {
        while (n < cap && !d->ready.empty()) {
            out[n++] = d->ready.back();
            d->ready.pop_back();
        }
    }
    d->lock.unlock();
    return n;
}

// Complete a batch: release every successor edge, banking newly-ready tasks.
// Returns the number of tasks still outstanding (0 = DAG fully executed),
// or -1 if a successor counter underflowed (graph inconsistency).
int64_t pt_dag_complete(void* h, const int32_t* done, int32_t n) {
    Dag* d = (Dag*)h;
    d->lock.lock();
    for (int32_t j = 0; j < n; j++) {
        int32_t t = done[j];
        for (int32_t e = d->succ_off[t]; e < d->succ_off[t + 1]; e++) {
            int32_t s = d->succ[e];
            if (--d->indeg[s] == 0) {
                if (d->use_prio) d->pready.emplace(d->prio[s], s);
                else d->ready.push_back(s);
            } else if (d->indeg[s] < 0) {
                d->lock.unlock();
                return -1;
            }
        }
    }
    d->remaining -= n;
    int64_t rem = d->remaining;
    d->lock.unlock();
    return rem;
}

int64_t pt_dag_remaining(void* h) {
    Dag* d = (Dag*)h;
    d->lock.lock();
    int64_t r = d->remaining;
    d->lock.unlock();
    return r;
}

// ---------------------------------------------------------------------------
// atomic counter with zero detection (the nb_tasks/nb_pending_actions
// discipline: the transition TO zero must be observed exactly once)
// ---------------------------------------------------------------------------
struct Counter {
    std::atomic<int64_t> v;
};

void* pt_counter_new(int64_t init) {
    Counter* c = new Counter();
    c->v.store(init);
    return c;
}
void pt_counter_free(void* h) { delete (Counter*)h; }

// returns the new value; caller fires termination iff it observes 0
int64_t pt_counter_add(void* h, int64_t delta) {
    return ((Counter*)h)->v.fetch_add(delta, std::memory_order_acq_rel)
           + delta;
}

int64_t pt_counter_get(void* h) {
    return ((Counter*)h)->v.load(std::memory_order_acquire);
}

}  // extern "C"
