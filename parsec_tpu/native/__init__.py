"""ctypes bindings for the native runtime core (``src/core.cpp``).

The dispatch hot-path structures of the foundation tier (SURVEY §2.1) in
C++ behind a C ABI: the ABA-counted lock-free LIFO (``class/lifo.h``
analog), the spinlocked dequeue and maxheap, the hashed dependency table
implementing the satisfied-mask protocol (``parsec_update_deps_with_mask``,
``parsec.c:1577``), and the zero-detecting atomic counter
(``parsec_internal.h:124-144`` discipline).

``ensure_built()`` compiles the shared library on demand (cached under
``build/``, rebuilt when the source is newer).  Loading is best-effort: when
no toolchain is available the runtime falls back to the pure-Python
structures, controlled by the ``runtime_native`` MCA param.

Integration points:

- :mod:`parsec_tpu.runtime.deps` keys the native dep table with an exact
  (injective) 64-bit packing of (taskpool, class, params) when the task
  shape fits, falling back per-key to the Python tracker otherwise;
- the ``ll``/``llp`` schedulers back their per-stream queues with
  :class:`NativeLifo` when available (the reference's ll *is* its lock-free
  LIFO).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any

from ..core.params import params as _params

_params.register("runtime_native", True,
                 "use the native (C++) dep table / queues when buildable")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "core.cpp")
_SO = os.path.join(_HERE, "build", "libparsec_tpu_native.so")

_lock = threading.Lock()
_lib: Any = None
_tried = False


def ensure_built(force: bool = False) -> str | None:
    """Compile ``core.cpp`` → ``build/libparsec_tpu_native.so`` if stale.
    Returns the library path, or None when the build fails."""
    try:
        if (not force and os.path.exists(_SO)
                and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
            return _SO
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-Wall", "-mcx16",
               "-pthread", "-shared", "-o", _SO, _SRC, "-latomic"]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except Exception:
        return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, i64, vp = ctypes.c_uint64, ctypes.c_int64, ctypes.c_void_p
    pu64 = ctypes.POINTER(ctypes.c_uint64)
    sigs = {
        "pt_lifo_new": ([], vp),
        "pt_lifo_free": ([vp], None),
        "pt_lifo_push": ([vp, u64], None),
        "pt_lifo_pop": ([vp, pu64], ctypes.c_int),
        "pt_lifo_size": ([vp], ctypes.c_long),
        "pt_deque_new": ([], vp),
        "pt_deque_free": ([vp], None),
        "pt_deque_push_back": ([vp, u64], None),
        "pt_deque_push_front": ([vp, u64], None),
        "pt_deque_pop_front": ([vp, pu64], ctypes.c_int),
        "pt_deque_pop_back": ([vp, pu64], ctypes.c_int),
        "pt_deque_size": ([vp], ctypes.c_long),
        "pt_heap_new": ([], vp),
        "pt_heap_free": ([vp], None),
        "pt_heap_push": ([vp, i64, u64], None),
        "pt_heap_pop": ([vp, pu64], ctypes.c_int),
        "pt_heap_size": ([vp], ctypes.c_long),
        "pt_deptable_new": ([u64], vp),
        "pt_deptable_free": ([vp], None),
        "pt_deptable_release": ([vp, u64, u64, u64], ctypes.c_int),
        "pt_deptable_count": ([vp], ctypes.c_long),
        "pt_dag_new": ([ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
                        ctypes.POINTER(ctypes.c_int32),
                        ctypes.POINTER(ctypes.c_int32),
                        ctypes.POINTER(ctypes.c_int64)], vp),
        "pt_dag_free": ([vp], None),
        "pt_dag_fetch": ([vp, ctypes.POINTER(ctypes.c_int32),
                          ctypes.c_int32], ctypes.c_int32),
        "pt_dag_complete": ([vp, ctypes.POINTER(ctypes.c_int32),
                             ctypes.c_int32], i64),
        "pt_dag_remaining": ([vp], i64),
        "pt_counter_new": ([i64], vp),
        "pt_counter_free": ([vp], None),
        "pt_counter_add": ([vp, i64], i64),
        "pt_counter_get": ([vp], i64),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def load() -> Any:
    """The loaded library, or None when not buildable.  The
    ``runtime_native`` MCA param is enforced at the integration points
    (dep tracking, schedulers), not here."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = ensure_built()
        if so is None:
            return None
        try:
            _lib = _bind(ctypes.CDLL(so))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


class _Handle:
    """Owns one native object; frees it on GC."""

    __slots__ = ("_lib", "_h", "_free")

    def __init__(self, lib, h, free_name: str) -> None:
        self._lib = lib
        self._h = h
        self._free = getattr(lib, free_name)

    def __del__(self):
        h, self._h = self._h, None
        if h:
            try:
                self._free(h)
            except Exception:
                pass


class NativeLifo(_Handle):
    def __init__(self) -> None:
        lib = load()
        super().__init__(lib, lib.pt_lifo_new(), "pt_lifo_free")

    def push(self, value: int) -> None:
        self._lib.pt_lifo_push(self._h, value)

    def pop(self) -> int | None:
        out = ctypes.c_uint64()   # per-call: ctypes drops the GIL
        if self._lib.pt_lifo_pop(self._h, ctypes.byref(out)):
            return out.value
        return None

    def __len__(self) -> int:
        return self._lib.pt_lifo_size(self._h)


class NativeDeque(_Handle):
    def __init__(self) -> None:
        lib = load()
        super().__init__(lib, lib.pt_deque_new(), "pt_deque_free")

    def push_back(self, v: int) -> None:
        self._lib.pt_deque_push_back(self._h, v)

    def push_front(self, v: int) -> None:
        self._lib.pt_deque_push_front(self._h, v)

    def pop_front(self) -> int | None:
        out = ctypes.c_uint64()   # per-call: ctypes drops the GIL
        if self._lib.pt_deque_pop_front(self._h, ctypes.byref(out)):
            return out.value
        return None

    def pop_back(self) -> int | None:
        out = ctypes.c_uint64()   # per-call: ctypes drops the GIL
        if self._lib.pt_deque_pop_back(self._h, ctypes.byref(out)):
            return out.value
        return None

    def __len__(self) -> int:
        return self._lib.pt_deque_size(self._h)


class NativeHeap(_Handle):
    def __init__(self) -> None:
        lib = load()
        super().__init__(lib, lib.pt_heap_new(), "pt_heap_free")

    def push(self, priority: int, v: int) -> None:
        self._lib.pt_heap_push(self._h, priority, v)

    def pop(self) -> int | None:
        out = ctypes.c_uint64()   # per-call: ctypes drops the GIL
        if self._lib.pt_heap_pop(self._h, ctypes.byref(out)):
            return out.value
        return None

    def __len__(self) -> int:
        return self._lib.pt_heap_size(self._h)


class NativeDepTable(_Handle):
    """key64 -> {required, satisfied} with removal-on-ready.

    ``release`` returns 1 when the key just became ready, 0 otherwise and
    raises on a double-set bit (the PARSEC_DEBUG_PARANOID assert)."""

    def __init__(self, nbuckets: int = 1 << 14) -> None:
        lib = load()
        super().__init__(lib, lib.pt_deptable_new(nbuckets),
                         "pt_deptable_free")
        self._release = lib.pt_deptable_release   # bound-method cache

    def release(self, key64: int, bits: int, required_mask: int) -> bool:
        rc = self._release(self._h, key64, bits, required_mask)
        if rc < 0:
            raise AssertionError(
                f"dep key {key64:#x}: bits {bits:#x} satisfied twice")
        return bool(rc)

    def __len__(self) -> int:
        return self._lib.pt_deptable_count(self._h)


class NativeDag(_Handle):
    """Compiled-DAG executor: indegree counters + CSR successors native-side.

    ``fetch(buf)`` fills a caller-owned ``(ctypes.c_int32 * cap)`` buffer
    with ready task ids; ``complete(buf, n)`` releases all successors of the
    batch and returns the outstanding count.  The two calls are the entire
    select→release loop — Python touches only the chore bodies in between
    (the scheduling.c:562-575 hot loop, compiled)."""

    def __init__(self, indeg, succ_off, succ, prio=None) -> None:
        import numpy as np
        lib = load()
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        indeg = np.ascontiguousarray(indeg, dtype=np.int32)
        succ_off = np.ascontiguousarray(succ_off, dtype=np.int32)
        succ = np.ascontiguousarray(succ, dtype=np.int32)
        self.ntasks = int(indeg.shape[0])
        pprio = None
        if prio is not None:
            prio = np.ascontiguousarray(prio, dtype=np.int64)
            pprio = prio.ctypes.data_as(i64p)
        h = lib.pt_dag_new(self.ntasks, indeg.ctypes.data_as(i32p),
                           succ_off.ctypes.data_as(i32p),
                           succ.ctypes.data_as(i32p), pprio)
        super().__init__(lib, h, "pt_dag_free")
        self._fetch = lib.pt_dag_fetch
        self._complete = lib.pt_dag_complete

    def fetch(self, buf, cap: int) -> int:
        return self._fetch(self._h, buf, cap)

    def complete(self, buf, n: int) -> int:
        rem = self._complete(self._h, buf, n)
        if rem < 0:
            raise RuntimeError("compiled DAG successor counter underflow "
                               "(inconsistent task graph)")
        return rem

    def remaining(self) -> int:
        return self._lib.pt_dag_remaining(self._h)


class NativeCounter(_Handle):
    def __init__(self, init: int = 0) -> None:
        lib = load()
        super().__init__(lib, lib.pt_counter_new(init), "pt_counter_free")

    def add(self, delta: int) -> int:
        return self._lib.pt_counter_add(self._h, delta)

    def get(self) -> int:
        return self._lib.pt_counter_get(self._h)
