"""JDF: the textual PTG front-end.

Rebuild of the reference's JDF compiler (``parsec/interfaces/ptg/ptg-compiler``,
SURVEY §2.7) as a parser into :class:`~parsec_tpu.ptg.dsl.PTGBuilder` — both
front-ends share one backend, mirroring ``parsec_ptgpp`` emitting code against
one runtime ABI.  Where the reference lexes C expressions (``parsec.l``) and
generates C (``jdf2c.c``), this front-end compiles *Python* expressions and
bodies — the idiomatic host language here — while keeping the JDF structure:

Comments: ``/* block */`` and *full-line* ``//`` outside BODY/prologue
regions only — trailing ``// …`` after code is not a comment because ``//``
is Python floor division inside expressions; bodies use Python ``#``.

.. code-block:: none

    /* comments, and full-line // comments */
    %{
    # python prologue: names defined here are visible to every
    # expression and body
    %}

    NT    [type = int]          /* scalar global, bound at build()    */
    V     [type = data]         /* data-collection global             */

    T(i)                        /* task class + parameters            */
      i = 0 .. NT-1             /* execution-space range (inclusive)  */
      : V(i)                    /* data affinity -> owning rank       */
      RW A <- (i == 0) ? V(0) : A T(i-1)     /* guarded input arrows  */
           -> (i <  NT-1) ? A T(i+1)         /* guarded output arrows */
           -> (i == NT-1) ? V(0)
      ; NT - i                  /* priority expression                */
    BODY
      A += 1       # python body: flow names bound to the tile arrays
    END
    BODY [type = tpu  dyld = gemm]
    END

Grammar notes (vs ``parsec.y``): execution-space ranges are ``lo .. hi`` or
``lo .. hi .. step``; arrow targets are ``FLOW Class(args)`` (task dep) or
``DataGlobal(args)`` (collection read/write-back); guards are
``(expr) ? target`` or ``(expr) ? target : target``.  A dep may carry
``[type = NAME]`` reshape properties — ``NAME`` must resolve (via build
bindings or the prologue) to a :class:`~parsec_tpu.data.datatype.TileType`,
and the consumer of that edge observes the datum converted to it
(read-side reshape, :mod:`parsec_tpu.data.reshape`).  ``<- NEW [type=T]``
allocates a fresh tile of type ``T`` (Ex03's first-link form); ``<- NULL``
declares an explicitly data-less input and ``-> NULL`` drops the datum.

Sanity checking mirrors ``jdf_sanity_checks`` (``jdf.h:68-86``): unknown
target classes/flows/collections, missing ranges, CTL flows with data
targets, and malformed arrows all raise :class:`JDFError` at parse or build
time — exercised by the must-fail suite (the ``ptgpp`` error-case tests,
SURVEY §4).
"""

from __future__ import annotations

import re
from typing import Any, Callable

from .dsl import CTL, READ, RW, WRITE, PTGBuilder, PTGTaskpool

_ACCESS = {"RW": RW, "READ": READ, "WRITE": WRITE, "CTL": CTL}


class JDFError(ValueError):
    """Parse-time or build-time JDF rejection (sanity-check failure)."""


# ---------------------------------------------------------------------------
# lexical helpers
# ---------------------------------------------------------------------------

_RE_BODY_KW = re.compile(r"\s*BODY(\s|\[|$)")


def _strip_comments(text: str) -> str:
    """Remove ``/* */`` blocks and full-line ``//`` comments — but never
    inside BODY…END regions, whose content is Python (where ``//`` is floor
    division and ``#`` comments naturally).  Trailing ``// …`` after code is
    deliberately NOT a comment for the same reason."""
    out: list[str] = []
    in_body = False
    in_block = False
    for line in text.split("\n"):
        if in_body:
            out.append(line)
            if line.strip() == "END":
                in_body = False
            continue
        kept: list[str] = []
        j = 0
        while j < len(line):
            if in_block:
                end = line.find("*/", j)
                if end < 0:
                    j = len(line)
                else:
                    in_block = False
                    j = end + 2
                continue
            start = line.find("/*", j)
            if start < 0:
                kept.append(line[j:])
                break
            kept.append(line[j:start])
            in_block = True
            j = start + 2
        s = "".join(kept)
        if s.lstrip().startswith("//"):
            s = ""
        if _RE_BODY_KW.match(s):
            in_body = True
        out.append(s)
    return "\n".join(out)


def _split_top(s: str, sep: str) -> list[str]:
    """Split on ``sep`` at paren depth 0 (guards/ternaries contain parens)."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


# ---------------------------------------------------------------------------
# parsed representation
# ---------------------------------------------------------------------------

class _Arrow:
    __slots__ = ("direction", "guard_src", "then_tgt", "else_tgt", "line",
                 "props")

    def __init__(self, direction, guard_src, then_tgt, else_tgt, line,
                 props=None) -> None:
        self.direction = direction      # "in" | "out"
        self.guard_src = guard_src      # str | None
        self.then_tgt = then_tgt        # (kind, name, flow, args_src)
        self.else_tgt = else_tgt        # same | None
        self.line = line
        self.props = props or {}        # [type=NAME ...] dep properties


class _FlowDecl:
    __slots__ = ("access", "name", "arrows")

    def __init__(self, access, name) -> None:
        self.access = access
        self.name = name
        self.arrows: list[_Arrow] = []


class _TaskDecl:
    __slots__ = ("name", "params", "ranges", "affinity_src", "flows",
                 "priority_src", "bodies", "line", "props", "simcost_src",
                 "derived")

    def __init__(self, name, params, line, props=None) -> None:
        self.name = name
        self.params = params
        self.ranges: dict[str, tuple[str, str, str | None]] = {}
        self.affinity_src: tuple[str, str] | None = None  # (collection, args)
        self.flows: list[_FlowDecl] = []
        self.priority_src: str | None = None
        self.bodies: list[tuple[dict, str]] = []          # (props, code)
        self.line = line
        self.props = props or {}        # UD overrides (jdf.h:185-210)
        self.simcost_src: str | None = None
        # derived locals (`m = t % lmt` lines whose name is not a param,
        # cf. jdf_variable_list entries without a param): evaluated in
        # declaration order on top of the bound params, visible to
        # affinity/guards/arrow args/priority/bodies
        self.derived: dict[str, str] = {}


class JDF:
    """A parsed JDF template; :meth:`build` binds globals and materializes
    the taskpool (the ``parsec_<name>_new`` generated-constructor analog)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.prologue_src: list[str] = []
        self.globals_decl: dict[str, dict] = {}   # name -> props
        self.tasks: dict[str, _TaskDecl] = {}
        self.options: dict[str, str] = {}         # %option lines
        # rewrite notes from jdf_c.resolve_read_chains (empty when the
        # pass hasn't run or found nothing to forward)
        self.read_chain_notes: list[str] = []

    # -- build ---------------------------------------------------------------
    def build(self, **bindings: Any) -> PTGTaskpool:
        ns: dict[str, Any] = {}
        for src in self.prologue_src:
            exec(compile(src, f"<jdf:{self.name}:prologue>", "exec"), ns)
        ns.pop("__builtins__", None)
        # <math.h> equivalents for expressions (reference JDFs compute
        # e.g. reduction-tree depths with ceil/log in global defaults);
        # prologue definitions win.  NOT `pow`: math.pow would shadow the
        # int-preserving builtin every Python-grammar JDF already sees
        import math as _math
        for _mn in ("ceil", "floor", "log", "log2", "sqrt", "fabs"):
            ns.setdefault(_mn, getattr(_math, _mn))
        self._last_ns = ns    # introspection: tests/tools peek at prologue state

        for gname, props in self.globals_decl.items():
            if gname in bindings:
                continue
            if "default" in props:
                env = dict(ns)
                env.update(bindings)
                bindings[gname] = eval(
                    compile(props["default"], "<jdf:default>", "eval"), env)
            else:
                raise JDFError(f"global '{gname}' needs a value at build()")
        for gname in bindings:
            if gname not in self.globals_decl:
                raise JDFError(f"build() got unknown global '{gname}'")

        self._sanity_check()
        builder = PTGBuilder(self.name, **bindings)

        def resolve(pname: str, val: str, line: int) -> Any:
            """Look a UD property value up in the prologue/bindings."""
            env = dict(ns)
            env.update(bindings)
            if val not in env:
                raise JDFError(
                    f"line {line}: [{pname} = {val}] does not name a "
                    f"prologue or build() binding")
            return env[val]

        # pool-level %option lines (jdf.h UD pool properties)
        for oname, oval in self.options.items():
            if oname == "nb_local_tasks_fn":
                builder.option(nb_local_tasks_fn=resolve(oname, oval, 0))
            elif oname == "termdet":
                builder.option(termdet=oval)
            else:
                raise JDFError(f"unknown %option '{oname}'")

        def expr(src: str) -> Callable:
            code = compile(src.strip(), f"<jdf:{self.name}>", "eval")

            def fn(g, l):
                # everything goes in eval's *globals*: comprehension scopes
                # inside the expression cannot see an eval-locals mapping
                env = dict(ns)
                env.update(vars(g))
                env.update(vars(l))
                return eval(code, env)
            return fn

        for td in self.tasks.values():
            params = {}
            for p in td.params:
                lo, hi, step = td.ranges[p]
                params[p] = _mk_range(expr(lo), expr(hi),
                                      expr(step) if step else None)
            tcb = builder.task(td.name, **params)

            # derived locals layer on top of the bound params: every
            # expression of this task evaluates them (in order) first
            dcodes = [(dn, compile(src.strip(),
                                   f"<jdf:{self.name}:{td.name}:{dn}>",
                                   "eval"))
                      for dn, src in td.derived.items()]

            def texpr(src: str, _dc=dcodes) -> Callable:
                code = compile(src.strip(), f"<jdf:{self.name}>", "eval")

                def fn(g, l):
                    env = dict(ns)
                    env.update(vars(g))
                    env.update(vars(l))
                    for dn, c in _dc:
                        env[dn] = eval(c, env)
                    return eval(code, env)
                return fn

            if td.affinity_src is not None:
                coll, args = td.affinity_src
                key_fn = _mk_key(texpr, args)
                tcb.affinity(coll, key_fn)
            if td.priority_src is not None:
                tcb.priority(texpr(td.priority_src))
            if td.simcost_src is not None:
                tcb.simcost(texpr(td.simcost_src))
            for pname, pval in td.props.items():
                fn = resolve(pname, str(pval), td.line)
                if pname == "make_key_fn":
                    tcb.make_key(fn)
                elif pname == "find_deps_fn":
                    tcb.find_deps(fn)
                elif pname == "startup_fn":
                    tcb.startup(fn)
                elif pname == "hash_struct":
                    from ..runtime.task import KeyHashStruct
                    if isinstance(fn, KeyHashStruct):
                        tcb._hash_struct = fn
                    elif isinstance(fn, dict):
                        tcb.hash_struct(**fn)
                    else:
                        raise JDFError(
                            f"line {td.line}: hash_struct must name a "
                            f"KeyHashStruct or a dict of key_* callables")
                else:
                    raise JDFError(
                        f"line {td.line}: unknown task property "
                        f"'{pname}'")
            typeenv = dict(ns)
            typeenv.update(bindings)
            for fd in td.flows:
                fb = tcb.flow(fd.name, fd.access)
                for ar in fd.arrows:
                    self._attach_arrow(fb, ar, fd, td, texpr, typeenv)
            for props, code_str in td.bodies:
                btype = props.get("type", "python")
                evaluate = None
                if "evaluate" in props:
                    # BODY [evaluate = fn]: chore-selection hook from the
                    # prologue, (es, task) -> HOOK_RETURN_* (jdf.h
                    # JDF_BODY_PROP_EVALUATE)
                    evaluate = resolve("evaluate", str(props["evaluate"]),
                                       td.line)
                if btype in ("python", "cpu"):
                    tcb.body(_mk_body(code_str, ns, td.name, dcodes),
                             evaluate=evaluate)
                else:
                    dyld = props.get("dyld")
                    if not dyld:
                        raise JDFError(
                            f"{td.name}: device BODY needs dyld = <kernel>")
                    tcb.body(device=btype, dyld=dyld, evaluate=evaluate)
        return builder.build()

    # -- arrows --------------------------------------------------------------
    def _attach_arrow(self, fb, ar: _Arrow, fd: _FlowDecl, td: _TaskDecl,
                      expr, typeenv: dict | None = None) -> None:
        guard = expr(ar.guard_src) if ar.guard_src else None
        neg = (lambda g, l: not guard(g, l)) if guard else None
        dtt = None
        tname = ar.props.get("type")
        if tname is not None:
            from ..data.datatype import TileType
            dtt = (typeenv or {}).get(tname)
            if not isinstance(dtt, TileType):
                raise JDFError(
                    f"line {ar.line}: [type={tname}] must name a TileType "
                    f"global or prologue binding (got "
                    f"{type(dtt).__name__})")
        # [type_remote = NAME, displ_remote = expr]: partial-tile wire
        # datatype (stencil_1D.jdf:83-92 role).  NAME resolves to a
        # WireRegion (prologue/build binding); displ_remote is a BYTE
        # offset expression evaluated per task instance; the edge ships
        # region.slices(displ) to remote peers instead of the full tile.
        wire = None
        wname = ar.props.get("type_remote")
        if wname is not None and isinstance(wname, str):
            from ..data.datatype import WireRegion
            region = (typeenv or {}).get(wname)
            if isinstance(region, WireRegion):
                displ_fn = (expr(str(ar.props["displ_remote"]))
                            if "displ_remote" in ar.props else None)

                def wire(g, l, _r=region, _d=displ_fn):
                    return _r.slices(int(_d(g, l)) if _d else 0)
            # any other binding (unbound FULL, a TileType doubling as the
            # full-tile arena — the reference's `type = DEFAULT
            # type_remote = DEFAULT` idiom, merge_sort.jdf) keeps the
            # full-tile wire, the reference's default datatype behavior
        for tgt, gfn in ((ar.then_tgt, guard),
                        (ar.else_tgt, neg if ar.else_tgt else None)):
            if tgt is None:
                continue
            kind, name, flow, args_src = tgt
            if kind in ("new", "null"):
                if ar.direction == "out":
                    if kind == "new":
                        raise JDFError(
                            f"line {ar.line}: NEW is an input-only target")
                    continue    # `-> NULL`: the datum is dropped — no dep
                if kind == "new" and fd.access == CTL:
                    # CTL flows carry no data: nothing to allocate.  Reject
                    # here with the line number instead of letting the DSL
                    # layer surface a raw ValueError.
                    raise JDFError(
                        f"line {ar.line}: CTL flow {fd.name} cannot take "
                        f"<- NEW (control flows carry no data)")
                if kind == "new" and dtt is None:
                    # NEW allocates at the flow's declared type; JDF flows
                    # declare it through the arrow's [type=...] property
                    raise JDFError(
                        f"line {ar.line}: NEW needs a [type = ...] "
                        f"property naming the tile type to allocate")
                fb.input(new=(kind == "new"), null=(kind == "null"),
                         guard=gfn, dtt=dtt)
                continue
            if kind == "task":
                t_decl = self.tasks[name]
                args = [a.strip() for a in _split_top(args_src, ",")]
                if len(args) != len(t_decl.params):
                    raise JDFError(
                        f"line {ar.line}: {name}() takes "
                        f"{len(t_decl.params)} params, got {len(args)}")
                # range args (`0 .. NB .. 2`): the arrow fans out (output)
                # or joins N arrivals (input; CTL only)
                arg_fns: list = []
                any_rng = False
                for a in args:
                    parts = [p.strip() for p in a.split("..")]
                    if len(parts) == 1:
                        arg_fns.append((expr(a), None, None))
                    elif len(parts) in (2, 3):
                        any_rng = True
                        arg_fns.append(
                            (expr(parts[0]), expr(parts[1]),
                             expr(parts[2]) if len(parts) == 3 else None))
                    else:
                        raise JDFError(
                            f"line {ar.line}: bad range argument {a!r}")
                pnames = list(t_decl.params)

                def params_fn(g, l, _fns=arg_fns, _ps=pnames,
                              _rng=any_rng):
                    import itertools as _it
                    axes = []
                    for lo_fn, hi_fn, step_fn in _fns:
                        if hi_fn is None:
                            axes.append((lo_fn(g, l),))
                        else:
                            step = int(step_fn(g, l)) if step_fn else 1
                            axes.append(range(
                                int(lo_fn(g, l)),
                                int(hi_fn(g, l)) + (1 if step > 0 else -1),
                                step))
                    if not _rng:
                        return {p: v[0] for p, v in zip(_ps, axes)}
                    return tuple(dict(zip(_ps, combo))
                                 for combo in _it.product(*axes))

                ref = (name, flow, params_fn)
                if ar.direction == "in":
                    if any_rng and fd.access != CTL:
                        raise JDFError(
                            f"line {ar.line}: range input on data flow "
                            f"{fd.name} — N producers for one datum is "
                            f"nondeterministic; range fan-in is CTL-only")
                    # [type_remote] on an INPUT arrow is accepted for
                    # reference fidelity but carries no runtime action:
                    # the wire view is a producer-side (output dep)
                    # decision; the consumer recognizes a region payload
                    # by shape (the body's local-vs-remote branch)
                    fb.input(pred=ref, guard=gfn, dtt=dtt, ranged=any_rng)
                else:
                    fb.output(succ=ref, guard=gfn, dtt=dtt, wire=wire)
            else:   # data
                if fd.access == CTL:
                    raise JDFError(
                        f"line {ar.line}: CTL flow {fd.name} cannot "
                        f"reference data {name}()")
                key_fn = _mk_key(expr, args_src)
                if ar.direction == "in":
                    fb.input(data=(name, key_fn), guard=gfn, dtt=dtt)
                else:
                    fb.output(data=(name, key_fn), guard=gfn, dtt=dtt)

    # -- sanity (jdf_sanity_checks analog) -----------------------------------
    def _sanity_check(self) -> None:
        data_globals = {g for g, p in self.globals_decl.items()
                        if p.get("type") == "data"}
        for td in self.tasks.values():
            for p in td.params:
                if p not in td.ranges:
                    raise JDFError(
                        f"{td.name}: parameter '{p}' has no range line")
            for p in td.ranges:
                if p not in td.params:
                    raise JDFError(
                        f"{td.name}: range for '{p}' which is not a "
                        f"parameter")
            if td.affinity_src is not None \
                    and td.affinity_src[0] not in data_globals:
                raise JDFError(
                    f"{td.name}: affinity references '{td.affinity_src[0]}' "
                    f"which is not a [type = data] global")
            if not td.bodies:
                raise JDFError(f"{td.name}: no BODY")
            seen_flows = set()
            for fd in td.flows:
                if fd.name in seen_flows:
                    raise JDFError(f"{td.name}: duplicate flow {fd.name}")
                seen_flows.add(fd.name)
                for ar in fd.arrows:
                    for tgt in (ar.then_tgt, ar.else_tgt):
                        if tgt is None:
                            continue
                        kind, name, flow, _args = tgt
                        if kind in ("new", "null"):
                            continue
                        if kind == "task":
                            if name not in self.tasks:
                                raise JDFError(
                                    f"line {ar.line}: unknown task class "
                                    f"'{name}'")
                            t_flows = {f.name for f in
                                       self.tasks[name].flows}
                            if flow not in t_flows:
                                raise JDFError(
                                    f"line {ar.line}: {name} has no flow "
                                    f"'{flow}'")
                        elif name not in data_globals:
                            raise JDFError(
                                f"line {ar.line}: '{name}' is neither a "
                                f"task class (missing flow name?) nor a "
                                f"[type = data] global")
                    if fd.access == WRITE and ar.direction == "in" \
                            and any(t is not None and t[0] == "task"
                                    for t in (ar.then_tgt, ar.else_tgt)):
                        raise JDFError(
                            f"line {ar.line}: WRITE flow {fd.name} cannot "
                            f"have a task input dependency")


def _mk_range(lo_fn, hi_fn, step_fn):
    def rng(g, l):
        step = int(step_fn(g, l)) if step_fn else 1
        hi = int(hi_fn(g, l))
        # JDF ranges are inclusive of hi in the step direction
        return range(int(lo_fn(g, l)), hi + (1 if step > 0 else -1), step)
    return rng


def _mk_key(expr, args_src: str):
    fns = [expr(a) for a in _split_top(args_src, ",") if a.strip()]

    def key_fn(g, l):
        return tuple(fn(g, l) for fn in fns)
    return key_fn


def _mk_body(code_str: str, prologue_ns: dict, tname: str,
             derived_codes: list | None = None):
    code = compile(_dedent(code_str), f"<jdf:{tname}:body>", "exec")

    def body(es, task, g, l):
        env = dict(prologue_ns)
        env.update(vars(g))
        env.update(vars(l))
        for dn, c in derived_codes or ():
            env[dn] = eval(c, env)
        env["es"], env["task"] = es, task
        before = {}
        for f in task.task_class.flows:
            if f.is_ctl:
                continue
            copy = task.data[f.flow_index]
            before[f.name] = copy.value if copy is not None else None
            env[f.name] = before[f.name]
        exec(code, env)
        for f in task.task_class.flows:   # functional rebinds write back
            if f.is_ctl:
                continue
            copy = task.data[f.flow_index]
            if copy is not None and env.get(f.name) is not before[f.name]:
                copy.value = env[f.name]

    return body


def _dedent(code: str) -> str:
    import textwrap
    return textwrap.dedent(code)


# ---------------------------------------------------------------------------
# the parser
# ---------------------------------------------------------------------------

_RE_GLOBAL = re.compile(r"^(\w+)\s*(?:=\s*(?P<default>[^\[]+?))?\s*"
                        r"(?:\[(?P<props>[^\]]*)\])?\s*$")
_RE_TASK = re.compile(r"^(\w+)\s*\(([\w\s,]*)\)\s*"
                      r"(?:\[(?P<props>[^\]]*)\])?\s*$")
_RE_RANGE = re.compile(r"^(\w+)\s*=\s*(.+)$")
_RE_FLOW = re.compile(r"^(RW|READ|WRITE|CTL)\s+(\w+)\s*(.*)$")
_RE_TARGET_TASK = re.compile(r"^(\w+)\s+(\w+)\s*\((.*)\)$")
_RE_TARGET_DATA = re.compile(r"^(\w+)\s*\((.*)\)$")


_RE_PROP_KEY = re.compile(r"(\w+)\s*(=)?\s*")
_RE_PROP_BARE = re.compile(r"[\w.\-*%/+]+")


def scan_balanced(s: str, i: int) -> int:
    """Index of the ``)`` closing the paren group opening at ``s[i]``
    (arbitrary depth; ``len(s) - 1`` when unterminated).  Shared by the
    native and C-syntax property scanners."""
    depth, j, n = 0, i, len(s)
    while j < n:
        if s[j] == "(":
            depth += 1
        elif s[j] == ")":
            depth -= 1
            if depth == 0:
                return j
        j += 1
    return n - 1


def _parse_props(s: str | None) -> dict:
    """``key = value`` pairs and bare flags.  Values are either a
    balanced parenthesized expression at ARBITRARY depth (displ_remote
    formulas — a regex depth cap here once misparsed a deep expression
    as a flag and shipped the wrong ghost columns) or a spaceless token
    run."""
    out: dict = {}
    if not s:
        return out
    i, n = 0, len(s)
    while i < n:
        m = _RE_PROP_KEY.match(s, i)
        if m is None:
            i += 1
            continue
        key, has_eq = m.group(1), m.group(2)
        i = m.end()
        if not has_eq:
            out[key] = True
            continue
        if i < n and s[i] == "(":
            j = scan_balanced(s, i)
            out[key] = s[i:j + 1]
            i = j + 1
        else:
            mv = _RE_PROP_BARE.match(s, i)
            out[key] = mv.group(0) if mv else True
            i = mv.end() if mv else i
    return out


def unparse_jdf(jdf: JDF) -> str:
    """Render a parsed :class:`JDF` back to JDF text (``jdf_unparse``,
    ``jdf.h:137`` / ``jdf_unparse.c``): the round-trip tool — the output
    re-parses to an equivalent template (prologues, %options, globals,
    task properties, execution space, derived locals, affinity, SIMCOST,
    flows with guarded/ranged arrows and dep properties, priorities,
    bodies)."""
    out: list[str] = []
    for src in jdf.prologue_src:
        out.append("%{" + src + "%}")
    for oname, oval in jdf.options.items():
        out.append(f"%option {oname} = {oval}")
    if jdf.options:
        out.append("")
    for gname, props in jdf.globals_decl.items():
        line = gname
        if "default" in props:
            line += f" = {props['default']}"
        rest = [f"{k} = {v}" if v is not True else k
                for k, v in props.items() if k != "default"]
        if rest:
            line += "  [" + "  ".join(rest) + "]"
        out.append(line)
    out.append("")

    def tgt(t: tuple) -> str:
        kind, name, flow, args = t
        if kind == "new":
            return "NEW"
        if kind == "null":
            return "NULL"
        if kind == "task":
            return f"{flow} {name}({args})"
        return f"{name}({args})"

    for td in jdf.tasks.values():
        head = f"{td.name}({', '.join(td.params)})"
        if td.props:
            head += "  [" + "  ".join(f"{k} = {v}"
                                      for k, v in td.props.items()) + "]"
        out.append(head)
        for p in td.params:
            lo, hi, step = td.ranges[p]
            if step is not None:
                out.append(f"  {p} = {lo} .. {hi} .. {step}")
            elif lo == hi:
                out.append(f"  {p} = {lo}")
            else:
                out.append(f"  {p} = {lo} .. {hi}")
        for dn, src in td.derived.items():
            out.append(f"  {dn} = {src}")
        if td.affinity_src is not None:
            out.append(f"  : {td.affinity_src[0]}({td.affinity_src[1]})")
        if td.simcost_src is not None:
            out.append(f"  SIMCOST {td.simcost_src}")
        for fd in td.flows:
            acc = {RW: "RW", READ: "READ", WRITE: "WRITE",
                   CTL: "CTL"}[fd.access]
            prefix = f"  {acc} {fd.name} "
            pad = " " * len(prefix)
            first = True
            for ar in fd.arrows:
                arrow = "<-" if ar.direction == "in" else "->"
                seg = tgt(ar.then_tgt)
                if ar.guard_src is not None:
                    # guard_src is stored parenthesized (the grammar
                    # requires it) — emit verbatim
                    seg = f"{ar.guard_src} ? {seg}"
                    if ar.else_tgt is not None:
                        seg += f" : {tgt(ar.else_tgt)}"
                if ar.props:
                    seg += "  [" + "  ".join(
                        f"{k} = {v}" if v is not True else k
                        for k, v in ar.props.items()) + "]"
                out.append((prefix if first else pad) + f"{arrow} {seg}")
                first = False
            if first:
                out.append(prefix.rstrip())
        if td.priority_src is not None:
            out.append(f"  ; {td.priority_src}")
        for props, code in td.bodies:
            line = "BODY"
            if props:
                line += " [" + "  ".join(f"{k} = {v}" if v is not True else k
                                         for k, v in props.items()) + "]"
            out.append(line)
            out.append(code)
            out.append("END")
        out.append("")
    return "\n".join(out)


def load_jdf(path: Any, name: str | None = None) -> JDF:
    """Parse a ``.jdf`` file from disk (the ``parsec_ptgpp <file>`` entry)."""
    import pathlib
    p = pathlib.Path(path)
    return parse_jdf(p.read_text(), name or p.stem)


def parse_jdf(text: str, name: str = "jdf") -> JDF:
    jdf = JDF(name)

    # %{ ... %} prologues come out first: their content is Python and must
    # not be touched by JDF comment stripping
    def grab_prologue(m):
        jdf.prologue_src.append(m.group(1))
        return "\n" * m.group(0).count("\n")
    text = re.sub(r"%\{(.*?)%\}", grab_prologue, text, flags=re.S)
    text = _strip_comments(text)

    lines = text.split("\n")
    i, n = 0, len(lines)
    cur: _TaskDecl | None = None
    cur_flow: _FlowDecl | None = None

    def err(msg):
        raise JDFError(f"line {i + 1}: {msg}")

    while i < n:
        raw = lines[i]
        line = raw.strip()
        if not line:
            i += 1
            continue

        if line.startswith("%"):
            # %option name = value (pool-level UD properties); other
            # %-directives are accepted and ignored
            if line.startswith("%option"):
                jdf.options.update(
                    {k: v for k, v in _parse_props(line[7:]).items()})
            i += 1
            continue

        if _RE_BODY_KW.match(line):
            if cur is None:
                err("BODY outside a task class")
            props = _parse_props(
                line[4:].strip().strip("[]") if "[" in line else None)
            body_lines = []
            i += 1
            while i < n and lines[i].strip() != "END":
                body_lines.append(lines[i])
                i += 1
            if i >= n:
                raise JDFError(f"{cur.name}: BODY without END")
            cur.bodies.append((props, "\n".join(body_lines)))
            cur_flow = None
            i += 1
            continue

        m = _RE_TASK.match(line)
        if m and ".." not in line and not line.startswith(":"):
            cur = _TaskDecl(
                m.group(1),
                [p.strip() for p in m.group(2).split(",") if p.strip()],
                i + 1,
                props=_parse_props(m.group("props")))
            if cur.name in jdf.tasks:
                err(f"duplicate task class {cur.name}")
            jdf.tasks[cur.name] = cur
            cur_flow = None
            i += 1
            continue

        if cur is None:
            mg = _RE_GLOBAL.match(line)
            if not mg:
                err(f"bad global declaration: {line!r}")
            props = _parse_props(mg.group("props"))
            if mg.group("default"):
                props["default"] = mg.group("default").strip()
            jdf.globals_decl[mg.group(1)] = props
            i += 1
            continue

        # inside a task class ------------------------------------------------
        if line.startswith(":"):
            md = _RE_TARGET_DATA.match(line[1:].strip())
            if not md:
                err(f"bad affinity: {line!r}")
            cur.affinity_src = (md.group(1), md.group(2))
            cur_flow = None
            i += 1
            continue

        if line.startswith(";"):
            cur.priority_src = line[1:].strip()
            cur_flow = None
            i += 1
            continue

        if line.startswith("SIMCOST"):
            # simulation-cost expression (parsec.y:635-641, PARSEC_SIM)
            cur.simcost_src = line[len("SIMCOST"):].strip()
            if not cur.simcost_src:
                err("SIMCOST needs an expression")
            cur_flow = None
            i += 1
            continue

        if line.startswith("<-") or line.startswith("->"):
            if cur_flow is None:
                err("dependency arrow outside a flow declaration")
            _parse_arrows(cur_flow, line, i + 1, err)
            i += 1
            continue

        mf = _RE_FLOW.match(line)
        if mf:
            cur_flow = _FlowDecl(_ACCESS[mf.group(1)], mf.group(2))
            cur.flows.append(cur_flow)
            rest = mf.group(3).strip()
            if rest:
                _parse_arrows(cur_flow, rest, i + 1, err)
            i += 1
            continue

        mr = _RE_RANGE.match(line)
        if mr and mr.group(1) in cur.params:
            parts = [p.strip() for p in mr.group(2).split("..")]
            if len(parts) == 1:
                # fixed value: a singleton range
                cur.ranges[mr.group(1)] = (parts[0], parts[0], None)
            elif len(parts) == 2:
                cur.ranges[mr.group(1)] = (parts[0], parts[1], None)
            elif len(parts) == 3:
                cur.ranges[mr.group(1)] = (parts[0], parts[1], parts[2])
            else:
                err(f"bad range: {line!r}")
            cur_flow = None
            i += 1
            continue

        if mr and ".." not in mr.group(2):
            # derived local: name = expr (the stencil's `m = t % lmt`,
            # Ex05's `loc = k + n` form)
            cur.derived[mr.group(1)] = mr.group(2).strip()
            cur_flow = None
            i += 1
            continue

        err(f"cannot parse: {line!r}")

    return jdf


def _parse_arrows(fd: _FlowDecl, s: str, lineno: int, err) -> None:
    """Parse one line of ``<- ...`` / ``-> ...`` arrow segments (a line may
    chain several, as JDF flows often put the first arrow on the flow line)."""
    # tokenize into (direction, segment) pairs by splitting on top-level
    # <- / -> occurrences
    segs: list[tuple[str, str]] = []
    depth = 0
    j = 0
    start = None
    direction = None
    while j < len(s):
        ch = s[j]
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if depth == 0 and s[j:j + 2] in ("<-", "->"):
            if direction is not None:
                segs.append((direction, s[start:j].strip()))
            direction = "in" if s[j] == "<" else "out"
            j += 2
            start = j
            continue
        j += 1
    if direction is None:
        err(f"expected <- or -> in {s!r}")
    segs.append((direction, s[start:].strip()))

    for direction, seg in segs:
        if not seg:
            err("empty dependency arrow")
        # trailing [type=NAME ...] dep properties (reshape-on-dep): the
        # first paren-top-level '[' opens them (targets only use parens)
        props = {}
        depth = 0
        bpos = -1
        for j, ch in enumerate(seg):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "[" and depth == 0:
                bpos = j
                break
        if bpos >= 0:
            if not seg.rstrip().endswith("]"):
                err(f"unterminated dep properties in {seg!r}")
            props = _parse_props(seg[bpos + 1:seg.rindex("]")])
            seg = seg[:bpos].strip()
        guard_src = None
        then_src, else_src = seg, None
        q = _split_top(seg, "?")
        if len(q) == 2:
            guard_src = q[0].strip()
            if not (guard_src.startswith("(") and guard_src.endswith(")")):
                err(f"guard must be parenthesized: {guard_src!r}")
            branches = _split_top(q[1], ":")
            then_src = branches[0].strip()
            if len(branches) == 2:
                else_src = branches[1].strip()
            elif len(branches) > 2:
                err(f"too many ':' in {seg!r}")
        elif len(q) > 2:
            err(f"too many '?' in {seg!r}")
        then_tgt = _parse_target(then_src, err)
        else_tgt = _parse_target(else_src, err) if else_src else None
        fd.arrows.append(_Arrow(direction, guard_src, then_tgt, else_tgt,
                                lineno, props))


def _parse_target(s: str, err) -> tuple:
    if s == "NEW":          # fresh-tile allocation (Ex03's `<- NEW`)
        return ("new", None, None, None)
    if s == "NULL":         # explicit no-data endpoint
        return ("null", None, None, None)
    mt = _RE_TARGET_TASK.match(s)
    if mt:
        return ("task", mt.group(2), mt.group(1), mt.group(3))
    md = _RE_TARGET_DATA.match(s)
    if md:
        return ("data", md.group(1), None, md.group(2))
    err(f"cannot parse dependency target {s!r}")
