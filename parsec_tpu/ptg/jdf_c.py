"""Mechanical C-syntax JDF ingestion: reference ``.jdf`` files, directly.

The reference's JDF front-end lexes C expressions and splices C bodies
(``parsec.l`` / ``parsec.y``); this repo's textual grammar
(:mod:`parsec_tpu.ptg.jdf`) is Python-expression-based by design.  This
module bridges them *mechanically*: :func:`convert_c_jdf` rewrites a
C-syntax JDF's **structure** — globals, execution spaces, derived locals,
affinities, guarded/ranged arrows, priorities, ``%option`` lines — into
the Python-expression grammar, and :func:`load_c_jdf` parses the result.

What converts:

- C prologues/epilogues (``extern "C" %{ ... %}``) are dropped — they hold
  includes and helper C functions; Python helpers go in ``bodies``/build
  bindings instead.
- ``%{ return EXPR; %}`` inline fragments become ``(EXPR)``.
- Expressions: ``&&``/``||``/``!`` → ``and``/``or``/``not``; ``->`` struct
  derefs become attribute access with a field map translating reference
  descriptor fields to this repo's collections (``lmt``→``mt``,
  ``super.myrank``→``myrank``, ...); bare ``/`` becomes floor division
  (JDF index arithmetic is integral in C).
- Globals: ``[type = "int"]``-style quoted props unquote;
  pointer-to-descriptor types become ``[type = data]``; ``default=``
  moves into the ``NAME = value`` position.  Collections referenced by
  affinities/data arrows but declared only in C code are synthesized as
  ``[type = data]`` globals.
- ``<- NEW`` arrows gain ``[type = DTT_DEFAULT]``; bind ``DTT_DEFAULT``
  to a :class:`~parsec_tpu.data.datatype.TileType` at ``build()``.
- ``%option`` lines keep the options this grammar knows and drop the
  rest (``no_taskpool_instance``, ``dynamic`` — process-model artifacts).

C task bodies: the **mechanical statement subset** auto-converts —
pointer-cast flow aliases (``int *Aint = (int*)A;``), deref
assignments/compound assignments (``*Aint = k+1``, ``*Aint += 1``),
plain declarations, ``if``/``else`` blocks, ``return``, and ``printf``
(dropped: output side effects carry no dataflow) — which covers the
reference's Ex02/Ex05/Ex06/Ex07 bodies verbatim.  Anything outside the
subset (C function calls, loops, pointer arithmetic) falls back: pass
``bodies`` mapping task names to Python body source (flow names in
scope, like any JDF body); unmapped unconvertible bodies become
``pass`` — structure-only ingestion, which is what graph/protocol
tests need.

Out-of-space successor arrows (``(k < NT) ? T PING(k+1)`` at
``k = NT-1``, ``rtt.jdf:16``) rely on the generated bounds check; the
runtime's execution-space membership drop covers them.

Read-chain forwarding: jdf2c's symbolic dataflow analysis forwards an
input arrow that names a predecessor READ flow with *no reciprocal
output arrow* (``<- A FANOUT(r-1, t)``, ``a2a.jdf:58``) to that flow's
data origin.  :func:`resolve_read_chains` does the mechanical version of
the same fixpoint after parsing: a READ flow whose single input is
``(base) ? D(args) : F SELF(shifted)`` with ``args`` invariant under the
shift resolves to ``D(args)``; any input referencing a reciprocal-less
READ flow is rewritten to that resolved origin.  ``load_c_jdf`` applies
it by default, so the reference's ``a2a.jdf`` ingests and drains all
rounds verbatim.
"""

from __future__ import annotations

import re
from typing import Any

from .jdf import JDF, parse_jdf, scan_balanced

# reference descriptor field -> this repo's collection attribute
_FIELD_MAP = {
    "super.myrank": "myrank",
    "super.nodes": "nodes",
    "super.mt": "mt",
    "super.nt": "nt",
    "lmt": "mt",
    "lnt": "nt",
    "llm": "lm",
    "lln": "ln",
}

_KNOWN_OPTIONS = ("nb_local_tasks_fn", "termdet")


def convert_expr(s: str, field_map: dict[str, str] | None = None) -> str:
    """One C expression → Python expression (structure-level subset)."""
    fm = dict(_FIELD_MAP)
    if field_map:
        fm.update(field_map)
    s = s.replace("&&", " and ").replace("||", " or ")
    s = re.sub(r"!(?![=])", " not ", s)
    s = s.replace("->", ".")
    # C casts over a call: (int)ceil(...) -> int(ceil(...)) — the shape
    # reference defaults use (reduce_col.jdf's tree depth); math names
    # resolve from the build env (jdf.py exposes <math.h> equivalents)
    s = re.sub(
        r"\(\s*(?:int|long|unsigned|size_t)\s*\)\s*"
        r"(\w+\s*\([^()]*(?:\([^()]*\)[^()]*)*\))",
        r"int(\1)", s)
    s = re.sub(
        r"\(\s*(?:float|double)\s*\)\s*"
        r"(\w+\s*\([^()]*(?:\([^()]*\)[^()]*)*\))",
        r"float(\1)", s)
    for k, v in sorted(fm.items(), key=lambda kv: -len(kv[0])):
        s = s.replace("." + k, "." + v)
    # integral division (C semantics for the non-negative index math JDFs
    # do); '//' stays itself.  An expression doing FLOAT math — a decimal
    # literal or a float-returning <math.h> call anywhere in it — keeps
    # true division: C's '/' on doubles is float division, and flooring
    # log(mt)/log(2.0) would silently drop a reduction-tree level at
    # every power-of-two size
    if not re.search(r"\d\.\d|\d\.(?!\w)|"
                     r"\b(?:log|log2|sqrt|fabs|pow)\s*\(", s):
        s = re.sub(r"(?<!/)/(?!/)", "//", s)
    return re.sub(r"\s+", " ", s).strip()


def _strip_line_comments(text: str) -> str:
    """Remove C ``//`` line comments, leaving string literals intact."""
    out = []
    for line in text.split("\n"):
        res: list[str] = []
        in_str: str | None = None
        i, n = 0, len(line)
        while i < n:
            ch = line[i]
            if in_str:
                res.append(ch)
                if ch == "\\" and i + 1 < n:
                    res.append(line[i + 1])
                    i += 2
                    continue
                if ch == in_str:
                    in_str = None
                i += 1
                continue
            if ch in "\"'":
                in_str = ch
                res.append(ch)
                i += 1
                continue
            if ch == "/" and i + 1 < n and line[i + 1] == "/":
                break
            res.append(ch)
            i += 1
        out.append("".join(res))
    return "\n".join(out)


def _convert_inline(s: str, fm) -> str:
    """``%{ return EXPR; %}`` fragments → ``(EXPR)``."""
    return re.sub(
        r"%\{\s*return\s+(.*?);\s*%\}",
        lambda m: "(" + convert_expr(m.group(1), fm) + ")", s, flags=re.S)


_RE_EXTERN = re.compile(r'extern\s+"C"\s*%\{.*?%\}', re.S)
_RE_GLOBAL_C = re.compile(r"^(\w+)\s*\[(.*)\]\s*$")
_RE_PROP_KEY_C = re.compile(r"(\w+)\s*=\s*")


def _scan_props_c(s: str) -> list[tuple[str, str]]:
    """``key = value`` pairs from a C-syntax property block.  Values are
    quoted strings, balanced parenthesized expressions at arbitrary
    depth (converted ``%{ return ...; %}`` fragments), or bare tokens."""
    out: list[tuple[str, str]] = []
    i, n = 0, len(s)
    while i < n:
        m = _RE_PROP_KEY_C.match(s, i)
        if m is None:
            i += 1
            continue
        key = m.group(1)
        i = m.end()
        if i < n and s[i] == '"':
            j = s.find('"', i + 1)
            j = n - 1 if j < 0 else j
            out.append((key, s[i + 1:j]))
            i = j + 1
        elif i < n and s[i] == "(":
            from .jdf import scan_balanced
            j = scan_balanced(s, i)
            # strip interior whitespace so the value rides the
            # single-token prop grammar downstream
            out.append((key, re.sub(r"\s+", "", s[i:j + 1])))
            i = j + 1
        else:
            mv = re.match(r"\S+", s[i:])
            out.append((key, mv.group(0) if mv else ""))
            i += len(mv.group(0)) if mv else 1
    return out


def _convert_global(line: str, fm) -> str:
    m = _RE_GLOBAL_C.match(line.strip())
    if not m:
        return line
    name, props_src = m.group(1), m.group(2)
    props = dict(_scan_props_c(props_src))
    ctype = props.get("type", "")
    default = props.get("default")
    if "*" in ctype or "matrix" in ctype or "collection" in ctype \
            or "dist" in ctype:
        out_type = "data"
    elif any(t in ctype for t in ("int", "float", "double")):
        out_type = "int" if "int" in ctype else "float"
    else:
        out_type = "object"
    head = name if default is None else \
        f"{name} = {convert_expr(default, fm)}"
    return f"{head}  [type = {out_type}]"


_RE_PTR_DECL = re.compile(
    r"^(?:\w+\s+)+\*\s*(\w+)\s*=\s*\(\s*\w+\s*\*\s*\)\s*(\w+)$")
_RE_PLAIN_DECL = re.compile(
    r"^(?:int|float|double|long|unsigned|size_t)\s+(\w+)\s*=\s*(.+)$")
_RE_C_ASSIGN = re.compile(r"^(\*?\s*\w+)\s*(=(?!=)|\+=|-=|\*=)\s*(.+)$")


def _convert_rhs(rhs: str, aliases: set[str], fm) -> str | None:
    """Convert an expression of the simple subset, or None.  The subset
    has no function calls and no C-only operators: the converted text
    must compile as a Python expression and contain no call syntax —
    otherwise the body degrades to the override/pass fallback instead
    of shipping Python that crashes at build or task time."""
    out = convert_expr(_deref(rhs, aliases), fm)
    if re.search(r"[\w\]]\s*\(", out):
        return None                      # calls are outside the subset
    try:
        compile(out, "<jdf_c:body>", "eval")
    except SyntaxError:
        return None                      # e.g. a leftover C ternary
    return out


def convert_c_body(src: str, field_map: dict | None = None) -> str | None:
    """Mechanically convert a C task body of the simple statement subset
    to Python, or return None when any statement falls outside it.

    The subset (all the reference's Ex02/Ex05/Ex06/Ex07 bodies): flow
    pointer aliases (``int *Aint = (int*)A;`` — tiles are numpy arrays,
    ``*Aint`` becomes ``Aint[0]``), assignments and compound assignments
    through the deref, plain arithmetic declarations, ``if``/``else``
    with braced or single statements, ``return``, and ``printf`` calls
    (dropped — output side effects carry no dataflow)."""
    s = src.strip()
    if s.startswith("{") and s.endswith("}"):
        s = s[1:-1]
    aliases: set[str] = set()
    lines: list[str] = []
    if _c_stmts(s, lines, "", aliases, field_map) is None:
        return None
    out = "\n".join(ln for ln in lines if ln.strip())
    return out or "pass"


def _deref(expr: str, aliases: set[str]) -> str:
    """``*Aint`` -> ``Aint[0]`` for known pointer aliases (the simple
    subset has no pointer arithmetic, so every ``* alias`` is a deref)."""
    for a in aliases:
        expr = re.sub(r"\*\s*" + a + r"\b", f"{a}[0]", expr)
    return expr


def _c_stmts(s: str, lines: list[str], indent: str, aliases: set[str],
             fm) -> bool | None:
    """Convert a statement sequence; None = outside the subset."""
    i, n = 0, len(s)
    while i < n:
        while i < n and s[i] in " \t\r\n":
            i += 1
        if i >= n:
            break
        if s.startswith("if", i) and re.match(r"if\b", s[i:]):
            j = s.find("(", i)
            if j < 0:
                return None
            k = scan_balanced(s, j)
            cond = _convert_rhs(s[j + 1:k], aliases, fm)
            if cond is None:
                return None
            lines.append(f"{indent}if {cond}:")
            i = _c_block(s, k + 1, lines, indent + "    ", aliases, fm)
            if i is None:
                return None
            while i < n and s[i] in " \t\r\n":
                i += 1
            if s.startswith("else", i):
                lines.append(f"{indent}else:")
                i = _c_block(s, i + 4, lines, indent + "    ", aliases, fm)
                if i is None:
                    return None
            continue
        j = s.find(";", i)
        if j < 0:
            return None
        if _c_stmt(s[i:j].strip(), lines, indent, aliases, fm) is None:
            return None
        i = j + 1
    return True


def _c_block(s: str, i: int, lines: list[str], indent: str,
             aliases: set[str], fm) -> int | None:
    """One braced block or single statement starting at/after ``i``;
    returns the index past it."""
    n = len(s)
    while i < n and s[i] in " \t\r\n":
        i += 1
    if i < n and s[i] == "{":
        depth, j = 0, i
        while j < n:
            if s[j] == "{":
                depth += 1
            elif s[j] == "}":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        if depth != 0:
            return None
        if _c_stmts(s[i + 1:j], lines, indent, aliases, fm) is None:
            return None
        return j + 1
    j = s.find(";", i)
    if j < 0:
        return None
    if _c_stmt(s[i:j].strip(), lines, indent, aliases, fm) is None:
        return None
    return j + 1


def _c_stmt(stmt: str, lines: list[str], indent: str, aliases: set[str],
            fm) -> bool | None:
    if not stmt:
        return True
    m = _RE_PTR_DECL.match(stmt)
    if m:
        name, flow = m.groups()
        aliases.add(name)
        lines.append(f"{indent}{name} = {flow}")
        return True
    if re.match(r"printf\s*\(", stmt):
        lines.append(f"{indent}pass  # {' '.join(stmt.split())}")
        return True
    if stmt == "return":
        lines.append(f"{indent}return")
        return True
    m = _RE_PLAIN_DECL.match(stmt)
    if m:
        name, rhs = m.groups()
        conv = _convert_rhs(rhs, aliases, fm)
        if conv is None:
            return None
        lines.append(f"{indent}{name} = {conv}")
        return True
    m = _RE_C_ASSIGN.match(stmt)
    if m:
        lhs, op, rhs = m.groups()
        conv = _convert_rhs(rhs, aliases, fm)
        if conv is None:
            return None
        lines.append(f"{indent}{_deref(lhs.strip(), aliases)} {op} {conv}")
        return True
    return None


def convert_c_jdf(text: str, bodies: dict[str, str] | None = None,
                  field_map: dict[str, str] | None = None) -> str:
    """Rewrite a C-syntax JDF into the Python-expression grammar."""
    bodies = bodies or {}
    text = _RE_EXTERN.sub("", text)
    # strip C comments OUTSIDE bodies later; blanket-strip block comments
    # now (C-syntax files comment with /* */ everywhere, incl. body stubs)
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    # line comments too (BEFORE inline conversion: converted expressions
    # legitimately contain Python's // floor division); string-literal
    # aware, so a '//' inside a printf format survives
    text = _strip_line_comments(text)
    text = _convert_inline(text, field_map)

    out: list[str] = []
    lines = _merge_continuations(text.split("\n"))
    i, n = 0, len(lines)
    cur_task: str | None = None
    seen_globals: set[str] = set()
    task_names: set[str] = set()
    data_used: set[str] = set()   # collections referenced anywhere

    # pre-scan task names (a task header is NAME(params) on its own line
    # with a following range line somewhere before a BODY)
    for ln in lines:
        m = re.match(r"^(\w+)\s*\(([\w\s,]*)\)\s*(?:\[.*\])?\s*$", ln.strip())
        if m and ".." not in ln:
            task_names.add(m.group(1))

    while i < n:
        raw = lines[i]
        line = raw.strip()
        if not line:
            out.append("")
            i += 1
            continue
        if line.startswith("%option"):
            kept = [f"{k} = {v}" for k, v in
                    re.findall(r"(\w+)\s*=\s*(\S+)", line)
                    if k in _KNOWN_OPTIONS]
            if kept:
                out.append("%option " + "  ".join(kept))
            i += 1
            continue
        if line == "BODY" or line.startswith("BODY"):
            # swallow the C body; emit the Python body (or pass)
            depth_body = []
            i += 1
            while i < n and lines[i].strip() != "END":
                depth_body.append(lines[i])
                i += 1
            i += 1  # consume END
            out.append("BODY")
            body = bodies.get(cur_task or "")
            if body is None:
                # no override: try the mechanical C-statement subset
                body = convert_c_body("\n".join(depth_body),
                                      field_map) or "pass"
            for bl in body.split("\n"):
                out.append("  " + bl)
            out.append("END")
            continue
        m = re.match(r"^(\w+)\s*\(([\w\s,]*)\)\s*(\[.*\])?\s*$", line)
        if m and ".." not in line and m.group(1) in task_names:
            cur_task = m.group(1)
            out.append(line)
            i += 1
            continue
        if cur_task is None:
            conv = _convert_global(line, field_map)
            gm = re.match(r"^(\w+)", conv)
            if gm:
                seen_globals.add(gm.group(1))
            out.append(conv)
            i += 1
            continue
        # inside a task: ranges / derived / affinity / arrows / priority
        if line.startswith(":"):
            md = re.match(r"^:\s*(\w+)\s*\((.*)\)\s*$", line)
            if md:
                data_used.add(md.group(1))
                out.append(f"  : {md.group(1)}"
                           f"({convert_expr(md.group(2), field_map)})")
            else:
                out.append(line)
            i += 1
            continue
        if line.startswith(";"):
            out.append(f"  ; {convert_expr(line[1:], field_map)}")
            i += 1
            continue
        if line.startswith("<-") or line.startswith("->") or \
                re.match(r"^(RW|READ|WRITE|CTL)\s", line):
            out.append("  " + _convert_arrow_line(line, field_map,
                                                  task_names, data_used))
            i += 1
            continue
        mr = re.match(r"^(\w+)\s*=\s*(.+)$", line)
        if mr:
            parts = [p.strip() for p in mr.group(2).split("..")]
            conv = " .. ".join(convert_expr(p, field_map) for p in parts)
            out.append(f"  {mr.group(1)} = {conv}")
            i += 1
            continue
        out.append(raw)
        i += 1

    # synthesize [type = data] globals for collections declared only in C
    synth = [name for name in sorted(data_used)
             if name not in seen_globals and name not in task_names]
    header = [f"{name}  [type = data]" for name in synth]
    body_text = "\n".join(out)
    if "DTT_DEFAULT" in body_text and "DTT_DEFAULT" not in seen_globals:
        # NEW arrows allocate at this type: bind a TileType at build()
        header.append("DTT_DEFAULT  [type = object]")
    return "\n".join(header + [body_text])


def _open_ternary(line: str) -> bool:
    """A paren-top-level ``?`` still awaiting its ``:`` — the reference
    wraps guarded arrows across lines (``ep.jdf``'s else branch on its
    own ``: S TASK(i, l-1)`` line)."""
    depth, q = 0, 0
    for ch in line:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif depth == 0:
            if ch == "?":
                q += 1
            elif ch == ":" and q > 0:
                q -= 1
    return q > 0


def _merge_continuations(lines: list[str]) -> list[str]:
    """Join lines whose ``[...]`` dep-property block spans several source
    lines (the reference wraps long property lists), and ternary-else
    continuation lines (``: target`` / ``? target`` under an open
    top-level ``?``); BODY regions are C code and stay untouched."""
    out: list[str] = []
    i, n = 0, len(lines)
    in_body = False
    while i < n:
        line = lines[i]
        s = line.strip()
        if in_body:
            out.append(line)
            if s == "END":
                in_body = False
            i += 1
            continue
        if s == "BODY" or s.startswith("BODY"):
            in_body = True
            out.append(line)
            i += 1
            continue
        merged = True
        while merged and i + 1 < n:
            merged = False
            depth = line.count("[") - line.count("]")
            while depth > 0 and i + 1 < n:
                i += 1
                nxt = lines[i]
                line = line.rstrip() + " " + nxt.strip()
                depth += nxt.count("[") - nxt.count("]")
                merged = True
            if i + 1 < n:
                nxt = lines[i + 1].strip()
                arrowish = "<-" in line or "->" in line
                # `? then` continues an arrow whose guard sat alone on
                # the previous line; `: else` continues an open ternary
                if (nxt.startswith("?") and arrowish) or (
                        nxt.startswith(":") and _open_ternary(line)):
                    i += 1
                    line = line.rstrip() + " " + nxt
                    merged = True
        out.append(line)
        i += 1
    return out


def _convert_arrow_line(line: str, fm, task_names: set[str],
                        data_used: set) -> str:
    """Convert the expressions inside one flow/arrow line, preserving the
    arrow structure the grammar shares with the reference."""
    # split off a trailing [props] block (dep properties)
    props = ""
    pm = re.search(r"\[([^\]]*)\]\s*$", line)
    if pm:
        props_src = pm.group(1)
        line = line[:pm.start()].rstrip()
        kept = [f"{k} = {v}" for k, v in _scan_props_c(props_src)]
        if kept:
            props = "  [" + "  ".join(kept) + "]"

    def conv_target(t: str) -> str:
        t = t.strip()
        if t == "NEW":
            return "NEW"       # [type=] appended at line level below
        if t == "NULL":
            return "NULL"
        mt = re.match(r"^(\w+)\s+(\w+)\s*\((.*)\)$", t)
        if mt:
            args = ", ".join(
                " .. ".join(convert_expr(p, fm) for p in a.split(".."))
                for a in _split_args(mt.group(3)))
            return f"{mt.group(1)} {mt.group(2)}({args})"
        md = re.match(r"^(\w+)\s*\((.*)\)$", t)
        if md:
            if md.group(1) not in task_names:
                data_used.add(md.group(1))
            args = ", ".join(convert_expr(a, fm)
                             for a in _split_args(md.group(2)))
            return f"{md.group(1)}({args})"
        return t

    def conv_segment(seg: str) -> str:
        seg = seg.strip()
        q = _split_top(seg, "?")
        if len(q) == 2:
            guard = convert_expr(q[0].strip(), fm)
            if not guard.startswith("("):
                guard = f"({guard})"
            branches = _split_top(q[1], ":")
            s = f"{guard} ? {conv_target(branches[0])}"
            if len(branches) == 2:
                s += f" : {conv_target(branches[1])}"
            return s
        return conv_target(seg)

    # flow prefix?
    prefix = ""
    mf = re.match(r"^(RW|READ|WRITE|CTL)\s+(\w+)\s*(.*)$", line)
    if mf:
        prefix = f"{mf.group(1)} {mf.group(2)} "
        line = mf.group(3).strip()
    if not line:
        return prefix.rstrip()
    # split arrow chain
    segs = []
    direction = None
    start = 0
    j = 0
    depth = 0
    while j < len(line):
        ch = line[j]
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if depth == 0 and line[j:j + 2] in ("<-", "->"):
            if direction is not None:
                segs.append((direction, line[start:j]))
            direction = line[j:j + 2]
            j += 2
            start = j
            continue
        j += 1
    segs.append((direction, line[start:]))
    parts = []
    for d, seg in segs:
        conv = conv_segment(seg)
        if re.search(r"\bNEW\b", conv):
            conv += "  [type = DTT_DEFAULT]"
        parts.append(f"{d} {conv}")
    return prefix + (" ".join(parts)) + props


def _split_args(s: str) -> list[str]:
    return [a for a in _split_top(s, ",") if a.strip()]


def _split_top(s: str, sep: str) -> list[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _subst_ids(expr: str, mapping: dict[str, str]) -> str:
    """Simultaneous identifier substitution in an expression string; a
    replacement that is itself a compound expression is parenthesized."""
    if not expr:
        return expr

    def rep(m: re.Match) -> str:
        w = m.group(0)
        if w not in mapping:
            return w
        v = mapping[w].strip()
        return v if re.fullmatch(r"\w+", v) else f"({v})"

    # (?<!\.) keeps attribute names out of the substitution: a task
    # parameter named like a collection attribute (descA.nb vs param nb)
    # must not rewrite the attribute access
    return re.sub(r"(?<!\.)\b\w+\b", rep, expr)


def _norm_expr(s: str | None) -> str:
    return re.sub(r"\s+", "", s or "")


def resolve_read_chains(jdf: JDF) -> list[str]:
    """jdf2c's read-chain forwarding, as a post-parse fixpoint
    (``jdf2c.c`` resolves such chains during its symbolic dataflow pass;
    this runtime activates inputs from the producer side, so an input
    arrow with no reciprocal output would never fire).

    For every input arrow whose source is a task flow (S, G) such that
    S.G declares **no output arrow back to the consuming flow**, resolve
    S.G's data *origin* and rewrite the input to read that data
    directly.  An origin exists when S.G is a READ flow whose single
    input collapses — base-case data with arguments invariant under the
    self-chain's index shift (``(r == 0) ? descA(t, 0) : A FANOUT(r-1,
    t)`` resolves to ``descA(t, 0)`` for every r).  Returns a list of
    human-readable rewrite notes (tests assert on them)."""
    from .dsl import READ

    # reciprocity index: (src task, src flow) -> {(dst task, dst flow)}
    recip: set[tuple] = set()
    for t in jdf.tasks.values():
        for fd in t.flows:
            for ar in fd.arrows:
                if ar.direction != "out":
                    continue
                for tgt in (ar.then_tgt, ar.else_tgt):
                    if tgt and tgt[0] == "task":
                        recip.add((t.name, fd.name, tgt[1], tgt[2]))

    def flow_of(tname: str, fname: str):
        t = jdf.tasks.get(tname)
        if t is None:
            return None, None
        return t, next((f for f in t.flows if f.name == fname), None)

    def origin(tname: str, fname: str, depth: int = 0):
        """Data origin of READ flow ``tname.fname`` in its own params:
        ``("data", coll, None, args)`` or None."""
        if depth > 8:
            return None
        t, fd = flow_of(tname, fname)
        if fd is None or fd.access is not READ:
            return None
        ins = [ar for ar in fd.arrows if ar.direction == "in"]
        if len(ins) != 1:
            return None
        ar = ins[0]
        then, els = ar.then_tgt, ar.else_tgt
        if els is None:
            return then if then[0] == "data" else None
        if then[0] != "data":
            return None
        if els[0] == "data":
            if els[1] == then[1] and _norm_expr(els[3]) == _norm_expr(
                    then[3]):
                return then
            return None
        if els[0] != "task":
            return None
        mapping = dict(zip(jdf.tasks[els[1]].params,
                           _split_args(els[3] or "")))
        if (els[1], els[2]) == (tname, fname):
            # self chain: the base data args must be a fixpoint of the
            # index shift (independent of the recurrence variable)
            if _norm_expr(_subst_ids(then[3], mapping)) == _norm_expr(
                    then[3]):
                return then
            return None
        o = origin(els[1], els[2], depth + 1)
        if o is None:
            return None
        resolved_args = _subst_ids(o[3], mapping)
        if o[1] == then[1] and _norm_expr(resolved_args) == _norm_expr(
                then[3]):
            return then
        return None

    notes: list[str] = []
    for t in jdf.tasks.values():
        for fd in t.flows:
            for ar in fd.arrows:
                if ar.direction != "in":
                    continue
                for attr in ("then_tgt", "else_tgt"):
                    tgt = getattr(ar, attr)
                    if not tgt or tgt[0] != "task":
                        continue
                    src_t, src_f = tgt[1], tgt[2]
                    if (src_t, src_f, t.name, fd.name) in recip:
                        continue           # producer forwards; no rewrite
                    o = origin(src_t, src_f)
                    if o is None:
                        continue
                    src_task = jdf.tasks[src_t]
                    mapping = dict(zip(src_task.params,
                                       _split_args(tgt[3] or "")))
                    new_args = _subst_ids(o[3], mapping)
                    setattr(ar, attr, ("data", o[1], None, new_args))
                    notes.append(
                        f"{t.name}.{fd.name} <- {src_t}.{src_f} resolved "
                        f"to {o[1]}({new_args})")
    return notes


def load_c_jdf(path: Any, bodies: dict[str, str] | None = None,
               name: str | None = None,
               field_map: dict[str, str] | None = None,
               forward_read_chains: bool = True) -> JDF:
    """Convert + parse a C-syntax ``.jdf`` file from disk."""
    import pathlib
    p = pathlib.Path(path)
    jdf = parse_jdf(convert_c_jdf(p.read_text(), bodies, field_map),
                    name or p.stem)
    if forward_read_chains:
        jdf.read_chain_notes = resolve_read_chains(jdf)
    return jdf
