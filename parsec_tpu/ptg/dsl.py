"""PTG: the Parameterized Task Graph DSL, algebraic builder form.

Rebuild of the reference's JDF front-end (SURVEY §2.7) as a Python-embedded
algebraic API instead of a flex/bison→C compiler: a taskpool is described
problem-size-independently by task classes with

- *parameters* spanning an execution space (range expressions that may depend
  on globals and on previously-bound parameters — triangular spaces work),
- a *data affinity* (``: A(k)``) fixing the owning rank,
- *flows* (``RW``/``READ``/``WRITE``/``CTL``) with guarded input/output
  dependency arrows to other task classes or to the collection,
- per-device *bodies* (chores), and an optional priority expression.

The builder materializes :class:`~parsec_tpu.runtime.task.TaskClass` objects
and a :class:`PTGTaskpool` whose startup enumerates the execution space and
schedules the tasks whose IN-dep masks are empty (the generated
``startup``/``internal_init`` contract, ``jdf2c.c:3035``/``:3431``).  The JDF
*textual* front-end (:mod:`parsec_tpu.ptg.jdf`) parses into this same builder,
so both front-ends share one backend — mirroring ``parsec_ptgpp`` emitting
code against one runtime ABI.

Guard/range/assignment expressions are callables ``fn(g, l)`` receiving
read-only namespaces of globals and locals; the JDF parser compiles its
expression strings into exactly these.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Callable, Iterable

from ..data.data import ACCESS_READ, ACCESS_RW, ACCESS_WRITE
from ..runtime.task import (FLOW_CTL, HOOK_RETURN_DONE, Chore, Dep, Flow,
                            TaskClass)
from ..runtime.taskpool import Taskpool

READ = ACCESS_READ
WRITE = ACCESS_WRITE
RW = ACCESS_RW
CTL = FLOW_CTL


class _NS(SimpleNamespace):
    def __getitem__(self, k):
        return getattr(self, k)


def _ns(d: dict) -> _NS:
    return _NS(**d)


class _DictNS:
    """Live attribute view over a dict (globals namespace, hot path: built
    once per builder; later mutations of the dict are visible)."""

    __slots__ = ("_d",)

    def __init__(self, d: dict) -> None:
        object.__setattr__(self, "_d", d)

    def __getattr__(self, k):
        try:
            return self._d[k]
        except KeyError:
            raise AttributeError(k)

    def __getitem__(self, k):
        return self._d[k]

    @property
    def __dict__(self):   # vars(g) support (the JDF expression evaluator)
        return self._d


class FlowBuilder:
    def __init__(self, tcb: "TaskClassBuilder", name: str, access: Any,
                 dtt: Any = None) -> None:
        self._tcb = tcb
        self.name = name
        self.access = access
        self.dtt = dtt
        self._deps_in: list[Dep] = []
        self._deps_out: list[Dep] = []

    def input(self, pred: tuple | None = None, data: tuple | None = None,
              guard: Callable | None = None, dtt: Any = None,
              new: bool = False, null: bool = False,
              ranged: bool = False) -> "FlowBuilder":
        """Add an input arrow.

        ``pred=(class_name, flow_name, params_fn)`` for a task predecessor;
        ``data=(collection_or_name, key_fn)`` for a direct collection read;
        ``new=True`` for a fresh-tile allocation (JDF ``<- NEW``; the flow
        needs a declared tile type); ``null=True`` for an explicit no-data
        input (JDF ``<- NULL``).  ``params_fn(g, l) -> dict`` binds the
        predecessor's locals; ``key_fn(g, l) -> tuple`` the collection key.
        ``ranged=True`` marks a *fan-in* arrow whose ``params_fn`` returns a
        sequence of predecessor instances, each expected to arrive (the JDF
        range-input form ``<- ctl T(k, 0 .. NB .. 2)``; CTL joins)."""
        if new and dtt is None and self.dtt is None:
            raise ValueError(
                f"flow {self.name}: NEW needs a tile type to allocate "
                f"(pass dtt= on the arrow or declare it on the flow)")
        if ranged and self.access != CTL:
            # N producers racing one datum slot is nondeterministic — the
            # counted fan-in protocol is for control joins only (both
            # front-ends inherit this check)
            raise ValueError(
                f"flow {self.name}: ranged fan-in input on a data flow; "
                f"range inputs are CTL-only")
        self._deps_in.append(self._tcb._mk_dep(pred, data, guard, dtt,
                                               new=new, null=null,
                                               ranged=ranged))
        if new and dtt is not None and self.dtt is None:
            self.dtt = dtt      # NEW allocates at the flow's declared type
        return self

    def output(self, succ: tuple | None = None, data: tuple | None = None,
               guard: Callable | None = None, dtt: Any = None,
               wire: Any = None) -> "FlowBuilder":
        """``wire=`` tags the edge with a partial-tile wire datatype
        (JDF ``[type_remote = .., displ_remote = ..]``): a tuple of
        slices or ``wire_fn(g, l) -> slices`` selecting the sub-view a
        REMOTE consumer receives; same-rank edges always share the full
        tile (see data/datatype.py WireRegion)."""
        self._deps_out.append(self._tcb._mk_dep(succ, data, guard, dtt,
                                                wire=wire))
        return self

    def _build(self) -> Flow:
        return Flow(self.name, self.access, deps_in=self._deps_in,
                    deps_out=self._deps_out, dtt=self.dtt)


class TaskClassBuilder:
    def __init__(self, ptg: "PTGBuilder", name: str,
                 params: dict[str, Callable]) -> None:
        self._ptg = ptg
        self.name = name
        # param name -> fn(g, l) -> iterable (l holds previously-bound params)
        self.param_ranges = dict(params)
        self._flows: list[FlowBuilder] = []
        self._chores: list[Chore] = []
        self._affinity: Callable | None = None
        self._priority: Callable | None = None
        self._time_estimate: Callable | None = None
        # user-defined overrides (jdf.h:185-210) + SIMCOST (parsec.y:635)
        self._make_key: Callable | None = None
        self._find_deps: Callable | None = None
        self._hash_struct: Any = None
        self._startup: Callable | None = None
        self._simcost: Callable | None = None
        self._stage_in_hook: Callable | None = None
        self._stage_out_hook: Callable | None = None

    # -- structure ----------------------------------------------------------
    def affinity(self, collection: Any, key_fn: Callable) -> "TaskClassBuilder":
        dc_get = self._ptg._dc_getter(collection)

        def aff(locals_: dict) -> tuple:
            g, l = self._ptg._g_ns(), _ns(locals_)
            return dc_get(), key_fn(g, l)

        self._affinity = aff
        return self

    def flow(self, name: str, access: Any, dtt: Any = None) -> FlowBuilder:
        fb = FlowBuilder(self, name, access, dtt)
        self._flows.append(fb)
        return fb

    def priority(self, fn: Callable) -> "TaskClassBuilder":
        g_ns = self._ptg._g_ns
        self._priority = lambda locals_: int(fn(g_ns(), _ns(locals_)))
        return self

    def time_estimate(self, fn: Callable) -> "TaskClassBuilder":
        self._time_estimate = fn
        return self

    # -- user-defined overrides (the jdf.h:185-210 UD property family) ------
    def make_key(self, fn: Callable) -> "TaskClassBuilder":
        """``make_key_fn``: custom task-key construction, ``fn(g, l) -> key``
        (any hashable; non-tuples are wrapped by the runtime)."""
        g_ns = self._ptg._g_ns
        self._make_key = lambda locals_: fn(g_ns(), _ns(locals_))
        return self

    def find_deps(self, fn: Callable) -> "TaskClassBuilder":
        """``find_deps_fn``: custom dep-storage location,
        ``fn(taskpool, g, l) -> hashable identity``."""
        g_ns = self._ptg._g_ns
        self._find_deps = lambda tp, locals_: fn(tp, g_ns(), _ns(locals_))
        return self

    def hash_struct(self, key_hash: Callable | None = None,
                    key_equal: Callable | None = None,
                    key_print: Callable | None = None) -> "TaskClassBuilder":
        """``hash_struct``: user key hashing/equality/printing over the raw
        key tuples (``parsec_key_fn_t`` analog)."""
        from ..runtime.task import KeyHashStruct
        self._hash_struct = KeyHashStruct(key_hash, key_equal, key_print)
        return self

    def startup(self, fn: Callable) -> "TaskClassBuilder":
        """``startup_fn``: custom startup enumeration for this class,
        ``fn(taskpool, context, g) -> iterable of locals dicts`` naming the
        initially-ready instances (replacing the empty-IN-mask scan)."""
        self._startup = fn
        return self

    def simcost(self, fn: Callable) -> "TaskClassBuilder":
        """``SIMCOST``: simulated execution cost ``fn(g, l) -> float``; the
        pool then tracks ``largest_simulation_date`` (PARSEC_SIM model)."""
        g_ns = self._ptg._g_ns
        self._simcost = lambda locals_: fn(g_ns(), _ns(locals_))
        return self

    def stage_hooks(self, stage_in: Callable | None = None,
                    stage_out: Callable | None = None
                    ) -> "TaskClassBuilder":
        """User transfer hooks for this class's device tasks
        (``stage_custom.jdf`` role, ``device_gpu.h:61-77``): each is
        ``fn(device, task)`` replacing the default versioned stage-in /
        stage-out around the device dispatch.  Only the arguments given
        are updated — separate calls may set the two hooks."""
        if stage_in is not None:
            self._stage_in_hook = stage_in
        if stage_out is not None:
            self._stage_out_hook = stage_out
        return self

    def body(self, fn: Callable | None = None, device: str = "cpu",
             dyld: str | None = None,
             evaluate: Callable | None = None) -> Any:
        """Attach a body for ``device`` (multiple BODY...END analog).

        CPU bodies are callables ``fn(es, task, g, l)``; device bodies may
        instead name a kernel-registry entry via ``dyld`` (the JDF ``dyld=``
        incarnation contract).  Usable as a decorator: ``@tc.body``.
        """
        def attach(f: Callable | None) -> Callable | None:
            if device in ("cpu", "recursive"):
                # recursive incarnations are host callables too: the body
                # spawns a nested taskpool via runtime.recursive_call and
                # returns its ASYNC (PARSEC_DEV_RECURSIVE, device.h:64)
                hook = self._wrap_cpu_body(f)
            else:
                from ..device.hooks import make_device_hook
                hook = make_device_hook(device, f, dyld, self._ptg)
            self._chores.append(Chore(device, hook=hook, evaluate=evaluate,
                                      dyld=dyld))
            return f

        if fn is None and dyld is not None:
            return attach(None)
        if fn is None:
            return attach  # decorator form
        return attach(fn)

    def _wrap_cpu_body(self, f: Callable) -> Callable:
        g_ns = self._ptg._g_ns

        def hook(es: Any, task: Any) -> int:
            rc = f(es, task, g_ns(), _ns(task.locals))
            return HOOK_RETURN_DONE if rc is None else rc

        # the compiled-DAG executor (runtime/dagrun.py) bypasses this
        # wrapper and calls the body directly with a namespace it builds
        # once per task — the unwrap halves the per-task Python layers
        hook.ptg_body = f
        hook.ptg_gns = g_ns
        return hook

    # -- helpers ------------------------------------------------------------
    def _mk_dep(self, ref: tuple | None, data: tuple | None,
                guard: Callable | None, dtt: Any,
                new: bool = False, null: bool = False,
                ranged: bool = False, wire: Any = None) -> Dep:
        g_ns = self._ptg._g_ns
        gfn = None
        if guard is not None:
            gfn = lambda locals_: guard(g_ns(), _ns(locals_))
        wfn = wire
        if callable(wire):
            wfn = lambda locals_: wire(g_ns(), _ns(locals_))
        if new or null:
            # NEW: all targets None — resolve_data_inputs leaves the slot
            # empty and prepare_input allocates scratch of the flow type;
            # NULL: the flow explicitly carries no data for these locals
            return Dep(guard=gfn, dtt=dtt, null=null)
        if ref is not None:
            cls_name, flow_name, params_fn = ref
            tparams = lambda locals_: params_fn(g_ns(), _ns(locals_))
            return Dep(guard=gfn, target_class=cls_name,
                       target_flow=flow_name, target_params=tparams, dtt=dtt,
                       ranged=ranged, wire=wfn)
        if data is not None:
            collection, key_fn = data
            dc_get = self._ptg._dc_getter(collection)

            def data_ref(locals_: dict) -> tuple:
                key = key_fn(g_ns(), _ns(locals_))
                if not isinstance(key, tuple):
                    key = (key,)
                return dc_get(), key

            return Dep(guard=gfn, data_ref=data_ref, dtt=dtt, wire=wfn)
        # pure CTL arrow with neither: invalid
        raise ValueError("dep needs a task ref or a data ref")

    def _enumerate_space(self) -> Iterable[dict]:
        """Yield every locals assignment in the execution space."""
        g = self._ptg._g_ns()
        names = list(self.param_ranges)

        def rec(i: int, partial: dict):
            if i == len(names):
                yield dict(partial)
                return
            name = names[i]
            for v in self.param_ranges[name](g, _ns(partial)):
                partial[name] = v
                yield from rec(i + 1, partial)
            partial.pop(name, None)

        yield from rec(0, {})

    def _build(self) -> TaskClass:
        tc = TaskClass(
            self.name,
            params=list(self.param_ranges),
            flows=[fb._build() for fb in self._flows],
            chores=list(self._chores),
            affinity=self._affinity,
            priority=self._priority,
            time_estimate=self._time_estimate,
            make_key_fn=self._make_key,
            find_deps_fn=self._find_deps,
            hash_struct=self._hash_struct,
            startup_fn=self._startup,
            simcost=self._simcost,
        )
        # device-task transfer overrides ride as plain attributes (the
        # device module reads them per dispatch; absent = defaults)
        if self._stage_in_hook is not None:
            tc.stage_in_hook = self._stage_in_hook
        if self._stage_out_hook is not None:
            tc.stage_out_hook = self._stage_out_hook

        # execution-space membership (the generated bounds-check role):
        # parameters validate in declaration order against their ranges.
        # This sits on the release hot path (one call per successor edge),
        # so locals-INDEPENDENT ranges — the overwhelmingly common case —
        # are captured once at first use (range membership is O(1));
        # dependent ranges re-evaluate in order.  Mutating the pool's
        # globals after execution starts is outside the contract anyway.
        g_ns = self._ptg._g_ns
        ranges = self.param_ranges
        cache: dict = {"static": None}

        class _Poison:
            def __getattr__(self, k):
                raise LookupError(k)

            def __getitem__(self, k):
                raise LookupError(k)

        # static box extents for the index-array dep-storage variant
        # (parsec_default_find_deps / `-M index-array`): captured lazily
        # at first use — like in_space's static capture below, so globals
        # bound between build() and execution start are honored
        def extents_fn() -> tuple | None:
            try:
                g = g_ns()
                st = tuple(rngfn(g, _Poison())
                           for rngfn in ranges.values())
                if all(isinstance(r, range) and r.step == 1 for r in st):
                    return tuple((r.start, r.stop) for r in st)
            except Exception:
                pass
            return None

        tc.space_extents_fn = extents_fn

        def in_space(locals_: dict) -> bool:
            st = cache["static"]
            if st is None:
                try:
                    g = g_ns()
                    poison = _Poison()
                    st = tuple(rngfn(g, poison)
                               for rngfn in ranges.values())
                except Exception:
                    st = False
                cache["static"] = st
            if st is not False:
                for pname, r in zip(ranges, st):
                    v = locals_.get(pname)
                    if v is None or v not in r:
                        return False
                return True
            g = g_ns()
            partial: dict = {}
            for pname, rngfn in ranges.items():
                v = locals_.get(pname)
                if v is None or v not in rngfn(g, _ns(partial)):
                    return False
                partial[pname] = v
            return True

        tc.in_space = in_space
        return tc


class PTGTaskpool(Taskpool):
    """A taskpool generated from a PTG description."""

    def __init__(self, name: str, builder: "PTGBuilder") -> None:
        super().__init__(name=name)
        self._builder = builder
        self._tc_builders: dict[str, TaskClassBuilder] = {}

    @property
    def globals(self) -> Any:
        """The bound JDF/builder globals as a namespace — what generated
        code reaches through ``__parsec_tp->super._g_<name>``; UD override
        functions receive the pool and read problem sizes through this."""
        return self._builder._g_ns()

    def validate(self, nb_ranks: int | None = None,
                 raise_on_error: bool = True) -> Any:
        """Statically verify this pool's dataflow (analysis.graphcheck):
        edge symmetry, access consistency, cycles, tile/rank bounds — the
        ``parsec_ptgpp`` compile-time contract, without executing a kernel.
        Returns the :class:`~parsec_tpu.analysis.GraphReport`; raises
        :class:`~parsec_tpu.analysis.GraphCheckError` in gate mode."""
        from ..analysis import check_ptg
        report = check_ptg(self, nb_ranks=nb_ranks)
        if raise_on_error:
            report.raise_if_failed()
        return report

    def nb_local_tasks(self) -> int:
        """Count tasks whose affinity lands on this rank (generated
        ``nb_local_tasks_fn`` analog); a pool-level UD override replaces
        the scan entirely."""
        if self._builder._nb_local_tasks_fn is not None:
            return int(self._builder._nb_local_tasks_fn(self))
        my_rank = self.context.my_rank if self.context else 0
        multi = (self.context is not None and self.context.nb_ranks > 1
                 and not self.local_only)
        n = 0
        for tc in self.task_classes:
            tcb = self._tc_builders[tc.name]
            for locals_ in tcb._enumerate_space():
                if multi and tc.affinity is not None:
                    dc, key = tc.affinity(locals_)
                    if not isinstance(key, tuple):
                        key = (key,)
                    if dc.rank_of(*key) != my_rank:
                        continue
                n += 1
        return n

    def startup(self, context: Any) -> list:
        """Enumerate initially-ready local tasks (empty IN-dep mask)."""
        from ..runtime.scheduling import resolve_data_inputs
        from ..runtime.task import Task
        multi = context.nb_ranks > 1 and not self.local_only
        out = []
        for tc in self.task_classes:
            tcb = self._tc_builders[tc.name]
            if tc.startup_fn is not None:
                # UD startup (JDF_PROP_UD_STARTUP_TASKS_FN_NAME): the user
                # enumerates the initially-ready instances themselves
                space = tc.startup_fn(self, context, tcb._ptg._g_ns())
            else:
                space = (l for l in tcb._enumerate_space()
                         if tc.input_dep_mask(l) == 0)
            for locals_ in space:
                if multi and tc.affinity is not None:
                    dc, key = tc.affinity(locals_)
                    if not isinstance(key, tuple):
                        key = (key,)
                    if dc.rank_of(*key) != my_rank_of(context):
                        continue
                prio = tc.priority(locals_) if tc.priority else 0
                t = Task(self, tc, dict(locals_), priority=prio)
                t.status = "ready"
                resolve_data_inputs(t)  # snapshot collection reads now
                out.append(t)
        return out


def my_rank_of(context: Any) -> int:
    return context.my_rank


class PTGBuilder:
    """Top-level builder: globals + task classes → :class:`PTGTaskpool`.

    Globals mirror JDF globals (problem sizes, collections); they are late
    bound so a built taskpool template can be re-parameterized.
    """

    def __init__(self, name: str, **globals_) -> None:
        self.name = name
        self.globals = dict(globals_)
        self._classes: list[TaskClassBuilder] = []
        self._g_view = _DictNS(self.globals)
        self._nb_local_tasks_fn: Callable | None = None
        self._termdet: str | None = None

    def global_(self, **kw) -> "PTGBuilder":
        self.globals.update(kw)
        return self

    def option(self, nb_local_tasks_fn: Callable | None = None,
               termdet: str | None = None) -> "PTGBuilder":
        """Pool-level UD options (JDF ``%option`` analog):
        ``nb_local_tasks_fn(taskpool) -> int`` replaces the execution-space
        scan (``JDF_PROP_UD_NB_LOCAL_TASKS_FN_NAME``); ``termdet`` selects
        this pool's termination detector (``JDF_PROP_TERMDET_NAME``)."""
        if nb_local_tasks_fn is not None:
            self._nb_local_tasks_fn = nb_local_tasks_fn
        if termdet is not None:
            self._termdet = termdet
        return self

    def _g_ns(self) -> _DictNS:
        return self._g_view   # live view: global updates stay visible

    def _dc_getter(self, collection: Any) -> Callable[[], Any]:
        if isinstance(collection, str):
            return lambda: self.globals[collection]
        return lambda: collection

    def task(self, name: str, **params: Callable) -> TaskClassBuilder:
        tcb = TaskClassBuilder(self, name, params)
        self._classes.append(tcb)
        return tcb

    def build(self) -> PTGTaskpool:
        tp = PTGTaskpool(self.name, self)
        tp.termdet_name = self._termdet
        for tcb in self._classes:
            tc = tp.add_task_class(tcb._build())
            tp._tc_builders[tc.name] = tcb
        return tp


# convenience range constructors mirroring JDF "low .. high" syntax
def span(low: Callable | int, high: Callable | int, step: int = 1) -> Callable:
    """Inclusive range ``low .. high`` like JDF execution-space ranges."""

    def rng(g: _NS, l: _NS) -> range:
        lo = low(g, l) if callable(low) else low
        hi = high(g, l) if callable(high) else high
        return range(lo, hi + 1, step)

    return rng
